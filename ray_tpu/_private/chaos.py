"""Chaos engine: seeded, deterministic fault injection for the control plane.

The recovery machinery (task retries, lineage reconstruction, liveness
beats, gang restart from committed checkpoints) is only as trustworthy as
the faults it has been exercised against.  This module is the single
place faults come from: every injection point in the runtime asks
``chaos.hit(site, ...)`` on its hot path (a no-op attribute check when
chaos is off) and the engine decides — deterministically — whether a
fault fires there.

Two trigger modes, combinable:

* **Schedules** — explicit fault specs that fire on the N-th matching
  hit of a site (optionally: only after ``after_s`` seconds, every
  ``every`` hits, at most ``max_fires`` times).  This is the replayable
  mode: the same schedule against the same workload fires the same
  faults at the same points.
* **Probabilities** — per-``site[.op]`` firing probabilities drawn from
  a ``random.Random`` seeded per (seed, site, op).  The *decision
  sequence* per site is a pure function of the seed: the k-th hit of a
  site always gets the same draw for a given seed (soak mode).

Configuration reaches every process through the ``RTPU_CHAOS`` env var
(inherited by the GCS, raylets and workers at spawn): either a bare
integer seed, or JSON::

    RTPU_CHAOS='{"seed": 7,
                 "schedule": [{"site": "raylet.dispatch", "op": "kill_worker",
                               "at": 3, "proc": "raylet", "head": false}],
                 "p": {"protocol.send.delay": 0.01},
                 "delay_s": 0.05}'

Spec filters: ``proc`` (role: driver/worker/raylet/gcs), ``head``
(raylet head-ness), ``method`` (the site's method/context string).
The sites wired through the runtime are *declared* in :data:`SITES`
below — the one authoritative table (rtpulint RTPU004 rejects any
``chaos.hit(...)`` whose site isn't in it, the registry round-trip in
``tests/test_static_analysis.py`` requires every declared site to be
exercised by the test tree, and ``python -m ray_tpu.analysis
--gen-docs`` renders it into docs/FAULT_TOLERANCE.md).

Every fired fault is appended to the chaos log (``RTPU_CHAOS_LOG`` path;
JSONL of ``{n, site, op, method, seq, ts}`` — everything except ``ts``
is deterministic, so two runs of the same seed+schedule compare equal
once ``ts`` is projected away; ``ts`` is written synchronously even for
self-kill ops, which is what benches compute detect latency from) and
shipped to the GCS event ring as a ``CHAOS_INJECT`` structured event
when the host process installed a notifier, so fault→detect→recover
latency is measurable from one event stream.
"""

from __future__ import annotations

import json
import os
import random
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

# ops the engine executes itself (process-generic); everything else is
# returned to the caller, which owns the op's semantics at that site
_SELF_KILL_OPS = ("kill",)

# The declared injection-site registry: site -> {"ops": [...],
# "where": one-line description of the code path that calls
# ``chaos.hit(site)`` and what each op does there}. Adding a
# ``chaos.hit`` call REQUIRES a row here (rtpulint RTPU004), and every
# row must be exercised by tests/ (the RTPU004 round-trip) — an
# undeclared site is a typo that silently never fires; an unexercised
# one is a fault path that ships untested. docs/FAULT_TOLERANCE.md's
# site table is rendered from this dict, never hand-edited.
SITES: Dict[str, Dict[str, Any]] = {
    "protocol.send": {
        "ops": ["drop", "delay", "dup", "reset"],
        "where": ("every framed message, BOTH wire implementations — "
                  "the asyncio `Connection` loops (protocol.py) and "
                  "the native frame pump's direct-execution lane "
                  "(direct.py) hit the site at the frame boundary with "
                  "identical semantics, so one seeded schedule replays "
                  "against either (`method` filter available)"),
    },
    "protocol.recv": {
        "ops": ["drop", "delay", "dup", "reset"],
        "where": ("receive side of the same frame boundary, both wire "
                  "implementations (`method` filter available)"),
    },
    "rpc.request": {
        "ops": ["kill"],
        "where": ("every served request, any process — SIGKILL self "
                  "before the handler runs"),
    },
    "worker.execute": {
        "ops": ["kill"],
        "where": ("the N-th task a worker starts executing (`method` "
                  "filter = function name)"),
    },
    "raylet.dispatch": {
        "ops": ["kill_worker", "kill", "preempt"],
        "where": ("the N-th task a raylet dispatches: `kill_worker` "
                  "SIGKILLs the target worker, `kill` the raylet "
                  "itself, `preempt` starts a graceful drain "
                  "(`grace_s`)"),
    },
    "object.pull": {
        "ops": ["evict", "corrupt"],
        "where": ("a pull about to be served: `evict` drops the "
                  "primary copy + directory entry, `corrupt` flips "
                  "bytes (caught by the pull crc)"),
    },
    "serve.controller.tick": {
        "ops": ["kill"],
        "where": ("the N-th serve control-loop tick — SIGKILL the "
                  "controller; the GCS restarts it and it recovers "
                  "from the journal (docs/SERVE_HA.md)"),
    },
    "serve.replica.request": {
        "ops": ["kill"],
        "where": ("the N-th request a serve replica accepts (`method` "
                  "filter = deployment name)"),
    },
    "dag.channel": {
        "ops": ["kill", "reset", "drop", "delay"],
        "where": ("compiled-DAG channel frames (dag/channel.py): "
                  "`kill` SIGKILLs the stage worker mid-graph, "
                  "`reset` severs the peer channel, `drop`/`delay` "
                  "lose/stall one frame (`method` filter = frame "
                  "method, dag_exec / dag_result)"),
    },
    "dag.stage": {
        "ops": ["kill"],
        "where": ("the worker hosting one specific compiled-DAG stage "
                  "at its N-th execution (`method` filter = the stage "
                  "id as a string)"),
    },
    "net.partition": {
        "ops": ["partition"],
        "where": ("every cross-node frame send — the netx "
                  "client/server lanes, the direct-execution lane and "
                  "the asyncio "
                  "`Connection` writer all consult the site before "
                  "writing: while a spec matches, frames from this "
                  "node toward the target host are dropped and the "
                  "connection severed (ONE direction of the pair; the "
                  "reverse stays up). `method` filter = "
                  "`<src_ip>><dst_ip>` so a schedule names the "
                  "direction; combine with `until_s` for a partition "
                  "that heals after a window, exercising "
                  "reconnect/backoff + fallback with no lost or "
                  "duplicated invocation"),
    },
    "llm.kv_ship": {
        "ops": ["drop", "delay", "reset", "corrupt"],
        "where": ("disaggregated LLM serving's prefill→decode KV "
                  "handoff (serve/llm/disagg.py), receive side "
                  "mid-handoff: `drop` loses the frame, `corrupt` "
                  "flips a byte so the CRC rejects it, `reset` raises "
                  "KVShipError — every op degrades to a decode-side "
                  "re-prefill with no leaked KV pages (`method` "
                  "filter = __llm_adopt__)"),
    },
}


class FaultSpec:
    """One schedule entry. Owns its own hit counter so two entries on
    the same site (e.g. different method filters) count independently —
    entry order in the schedule never changes what fires."""

    __slots__ = ("site", "op", "at", "every", "max_fires", "proc", "head",
                 "method", "args", "n", "fires")

    def __init__(self, spec: Dict[str, Any]):
        self.site = spec["site"]
        self.op = spec["op"]
        self.at = int(spec.get("at", 1))
        self.every = int(spec.get("every", 0))
        self.max_fires = int(spec.get("max_fires", 1))
        self.proc = spec.get("proc")
        self.head = spec.get("head")
        self.method = spec.get("method")
        self.args = {k: v for k, v in spec.items()
                     if k not in ("site", "op", "at", "every", "max_fires",
                                  "proc", "head", "method")}
        self.n = 0       # matching hits seen
        self.fires = 0   # times fired

    def matches(self, role: str, is_head: Optional[bool],
                method: Optional[str]) -> bool:
        if self.proc is not None and self.proc != role:
            return False
        if self.head is not None and is_head is not None \
                and bool(self.head) != bool(is_head):
            return False
        if self.method is not None and method != self.method:
            return False
        return True

    def should_fire(self, elapsed_s: float) -> bool:
        if self.max_fires and self.fires >= self.max_fires:
            return False
        after = self.args.get("after_s")
        if after is not None and elapsed_s < float(after):
            return False
        # a sustained fault with `until_s` heals itself: past the
        # window the spec stops firing even with max_fires=0 — how a
        # partition "ends" without any process coordinating the repair
        until = self.args.get("until_s")
        if until is not None and elapsed_s >= float(until):
            return False
        if self.n == self.at:
            return True
        if self.every > 0 and self.n > self.at \
                and (self.n - self.at) % self.every == 0:
            return True
        return False


class ChaosEngine:
    def __init__(self, seed: int = 0,
                 schedule: Optional[List[Dict[str, Any]]] = None,
                 probs: Optional[Dict[str, float]] = None,
                 role: str = "driver", is_head: Optional[bool] = None,
                 log_path: Optional[str] = None,
                 delay_s: float = 0.05):
        self.seed = int(seed)
        self.schedule = [FaultSpec(s) for s in (schedule or [])]
        self.probs = dict(probs or {})
        self.role = role
        self.is_head = is_head
        self.log_path = log_path
        self.delay_s = float(delay_s)  # default for delay ops without args
        self.start = time.monotonic()
        self.fired: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._rngs: Dict[str, random.Random] = {}
        self._prob_hits: Dict[str, int] = {}
        self._notifier: Optional[Callable[[Dict[str, Any]], None]] = None
        self._fire_seq = 0

    # ----------------------------------------------------------- decisions

    def _rng(self, key: str) -> random.Random:
        rng = self._rngs.get(key)
        if rng is None:
            # derive per-(seed, key) so one site's draw count never
            # perturbs another site's sequence
            rng = random.Random(f"{self.seed}:{key}")
            self._rngs[key] = rng
        return rng

    def hit(self, site: str, method: Optional[str] = None
            ) -> Optional[Dict[str, Any]]:
        """Record one hit of ``site``; return the action to inject (an
        op + args dict) or None. At most one action per hit."""
        with self._lock:
            elapsed = time.monotonic() - self.start
            for spec in self.schedule:
                if spec.site != site or \
                        not spec.matches(self.role, self.is_head, method):
                    continue
                spec.n += 1
                if spec.should_fire(elapsed):
                    spec.fires += 1
                    action = {"op": spec.op, "site": site,
                              "method": method, **spec.args}
                    self._record(action, spec.n)
                    return self._execute_generic(action)
            # probabilistic mode: keys "site.op" or "site.method.op"
            for key, p in self.probs.items():
                ksite, _, kop = key.rpartition(".")
                if ksite != site and not (
                        method is not None
                        and ksite == f"{site}.{method}"):
                    continue
                n = self._prob_hits.get(key, 0) + 1
                self._prob_hits[key] = n
                if self._rng(key).random() < float(p):
                    action = {"op": kop, "site": site, "method": method}
                    self._record(action, n)
                    return self._execute_generic(action)
        return None

    # ------------------------------------------------------------ plumbing

    def _record(self, action: Dict[str, Any], n: int):
        self._fire_seq += 1
        rec = {"n": n, "site": action["site"], "op": action["op"],
               "method": action.get("method"), "seq": self._fire_seq}
        self.fired.append(rec)
        if self.log_path:
            try:
                with open(self.log_path, "a") as f:
                    # ts is the ONE non-deterministic field (benches
                    # compute detect latency from it — the synchronous
                    # append survives even a self-SIGKILL op); replay
                    # comparisons project it away
                    json.dump({**rec, "ts": time.time()}, f,
                              sort_keys=True)
                    f.write("\n")
            except OSError:
                pass
        notifier = self._notifier
        if notifier is not None:
            try:
                from ray_tpu.util import events as ev
                notifier(ev.make_event(
                    "WARNING", "CHAOS_INJECT",
                    f"chaos fault {action['op']} at {action['site']} "
                    f"(hit {n})", **{k: v for k, v in rec.items()
                                     if v is not None}))
            except Exception:
                pass

    def _execute_generic(self, action: Dict[str, Any]
                         ) -> Optional[Dict[str, Any]]:
        """Execute process-generic ops inline; return site-specific ones
        to the caller."""
        if action["op"] in _SELF_KILL_OPS:
            # SIGKILL self: the realistic process-death fault (no atexit,
            # no cleanup) — exactly what a preempted/OOM-killed process
            # looks like to the rest of the cluster
            os.kill(os.getpid(), signal.SIGKILL)
            return None  # unreachable
        return action

    def set_notifier(self, fn: Optional[Callable[[Dict[str, Any]], None]]):
        self._notifier = fn


# -------------------------------------------------------------- module API

_ENGINE: Optional[ChaosEngine] = None


def enabled() -> bool:
    return _ENGINE is not None


def engine() -> Optional[ChaosEngine]:
    return _ENGINE


def hit(site: str, method: Optional[str] = None) -> Optional[Dict[str, Any]]:
    eng = _ENGINE
    if eng is None:
        return None
    return eng.hit(site, method)


def configure(seed: int = 0, schedule: Optional[List[Dict[str, Any]]] = None,
              probs: Optional[Dict[str, float]] = None,
              role: str = "driver", is_head: Optional[bool] = None,
              log_path: Optional[str] = None,
              delay_s: float = 0.05) -> ChaosEngine:
    """Programmatic setup (tests). Replaces any existing engine."""
    global _ENGINE
    _ENGINE = ChaosEngine(seed=seed, schedule=schedule, probs=probs,
                          role=role, is_head=is_head, log_path=log_path,
                          delay_s=delay_s)
    return _ENGINE


def clear():
    global _ENGINE
    _ENGINE = None


def parse_env(raw: str) -> Dict[str, Any]:
    """RTPU_CHAOS value → config dict. A bare integer means seed-only
    (soak probabilities/schedules come programmatically or via JSON)."""
    raw = raw.strip()
    if not raw:
        return {}
    try:
        return {"seed": int(raw)}
    except ValueError:
        pass
    cfg = json.loads(raw)
    if not isinstance(cfg, dict):
        raise ValueError(f"RTPU_CHAOS must be an int seed or a JSON "
                         f"object, got: {type(cfg).__name__}")
    return cfg


def init_from_env(role: str, is_head: Optional[bool] = None
                  ) -> Optional[ChaosEngine]:
    """Per-process setup from ``RTPU_CHAOS`` (no-op when unset). Called
    by every process entrypoint with its role so spec ``proc`` filters
    resolve; the env rides process spawn, so one export at the driver
    covers the whole cluster."""
    global _ENGINE
    raw = os.environ.get("RTPU_CHAOS")
    if not raw:
        return None
    try:
        cfg = parse_env(raw)
    except (ValueError, json.JSONDecodeError) as e:
        # a typo in a debug knob must not kill every process at startup
        import logging
        logging.getLogger(__name__).warning(
            "ignoring malformed RTPU_CHAOS=%r: %s", raw, e)
        return None
    if not cfg:
        return None
    _ENGINE = ChaosEngine(
        seed=cfg.get("seed", 0), schedule=cfg.get("schedule"),
        probs=cfg.get("p"), role=role, is_head=is_head,
        log_path=os.environ.get("RTPU_CHAOS_LOG"),
        delay_s=float(cfg.get("delay_s", 0.05)))
    return _ENGINE


def read_log(path: str) -> List[Dict[str, Any]]:
    """Parse a chaos log file into fired-fault records (replay
    comparison helper; entries carry no timestamps by design)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
    except OSError:
        pass
    return out
