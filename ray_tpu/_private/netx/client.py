"""The process-wide netx client: pooled cross-node frame connections.

ONE native pump + one IO thread per process serves every off-box (and
forced-TCP) fast-path consumer — raylet object pulls, actor calls on
the direct lane, keepalives.  Requests can be issued from any thread
(``request``) or from an asyncio coroutine (``call_async``); replies
are routed back by ``(cid, seq)``.  ``px_chunk`` notifies bypass the
request table entirely: each carries a stream id that resolves to a
sink writing straight into a plasma create buffer ON the IO thread —
no asyncio hop, no staging copy.

Connection hygiene is the tentpole's pool contract: ``ping``
keepalives on quiet connections (kill after 3 missed windows), idle
reaping after ``RTPU_NET_IDLE_S``, an ``RTPU_NET_POOL_MAX`` cap
evicting LRU-idle peers, and exponential-backoff redial starting at
``RTPU_NET_RECONNECT_S`` so a flapping peer can't melt the dialer.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, Optional, Tuple

import msgpack

from ray_tpu._private import chaos, protocol, rpccore
from ray_tpu._private.netx import endpoints

logger = logging.getLogger(__name__)

_REQUEST, _REPLY, _ERROR, _NOTIFY = (protocol.REQUEST, protocol.REPLY,
                                     protocol.ERROR, protocol.NOTIFY)

_BACKOFF_CAP_S = 5.0


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def keepalive_s() -> float:
    return _env_f("RTPU_NET_KEEPALIVE_S", 10.0)


def idle_s() -> float:
    return _env_f("RTPU_NET_IDLE_S", 60.0)


def reconnect_s() -> float:
    return _env_f("RTPU_NET_RECONNECT_S", 0.2)


def pool_max() -> int:
    return int(_env_f("RTPU_NET_POOL_MAX", 16))


def stall_s() -> float:
    return _env_f("RTPU_NET_STALL_S", 10.0)


def _pack(body) -> bytes:
    return msgpack.packb(body, use_bin_type=True)


def chaos_send(pump: rpccore.Pump, cid: int, method: str, data: bytes,
               peer_host: str = "") -> bool:
    """One frame through BOTH outbound fault gates: the net.partition
    site (drop + sever — an unplugged cable, not a polite reset) and
    the protocol.send frame faults, with the same drop/delay/dup/reset
    semantics as the asyncio Connection and the direct lane.  Returns
    False when the connection is gone."""
    if peer_host and endpoints.partitioned(peer_host):
        pump.close_conn(cid)
        return False
    eng = chaos._ENGINE
    if eng is not None:
        act = eng.hit("protocol.send", method)
        if act is not None:
            op = act["op"]
            if op == "drop":
                return True  # lost on the wire; peer never sees it
            if op == "delay":
                time.sleep(float(act.get("delay_s", eng.delay_s)))
            elif op == "reset":
                pump.close_conn(cid)
                return False
            elif op == "dup":
                pump.send(cid, data)
    return pump.send(cid, data)


class PullBusy(Exception):
    """Server at its serve-concurrency cap — retry later (maps onto the
    raylet's tree-broadcast busy/backoff discipline)."""


class PullNotFound(Exception):
    """The replica no longer holds the object (evicted/raced)."""


class _Conn:
    __slots__ = ("addr", "cid", "peer_host", "last_used", "last_heard",
                 "ping_sent", "inflight")

    def __init__(self, addr: str, cid: int, peer_host: str):
        now = time.monotonic()
        self.addr = addr
        self.cid = cid
        self.peer_host = peer_host
        self.last_used = now
        self.last_heard = now
        self.ping_sent: Optional[float] = None
        self.inflight = 0


class _Sink:
    """One in-flight pull stream: chunk frames land here (on the IO
    thread) and are written offset-addressed into the destination
    buffer, so duplicated frames are idempotent and resume-after-
    reconnect is just 'continue from .got'."""

    __slots__ = ("stream", "cid", "buf", "got", "total", "event", "error",
                 "last_progress")

    def __init__(self, stream: int, buf, got: int, total: int):
        self.stream = stream
        self.cid = -1
        self.buf = buf
        self.got = got
        self.total = total
        self.event = threading.Event()
        self.error: Optional[BaseException] = None
        self.last_progress = time.monotonic()

    def fail(self, err: BaseException):
        if self.error is None:
            self.error = err
        self.event.set()

    def finish(self):
        self.event.set()


class NetxClient:
    """See module docstring. One instance per process (``get_client``)."""

    def __init__(self):
        self._pump = rpccore.Pump()
        self._lock = threading.Lock()
        self._dial_cv = threading.Condition(self._lock)
        self._conns: Dict[str, _Conn] = {}
        self._by_cid: Dict[int, _Conn] = {}
        self._dialing: set = set()
        self._backoff: Dict[str, Tuple[float, float]] = {}
        self._pending: Dict[Tuple[int, int],
                            Callable[[bool, Any], None]] = {}
        self._streams: Dict[int, _Sink] = {}
        self._seq = itertools.count(1)
        self._sids = itertools.count(1)
        self._closed = False
        self._last_tend = 0.0
        self.stats = {"requests": 0, "chunks_in": 0, "bytes_in": 0,
                      "redials": 0, "reaped": 0, "pings": 0}
        self._thread = threading.Thread(
            target=self._run, name="rtpu-netx-io", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ dialing

    def _conn_for(self, address: str) -> _Conn:
        """Pooled connection to ``address`` (dial on miss). Backoff gate
        fails fast so callers fall back to their slow path instead of
        hammering a dead peer."""
        deadline = time.monotonic() + 10.0
        with self._lock:
            while True:
                if self._closed:
                    raise ConnectionError("netx client closed")
                conn = self._conns.get(address)
                if conn is not None:
                    conn.last_used = time.monotonic()
                    return conn
                gate = self._backoff.get(address)
                if gate is not None and time.monotonic() < gate[0]:
                    raise ConnectionError(
                        f"netx: {address} in reconnect backoff")
                if address not in self._dialing:
                    self._dialing.add(address)
                    break
                # another thread is dialing this peer: wait for it
                if not self._dial_cv.wait(
                        timeout=max(0.0, deadline - time.monotonic())):
                    raise ConnectionError(
                        f"netx: timed out waiting for dial of {address}")
        try:
            cid = self._pump.dial(address)
        except Exception:
            with self._lock:
                delay = self._backoff.get(address, (0.0, reconnect_s()))[1]
                self._backoff[address] = (time.monotonic() + delay,
                                          min(delay * 2, _BACKOFF_CAP_S))
                self._dialing.discard(address)
                self._dial_cv.notify_all()
            raise
        conn = _Conn(address, cid, endpoints.host_of(address))
        with self._lock:
            if self._backoff.pop(address, None) is not None:
                self.stats["redials"] += 1
            self._conns[address] = conn
            self._by_cid[cid] = conn
            self._dialing.discard(address)
            self._dial_cv.notify_all()
        return conn

    # ----------------------------------------------------------- requests

    def _start_request(self, address: str, method: str, payload: Any,
                       done: Callable[[bool, Any], None]
                       ) -> Optional[Tuple[int, int]]:
        """Register + send one REQUEST; ``done(ok, payload_or_exc)``
        fires exactly once, from the IO thread (or inline on immediate
        failure). Returns the pending key for timeout cleanup."""
        try:
            conn = self._conn_for(address)
        except Exception as e:
            done(False, e)
            return None
        seq = next(self._seq)
        key = (conn.cid, seq)
        with self._lock:
            self._pending[key] = done
            conn.inflight += 1
            conn.last_used = time.monotonic()
            self.stats["requests"] += 1
        data = _pack([_REQUEST, seq, method, payload])
        if not chaos_send(self._pump, conn.cid, method, data,
                          conn.peer_host):
            # the conn died between pooling and send (or a fault severed
            # it): close_conn's KIND_CLOSED normally fails the pending,
            # but if the close already drained we must fail it here
            self._pump.close_conn(conn.cid)
            with self._lock:
                cb = self._pending.pop(key, None)
            if cb is not None:
                cb(False, ConnectionError(
                    f"netx: send to {address} failed"))
            return None
        return key

    def request(self, address: str, method: str, payload: Any,
                timeout: float = 30.0) -> Any:
        """Synchronous request from any thread."""
        slot: Dict[str, Any] = {}
        ev = threading.Event()

        def done(ok, r):
            slot["ok"] = ok
            slot["r"] = r
            ev.set()

        key = self._start_request(address, method, payload, done)
        if not ev.wait(timeout):
            if key is not None:
                with self._lock:
                    cb = self._pending.pop(key, None)
                    conn = self._by_cid.get(key[0])
                    if cb is not None and conn is not None:
                        conn.inflight = max(0, conn.inflight - 1)
            raise TimeoutError(f"netx: {method} to {address} timed out")
        if not slot["ok"]:
            r = slot["r"]
            raise r if isinstance(r, BaseException) \
                else protocol.RpcError(r)
        return slot["r"]

    def call_async(self, address: str, method: str, payload: Any
                   ) -> "asyncio.Future":
        """Issue a request from a running event loop. The send happens
        INLINE in this call, so per-peer wire order follows call order —
        exactly what the actor sequence lane needs."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()

        def done(ok, r):
            def _set():
                if fut.cancelled():
                    return
                if ok:
                    fut.set_result(r)
                else:
                    fut.set_exception(
                        r if isinstance(r, BaseException)
                        else protocol.RpcError(r))
            try:
                loop.call_soon_threadsafe(_set)
            except RuntimeError:
                pass  # loop gone (shutdown)

        self._start_request(address, method, payload, done)
        return fut

    def _notify(self, cid: int, method: str, payload: Any,
                peer_host: str = ""):
        data = _pack([_NOTIFY, None, method, payload])
        chaos_send(self._pump, cid, method, data, peer_host)

    # -------------------------------------------------------- pull streams

    def get_header(self, address: str, object_id_hex: str,
                   timeout: float = 30.0) -> Dict[str, Any]:
        """``px_get``: does the peer hold the object, how big, or busy."""
        return self.request(address, "px_get",
                            {"object_id": object_id_hex}, timeout)

    def pull_into(self, address: str, object_id_hex: str, buf, total: int,
                  offset: int = 0, attempts: int = 5,
                  stall_timeout: Optional[float] = None) -> int:
        """Stream the object's bytes into ``buf`` (a plasma create
        buffer) via windowed ``px_chunk`` frames. Transport failures
        resume from the high-water mark on a fresh connection; data
        failures (crc, server error) raise so the caller treats the
        replica as bad. Returns the byte count written."""
        if stall_timeout is None:
            stall_timeout = stall_s()
        mv = memoryview(buf)
        got = offset
        last_err: Optional[BaseException] = None
        for attempt in range(attempts):
            if attempt:
                time.sleep(min(reconnect_s() * (2 ** (attempt - 1)), 1.0))
            sid = next(self._sids)
            sink = _Sink(sid, mv, got, total)
            try:
                conn = self._conn_for(address)
            except Exception as e:
                last_err = e
                continue
            sink.cid = conn.cid
            with self._lock:
                self._streams[sid] = sink
            try:
                r = self.request(
                    address, "px_pull",
                    {"object_id": object_id_hex, "offset": got,
                     "stream": sid, "from_host": endpoints.node_ip()},
                    timeout=max(stall_timeout, 30.0))
            except Exception as e:
                with self._lock:
                    self._streams.pop(sid, None)
                last_err = e
                if isinstance(e, (ConnectionError, TimeoutError)):
                    continue
                raise
            if r.get("busy"):
                with self._lock:
                    self._streams.pop(sid, None)
                raise PullBusy(address)
            if not r.get("found", True):
                with self._lock:
                    self._streams.pop(sid, None)
                raise PullNotFound(object_id_hex)
            while not sink.event.wait(timeout=0.5):
                if time.monotonic() - sink.last_progress > stall_timeout:
                    with self._lock:
                        self._streams.pop(sid, None)
                    self._notify(sink.cid, "px_ack",
                                 {"stream": sid, "got": -1},
                                 conn.peer_host)
                    sink.fail(TimeoutError(
                        f"netx: pull of {object_id_hex[:8]} from "
                        f"{address} stalled at {sink.got}/{total}"))
                    break
            if sink.error is None:
                return sink.got - offset
            last_err = sink.error
            got = max(got, sink.got)  # resume, never re-transfer
            if not isinstance(sink.error, (ConnectionError, TimeoutError)):
                raise sink.error
        raise last_err if last_err is not None else ConnectionError(
            f"netx: pull from {address} failed")

    # ------------------------------------------------------------- IO loop

    def _run(self):
        while not self._closed:
            try:
                evs = self._pump.next_batch(250)
            except Exception:
                return  # pump destroyed under us
            if evs is None:
                return  # shutdown
            for cid, kind, body in evs:
                if kind == rpccore.KIND_CLOSED:
                    self._on_closed(cid)
                elif kind == rpccore.KIND_FRAME:
                    try:
                        self._on_frame(cid, body)
                    except Exception:
                        logger.exception("netx client: frame failed")
            self._tend()

    def _on_closed(self, cid: int):
        with self._lock:
            conn = self._by_cid.pop(cid, None)
            if conn is not None and self._conns.get(conn.addr) is conn:
                del self._conns[conn.addr]
                # arm backoff so the NEXT dial of a flapping peer waits
                if conn.addr not in self._backoff:
                    self._backoff[conn.addr] = (
                        time.monotonic() + reconnect_s(),
                        min(reconnect_s() * 2, _BACKOFF_CAP_S))
            dead = [k for k in self._pending if k[0] == cid]
            cbs = [self._pending.pop(k) for k in dead]
            sinks = [s for s in self._streams.values() if s.cid == cid]
            for s in sinks:
                self._streams.pop(s.stream, None)
        err = ConnectionError("netx: connection closed")
        for cb in cbs:
            cb(False, err)
        for s in sinks:
            s.fail(err)

    def _on_frame(self, cid: int, body: bytes):
        try:
            mtype, seq, method, payload = msgpack.unpackb(body, raw=False)
        except Exception:
            self._pump.close_conn(cid)
            return
        eng = chaos._ENGINE
        if eng is not None and mtype in (_REQUEST, _NOTIFY):
            # inbound frame-fault site, same semantics as the asyncio
            # reader and the direct lane (replies exempt: reply loss is
            # modeled sender-side)
            act = eng.hit("protocol.recv", method)
            if act is not None:
                op = act["op"]
                if op == "drop":
                    return
                if op == "delay":
                    time.sleep(float(act.get("delay_s", eng.delay_s)))
                elif op == "reset":
                    self._pump.close_conn(cid)
                    return
                elif op == "dup" and method == "px_chunk":
                    self._on_chunk(cid, payload)  # idempotent write
        conn = self._by_cid.get(cid)
        if conn is not None:
            conn.last_heard = time.monotonic()
            conn.ping_sent = None
        if mtype in (_REPLY, _ERROR):
            with self._lock:
                cb = self._pending.pop((cid, seq), None)
                if conn is not None and cb is not None:
                    conn.inflight = max(0, conn.inflight - 1)
            if cb is not None:
                if mtype == _REPLY:
                    cb(True, payload)
                else:
                    cb(False, protocol.RpcError(payload))
        elif mtype == _NOTIFY and method == "px_chunk":
            self._on_chunk(cid, payload)

    def _on_chunk(self, cid: int, payload: Dict[str, Any]):
        sid = payload.get("stream")
        with self._lock:
            sink = self._streams.get(sid)
        if sink is None or sink.cid != cid:
            return  # cancelled/stale stream: ignore the straggler
        off = int(payload["offset"])
        data = payload["data"]
        crc = payload.get("crc")
        if crc is not None and (zlib.crc32(data) & 0xFFFFFFFF) != crc:
            with self._lock:
                self._streams.pop(sid, None)
            peer = self._by_cid.get(cid)
            self._notify(cid, "px_ack", {"stream": sid, "got": -1},
                         peer.peer_host if peer else "")
            sink.fail(IOError(
                f"netx: chunk crc mismatch at offset {off}"))
            return
        end = off + len(data)
        if end > sink.total:
            with self._lock:
                self._streams.pop(sid, None)
            sink.fail(IOError("netx: chunk overruns object size"))
            return
        sink.buf[off:end] = data  # straight into plasma memory
        # .got is the CONTIGUOUS high-water mark: a dropped frame leaves
        # a gap that later chunks must not paper over — the stream then
        # stalls at the gap and resume re-requests from .got, so a hole
        # can never be sealed into the store
        if off <= sink.got < end:
            sink.got = end
        sink.last_progress = time.monotonic()
        self.stats["chunks_in"] += 1
        self.stats["bytes_in"] += len(data)
        peer = self._by_cid.get(cid)
        self._notify(cid, "px_ack", {"stream": sid, "got": sink.got},
                     peer.peer_host if peer else "")
        if sink.got >= sink.total:
            with self._lock:
                self._streams.pop(sid, None)
            sink.finish()

    # ------------------------------------------------------- pool hygiene

    def _tend(self):
        now = time.monotonic()
        if now - self._last_tend < 1.0:
            return
        self._last_tend = now
        ka, idle, cap = keepalive_s(), idle_s(), pool_max()
        to_close, to_ping = [], []
        with self._lock:
            streaming = {s.cid for s in self._streams.values()}
            conns = list(self._conns.values())
            for c in conns:
                busy = c.inflight > 0 or c.cid in streaming
                if not busy and now - c.last_used > idle:
                    to_close.append(c)
                    continue
                if busy:
                    # a peer executing our request may not pong for the
                    # duration (single-lane servers, GIL-holding TPU
                    # init): the inflight call is the liveness signal,
                    # process death still arrives as KIND_CLOSED, and
                    # streams carry their own stall timer
                    c.ping_sent = None
                    continue
                if c.ping_sent is not None \
                        and now - c.ping_sent > max(3 * ka, 5.0):
                    to_close.append(c)  # peer unresponsive: declare dead
                    continue
                if now - c.last_heard > ka and c.ping_sent is None:
                    to_ping.append(c)
            if len(conns) - len(to_close) > cap:
                idlers = sorted(
                    (c for c in conns
                     if c.inflight == 0 and c.cid not in streaming
                     and c not in to_close),
                    key=lambda c: c.last_used)
                to_close.extend(
                    idlers[:len(conns) - len(to_close) - cap])
        for c in to_close:
            self.stats["reaped"] += 1
            self._pump.close_conn(c.cid)
        for c in to_ping:
            c.ping_sent = now
            seq = next(self._seq)
            with self._lock:
                self._pending[(c.cid, seq)] = lambda ok, r: None
            self.stats["pings"] += 1
            if not chaos_send(self._pump, c.cid, "ping",
                              _pack([_REQUEST, seq, "ping", {}]),
                              c.peer_host):
                self._pump.close_conn(c.cid)

    # ------------------------------------------------------------ lifecycle

    def close(self):
        self._closed = True
        self._pump.shutdown()
        self._thread.join(timeout=2.0)
        self._pump.destroy()
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
            sinks = list(self._streams.values())
            self._streams.clear()
            self._conns.clear()
            self._by_cid.clear()
        err = ConnectionError("netx client closed")
        for cb in pending:
            cb(False, err)
        for s in sinks:
            s.fail(err)


# ------------------------------------------------------------- module API

_CLIENT: Optional[NetxClient] = None
_CLIENT_FAILED = False
_CLIENT_LOCK = threading.Lock()


def get_client() -> Optional[NetxClient]:
    """The process-wide client, created on first use. None when the
    plane is gated off (RTPU_NETX=0) or the native pump is unavailable
    — callers then stay on their unix/asyncio paths."""
    global _CLIENT, _CLIENT_FAILED
    if _CLIENT is not None:
        return _CLIENT
    if _CLIENT_FAILED:
        return None
    with _CLIENT_LOCK:
        if _CLIENT is None and not _CLIENT_FAILED:
            if not endpoints.enabled() or not rpccore.available():
                _CLIENT_FAILED = True
                return None
            try:
                _CLIENT = NetxClient()
            except Exception:
                logger.warning("netx client unavailable", exc_info=True)
                _CLIENT_FAILED = True
    return _CLIENT


def reset_client_for_tests():
    global _CLIENT, _CLIENT_FAILED
    with _CLIENT_LOCK:
        if _CLIENT is not None:
            try:
                _CLIENT.close()
            except Exception:
                pass
        _CLIENT = None
        _CLIENT_FAILED = False
