"""netx: the unified cross-node transport plane.

Every fast path in the runtime — the direct-execution lane (direct.py),
compiled-DAG channels (dag/channel.py), and bulk object transfer — was
same-host only: endpoints were unix socket paths, and the one TCP
surface (raylet pull_object) rode the asyncio control plane at
~63 MiB/s (SCALE.md).  netx takes them all off-box:

* **endpoints** — who am I (``RTPU_NODE_IP`` → resolved hostname →
  loopback), which advertised endpoint to dial (unix for on-box peers,
  ``host:port`` otherwise), and the ``net.partition`` chaos gate.
* **client** — ONE shared native frame pump + IO thread per process:
  pooled request/reply connections to any pump server (raylet transfer
  servers, worker direct sockets) with keepalive pings, idle reaping
  and exponential-backoff redial, plus the ``px_*`` pull protocol that
  streams object chunks straight into a plasma create buffer.
* **server** — the raylet-side transfer server: ``px_get`` headers and
  windowed, round-robin-interleaved ``px_chunk`` streams served by the
  native pump (the asyncio loop is only consulted for store admission,
  spill restore and the serve-concurrency tree cap).

Wire frames are standard schema-1.x msgpack frames
(docs/WIRE_PROTOCOL.md §1.8); ``RTPU_NETX=0`` turns the whole plane
off and every caller degrades to the unix/asyncio paths.
"""

from ray_tpu._private.netx.endpoints import (  # noqa: F401
    enabled, force_tcp, host_of, node_ip, partitioned, pick, same_host)
from ray_tpu._private.netx.client import (  # noqa: F401
    NetxClient, get_client, reset_client_for_tests)
from ray_tpu._private.netx.server import NetxServer  # noqa: F401
