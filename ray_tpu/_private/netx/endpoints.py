"""The endpoint plane: node identity and dial-side endpoint choice.

Every component that used to hardcode ``127.0.0.1`` or a unix socket
path asks this module instead:

* ``node_ip()`` — the address this node ADVERTISES (``RTPU_NODE_IP``,
  else the resolved hostname when it isn't loopback, else 127.0.0.1).
* ``pick(unix, tcp)`` — the address a CLIENT dials given a peer's
  advertised pair: the unix path for on-box peers (cheapest), the
  ``host:port`` otherwise.
* ``partitioned(peer_host)`` — the ``net.partition`` chaos gate: true
  while a fault spec severs the ``node_ip()>peer_host`` direction.
"""

from __future__ import annotations

import os
import socket
import threading

_NODE_IP = None
_NODE_IP_LOCK = threading.Lock()


def enabled() -> bool:
    """Master gate for the netx plane (TCP endpoint advertisement and
    the off-box fast paths). Default ON; ``RTPU_NETX=0`` restores the
    unix-only seed behaviour."""
    return os.environ.get("RTPU_NETX", "1").lower() not in (
        "0", "false", "no")


def force_tcp() -> bool:
    """``RTPU_NET_FORCE_TCP=1``: treat every peer as off-box so the
    simulated multi-"host" harness exercises the TCP lanes on one
    machine."""
    return os.environ.get("RTPU_NET_FORCE_TCP", "").lower() in (
        "1", "true", "yes")


def node_ip() -> str:
    """The IP this node binds and advertises. Cached per process —
    RTPU_NODE_IP is read once, like the rest of the node identity."""
    global _NODE_IP
    ip = _NODE_IP
    if ip is None:
        with _NODE_IP_LOCK:
            if _NODE_IP is None:
                _NODE_IP = _detect_node_ip()
            ip = _NODE_IP
    return ip


def _detect_node_ip() -> str:
    ip = os.environ.get("RTPU_NODE_IP", "").strip()
    if ip:
        return ip
    try:
        ip = socket.gethostbyname(socket.gethostname())
        if ip and not ip.startswith("127."):
            return ip
    except OSError:
        pass
    return "127.0.0.1"


def host_of(address: str) -> str:
    """Host part of a ``host:port`` address ('' for unix endpoints)."""
    if not address or address.startswith("unix:") or address.startswith("/"):
        return ""
    if address.startswith("tcp:"):
        address = address[4:]
    return address.rsplit(":", 1)[0]


def same_host(address: str) -> bool:
    """True when ``address`` is served from this node (so its unix
    sibling is reachable). Unix endpoints are same-host by definition;
    ``host:port`` matches loopback or our advertised IP, unless the
    harness forces everything off-box."""
    if not address:
        return False
    if address.startswith("unix:") or address.startswith("/"):
        return True
    if force_tcp():
        return False
    host = host_of(address)
    return host in ("localhost", "127.0.0.1", node_ip())


def pick(unix_address, tcp_address) -> str:
    """Dial-side endpoint choice from a peer's advertised pair. Prefer
    the unix path when the peer is on this box (or advertises nothing
    else); otherwise the TCP endpoint. '' when neither is advertised."""
    unix_address = unix_address or ""
    tcp_address = tcp_address or ""
    if unix_address and (not tcp_address or same_host(tcp_address)):
        return unix_address
    return tcp_address or unix_address


def partitioned(peer_host: str) -> bool:
    """The ``net.partition`` chaos site: drop ONE direction of a host
    pair. A spec with ``method="<src_ip>>{dst_ip}"`` severs frames from
    src to dst while leaving the reverse direction up — the classic
    asymmetric partition that heals via reconnect/fallback."""
    if not peer_host:
        return False
    from ray_tpu._private import chaos
    if not chaos.enabled():
        return False
    act = chaos.hit("net.partition", f"{node_ip()}>{peer_host}")
    return bool(act) and act.get("op") == "partition"


def _reset_for_tests():
    global _NODE_IP
    with _NODE_IP_LOCK:
        _NODE_IP = None
