"""The raylet's netx transfer server: objects out, at wire speed.

A native pump with a TCP listener plus one serve thread.  Headers
(``px_get``) and stream admission (``px_pull``) are resolved ON the
raylet's asyncio loop (``run_coroutine_threadsafe``) so they share the
exact store discipline of ``handle_pull_object`` — the chaos
``object.pull`` site, spill restore, and the tree-broadcast
serve-concurrency cap in ``_serving_pulls``.  The bytes themselves
never touch the loop: the serve thread reads chunks straight out of
the pinned plasma buffer, crc32s them, and pushes ``px_chunk``
NOTIFYs through the pump.

Flow control is receiver-driven: the client acks its contiguous
high-water mark (``px_ack``) and the server sends at most
``window_chunks`` ahead of it, bounding pump out-buffer memory per
stream no matter how large the object.  Multiple streams interleave
round-robin so one giant transfer can't starve its siblings — the
fairness a broadcast tree needs while every generation serves.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
import zlib
from typing import Any, Dict, Optional, Tuple

import msgpack

from ray_tpu._private import chaos, protocol, rpccore, schema
from ray_tpu._private.netx import endpoints
from ray_tpu.common.ids import ObjectID

logger = logging.getLogger(__name__)

_REQUEST, _REPLY, _ERROR, _NOTIFY = (protocol.REQUEST, protocol.REPLY,
                                     protocol.ERROR, protocol.NOTIFY)

CHUNK = 4 * 1024 * 1024        # matches the raylet pull chunk
WINDOW_CHUNKS = 8              # unacked chunks in flight per stream


def _pack(body) -> bytes:
    return msgpack.packb(body, use_bin_type=True)


class _Stream:
    __slots__ = ("cid", "sid", "oid", "buf", "total", "start", "sent",
                 "acked", "peer_host", "corrupt", "key", "capped",
                 "last_ack_t")

    def __init__(self, cid: int, sid: int, oid: ObjectID, buf, total: int,
                 start: int, peer_host: str, corrupt: bool,
                 key, capped: bool):
        self.cid = cid
        self.sid = sid
        self.oid = oid
        self.buf = buf
        self.total = total
        self.start = start
        self.sent = start
        self.acked = start
        self.peer_host = peer_host
        self.corrupt = corrupt
        self.key = key
        self.capped = capped
        self.last_ack_t = time.monotonic()


class NetxServer:
    """See module docstring. Owned by the raylet; one per node."""

    def __init__(self, raylet, host: str, loop: asyncio.AbstractEventLoop,
                 chunk: int = CHUNK, window_chunks: int = WINDOW_CHUNKS):
        self.raylet = raylet
        self.loop = loop
        self.chunk = chunk
        self.window = window_chunks * chunk
        self.pump = rpccore.Pump()
        port = self.pump.listen_tcp(host, 0)
        self.address = f"{host}:{port}"
        self._streams: Dict[Tuple[int, int], _Stream] = {}
        self._rr = 0
        self._last_refresh = time.monotonic()
        self.stats = {"streams": 0, "chunks_out": 0, "bytes_out": 0}
        self._thread = threading.Thread(
            target=self._serve, name="rtpu-netx-serve", daemon=True)
        self._thread.start()

    def close(self):
        self.pump.shutdown()
        self._thread.join(timeout=2.0)
        self.pump.destroy()

    # ----------------------------------------------------------- serve loop

    def _serve(self):
        while True:
            can_send = any(
                st.sent < st.total and st.sent - st.acked < self.window
                for st in self._streams.values())
            timeout = 0 if can_send else (50 if self._streams else 250)
            try:
                evs = self.pump.next_batch(timeout)
            except Exception:
                return  # pump destroyed under us
            if evs is None:
                return  # shutdown
            for cid, kind, body in evs:
                if kind == rpccore.KIND_CLOSED:
                    self._on_closed(cid)
                elif kind == rpccore.KIND_FRAME:
                    try:
                        self._on_frame(cid, body)
                    except Exception:
                        logger.exception("netx server: frame failed")
            self._pump_streams()
            self._refresh()

    def _on_closed(self, cid: int):
        for key in [k for k in self._streams if k[0] == cid]:
            self._finish_stream(self._streams[key])

    def _on_frame(self, cid: int, body: bytes):
        try:
            mtype, seq, method, payload = msgpack.unpackb(body, raw=False)
        except Exception:
            self.pump.close_conn(cid)
            return
        eng = chaos._ENGINE
        if eng is not None and mtype in (_REQUEST, _NOTIFY):
            act = eng.hit("protocol.recv", method)
            if act is not None:
                op = act["op"]
                if op == "drop":
                    return
                if op == "delay":
                    time.sleep(float(act.get("delay_s", eng.delay_s)))
                elif op == "reset":
                    self.pump.close_conn(cid)
                    return
                # dup of an ack/pull request is naturally idempotent
        if mtype == _REQUEST:
            self._on_request(cid, seq, method, payload or {})
        elif mtype == _NOTIFY and method == "px_ack":
            self._on_ack(cid, payload or {})

    def _reply(self, cid: int, seq, payload: Any, peer_host: str = "",
               error: bool = False):
        from ray_tpu._private.netx.client import chaos_send
        mtype = _ERROR if error else _REPLY
        chaos_send(self.pump, cid,
                   "px_reply", _pack([mtype, seq, None, payload]),
                   peer_host)

    def _on_request(self, cid: int, seq, method: str,
                    payload: Dict[str, Any]):
        if method == "__hello__":
            err = schema.check_hello(payload)
            if err is not None:
                self._reply(cid, seq, err, error=True)
                self.pump.close_conn(cid)
            else:
                self._reply(cid, seq, schema.hello_payload())
            return
        if method == "ping":
            self._reply(cid, seq, {"server": "netx",
                                   "node_id": self.raylet.node_id})
            return
        if method not in ("px_get", "px_pull"):
            self._reply(cid, seq, f"netx: no such method {method}",
                        error=True)
            return
        oid_hex = payload.get("object_id", "")
        peer_host = payload.get("from_host", "")
        want_stream = method == "px_pull"
        offset = int(payload.get("offset", 0))
        sid = int(payload.get("stream", 0))
        token = f"netx{sid}:{cid}"
        try:
            fut = asyncio.run_coroutine_threadsafe(
                self._open(oid_hex, offset, token, want_stream), self.loop)
            r = fut.result(timeout=30.0)
        except Exception as e:
            self._reply(cid, seq, f"netx: open failed: {e!r}",
                        peer_host, error=True)
            return
        buf = r.pop("_buf", None)
        corrupt = r.pop("_corrupt", False)
        key = r.pop("_key", None)
        capped = r.pop("_capped", False)
        if want_stream and buf is not None:
            st = _Stream(cid, sid, ObjectID.from_hex(oid_hex), buf,
                         r["total_size"], offset, peer_host, corrupt,
                         key, capped)
            self._streams[(cid, sid)] = st
            self.stats["streams"] += 1
        self._reply(cid, seq, r, peer_host)

    def _on_ack(self, cid: int, payload: Dict[str, Any]):
        st = self._streams.get((cid, int(payload.get("stream", -1))))
        if st is None:
            return
        got = int(payload.get("got", 0))
        if got < 0:
            self._finish_stream(st)  # client cancelled (crc/stall)
            return
        if got > st.acked:
            st.acked = got
            st.last_ack_t = time.monotonic()

    # --------------------------------------------- loop-side store access

    async def _open(self, oid_hex: str, offset: int, token: str,
                    want_stream: bool) -> Dict[str, Any]:
        """Header + admission on the raylet loop: identical store
        discipline to handle_pull_object (chaos site, spill restore,
        serve-concurrency cap), returning a PINNED buffer for the serve
        thread when a stream is admitted."""
        r = self.raylet
        oid = ObjectID.from_hex(oid_hex)
        corrupt = False
        if chaos._ENGINE is not None:
            act = chaos.hit("object.pull", oid_hex)
            if act is not None:
                if act.get("op") == "evict":
                    await r._chaos_evict(oid)
                    return {"found": False}
                corrupt = act.get("op") == "corrupt"
        buf = r.store.get_buffer(oid)
        if buf is None and oid_hex in r.spilled:
            await r._restore_spilled(oid)
            buf = r.store.get_buffer(oid)
        if buf is None:
            return {"found": False}
        total = len(buf)
        if not want_stream:
            buf.release()
            r.store.release(oid)
            return {"found": True, "total_size": total}
        key = (oid_hex, token)
        capped = total >= r.config.object_serve_tree_min_bytes
        if capped and offset == 0:
            now = time.monotonic()
            for k, ts in list(r._serving_pulls.items()):
                if now - ts > 10.0:  # reader abandoned mid-pull
                    r._serving_pulls.pop(k, None)
            if key not in r._serving_pulls and \
                    len(r._serving_pulls) >= \
                    r.config.object_serve_concurrency:
                buf.release()
                r.store.release(oid)
                return {"found": True, "busy": True}
        if capped:
            r._serving_pulls[key] = time.monotonic()
        return {"found": True, "total_size": total, "_buf": buf,
                "_corrupt": corrupt, "_key": key, "_capped": capped}

    def _finish_stream(self, st: _Stream):
        self._streams.pop((st.cid, st.sid), None)
        raylet = self.raylet

        def _release():
            if st.capped:
                raylet._serving_pulls.pop(st.key, None)
            try:
                st.buf.release()
            except Exception:
                pass
            try:
                raylet.store.release(st.oid)
            except Exception:
                pass

        try:
            self.loop.call_soon_threadsafe(_release)
        except RuntimeError:
            pass  # loop already gone at shutdown

    # ---------------------------------------------------------- chunk pump

    def _pump_streams(self):
        if not self._streams:
            return
        sts = list(self._streams.values())
        self._rr = (self._rr + 1) % len(sts)
        for st in sts[self._rr:] + sts[:self._rr]:
            sent_any = 0
            # a couple of chunks per visit: round-robin interleave so
            # concurrent streams share the wire fairly
            while sent_any < 2 and st.sent < st.total \
                    and st.sent - st.acked < self.window:
                if not self._send_chunk(st):
                    break
                sent_any += 1
            if st.sent >= st.total:
                # all bytes are in the pump's out-buffer: the buffer
                # pin is no longer needed (resume re-opens it)
                self._finish_stream(st)
            elif time.monotonic() - st.last_ack_t > 60.0:
                self._finish_stream(st)  # reader abandoned mid-stream

    def _send_chunk(self, st: _Stream) -> bool:
        n = min(self.chunk, st.total - st.sent)
        data = bytes(st.buf[st.sent:st.sent + n])
        # crc over the CLEAN bytes, then tear: a chaos 'corrupt' must
        # be caught by the receiver's check, same as handle_pull_object
        crc = zlib.crc32(data) & 0xFFFFFFFF
        if st.corrupt and st.sent == st.start:
            torn = bytearray(data)
            torn[0] ^= 0xFF
            torn[-1] ^= 0xFF
            data = bytes(torn)
        payload = {"stream": st.sid, "offset": st.sent, "data": data,
                   "crc": crc, "total_size": st.total,
                   "last": st.sent + n >= st.total}
        if st.peer_host and endpoints.partitioned(st.peer_host):
            self.pump.close_conn(st.cid)  # KIND_CLOSED reaps the stream
            return False
        body = _pack([_NOTIFY, None, "px_chunk", payload])
        eng = chaos._ENGINE
        if eng is not None:
            act = eng.hit("protocol.send", "px_chunk")
            if act is not None:
                op = act["op"]
                if op == "drop":
                    st.sent += n  # lost in flight; the ack gap heals it
                    return True
                if op == "delay":
                    time.sleep(float(act.get("delay_s", eng.delay_s)))
                elif op == "reset":
                    self.pump.close_conn(st.cid)
                    return False
                elif op == "dup":
                    self.pump.send(st.cid, body)
        if not self.pump.send(st.cid, body):
            return False
        st.sent += n
        self.stats["chunks_out"] += 1
        self.stats["bytes_out"] += n
        return True

    def _refresh(self):
        """Keep _serving_pulls timestamps fresh for active capped
        streams so the 10 s abandoned-reader reap never fires on a
        long, healthy transfer."""
        now = time.monotonic()
        if now - self._last_refresh < 2.0:
            return
        self._last_refresh = now
        keys = [st.key for st in self._streams.values() if st.capped]
        if not keys:
            return
        raylet = self.raylet

        def _touch():
            ts = time.monotonic()
            for k in keys:
                if k in raylet._serving_pulls:
                    raylet._serving_pulls[k] = ts

        try:
            self.loop.call_soon_threadsafe(_touch)
        except RuntimeError:
            pass
