"""Control-plane microbenchmarks: tasks/s, actor calls/s, put/get throughput.

Role-equivalent to the reference's microbenchmark suite
(reference: python/ray/_private/ray_perf.py:93 and
ray_microbenchmark_helpers.py timeit) — the numbers that justify (or refute)
running the L1 runtime as Python asyncio processes instead of C++ on a TPU
host. A TPU host runs O(1-8) model workers whose step time is 10-100 ms;
the control plane only has to stay far off the critical path at that scale.

Run: ``python -m ray_tpu._private.ray_perf [--json out.json]``
"""

from __future__ import annotations

import json
import statistics
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


def timeit(name: str, fn: Callable[[], None], multiplier: float = 1,
           reps: int = 3, window_s: float = 1.0,
           ) -> Tuple[str, float, float]:
    """Measure fn() calls/s over `reps` windows; returns (name, mean, sd)."""
    # warmup: run for ~0.3 s
    start = time.perf_counter()
    while time.perf_counter() - start < 0.3:
        fn()
    rates: List[float] = []
    for _ in range(reps):
        count = 0
        start = time.perf_counter()
        while time.perf_counter() - start < window_s:
            fn()
            count += 1
        rates.append(multiplier * count / (time.perf_counter() - start))
    mean = statistics.fmean(rates)
    sd = statistics.pstdev(rates)
    print(f"{name}: {mean:,.1f} /s (+- {sd:,.1f})")
    return (name, mean, sd)


def main(json_path: Optional[str] = None) -> Dict[str, float]:
    import ray_tpu

    ray_tpu.init(ignore_reinit_error=True)
    results: List[Tuple[str, float, float]] = []

    # ------------------------------------------------------- object plane
    value = ray_tpu.put(0)

    results.append(timeit("get small (inline)", lambda: ray_tpu.get(value)))
    results.append(timeit("put small (inline)", lambda: ray_tpu.put(0)))

    arr = np.zeros(100 * 1024 * 1024, dtype=np.int64)  # 800 MB

    def put_large():
        ref = ray_tpu.put(arr)
        del ref

    gb = arr.nbytes / 1e9
    results.append(timeit("put gigabytes (plasma GB/s)", put_large,
                          multiplier=gb, reps=2))
    big = ray_tpu.put(arr)

    def get_large():
        v = ray_tpu.get(big)
        del v

    results.append(timeit("get 800MB zero-copy (gets/s)", get_large, reps=2))

    # -------------------------------------------------------------- tasks
    @ray_tpu.remote
    def small_value():
        return b"ok"

    results.append(timeit(
        "tasks sync (round-trips/s)",
        lambda: ray_tpu.get(small_value.remote())))

    def task_batch():
        ray_tpu.get([small_value.remote() for _ in range(200)])

    results.append(timeit("tasks async (tasks/s)", task_batch,
                          multiplier=200, reps=2, window_s=2.0))

    # ------------------------------------------------------------- actors
    @ray_tpu.remote
    class Responder:
        def ping(self):
            return b"ok"

    a = Responder.remote()
    ray_tpu.get(a.ping.remote())  # wait for creation

    results.append(timeit(
        "actor calls sync (round-trips/s)",
        lambda: ray_tpu.get(a.ping.remote())))

    def actor_batch():
        ray_tpu.get([a.ping.remote() for _ in range(200)])

    results.append(timeit("actor calls async (calls/s)", actor_batch,
                          multiplier=200, reps=2, window_s=2.0))

    c = Responder.options(max_concurrency=16).remote()
    ray_tpu.get(c.ping.remote())

    def actor_concurrent():
        ray_tpu.get([c.ping.remote() for _ in range(200)])

    results.append(timeit("actor calls concurrent (calls/s)",
                          actor_concurrent, multiplier=200, reps=2,
                          window_s=2.0))

    # --------------------------------------------------------------- wait
    refs = [small_value.remote() for _ in range(100)]
    ray_tpu.get(refs)

    results.append(timeit(
        "wait on 100 ready refs (waits/s)",
        lambda: ray_tpu.wait(refs, num_returns=100, timeout=10)))

    # ------------------------------------------------- compiled actor DAGs
    # 3-stage pipeline, compiled vs dynamic (ROADMAP item 3: amortized
    # dispatch for static topologies; docs/COMPILED_DAGS.md)
    @ray_tpu.remote
    class Stage:
        def step(self, x):
            return x

    from ray_tpu.dag import InputNode
    with InputNode() as inp:
        s1, s2, s3 = Stage.bind(), Stage.bind(), Stage.bind()
        pipe = s3.step.bind(s2.step.bind(s1.step.bind(inp)))

    ray_tpu.get(pipe.execute(0))  # create the actors before timing

    results.append(timeit(
        "3-stage pipeline dynamic (execs/s)",
        lambda: ray_tpu.get(pipe.execute(0))))

    cpipe = pipe.compile()
    if cpipe._compiled:
        results.append(timeit(
            "3-stage pipeline compiled (execs/s)",
            lambda: cpipe.execute(0)))

        def pipelined_batch():
            futs = [cpipe.execute_async(0) for _ in range(100)]
            for f in futs:
                f.result(30)

        results.append(timeit(
            "3-stage pipeline compiled pipelined (execs/s)",
            pipelined_batch, multiplier=100, reps=2, window_s=2.0))
    cpipe.teardown()

    ray_tpu.shutdown()

    summary = {name: mean for name, mean, _ in results}
    if json_path:
        with open(json_path, "w") as f:
            json.dump(summary, f, indent=2)
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    out = None
    if "--json" in sys.argv:
        out = sys.argv[sys.argv.index("--json") + 1]
    main(out)
