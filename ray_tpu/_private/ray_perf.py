"""Control-plane microbenchmarks: tasks/s, actor calls/s, put/get throughput.

Role-equivalent to the reference's microbenchmark suite
(reference: python/ray/_private/ray_perf.py:93 and
ray_microbenchmark_helpers.py timeit) — the numbers that justify (or refute)
running the L1 runtime as Python asyncio processes instead of C++ on a TPU
host. A TPU host runs O(1-8) model workers whose step time is 10-100 ms;
the control plane only has to stay far off the critical path at that scale.

Run: ``python -m ray_tpu._private.ray_perf [--json out.json]``

``--breakdown`` reports per-roundtrip PHASE attribution for the unary
sync path instead of one throughput number: each round trip is split
into wire / dispatch / execute / reply using the task-event phase
timestamps the state engine records (docs/OBSERVABILITY.md,
docs/TRACING.md) plus client-side clocks.  This is how a change to the
RPC plane (e.g. the native frame pump, RTPU_NATIVE_RPC) is graded as a
histogram, not a single mean.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


def timeit(name: str, fn: Callable[[], None], multiplier: float = 1,
           reps: int = 3, window_s: float = 1.0,
           ) -> Tuple[str, float, float]:
    """Measure fn() calls/s over `reps` windows; returns (name, mean, sd)."""
    # warmup: run for ~0.3 s
    start = time.perf_counter()
    while time.perf_counter() - start < 0.3:
        fn()
    rates: List[float] = []
    for _ in range(reps):
        count = 0
        start = time.perf_counter()
        while time.perf_counter() - start < window_s:
            fn()
            count += 1
        rates.append(multiplier * count / (time.perf_counter() - start))
    mean = statistics.fmean(rates)
    sd = statistics.pstdev(rates)
    print(f"{name}: {mean:,.1f} /s (+- {sd:,.1f})")
    return (name, mean, sd)


def main(json_path: Optional[str] = None) -> Dict[str, float]:
    import ray_tpu

    ray_tpu.init(ignore_reinit_error=True)
    results: List[Tuple[str, float, float]] = []

    # ------------------------------------------------------- object plane
    value = ray_tpu.put(0)

    results.append(timeit("get small (inline)", lambda: ray_tpu.get(value)))
    results.append(timeit("put small (inline)", lambda: ray_tpu.put(0)))

    arr = np.zeros(100 * 1024 * 1024, dtype=np.int64)  # 800 MB

    def put_large():
        ref = ray_tpu.put(arr)
        del ref

    gb = arr.nbytes / 1e9
    results.append(timeit("put gigabytes (plasma GB/s)", put_large,
                          multiplier=gb, reps=2))
    big = ray_tpu.put(arr)

    def get_large():
        v = ray_tpu.get(big)
        del v

    results.append(timeit("get 800MB zero-copy (gets/s)", get_large, reps=2))

    # -------------------------------------------------------------- tasks
    @ray_tpu.remote
    def small_value():
        return b"ok"

    results.append(timeit(
        "tasks sync (round-trips/s)",
        lambda: ray_tpu.get(small_value.remote())))

    def task_batch():
        ray_tpu.get([small_value.remote() for _ in range(200)])

    results.append(timeit("tasks async (tasks/s)", task_batch,
                          multiplier=200, reps=2, window_s=2.0))

    # ------------------------------------------------------------- actors
    @ray_tpu.remote
    class Responder:
        def ping(self):
            return b"ok"

    a = Responder.remote()
    ray_tpu.get(a.ping.remote())  # wait for creation

    results.append(timeit(
        "actor calls sync (round-trips/s)",
        lambda: ray_tpu.get(a.ping.remote())))

    def actor_batch():
        ray_tpu.get([a.ping.remote() for _ in range(200)])

    results.append(timeit("actor calls async (calls/s)", actor_batch,
                          multiplier=200, reps=2, window_s=2.0))

    c = Responder.options(max_concurrency=16).remote()
    ray_tpu.get(c.ping.remote())

    def actor_concurrent():
        ray_tpu.get([c.ping.remote() for _ in range(200)])

    results.append(timeit("actor calls concurrent (calls/s)",
                          actor_concurrent, multiplier=200, reps=2,
                          window_s=2.0))

    # --------------------------------------------------------------- wait
    refs = [small_value.remote() for _ in range(100)]
    ray_tpu.get(refs)

    results.append(timeit(
        "wait on 100 ready refs (waits/s)",
        lambda: ray_tpu.wait(refs, num_returns=100, timeout=10)))

    # ------------------------------------------------- compiled actor DAGs
    # 3-stage pipeline, compiled vs dynamic (ROADMAP item 3: amortized
    # dispatch for static topologies; docs/COMPILED_DAGS.md)
    @ray_tpu.remote
    class Stage:
        def step(self, x):
            return x

    from ray_tpu.dag import InputNode
    with InputNode() as inp:
        s1, s2, s3 = Stage.bind(), Stage.bind(), Stage.bind()
        pipe = s3.step.bind(s2.step.bind(s1.step.bind(inp)))

    ray_tpu.get(pipe.execute(0))  # create the actors before timing

    results.append(timeit(
        "3-stage pipeline dynamic (execs/s)",
        lambda: ray_tpu.get(pipe.execute(0))))

    cpipe = pipe.compile()
    if cpipe._compiled:
        results.append(timeit(
            "3-stage pipeline compiled (execs/s)",
            lambda: cpipe.execute(0)))

        def pipelined_batch():
            futs = [cpipe.execute_async(0) for _ in range(100)]
            for f in futs:
                f.result(30)

        results.append(timeit(
            "3-stage pipeline compiled pipelined (execs/s)",
            pipelined_batch, multiplier=100, reps=2, window_s=2.0))
    cpipe.teardown()

    ray_tpu.shutdown()

    summary = {name: mean for name, mean, _ in results}
    if json_path:
        with open(json_path, "w") as f:
            json.dump(summary, f, indent=2)
    print(json.dumps(summary))
    return summary


def breakdown(n: int = 400, json_path: Optional[str] = None
              ) -> Dict[str, Dict[str, float]]:
    """Per-roundtrip phase attribution for unary sync tasks.

    Phases (wall-clock, one host — client clocks and the task table's
    per-state stamps share a clock):

    - ``wire``:     client submit entry -> PENDING_SCHEDULING stamp
                    (owner-side submit bookkeeping: serialize + spec)
    - ``dispatch``: PENDING_SCHEDULING -> RUNNING (frame encode, the
                    send/recv path, worker wakeup + decode)
    - ``execute``:  RUNNING -> FINISHED (user fn + return shipping)
    - ``reply``:    FINISHED -> client get() return (reply frame,
                    owner-side result store, getter wakeup +
                    deserialize)
    """
    import ray_tpu
    from ray_tpu.experimental.state import api as state_api

    ray_tpu.init(ignore_reinit_error=True)

    @ray_tpu.remote
    def small_value():
        return b"ok"

    for _ in range(100):  # warm the lease/lane + function cache
        ray_tpu.get(small_value.remote())

    samples = []
    for _ in range(n):
        t0 = time.time()
        ref = small_value.remote()
        ray_tpu.get(ref)
        t1 = time.time()
        samples.append((ref.id().task_id().hex(), t0, t1))
    time.sleep(1.5)  # let the task-event flush tick ship the stamps

    recs = {r["task_id"]: r for r in state_api.list_tasks(limit=10 * n)}
    phases: Dict[str, List[float]] = {
        "wire": [], "dispatch": [], "execute": [], "reply": [],
        "total": []}
    missing = 0
    for tid, t0, t1 in samples:
        st = (recs.get(tid) or {}).get("state_ts") or {}
        if not all(k in st for k in ("PENDING_SCHEDULING", "RUNNING",
                                     "FINISHED")):
            missing += 1
            continue
        phases["wire"].append(st["PENDING_SCHEDULING"] - t0)
        phases["dispatch"].append(st["RUNNING"] - st["PENDING_SCHEDULING"])
        phases["execute"].append(st["FINISHED"] - st["RUNNING"])
        phases["reply"].append(t1 - st["FINISHED"])
        phases["total"].append(t1 - t0)
    ray_tpu.shutdown()

    def _q(v: List[float], q: float) -> float:
        s = sorted(v)
        return s[min(len(s) - 1, int(q * len(s)))]

    out: Dict[str, Dict[str, float]] = {}
    native = os.environ.get("RTPU_NATIVE_RPC", "1") not in ("0", "false")
    print(f"unary sync phase breakdown ({len(phases['total'])} samples, "
          f"{missing} missing stamps, native_rpc={'on' if native else 'off'})")
    print(f"{'phase':10s} {'p50':>9s} {'p90':>9s} {'p99':>9s} {'mean':>9s}")
    for k, v in phases.items():
        if not v:
            continue
        row = {"p50_us": _q(v, 0.5) * 1e6, "p90_us": _q(v, 0.9) * 1e6,
               "p99_us": _q(v, 0.99) * 1e6,
               "mean_us": statistics.fmean(v) * 1e6}
        out[k] = {kk: round(vv, 1) for kk, vv in row.items()}
        print(f"{k:10s} {row['p50_us']:8.0f}u {row['p90_us']:8.0f}u "
              f"{row['p99_us']:8.0f}u {row['mean_us']:8.0f}u")
    if json_path:
        with open(json_path, "w") as f:
            json.dump(out, f, indent=2)
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    out = None
    if "--json" in sys.argv:
        out = sys.argv[sys.argv.index("--json") + 1]
    if "--breakdown" in sys.argv:
        n = 400
        if "--n" in sys.argv:
            n = int(sys.argv[sys.argv.index("--n") + 1])
        breakdown(n, out)
    else:
        main(out)
