"""Raylet — the per-node manager.

Role-equivalent to the reference's raylet (reference: src/ray/raylet/
node_manager.cc, worker_pool.cc, scheduling/cluster_task_manager.h,
local_task_manager.cc) redesigned for this runtime:

  - owns the node's plasmax shared-memory segment (the reference runs the
    plasma store inside the raylet process too: object_manager.cc:32)
  - worker pool: prestarted + on-demand Python worker processes, keyed by
    runtime-env hash and TPU chip assignment (reference: worker_pool.cc
    PopWorker/PushWorker)
  - task dispatch: owners submit task specs; the raylet queues them, claims
    resources, assigns an idle/new worker, and pushes the task. This collapses
    the reference's two-hop lease protocol (RequestWorkerLease + owner-side
    PushTask, direct_task_transport.cc) into one hop through the raylet's
    event loop — on a TPU host the task rate is dominated by ML steps, not
    microtask dispatch, so the simpler protocol wins on clarity; leases
    reappear in the owner-side submitter as worker stickiness for repeated
    scheduling keys.
  - TPU chips are first-class resources with per-unit instance IDs: a task
    demanding num_tpus=k is granted k concrete chip IDs, exported to the
    worker as TPU_VISIBLE_CHIPS (the analogue of the reference's GPU unit
    instances + CUDA_VISIBLE_DEVICES, scheduling_ids.h:34 / worker.py:821)
  - placement-group bundles: prepare/commit/cancel/return 2-phase protocol
    driven by the GCS (reference: node_manager.proto:377-384)
  - object manager: serves chunked pulls of local objects to other raylets
    and fetches remote objects into the local store (reference:
    object_manager/{push,pull}_manager.cc), with locations from the GCS
    object directory.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import shutil
import signal
import random
import subprocess
import sys
import tempfile
import time
import zlib
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private import chaos, netx, protocol
from ray_tpu._private import task_events as tev
from ray_tpu._private.object_store import PlasmaxStore
from ray_tpu._private.sched import PendingTask, bundle_key_of, make_ledger
from ray_tpu.exceptions import ObjectStoreFullError
from ray_tpu.common.config import SystemConfig
from ray_tpu.common.ids import ObjectID

logger = logging.getLogger(__name__)

CHUNK = 4 * 1024 * 1024


def _write_file(path: str, data: bytes):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def _read_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def _chips_from_accel_type(accel: str) -> Optional[int]:
    """Per-host chip count from an accelerator type like
    'v5litepod-16' / 'v4-32': total chips divided by slice host count
    (the suffix counts TensorCores, 2/chip, on v2/v3/v4/v5p — parsing
    shared with the autoscaler via common/tpu.py)."""
    from ray_tpu.common.tpu import max_chips_per_host, slice_chips
    gen = accel.partition("-")[0]
    total = slice_chips(accel)
    if total is None or total <= 0:
        return None
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    hosts = max(1, len([h for h in hostnames.split(",") if h]))
    per_host = max(1, total // hosts)
    # physical per-host ceiling guards the common misconfig of a
    # multi-host slice without TPU_WORKER_HOSTNAMES set: no host
    # has more than 8 chips (v5e) / 4 chips (other gens)
    return min(per_host, max_chips_per_host(gen))


_MDS_CACHE: List[Optional[int]] = []


def _chips_from_metadata_server(timeout: float = 0.5) -> Optional[int]:
    """GCE TPU-VM metadata query (reference analogue: the
    resource_spec.py accelerator autodetection). Gated by
    TPU_SKIP_MDS_QUERY for zero-egress/tunneled environments; any
    failure is treated as 'not on a TPU VM' and cached process-wide so
    repeated raylet starts don't re-pay DNS timeouts."""
    if os.environ.get("TPU_SKIP_MDS_QUERY"):
        return None
    if _MDS_CACHE:
        return _MDS_CACHE[0]
    _MDS_CACHE.append(None)
    try:
        import urllib.request
        req = urllib.request.Request(
            "http://metadata.google.internal/computeMetadata/v1/"
            "instance/attributes/accelerator-type",
            headers={"Metadata-Flavor": "Google"})
        accel = urllib.request.urlopen(
            req, timeout=timeout).read().decode().strip()
        _MDS_CACHE[0] = _chips_from_accel_type(accel) if accel else None
    except Exception:
        pass
    return _MDS_CACHE[0]


def detect_tpu_chips(config: SystemConfig) -> int:
    """Chips this raylet may schedule. Order: explicit config >
    RTPU_NUM_TPUS > granted-chip env (TPU_VISIBLE_CHIPS — what a parent
    raylet/test granted us, the TPU analogue of CUDA_VISIBLE_DEVICES) >
    physical device files > GCE metadata > accelerator-type env >
    JAX-platform hint."""
    if config.tpu_chips_per_host >= 0:
        return config.tpu_chips_per_host
    env = os.environ.get("RTPU_NUM_TPUS")
    if env is not None:
        return int(env)
    granted = os.environ.get("TPU_VISIBLE_CHIPS")
    if granted is None:  # "" is a valid grant: zero chips
        granted = os.environ.get("TPU_VISIBLE_DEVICES")
    if granted is not None:
        return len([c for c in granted.split(",") if c.strip() != ""])
    # physical device files on real TPU VMs
    n = len([d for d in os.listdir("/dev")
             if d.startswith("accel") or d.startswith("vfio")]
            ) if os.path.isdir("/dev") else 0
    if n:
        # cross-check against the declared topology when present: the
        # granted slice may be smaller than the host's device files
        accel = os.environ.get("TPU_ACCELERATOR_TYPE")
        declared = _chips_from_accel_type(accel) if accel else None
        return min(n, declared) if declared else n
    # the free env check comes BEFORE the (network) metadata query
    accel = os.environ.get("TPU_ACCELERATOR_TYPE")
    if accel:
        declared = _chips_from_accel_type(accel)
        if declared:
            return declared
    mds = _chips_from_metadata_server()
    if mds:
        return mds
    # tunneled single-chip environments (axon) expose the chip via the JAX
    # platform plugin only
    if os.environ.get("JAX_PLATFORMS", "") in ("axon", "tpu"):
        return 1
    return 0


def detect_tpu_topology() -> Dict[str, Any]:
    """TPU slice metadata from the metadata/env (reference analogue:
    _private/resource_spec.py GPU autodetection)."""
    out: Dict[str, Any] = {}
    accel_type = os.environ.get("TPU_ACCELERATOR_TYPE") or \
        os.environ.get("PALLAS_AXON_TPU_GEN")
    if accel_type:
        out["topology"] = accel_type
    out["worker_index"] = int(os.environ.get("TPU_WORKER_ID", 0))
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    out["num_slice_hosts"] = len(hostnames.split(",")) if hostnames else 1
    slice_name = os.environ.get("TPU_SLICE_NAME")
    if slice_name:
        out["slice"] = slice_name
    return out


class WorkerHandle:
    def __init__(self, worker_id: str, proc: subprocess.Popen,
                 runtime_env_hash: str, tpu_chips: Tuple[int, ...]):
        self.worker_id = worker_id
        self.proc = proc
        self.runtime_env_hash = runtime_env_hash
        self.tpu_chips = tpu_chips
        self.conn: Optional[protocol.Connection] = None
        self.address: str = ""
        self.direct_address: str = ""  # native direct-call lane (1.7)
        self.direct_tcp_address: str = ""  # off-box direct lane (1.8)
        self.busy_task: Optional[str] = None
        self.leased_by: Optional[str] = None
        self.is_actor = False
        self.actor_id: Optional[str] = None
        self.idle_since = time.monotonic()
        self.ready = asyncio.get_event_loop().create_future()
        self.num_tasks = 0
        self.job_id: Optional[str] = None  # last job served (for log routing)
        self.log_paths: Tuple[str, str] = ("", "")  # (stdout, stderr)


class Raylet:
    def __init__(self, config: SystemConfig, node_id: str, session_dir: str,
                 gcs_address: str, resources: Dict[str, float],
                 labels: Dict[str, str], is_head: bool,
                 object_store_memory: Optional[int] = None):
        self.config = config
        self.node_id = node_id
        self.session_dir = session_dir
        self.gcs_address = gcs_address
        self.is_head = is_head
        self.labels = labels
        num_cpus = resources.get("CPU")
        if num_cpus is None:
            num_cpus = float(os.cpu_count() or 1)
        num_tpus = resources.get("TPU")
        if num_tpus is None:
            num_tpus = float(detect_tpu_chips(config))
        self.total_resources = {**resources, "CPU": num_cpus, "TPU": num_tpus}
        self.total_resources.setdefault(
            "memory", float(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
                            * 0.7))
        self.total_resources.setdefault(
            "object_store_memory",
            float(object_store_memory or config.object_store_memory_bytes))
        if self.total_resources["TPU"] == 0:
            self.total_resources.pop("TPU")
        self.tpu_info = detect_tpu_topology()
        # The scheduling ledger owns ALL resource accounting and the
        # pending-task queues: the node pool, per-PG-bundle pools
        # (prepare/commit 2-phase, reference: node_manager.proto:377-384),
        # concrete TPU chip IDs (two committed bundles own disjoint chip
        # sets; reference: placement_group_resource_manager.cc), and the
        # per-scheduling-class dispatch queues.  Backed by the C++
        # schedcore (src/schedcore/schedcore.cc — the dispatch hot loop
        # in native code, reference: local_task_manager.cc:99) with a
        # pure-Python fallback.
        self.led = make_ledger(self.total_resources,
                               list(range(int(num_tpus))))

        store_path = os.path.join("/dev/shm" if os.path.isdir("/dev/shm")
                                  else session_dir,
                                  f"rtpu_plasmax_{node_id[:12]}")
        # disk-backed overflow segment (reference: plasma fallback
        # allocation under /tmp, create_request_queue.cc). Sparse file:
        # costs no disk until an allocation actually overflows.
        fb_dir = config.object_store_fallback_dir or session_dir
        self.store = PlasmaxStore(
            store_path,
            capacity=int(object_store_memory
                         or config.object_store_memory_bytes),
            create=True,
            fallback_path=os.path.join(
                fb_dir, f"rtpu_plasmax_{node_id[:12]}.fb"))
        self.store_path = store_path

        # pull admission: bounds the BYTES of concurrent inbound pulls
        # so a burst of fetches can't blow the store (reference:
        # pull_manager.cc admission under memory pressure). Lazily
        # created on the event loop.
        self._pull_inflight_bytes = 0
        self._pull_waiters: Optional[Any] = None
        # push manager state: (oid, target) pairs with a push in flight
        # (dedup, reference: push_manager.cc)
        self._pushes_inflight: set = set()
        # oid hex -> [open buffer, last-chunk monotonic time]: the time
        # lets an interrupted push (sender died mid-stream) be reaped —
        # an unsealed create would otherwise brick the object here
        self._inbound_pushes: Dict[str, list] = {}
        # oid hex -> future: one active pull per object; followers await
        self._inflight_fetches: Dict[str, Any] = {}
        # object spilling (reference: local_object_manager.h:110 SpillObjects
        # + _private/external_storage.py): pinned primary copies go to a
        # pluggable ExternalStorage backend (filesystem default; S3/URI
        # via smart_open; the ray_storage cluster root) when the store
        # crosses the spill threshold; restored on demand by URI.
        from ray_tpu._private.external_storage import storage_from_config
        self.spill_dir = os.path.join(session_dir, f"spill_{node_id[:12]}")
        self.spill_storage = storage_from_config(
            config.object_spilling_config, self.spill_dir, node_id,
            storage_root=os.environ.get("RTPU_STORAGE"))
        self.spilled: Dict[str, Tuple[str, int]] = {}  # oid hex -> (uri, size)
        self.pinned: Dict[str, Dict[str, Any]] = {}  # oid hex -> {owner}, FIFO
        # lifetime counters for the node-stats agent (reference:
        # metric_defs.cc ray_spill_manager_* / scheduler counters)
        self._spill_count = 0
        self._spilled_bytes_total = 0
        self._restore_count = 0
        self._restored_bytes_total = 0
        self._tasks_dispatched_total = 0
        self._tasks_spilled_back_total = 0
        self._prev_cpu_sample: Optional[Tuple[float, float]] = None
        # versioned sync stream state (reference: ray_syncer.h): the
        # epoch distinguishes this process generation; the version
        # orders its reports; known_view tracks the GCS cluster-view
        # deltas already folded into cluster_view
        self._sync_epoch = time.time()
        self._sync_version = 0
        self._known_view_version = 0
        self.cluster_view: Dict[str, Dict[str, Any]] = {}
        # Serializes spill/restore. Two concurrent _spill_one calls on the
        # same object each hold a read ref, so each sees the other's ref as
        # "a reader", refuses the delete, and re-pins — leaving the refcount
        # permanently elevated and the store permanently full.
        self._spill_lock: Optional[asyncio.Lock] = None

        self.workers: Dict[str, WorkerHandle] = {}
        self.idle_workers: Dict[str, List[WorkerHandle]] = {}  # keyed by env hash
        self._spilling_classes: set = set()
        self._peer_raylets: Dict[str, Any] = {}
        self._peer_raylet_pending: Dict[str, Any] = {}
        # coalesced task_dispatch_status notifies (conn-id -> (conn, [..]))
        self._dispatch_status_buf: Dict[int, Any] = {}
        self._dispatch_status_flush_scheduled = False
        # outbound pull streams being served: (oid, conn-id) -> last ts
        self._serving_pulls: Dict[Tuple[str, Any], float] = {}
        # netx transfer server (cross-node object plane) — started in
        # start() when the native pump is available
        self._netx_server = None
        # worker leases: owner-held workers for direct task pushes
        # (reference: normal_task_submitter.cc lease-based dispatch)
        self._leases: Dict[str, Any] = {}
        self._lease_counter = 0
        self._last_lease_revoke = 0.0
        self._lease_owner_conns: Dict[str, Any] = {}
        # leases revoked but not yet drain-acked by their owner
        # (release_lease carrying inflight=0); value = revoke time
        self._revoking_leases: Dict[str, float] = {}
        # preemption drain state (TPU spot semantics): draining refuses
        # new work, lets in-flight work finish inside the grace window,
        # then the process exits like the preempted host it models
        self._draining = False
        self._drain_deadline_unix = 0.0
        self.gcs: Optional[protocol.Connection] = None
        self.server = protocol.Server(self._handlers())
        self.address = ""
        self._dispatch_event = asyncio.Event()
        self._shutdown = False
        self._worker_counter = 0
        self._running_tasks: Dict[str, Tuple[WorkerHandle, PendingTask]] = {}
        self._oom_killed_workers: Set[str] = set()
        # compiled-DAG stages hosted per worker: wid -> {dag_id: owner}.
        # On worker death every owner gets a dag_peer_down notify so its
        # CompiledDAG tears down + falls back immediately instead of
        # waiting out an execute timeout (ray_tpu/dag/compiled_dag.py).
        self._dag_stages: Dict[str, Dict[str, str]] = {}
        # content-addressed, shared across sessions on this host (reference:
        # runtime_env URI cache with refcounting; here cache entries are
        # immutable-by-hash so no refcounts are needed)
        self._runtime_env_cache_dir = os.path.join(
            tempfile.gettempdir(), "ray_tpu", "runtime_env_cache")

    # ----------------------------------------------------------------- wiring

    def _handlers(self):
        return {
            "submit_task": self.handle_submit_task,
            "submit_task_batch": self.handle_submit_task_batch,
            "task_done": self.handle_task_done,
            "worker_register": self.handle_worker_register,
            "create_actor_worker": self.handle_create_actor_worker,
            "kill_actor_worker": self.handle_kill_actor_worker,
            "prepare_bundle": self.handle_prepare_bundle,
            "commit_bundle": self.handle_commit_bundle,
            "cancel_bundle": self.handle_cancel_bundle,
            "return_bundle": self.handle_return_bundle,
            "pull_object": self.handle_pull_object,
            "receive_push": self.handle_receive_push,
            "fetch_object": self.handle_fetch_object,
            "free_objects": self.handle_free_objects,
            "pin_object": self.handle_pin_object,
            "request_spill": self.handle_request_spill,
            "contains_object": self.handle_contains_object,
            "list_objects": self.handle_list_objects,
            "get_info": self.handle_get_info,
            "node_stats": self.handle_node_stats,
            "dump_worker_stacks": self.handle_dump_worker_stacks,
            "profile_workers": self.handle_profile_workers,
            "cancel_task": self.handle_cancel_task,
            "lease_worker": self.handle_lease_worker,
            "release_lease": self.handle_release_lease,
            "task_stats": self.handle_task_stats,
            "preempt": self.handle_preempt,
            "dag_register": self.handle_dag_register,
            "dag_unregister": self.handle_dag_unregister,
            "_on_disconnect": self._on_disconnect,
        }

    async def start(self):
        # listen on unix socket (intra-node) and TCP (inter-node pulls)
        sock_path = os.path.join(self.session_dir,
                                 f"raylet_{self.node_id[:12]}.sock")
        await self.server.start_unix(sock_path)
        tcp_server = protocol.Server(self._handlers())
        # bind + advertise the node's real address (RTPU_NODE_IP, else
        # the resolved hostname) so off-box peers can actually dial us;
        # loopback remains the fallback when the IP won't bind (e.g. a
        # laptop whose hostname resolves to a stale DHCP lease)
        host = netx.node_ip()
        try:
            tcp_port = await tcp_server.start_tcp(host, 0)
        except OSError:
            host = "127.0.0.1"
            tcp_port = await tcp_server.start_tcp(host, 0)
        self._tcp_server = tcp_server
        self.address = f"{host}:{tcp_port}"
        self.unix_address = f"unix:{sock_path}"
        if netx.enabled():
            try:
                self._netx_server = netx.NetxServer(
                    self, host, asyncio.get_running_loop())
            except Exception:
                logger.warning("netx transfer server unavailable; "
                               "object pulls stay on asyncio",
                               exc_info=True)

        self.gcs = protocol.ReconnectingConnection(
            self.gcs_address, handler=self._gcs_request,
            on_reconnect=self._on_gcs_reconnect)
        reply = await self.gcs.call("register_node", self._register_payload())
        self.config = SystemConfig.from_json(reply["config"])
        loop = asyncio.get_running_loop()
        # task-event shipping runs on this loop (the raylet has no
        # global worker for the default thread flusher to use)
        tev.set_external_flusher()
        protocol.spawn(self._task_events_loop())
        protocol.spawn(self._dispatch_loop())
        protocol.spawn(self._report_loop())
        protocol.spawn(self._loop_tick_task())
        self._start_liveness_thread()
        protocol.spawn(self._idle_reaper_loop())
        protocol.spawn(self._log_monitor_loop())
        if self.config.memory_monitor_enabled:
            protocol.spawn(self._memory_monitor_loop())
        if self.config.prestart_workers:
            n = int(self.total_resources.get("CPU", 1))
            for _ in range(max(1, min(n, 4))):
                protocol.spawn(self._start_worker("", ()))
        logger.info("raylet %s up at %s (resources=%s)",
                    self.node_id[:8], self.address, self.total_resources)

    def _register_payload(self) -> Dict[str, Any]:
        return {
            "node_id": self.node_id,
            "raylet_address": self.address,
            # the netx transfer endpoint ('' when the native plane is
            # off) — peers chunk-pipeline object pulls through it
            # instead of the asyncio pull_object path
            "netx_address": self._netx_server.address
            if self._netx_server is not None else "",
            "object_store_path": self.store_path,
            "resources": self.total_resources,
            "labels": self.labels,
            "tpu": self.tpu_info,
            "hostname": os.uname().nodename,
            "is_head": self.is_head,
            # primary copies held here — lets a restarted GCS rebuild its
            # object directory (which is not persisted; locations are
            # node-volatile state, reference: gcs re-subscribes raylets)
            "objects": [h for h in self.pinned] + list(self.spilled),
            "sync_epoch": self._sync_epoch,
            "sync_version": self._sync_version,
        }

    async def _on_gcs_reconnect(self, conn):
        """GCS restarted: re-register this node + its object locations."""
        try:
            # the restarted GCS's view counter restarts too — a stale
            # known_view would make us ignore its deltas forever
            self._known_view_version = 0
            await conn.call("register_node", self._register_payload())
            logger.info("re-registered with restarted GCS")
        except Exception as e:
            logger.warning("GCS re-registration failed: %s", e)

    async def _gcs_request(self, method, payload, conn):
        # GCS calls back into us using the same connection
        fn = self._handlers().get(method)
        if fn is None:
            raise protocol.RpcError(f"raylet: no method {method}")
        return await fn(payload, conn)

    async def _on_disconnect(self, conn):
        # snapshot: _release_lease prunes conn.meta["leases"] in place —
        # iterating the live list skips every other lease, permanently
        # leaking the skipped ones' ledger capacity
        for lease_id in list(conn.meta.get("leases", ())):
            self._release_lease(lease_id)  # owner died holding leases
        # free this reader's outbound-pull serve slots: a leaked slot
        # makes an idle source answer "busy" until the stale sweep
        cid = id(conn)
        for k in list(self._serving_pulls):
            if k[1] == cid:
                self._serving_pulls.pop(k, None)
        wid = conn.meta.get("worker_id")
        if wid:
            await self._handle_worker_death(wid, "connection lost")

    # ----------------------------------------------------------- worker pool

    def _spawn_worker_proc(self, runtime_env: Dict[str, Any],
                           tpu_chips: Tuple[int, ...],
                           menv=None) -> WorkerHandle:
        self._worker_counter += 1
        worker_id = f"{self.node_id[:8]}-w{self._worker_counter}"
        env = dict(os.environ)
        env["RTPU_NODE_ID"] = self.node_id
        env["RTPU_RAYLET_ADDRESS"] = self.unix_address
        env["RTPU_GCS_ADDRESS"] = self.gcs_address
        env["RTPU_STORE_PATH"] = self.store_path
        env["RTPU_WORKER_ID"] = worker_id
        env["RTPU_SESSION_DIR"] = self.session_dir
        if tpu_chips:
            env[self.config.tpu_visible_chips_env] = ",".join(
                str(c) for c in tpu_chips)
            # persistent XLA compilation cache shared across workers and
            # sessions (SURVEY.md §7 compilation management): first compile
            # of a program pays once per host, not once per worker process
            cache = self.config.compilation_cache_dir or os.path.join(
                tempfile.gettempdir(), "ray_tpu", "xla_cache")
            env.setdefault("JAX_COMPILATION_CACHE_DIR", cache)
            # a driver pinned to CPU (typical: it must not grab libtpu away
            # from its own workers) passes JAX_PLATFORMS=cpu down the
            # environment — TPU workers must shed it or they'd never see
            # their chips
            if env.get("JAX_PLATFORMS") == "cpu":
                env.pop("JAX_PLATFORMS")
        else:
            # CPU-only workers must not initialize the TPU plugin: grabbing
            # libtpu would lock the chips away from TPU workers. Force the
            # override — the inherited env may pin a TPU platform (and the
            # axon tunnel's sitecustomize re-registers its plugin whenever
            # PALLAS_AXON_POOL_IPS is present, so clear that too).
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)
        for k, v in (runtime_env.get("env_vars") or {}).items():
            env[k] = v
        # materialized runtime env (pip venv / working_dir / py_modules):
        # reference analogue: runtime_env_agent.py handing the worker its
        # context (python exe + env + cwd)
        python_exe = sys.executable
        cwd = runtime_env.get("working_dir") or None
        pythonpath: List[str] = []
        if menv is not None:
            python_exe = menv.python_exe
            env.update(menv.env_vars)
            cwd = menv.cwd or cwd
            pythonpath.extend(menv.pythonpath)
        # ray_tpu itself must stay importable when cwd moves away from the
        # repo (python -m puts cwd first on sys.path)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        pythonpath.append(pkg_root)
        if env.get("PYTHONPATH"):
            pythonpath.append(env["PYTHONPATH"])
        env["PYTHONPATH"] = os.pathsep.join(pythonpath)
        if cwd is not None and not os.path.isdir(cwd):
            cwd = None
        log_base = os.path.join(self.session_dir, "logs")
        os.makedirs(log_base, exist_ok=True)
        out_path = os.path.join(log_base, f"worker-{worker_id}.out")
        err_path = os.path.join(log_base, f"worker-{worker_id}.err")
        out = open(out_path, "ab")
        err = open(err_path, "ab")
        cmd = [python_exe, "-m", "ray_tpu._private.default_worker"]
        if runtime_env.get("container"):
            # containerized worker (reference: runtime_env/container.py):
            # the runtime prefix mounts session dir + env cache and
            # forwards the bootstrap env by key (values come from
            # Popen(env=...) below)
            from ray_tpu._private import runtime_env as renv
            cmd = renv.container_command(
                runtime_env["container"], self.session_dir,
                self._runtime_env_cache_dir,
                env_keys=[k for k in env
                          if k.startswith(("RTPU_", "JAX_", "PYTHON",
                                           "TPU_"))]) + cmd
        proc = subprocess.Popen(
            cmd, env=env, cwd=cwd, stdout=out, stderr=err,
            start_new_session=True)
        handle = WorkerHandle(worker_id, proc,
                              runtime_env_hash=_env_hash(runtime_env),
                              tpu_chips=tpu_chips)
        handle.log_paths = (out_path, err_path)
        self.workers[worker_id] = handle
        return handle

    async def _start_worker(self, env_hash_or_env, tpu_chips) -> WorkerHandle:
        runtime_env = env_hash_or_env if isinstance(env_hash_or_env, dict) \
            else {}
        menv = None
        if runtime_env and (runtime_env.get("pip")
                            or runtime_env.get("conda")
                            or runtime_env.get("py_modules")
                            or str(runtime_env.get("working_dir", ""))
                            .startswith("gcs://")):
            from ray_tpu._private import runtime_env as renv

            # materialization does blocking work (venv create, pip install,
            # unzip) — run it in a thread; KV fetches hop back to the loop
            loop = asyncio.get_running_loop()

            def _kv_get_sync(key: str):
                fut = asyncio.run_coroutine_threadsafe(
                    self.gcs.call("kv_get", {"key": key}), loop)
                return (fut.result(timeout=60) or {}).get("value")

            menv = await loop.run_in_executor(
                None, lambda: renv.materialize(
                    runtime_env, self._runtime_env_cache_dir, _kv_get_sync))
        handle = self._spawn_worker_proc(runtime_env, tuple(tpu_chips),
                                         menv=menv)
        try:
            await asyncio.wait_for(handle.ready,
                                   self.config.worker_start_timeout_s)
        except asyncio.TimeoutError:
            handle.proc.kill()
            self.workers.pop(handle.worker_id, None)
            raise RuntimeError("worker failed to start in time")
        return handle

    async def handle_worker_register(self, payload, conn):
        wid = payload["worker_id"]
        handle = self.workers.get(wid)
        if handle is None:
            raise protocol.RpcError(f"unknown worker {wid}")
        handle.conn = conn
        handle.address = payload["address"]
        handle.direct_address = payload.get("direct_address") or ""
        handle.direct_tcp_address = payload.get(
            "direct_tcp_address") or ""
        conn.meta["worker_id"] = wid
        if not handle.ready.done():
            handle.ready.set_result(True)
        self._push_idle(handle)
        self._dispatch_event.set()
        return {"node_id": self.node_id,
                "config": self.config.to_json()}

    def _push_idle(self, handle: WorkerHandle):
        if handle.is_actor:
            return
        handle.busy_task = None
        handle.idle_since = time.monotonic()
        key = (handle.runtime_env_hash, handle.tpu_chips)
        self.idle_workers.setdefault(key, []).append(handle)

    def _pop_idle(self, env_hash: str,
                  tpu_chips: Tuple[int, ...]) -> Optional[WorkerHandle]:
        lst = self.idle_workers.get((env_hash, tpu_chips))
        while lst:
            handle = lst.pop()
            if handle.proc.poll() is None and handle.conn is not None:
                return handle
        return None

    def _event(self, severity: str, label: str, message: str, **fields):
        """Structured event: local JSONL + best-effort ship to the GCS
        ring (reference: RAY_EVENT)."""
        from ray_tpu.util import events as ev

        def _notify(method, payload):
            payload["source"] = "raylet"
            if self.gcs is not None:
                protocol.spawn(
                    self.gcs.notify(method, payload))

        ev.report(severity, label, message, gcs_notify=_notify, **fields)

    async def handle_dag_register(self, payload, conn):
        """A worker opened a compiled-DAG stage: remember (dag, owner) so
        its death can be pushed to the compiling driver."""
        wid = conn.meta.get("worker_id")
        if wid:
            self._dag_stages.setdefault(wid, {})[payload["dag_id"]] = \
                payload.get("owner_address") or ""
        return {}

    async def handle_dag_unregister(self, payload, conn):
        wid = conn.meta.get("worker_id")
        if wid and wid in self._dag_stages:
            self._dag_stages[wid].pop(payload.get("dag_id"), None)
            if not self._dag_stages[wid]:
                del self._dag_stages[wid]
        return {}

    async def _handle_worker_death(self, worker_id: str, reason: str):
        self._clean_leases_for_worker(worker_id)
        # compiled-DAG teardown: the owner falls back to dynamic dispatch
        # and re-compiles on its next call
        for dag_id, owner in (self._dag_stages.pop(worker_id, None)
                              or {}).items():
            if owner:
                protocol.spawn(self._notify_dag_owner(
                    owner, dag_id, worker_id))
        handle = self.workers.pop(worker_id, None)
        if handle is None:
            return
        oom = worker_id in self._oom_killed_workers
        if oom:
            self._oom_killed_workers.discard(worker_id)
            pct = self.config.memory_usage_threshold * 100
            reason = ("worker killed by the memory monitor: node memory "
                      f"usage exceeded {pct:.0f}% (OOM protection); {reason}")
        if oom or handle.busy_task:
            self._event(
                "WARNING" if oom else "ERROR",
                "OOM_KILL" if oom else "WORKER_DIED",
                f"worker {worker_id[:12]} died: {reason}",
                worker_id=worker_id, task=handle.busy_task or "")
        for lst in self.idle_workers.values():
            if handle in lst:
                lst.remove(handle)
        if handle.busy_task:
            entry = self._running_tasks.pop(handle.busy_task, None)
            if entry is not None:
                _, ptask = entry
                self._release_resources(ptask, handle.tpu_chips)
                handle.tpu_chips = ()
                # the dead worker can't report its own failure — this
                # raylet is the only process that saw it die
                tev.emit(ptask.spec.get("task_id"), tev.FAILED,
                         name=ptask.spec.get("fn_name"),
                         job_id=ptask.spec.get("job_id"),
                         node_id=self.node_id,
                         attempt=ptask.spec.get("attempt"),
                         error=f"WORKER_DIED: {reason}")
                msg = {"error": "WORKER_DIED",
                       "message": f"worker {worker_id} died: {reason}"}
                if ptask.reply_fut is not None and not ptask.reply_fut.done():
                    ptask.reply_fut.set_result(msg)
                else:
                    # dispatch already replied; the owner is waiting on a
                    # task_result that will never come — tell it directly
                    owner = ptask.spec.get("owner_address")
                    task_id = ptask.spec.get("task_id")
                    if owner and task_id:
                        protocol.spawn(
                            self._notify_owner_task_failed(
                                owner, task_id, msg))
        if handle.is_actor and handle.actor_id and self.gcs is not None:
            try:
                await self.gcs.call("actor_state_update", {
                    "actor_id": handle.actor_id, "state": "DEAD",
                    "restart": True, "reason": reason})
            except Exception:
                pass
        self._dispatch_event.set()

    async def _notify_dag_owner(self, owner: str, dag_id: str,
                                worker_id: str):
        try:
            conn = await protocol.connect(owner)
            try:
                await conn.notify("dag_peer_down",
                                  {"dag_id": dag_id,
                                   "worker_id": worker_id})
            finally:
                conn.close()
        except Exception:
            pass  # owner gone too — nothing to tear down

    async def _notify_owner_task_failed(self, owner: str, task_id: str,
                                        msg: Dict[str, Any]):
        try:
            conn = await protocol.connect(owner)
            try:
                await conn.notify("task_failed", {"task_id": task_id, **msg})
            finally:
                conn.close()
        except Exception:
            pass

    async def _idle_reaper_loop(self):
        while not self._shutdown:
            await asyncio.sleep(5.0)
            # reap dead procs
            for wid, h in list(self.workers.items()):
                if h.proc.poll() is not None:
                    await self._handle_worker_death(
                        wid, f"exit code {h.proc.returncode}")
            # kill long-idle surplus workers (reference:
            # idle_worker_killing_time_threshold_ms)
            soft = self.config.num_workers_soft_limit
            if soft < 0:
                soft = int(self.total_resources.get("CPU", 1)) + 2
            n_idle = sum(len(v) for v in self.idle_workers.values())
            if len(self.workers) > soft:
                cutoff = time.monotonic() - self.config.idle_worker_kill_s
                for lst in self.idle_workers.values():
                    for h in list(lst):
                        if len(self.workers) <= soft:
                            break
                        if h.idle_since < cutoff and not h.tpu_chips:
                            lst.remove(h)
                            self.workers.pop(h.worker_id, None)
                            h.proc.terminate()

    # ------------------------------------------------------------ scheduling

    def _release_resources(self, ptask: PendingTask,
                           chips: Tuple[int, ...] = ()):
        # freed capacity may unblock a pending task on every release path
        self._dispatch_event.set()
        self.report_soon()
        self.led.release(ptask, chips)

    def _infeasible(self, ptask: PendingTask) -> bool:
        """Can this node EVER satisfy the demand?"""
        if bundle_key_of(ptask.spec) is not None:
            return False  # bundle is (or will be) here; wait
        for k, v in ptask.demand.items():
            if self.total_resources.get(k, 0) < v:
                return True
        return False

    @staticmethod
    def _policy_routed(spec) -> bool:
        """Tasks with an explicit placement policy (SPREAD, node
        affinity, TPU topology) route through the GCS scheduler on
        arrival instead of soaking into the local queue — a feasible
        local node must not defeat SPREAD (reference: lease_policy.cc,
        the owner consults the scheduler before leasing)."""
        sched = spec.get("scheduling") or {}
        return bool(sched.get("spread") or sched.get("node_id")
                    or sched.get("tpu_topology"))

    async def handle_submit_task(self, payload, conn):
        fut = asyncio.get_running_loop().create_future()
        ptask = PendingTask(payload, fut)
        if self._draining:
            # a draining node accepts no new work: move it to a peer or
            # hand the owner a retryable error (its resubmit re-enters
            # here and spills once a peer has capacity)
            spill = await self._try_spillback(ptask, force=True)
            if spill is not None:
                return spill
            return {"error": "NODE_DRAINING",
                    "message": "node is draining (preemption notice)"}
        if not payload.get("spilled_from") and \
                (self._infeasible(ptask) or self._policy_routed(payload)):
            spill = await self._try_spillback(ptask, force=True)
            if spill is not None:
                return spill
        elif payload.get("spilled_from"):
            spill = await self._try_spillback(ptask,
                                              force=self._infeasible(ptask))
            if spill is not None:
                return spill
        self._note_queued(payload)
        self.led.append(ptask)
        self._dispatch_event.set()
        return await fut

    def _note_queued(self, spec):
        """Task accepted into this node's dispatch queue: the
        PENDING_NODE_ASSIGNMENT lifecycle transition (O(1) ring
        append; batched to the GCS off this path)."""
        tev.emit(spec.get("task_id"), tev.PENDING_NODE_ASSIGNMENT,
                 name=spec.get("fn_name"), job_id=spec.get("job_id"),
                 node_id=self.node_id, attempt=spec.get("attempt"))

    async def handle_submit_task_batch(self, payload, conn):
        """Batched submission (the >=10k tasks/s path; reference gets its
        throughput the same way — one RPC carrying many TaskSpecs). The
        reply is an immediate ack; dispatch-time failures flow back as
        `task_dispatch_status` notifies on the submitting connection so
        the owner's retry machinery sees the same error vocabulary as the
        unary path."""
        loop = asyncio.get_running_loop()
        accepted = 0
        for spec in payload["specs"]:
            fut = loop.create_future()
            ptask = PendingTask(spec, fut)

            def _on_done(f, task_id=spec["task_id"]):
                try:
                    reply = f.result()
                except Exception as e:  # noqa: BLE001 — crosses the wire
                    reply = {"error": "INTERNAL", "message": str(e)}
                # every dispatch outcome is reported — success carries
                # worker_address so the owner can tell "dispatched" from
                # "still queued" when this connection dies.  Failures go
                # out immediately; successes coalesce into one batched
                # notify per flush tick (they are bookkeeping, not the
                # result fast path — the worker sends results directly),
                # which halves the raylet's per-task sends.
                self._queue_dispatch_status(conn, {"task_id": task_id,
                                                   **reply})

            fut.add_done_callback(_on_done)
            if self._draining or self._infeasible(ptask) or \
                    spec.get("spilled_from") or self._policy_routed(spec):
                # rare path: resolve off-line so the batch ack stays fast
                async def _spill(pt=ptask):
                    force = self._draining or self._infeasible(pt) or (
                        self._policy_routed(pt.spec)
                        and not pt.spec.get("spilled_from"))
                    spill = await self._try_spillback(pt, force=force)
                    if spill is not None:
                        if not pt.reply_fut.done():
                            pt.reply_fut.set_result(spill)
                        return
                    if self._draining:
                        if not pt.reply_fut.done():
                            pt.reply_fut.set_result({
                                "error": "NODE_DRAINING",
                                "message": "node is draining "
                                           "(preemption notice)"})
                        return
                    self._note_queued(pt.spec)
                    self.led.append(pt)
                    self._dispatch_event.set()
                protocol.spawn(_spill())
            else:
                self._note_queued(spec)
                self.led.append(ptask)
            accepted += 1
        self._dispatch_event.set()
        return {"accepted": accepted}

    async def _try_spillback(self, ptask: PendingTask, force: bool):
        """Ask GCS for another node (reference: spillback in
        cluster_task_manager.cc). Returns a reply dict or None to keep local."""
        if ptask.spec.get("spilled_from") and not force:
            return None
        try:
            r = await self.gcs.call("schedule", {
                "demand": ptask.demand,
                "scheduling": ptask.spec.get("scheduling") or {},
                # locality: the GCS prefers nodes already holding the
                # task's plasma dependencies (reference: lease_policy.cc
                # best-node-by-dependency-bytes)
                "deps": list(ptask.spec.get("plasma_deps") or []),
            })
        except Exception:
            return None
        nid = r.get("node_id")
        if nid is None or nid == self.node_id:
            return None
        spec = dict(ptask.spec)
        spec["spilled_from"] = self.node_id
        # proactive dep push (push manager): overlap the transfer of
        # locally-held args with the peer's worker startup instead of
        # serializing behind its on-demand pull. Deliberately launched
        # BEFORE the submit (the peer's dispatch pulls missing deps
        # straight away); a failed submit then costs a redundant replica
        # on the peer, which eviction reclaims.
        loop = asyncio.get_running_loop()
        for d in spec.get("plasma_deps") or []:
            doid = ObjectID.from_hex(d)
            if self.store.contains(doid):
                protocol.spawn(self.push_object(
                    doid, r["raylet_address"], nid))
        try:
            remote = await self._raylet_peer(r["raylet_address"])
            reply = await remote.call("submit_task", spec)
        except Exception:
            return None
        self._tasks_spilled_back_total += 1
        return reply

    async def _raylet_peer(self, address: str) -> "protocol.Connection":
        """Cached connection to a peer raylet (spillback reuses it; a
        fresh dial per spilled task would dominate a backlog drain).
        Single-flight per address: concurrent spillback probes must not
        race N dials where all but the last-stored leak open."""
        return await protocol.single_flight_connect(
            self._peer_raylets, self._peer_raylet_pending, address,
            protocol.connect)

    async def _dispatch_loop(self):
        """The hot dispatch loop (reference:
        local_task_manager.cc:99 DispatchScheduledTasksToWorkers).

        Visits the HEAD of each scheduling class only: tasks in a class
        are interchangeable for feasibility, so a blocked head blocks the
        whole class and the rest need not be scanned. No awaits between
        the feasibility check and the resource take, so two pending tasks
        can never both be judged feasible against the same availability
        and then over-subscribe (spillback probes run as side tasks)."""
        while not self._shutdown:
            # bounded wait, not a pure event wait: a task queued here
            # while its only feasible node was down has NO local event
            # left to wake it when replacement capacity registers at the
            # GCS — the periodic tick re-probes stuck classes (the
            # spillback probe is cheap and rate-limited per class)
            try:
                await asyncio.wait_for(self._dispatch_event.wait(),
                                       timeout=1.0)
            except asyncio.TimeoutError:
                if self.led.pending_count() == 0:
                    continue
            self._dispatch_event.clear()
            now = time.monotonic()
            # one ledger poll atomically acquires resources for every
            # dispatchable class head (batched in C++ when native)
            dispatches, blocked, more = self.led.poll()
            for ptask, chips in dispatches:
                protocol.spawn(self._dispatch(ptask, chips))
            if blocked and self._leases and \
                    now - self._last_lease_revoke > 0.5 and \
                    any(pt.tpu_demand == 0
                        and pt.demand.get("CPU", 0) > 0
                        for pt in blocked):
                # leased capacity is starving queued CPU work: revoke
                # one lease (the owner drains in-flight pushes and falls
                # back to the normal path) — reference: lease revocation
                # under contention in local_task_manager.  Chip-bound
                # backlogs (TPU demands) don't revoke: CPU leases can't
                # unblock them and churning the pool helps nothing.
                self._last_lease_revoke = now
                lease_id = next(iter(self._leases))
                protocol.spawn(self._revoke_lease(lease_id))
            for ptask in blocked:
                # try spillback for plain tasks stuck too long
                cls = ptask.sched_class
                if now - ptask.submitted_at > 1.0 and \
                        cls not in self._spilling_classes and \
                        not ptask.spec.get("spilled_from") and \
                        not ptask.spec.get("placement_group"):
                    self._spilling_classes.add(cls)
                    protocol.spawn(self._spillback_class(cls))
            if more:
                self._dispatch_event.set()
                await asyncio.sleep(0)  # let dispatches make progress

    async def _spillback_class(self, cls):
        """Drain a stuck scheduling class to other nodes: keep asking the
        GCS for placements (its pessimistic in-flight accounting
        round-robins a burst across the cluster) and moving queued tasks
        out while the local node stays saturated. Each task is POPPED
        before its remote submit (no double-dispatch; the dispatch loop
        keeps running the class with the remaining tasks, so local
        capacity freeing up mid-drain is used immediately) and re-queued
        if the move fails. One drainer per class at a time."""
        try:
            while not self._shutdown:
                head = self.led.head(cls)
                if head is None:
                    return
                if self.led.feasible(head) or \
                        head.spec.get("spilled_from") or \
                        head.spec.get("placement_group"):
                    return
                self.led.pop_head(cls)
                try:
                    reply = await self._try_spillback(head, force=False)
                except Exception:
                    reply = None
                if reply is None:
                    # nowhere to go: requeue at the front, re-arm the
                    # stuck timer so the probe isn't hot
                    head.submitted_at = time.monotonic()
                    self.led.requeue_front(head)
                    return
                if head.reply_fut is not None and \
                        not head.reply_fut.done():
                    head.reply_fut.set_result(reply)
        finally:
            self._spilling_classes.discard(cls)
            self._dispatch_event.set()

    async def _dispatch(self, ptask: PendingTask, chips: Tuple[int, ...]):
        env_hash = _env_hash(ptask.spec.get("runtime_env") or {})
        handle = self._pop_idle(env_hash, chips)
        if handle is None:
            try:
                handle = await self._start_worker(
                    ptask.spec.get("runtime_env") or {}, chips)
            except Exception as e:
                self._release_resources(ptask, chips)
                if not ptask.reply_fut.done():
                    ptask.reply_fut.set_result(
                        {"error": "WORKER_START_FAILED", "message": str(e)})
                return
            # worker registered; it may have been grabbed as idle — reclaim
            for lst in self.idle_workers.values():
                if handle in lst:
                    lst.remove(handle)
        # pull missing dependencies from other nodes first
        deps = ptask.spec.get("plasma_deps") or []
        missing = [d for d in deps
                   if not self.store.contains(ObjectID.from_hex(d))]
        if missing:
            try:
                await asyncio.gather(*[
                    self._fetch_remote_object(ObjectID.from_hex(d))
                    for d in missing])
            except Exception as e:
                self._release_resources(ptask, chips)
                self._push_idle(handle)
                if not ptask.reply_fut.done():
                    ptask.reply_fut.set_result(
                        {"error": "OBJECT_FETCH_FAILED", "message": str(e)})
                return
        handle.busy_task = ptask.spec["task_id"]
        handle.job_id = ptask.spec.get("job_id") or handle.job_id
        handle.num_tasks += 1
        self._tasks_dispatched_total += 1
        # worker picked: closes the "schedule" phase of the synthesized
        # task trace (queue->schedule->dispatch->execute); the state
        # machine doesn't advance — this event only carries the stamp
        tev.emit(ptask.spec.get("task_id"), tev.PENDING_NODE_ASSIGNMENT,
                 node_id=self.node_id, attempt=ptask.spec.get("attempt"),
                 dispatch_ts=time.time())
        # chaos injection point: process faults keyed on dispatch count
        # (kill the dispatched-to worker, kill this raylet, or deliver a
        # preemption notice at the N-th task)
        chaos_act = None
        if chaos._ENGINE is not None:
            chaos_act = chaos.hit("raylet.dispatch",
                                  ptask.spec.get("fn_name"))
        self._running_tasks[ptask.spec["task_id"]] = (handle, ptask)
        try:
            push = {"spec": ptask.spec, "tpu_chips": list(chips)}
            await handle.conn.notify("push_task", push)
        except Exception as e:
            self._running_tasks.pop(ptask.spec["task_id"], None)
            self._release_resources(ptask, chips)
            if not ptask.reply_fut.done():
                ptask.reply_fut.set_result(
                    {"error": "WORKER_DIED", "message": str(e)})
            return
        # reply to the owner with the executing worker's address so the owner
        # can stream results / cancel directly
        if not ptask.reply_fut.done():
            ptask.reply_fut.set_result({
                "worker_id": handle.worker_id,
                "worker_address": handle.address,
            })
        if chaos_act is not None:
            self._apply_dispatch_chaos(chaos_act, handle)

    def _apply_dispatch_chaos(self, act: Dict[str, Any],
                              handle: WorkerHandle):
        op = act.get("op")
        if op == "kill_worker":
            # kill AFTER the push: the task is in flight, exercising the
            # full death path (_handle_worker_death → owner notify →
            # retry), not just a failed dispatch
            try:
                handle.proc.kill()
            except Exception:
                pass
        elif op == "preempt":
            grace = float(act.get("grace_s",
                                  self.config.preemption_grace_s))
            protocol.spawn(self._preempt_drain(grace, "chaos preemption"))

    # ------------------------------------------------------- worker leases

    async def handle_lease_worker(self, payload, conn):
        """Grant the caller a pinned worker for DIRECT owner->worker task
        pushes — the reference's lease-based dispatch
        (reference: src/ray/core_worker/transport/normal_task_submitter.cc):
        the lease holds the demand's resources in the ledger until
        released, and the raylet stays out of the per-task loop
        entirely (2 messages/task instead of 6)."""
        demand = dict(payload.get("resources") or {"CPU": 1.0})
        if self._draining:
            # drain semantics: a draining node grants no new leases —
            # the owner falls back to the normal path and the GCS
            # scheduler (which sees the draining flag) places elsewhere
            return {"error": "LEASE_UNAVAILABLE",
                    "message": "node is draining (preemption notice)"}
        if int(demand.get("TPU", 0) or 0):
            return {"error": "LEASE_UNSUPPORTED",
                    "message": "TPU tasks are not leasable (chips are "
                               "granted per task)"}
        self._lease_counter += 1
        lease_tag = f"lease-{self.node_id[:8]}-{self._lease_counter}"
        fut = asyncio.get_running_loop().create_future()
        ptask = PendingTask({"task_id": lease_tag, "resources": demand},
                            fut)
        chips = self.led.acquire(ptask)
        if chips is None:
            return {"error": "LEASE_UNAVAILABLE",
                    "message": "no free capacity for the lease demand"}
        handle = self._pop_idle(_env_hash({}), ())
        if handle is None:
            try:
                handle = await self._start_worker({}, ())
            except Exception as e:
                self._release_resources(ptask, chips)
                return {"error": "WORKER_START_FAILED", "message": str(e)}
            for lst in self.idle_workers.values():
                if handle in lst:
                    lst.remove(handle)
        if conn._closed:
            # the owner disconnected while we awaited the worker start:
            # its _on_disconnect cleanup already ran (and saw no lease)
            self._release_resources(ptask, chips)
            self._push_idle(handle)
            return {"error": "OWNER_DISCONNECTED",
                    "message": "lease owner went away during grant"}
        handle.leased_by = lease_tag
        handle.busy_task = lease_tag  # reaper: busy != reapable
        self._leases[lease_tag] = (handle, ptask, chips)
        self._lease_owner_conns[lease_tag] = conn
        conn.meta.setdefault("leases", []).append(lease_tag)
        return {"lease_id": lease_tag, "worker_id": handle.worker_id,
                "worker_address": handle.address,
                # 1.7 (optional — pre-1.7 owners ignore it): lets the
                # owner push leased tasks down the worker's native
                # direct-execution lane instead of the asyncio server
                "direct_address": handle.direct_address,
                # 1.8: the lane's host:port twin for off-box owners
                "direct_tcp_address": handle.direct_tcp_address}

    async def handle_release_lease(self, payload, conn):
        self._release_lease(payload.get("lease_id", ""))
        return {}

    async def handle_task_stats(self, payload, conn):
        """Leased workers report executed-task deltas so the node's
        dispatch gauges stay truthful for work the raylet never saw."""
        self._tasks_dispatched_total += int(payload.get("executed", 0))
        return {}

    async def _revoke_lease(self, lease_id: str):
        """Ask the owner to stop using the lease, then reclaim it once
        the owner acks the drain (a ``release_lease`` carrying
        ``inflight=0``).  Releasing immediately re-idled a worker that
        may still be executing the owner's in-flight leased tasks — the
        next dispatch would queue behind work of unknown length on a
        worker the ledger already counted as free.  A timer is the
        backstop for a wedged owner; a dead owner's ``_on_disconnect``
        releases directly."""
        conn = self._lease_owner_conns.get(lease_id)
        if conn is not None and not conn._closed:
            try:
                await conn.notify("revoke_lease", {"lease_id": lease_id})
            except Exception:
                self._release_lease(lease_id)
                return
            if lease_id in self._leases and \
                    lease_id not in self._revoking_leases:
                self._revoking_leases[lease_id] = time.monotonic()
                asyncio.get_running_loop().call_later(
                    self.config.lease_revoke_ack_timeout_s,
                    self._force_release_revoked, lease_id)
            return
        self._release_lease(lease_id)

    def _force_release_revoked(self, lease_id: str):
        """Revoke-ack timeout backstop: reclaim the lease anyway."""
        if self._revoking_leases.pop(lease_id, None) is not None and \
                lease_id in self._leases:
            logger.warning("lease %s revoke not acked in time; "
                           "force-releasing", lease_id)
            self._release_lease(lease_id)

    def _release_lease(self, lease_id: str):
        entry = self._leases.pop(lease_id, None)
        self._revoking_leases.pop(lease_id, None)
        owner = self._lease_owner_conns.pop(lease_id, None)
        if owner is not None:
            # prune the per-connection list — it must not grow
            # unboundedly across a long-lived driver's lease cycles
            try:
                owner.meta.get("leases", []).remove(lease_id)
            except ValueError:
                pass
        if entry is None:
            return
        handle, ptask, chips = entry
        self._release_resources(ptask, chips)
        handle.leased_by = None
        handle.busy_task = None
        if handle.worker_id in self.workers and handle.proc.poll() is None:
            self._push_idle(handle)

    def _clean_leases_for_worker(self, worker_id: str):
        """The leased worker died: refund the lease resources (the
        handle itself is already being torn down)."""
        for lid, (h, pt, ch) in list(self._leases.items()):
            if h.worker_id == worker_id:
                self._leases.pop(lid, None)
                self._lease_owner_conns.pop(lid, None)
                self._release_resources(pt, ch)

    # ------------------------------------------------------ preemption drain

    async def handle_preempt(self, payload, conn):
        """Preemption notice (TPU spot semantics): the host will be
        reclaimed after a grace window. Delivered by the cloud control
        plane (SIGUSR2 → raylet_main), the chaos engine, or the GCS
        ``preempt_node`` RPC. Idempotent — the first notice starts the
        drain; later ones report the deadline already set."""
        payload = payload or {}
        grace = float(payload.get("grace_s")
                      or self.config.preemption_grace_s)
        if not self._draining:
            protocol.spawn(self._preempt_drain(
                grace, payload.get("reason") or "preemption notice"))
        return {"draining": True,
                "deadline_unix": self._drain_deadline_unix
                or time.time() + grace}

    def preempt_from_signal(self):
        """Thread/signal-safe entry (raylet_main wires SIGUSR2 here)."""
        if not self._draining:
            protocol.spawn(self._preempt_drain(
                self.config.preemption_grace_s, "SIGUSR2 preemption signal"))

    async def _preempt_drain(self, grace_s: float, reason: str):
        """Graceful drain: stop taking work, move queued tasks to peers,
        let in-flight tasks finish inside the grace window, give
        trainers the chance to commit an out-of-band checkpoint, then
        die like the preempted host this models."""
        if self._draining:
            return
        self._draining = True
        deadline = time.monotonic() + grace_s
        self._drain_deadline_unix = time.time() + grace_s
        t0 = time.monotonic()
        self._event("WARNING", "PREEMPTION_NOTICE",
                    f"node {self.node_id[:8]} preempted ({reason}): "
                    f"draining for {grace_s:.1f}s",
                    node_id=self.node_id, grace_s=grace_s, reason=reason,
                    deadline_unix=self._drain_deadline_unix)
        # 1. mark draining in the GCS node table: the cluster scheduler
        # stops placing onto this node and peers stop spilling here
        try:
            await self.gcs.call("node_draining", {
                "node_id": self.node_id, "grace_s": grace_s,
                "deadline_unix": self._drain_deadline_unix,
                "reason": reason}, timeout=5)
        except Exception:
            logger.warning("could not report draining to GCS",
                           exc_info=True)
        # 2. stop granting leases (handle_lease_worker gates on
        # _draining) and revoke the ones out there — owners drain their
        # in-flight pushes and fall back to the normal path
        for lease_id in list(self._leases):
            protocol.spawn(self._revoke_lease(lease_id))
        # 3. signal local workers: trainers commit an out-of-band
        # checkpoint through their AsyncCheckpointer before the node dies
        # (air.session surfaces the deadline to the train loop)
        for h in list(self.workers.values()):
            if h.conn is not None:
                try:
                    await h.conn.notify("preemption_notice", {
                        "deadline_unix": self._drain_deadline_unix,
                        "grace_s": grace_s})
                except Exception:
                    logger.debug("drain: preemption_notice to worker "
                                 "%s failed (already gone?)",
                                 h.worker_id, exc_info=True)
        # 4. queued (undispatched) tasks can't run here any more: move
        # them to peers, or fail them retryably so the owner resubmits
        for pt in list(self.led.pending_tasks()):
            self.led.remove(pt)
            spill = None
            try:
                spill = await self._try_spillback(pt, force=True)
            except Exception:
                spill = None
            if pt.reply_fut is not None and not pt.reply_fut.done():
                pt.reply_fut.set_result(spill or {
                    "error": "NODE_DRAINING",
                    "message": "node is draining (preemption notice)"})
        # 5. let in-flight tasks/leases finish inside the grace window
        while time.monotonic() < deadline:
            if not self._running_tasks and not self._leases:
                break
            await asyncio.sleep(0.1)
        drained_clean = not self._running_tasks and not self._leases
        self._event("WARNING", "NODE_PREEMPTED",
                    f"node {self.node_id[:8]} drained in "
                    f"{time.monotonic() - t0:.2f}s "
                    f"({'clean' if drained_clean else 'grace expired'}); "
                    "terminating", node_id=self.node_id,
                    drain_s=time.monotonic() - t0, clean=drained_clean)
        # 6. graceful goodbye: the GCS marks the node dead NOW instead of
        # waiting out the heartbeat timeout (fast failover)
        try:
            await self.gcs.call("node_drained",
                                {"node_id": self.node_id,
                                 "reason": reason}, timeout=5)
        except Exception:
            pass
        await asyncio.sleep(0.05)  # let the last notifies flush
        self.shutdown()
        os._exit(0)

    def _queue_dispatch_status(self, conn, status: Dict[str, Any]):
        """Coalesce per-task dispatch statuses into one batched notify
        per flush tick.  Failures flush immediately (retry latency);
        successes are bookkeeping and ride the 2 ms coalescing window."""
        entry = self._dispatch_status_buf.get(id(conn))
        if entry is None:
            entry = (conn, [])
            self._dispatch_status_buf[id(conn)] = entry
        entry[1].append(status)
        if status.get("error"):
            self._flush_dispatch_statuses()
        elif not self._dispatch_status_flush_scheduled:
            self._dispatch_status_flush_scheduled = True
            asyncio.get_running_loop().call_later(
                0.002, self._flush_dispatch_statuses)

    def _flush_dispatch_statuses(self):
        self._dispatch_status_flush_scheduled = False
        bufs = self._dispatch_status_buf
        if not bufs:
            return
        self._dispatch_status_buf = {}

        async def _send(conn, statuses):
            try:
                # the coalesced batch notify is a 1.1 addition: peers
                # that negotiated an older minor (or never sent
                # __hello__ at all) get the per-task form they know
                ver = conn.meta.get("peer_protocol_version")
                if ver is not None and tuple(ver[:2]) >= (1, 1):
                    await conn.notify("task_dispatch_status_batch",
                                      {"statuses": statuses})
                else:
                    for status in statuses:
                        await conn.notify("task_dispatch_status", status)
            except Exception:
                pass  # owner-side on_close handles a dead conn

        for conn, statuses in bufs.values():
            protocol.spawn(_send(conn, statuses))

    async def handle_task_done(self, payload, conn):
        task_id = payload["task_id"]
        entry = self._running_tasks.pop(task_id, None)
        if entry is None:
            return {}
        handle, ptask = entry
        self._release_resources(ptask, handle.tpu_chips)
        if handle.tpu_chips:
            # TPU workers are not reused across plain tasks: libtpu holds the
            # chips until process exit, so the worker is retired to free them.
            # Long-lived TPU use goes through actors (Train/Serve/RLlib).
            handle.tpu_chips = ()
            self.workers.pop(handle.worker_id, None)
            handle.proc.terminate()
        else:
            self._push_idle(handle)
        self._dispatch_event.set()
        return {}

    async def handle_profile_workers(self, payload, conn):
        """Timed sampling profiles of this node's workers -> folded
        stacks (reference: profile_manager.py). worker_id narrows to
        one; profiles of several workers run concurrently."""
        want = payload.get("worker_id")
        duration = min(float(payload.get("duration_s") or 2.0), 30.0)
        targets = [(wid, h) for wid, h in list(self.workers.items())
                   if h.conn is not None and (not want or wid == want)]

        req = {"duration_s": duration}
        if payload.get("interval_s") is not None:
            req["interval_s"] = payload["interval_s"]

        async def _one(wid, handle):
            try:
                return await asyncio.wait_for(
                    handle.conn.call("profile_worker", dict(req)),
                    timeout=duration + 10)
            except Exception as e:
                return {"worker_id": wid,
                        "error": f"{type(e).__name__}: {e}"}

        out = list(await asyncio.gather(
            *[_one(wid, h) for wid, h in targets])) if targets else []
        return {"node_id": self.node_id, "workers": out}

    async def handle_dump_worker_stacks(self, payload, conn):
        """On-demand live stack snapshot of this node's workers
        (reference: dashboard/modules/reporter/profile_manager.py).
        payload.worker_id narrows to one worker; default = all."""
        want = payload.get("worker_id")
        out = []
        for wid, handle in list(self.workers.items()):
            if want and wid != want:
                continue
            if handle.conn is None:
                continue
            try:
                r = await asyncio.wait_for(
                    handle.conn.call("dump_stacks", {}), timeout=5)
                out.append(r)
            except Exception as e:
                out.append({"worker_id": wid,
                            "error": f"{type(e).__name__}: {e}"})
        return {"node_id": self.node_id, "workers": out}

    async def handle_cancel_task(self, payload, conn):
        task_id = payload["task_id"]
        for pt in self.led.pending_tasks():
            if pt.spec["task_id"] == task_id:
                self.led.remove(pt)
                if not pt.reply_fut.done():
                    pt.reply_fut.set_result({"error": "CANCELLED"})
                return {"cancelled": "queued"}
        entry = self._running_tasks.get(task_id)
        if entry is not None:
            handle, _ = entry
            if payload.get("force"):
                handle.proc.send_signal(signal.SIGKILL)
            else:
                try:
                    await handle.conn.notify("cancel_task",
                                             {"task_id": task_id})
                except Exception:
                    pass
            return {"cancelled": "running"}
        return {"cancelled": "not_found"}

    # ------------------------------------------------------------- actors

    async def handle_create_actor_worker(self, payload, conn):
        """GCS asks this node to host an actor."""
        if self._draining:
            return {"error": "node is draining (preemption notice)",
                    "retryable": True}
        spec = payload["create_spec"]
        demand = dict(payload.get("resources", {}))
        ptask = PendingTask({"resources": demand,
                             "placement_group": spec.get("placement_group"),
                             "task_id": "actor-" + payload["actor_id"],
                             "scheduling": {}}, None)
        chips = self.led.acquire(ptask)
        if chips is None:
            return {"error": "insufficient resources", "retryable": True}
        try:
            handle = await self._start_worker(spec.get("runtime_env") or {},
                                              chips)
        except Exception as e:
            self._release_resources(ptask, chips)
            return {"error": str(e), "retryable": True}
        for lst in self.idle_workers.values():
            if handle in lst:
                lst.remove(handle)
        handle.is_actor = True
        handle.actor_id = payload["actor_id"]
        handle.job_id = spec.get("job_id")
        handle.tpu_chips = chips
        # busy_task keys the resource release on worker death
        handle.busy_task = "actor-" + payload["actor_id"]
        self._running_tasks["actor-" + payload["actor_id"]] = (handle, ptask)
        try:
            await handle.conn.call("become_actor", {
                "actor_id": payload["actor_id"],
                "create_spec": spec,
                "tpu_chips": list(chips),
            }, timeout=self.config.worker_start_timeout_s)
        except Exception as e:
            await self._handle_worker_death(handle.worker_id, str(e))
            return {"error": f"actor init failed: {e}", "retryable": False}
        return {"worker_address": handle.address,
                "worker_id": handle.worker_id,
                # 1.8: direct-lane endpoints ride the actor record so
                # callers anywhere in the fleet can skip the asyncio
                # server for actor_call
                "direct_address": handle.direct_address,
                "direct_tcp_address": handle.direct_tcp_address}

    async def handle_kill_actor_worker(self, payload, conn):
        aid = payload["actor_id"]
        for handle in self.workers.values():
            if handle.actor_id == aid:
                handle.proc.terminate()
                return {}
        return {}

    # --------------------------------------------------------------- bundles

    # The 2-phase bundle protocol is implemented by the ledger (C++
    # schedcore / Python fallback): prepare deducts the node pool and
    # reserves concrete chips; commit turns the reservation into a
    # per-bundle pool; return credits non-TPU resources in full but only
    # physically-free chips (chips held by a still-running PG task come
    # home via release — the round-2 race fix).  All four handlers are
    # idempotent under GCS-restart retries.

    async def handle_prepare_bundle(self, payload, conn):
        ok = self.led.prepare_bundle(
            (payload["pg_id"], payload["bundle_index"]),
            payload["resources"])
        return {"ok": ok}

    async def handle_commit_bundle(self, payload, conn):
        ok = self.led.commit_bundle(
            (payload["pg_id"], payload["bundle_index"]))
        if ok:
            self._dispatch_event.set()
        return {"ok": ok}

    async def handle_cancel_bundle(self, payload, conn):
        self.led.cancel_bundle((payload["pg_id"], payload["bundle_index"]))
        return {"ok": True}

    async def handle_return_bundle(self, payload, conn):
        key = (payload["pg_id"], payload["bundle_index"])
        self.led.return_bundle(key)
        # tasks queued against ANY bundle of this PG can never run now
        # (a task can queue for a sibling bundle this node never
        # hosted — the removed PG's return_bundle would never arrive
        # for it here); fail them all and free the scheduling classes
        for pt in self.led.drain_pg(payload["pg_id"]):
            if pt.reply_fut is not None and not pt.reply_fut.done():
                pt.reply_fut.set_result({
                    "error": "PLACEMENT_GROUP_REMOVED",
                    "message":
                        f"placement group {payload['pg_id']} was removed",
                })
        self._dispatch_event.set()
        return {"ok": True}

    # ---------------------------------------------------------- object plane

    async def handle_pull_object(self, payload, conn):
        """Serve chunks of a local object to a remote raylet.

        The chunk copy runs in the executor: 20 concurrent 1 GiB pulls
        are thousands of multi-MiB memcpys, and doing them inline
        starves the event loop for tens of seconds (long enough that
        in-loop heartbeats used to miss the GCS death timeout — the
        full-size broadcast regression).

        Outbound streams are CAPPED (object_serve_concurrency): a new
        reader over the limit gets "busy" and retries elsewhere — with
        every completed pull registering a new source, a broadcast
        fans out as a tree instead of serializing N readers on the
        object's first holder (reference: push_manager.cc)."""
        oid = ObjectID.from_hex(payload["object_id"])
        offset = payload.get("offset", 0)
        stream_key = (oid.hex(), id(conn))
        corrupt = False
        if chaos._ENGINE is not None:
            # chaos injection point (object plane): lose or corrupt the
            # primary copy right before serving a pull
            act = chaos.hit("object.pull", oid.hex())
            if act is not None:
                if act.get("op") == "evict":
                    await self._chaos_evict(oid)
                    return {"found": False}
                corrupt = act.get("op") == "corrupt"
        buf = self.store.get_buffer(oid)
        if buf is None and oid.hex() in self.spilled:
            await self._restore_spilled(oid)
            buf = self.store.get_buffer(oid)
        if buf is None:
            return {"found": False}
        try:
            total = len(buf)
            # the stream cap only pays for LONG transfers (the tree
            # needs generations to grow; for small objects the
            # busy-retry latency costs more than head serialization)
            if offset == 0 and \
                    total >= self.config.object_serve_tree_min_bytes:
                now = time.monotonic()
                for k, ts in list(self._serving_pulls.items()):
                    if now - ts > 10.0:  # reader abandoned mid-pull
                        self._serving_pulls.pop(k, None)
                limit = self.config.object_serve_concurrency
                if stream_key not in self._serving_pulls and \
                        len(self._serving_pulls) >= limit:
                    return {"found": True, "busy": True}
            n = min(payload.get("length", CHUNK), total - offset)
            if offset + n >= total:
                self._serving_pulls.pop(stream_key, None)  # last chunk
            elif total >= self.config.object_serve_tree_min_bytes:
                self._serving_pulls[stream_key] = time.monotonic()
            def _read_chunk():
                d = bytes(buf[offset:offset + n])
                # per-chunk crc: the receiver verifies and treats a
                # mismatch (wire/storage corruption — or chaos) as a
                # failed replica, retrying elsewhere instead of sealing
                # a corrupt object
                return d, zlib.crc32(d)

            data, crc = await asyncio.get_running_loop().run_in_executor(
                None, _read_chunk)
            if corrupt:
                torn = bytearray(data)
                torn[0] ^= 0xFF
                torn[-1] ^= 0xFF
                data = bytes(torn)
            return {"found": True, "total_size": total, "data": data,
                    "crc": crc}
        finally:
            buf.release()
            self.store.release(oid)

    async def _chaos_evict(self, oid: ObjectID):
        """Chaos 'evict' op: drop this node's primary copy (shm + spill)
        and its directory entry — the fault lineage reconstruction is
        built to recover from."""
        hex_id = oid.hex()
        if self.pinned.pop(hex_id, None) is not None:
            self.store.release(oid)
        self.store.delete(oid)
        ent = self.spilled.pop(hex_id, None)
        if ent is not None:
            try:
                self.spill_storage.delete(ent[0])
            except Exception:
                pass
        try:
            await self.gcs.call("remove_object_location", {
                "object_id": hex_id, "node_id": self.node_id})
        except Exception:
            pass

    async def _admit_pull(self, nbytes: int):
        """Block until `nbytes` of inbound-pull budget is available
        (reference: pull_manager.cc caps in-flight pull bytes under
        memory pressure so a fetch burst can't blow the store)."""
        if self._pull_waiters is None:
            self._pull_waiters = asyncio.Condition()
        budget = max(
            CHUNK,
            int(self.store.capacity()
                * self.config.pull_admission_fraction))
        nbytes = min(nbytes, budget)  # one giant object always admits
        async with self._pull_waiters:
            while self._pull_inflight_bytes + nbytes > budget:
                await self._pull_waiters.wait()
            self._pull_inflight_bytes += nbytes
        return nbytes

    async def _release_pull(self, nbytes: int):
        async with self._pull_waiters:
            self._pull_inflight_bytes -= nbytes
            self._pull_waiters.notify_all()

    async def _fetch_remote_object(self, oid: ObjectID):
        """Pull an object from another node into the local store."""
        # dedup concurrent pulls of one object (reference:
        # pull_manager.cc tracks one active pull per object): followers
        # await the leader's outcome instead of racing on the create
        fut = self._inflight_fetches.get(oid.hex())
        if fut is not None:
            await fut
            return
        fut = asyncio.get_running_loop().create_future()
        self._inflight_fetches[oid.hex()] = fut
        try:
            await self._fetch_remote_object_once(oid)
            fut.set_result(None)
        except BaseException as e:
            fut.set_exception(e)
            # consume the exception if nobody awaits the future
            fut.exception()
            raise
        finally:
            self._inflight_fetches.pop(oid.hex(), None)

    async def _fetch_remote_object_once(self, oid: ObjectID):
        if oid.hex() in self.spilled:  # our own disk copy: restore, done
            if await self._restore_spilled(oid):
                return
        # an empty directory answer is retried with backoff: the entry
        # may lag the put (location registration in flight) or be in a
        # transient hole (a false node death purged it; the holder's
        # next pin/report re-adds it) — failing the task on one empty
        # read turns those windows into OBJECT_FETCH_FAILED storms
        locs: list = []
        for attempt in range(6):
            r = await self.gcs.call("get_object_locations",
                                    {"object_id": oid.hex()})
            locs = [l for l in r["locations"]
                    if l["node_id"] != self.node_id]
            if locs or attempt == 5:
                break
            await asyncio.sleep(0.5 * (attempt + 1))
        # one deadline for the WHOLE fetch (spanning all replica
        # passes): each push-join below consumes from it rather than
        # re-arming, so a fetch can never exceed the advertised bound
        join_deadline = time.monotonic() + self.config.arg_fetch_timeout_s
        last_err = None
        # Tree broadcast (reference: push_manager.cc's role): sources
        # cap concurrent outbound streams, surplus readers get "busy"
        # and retry against a REFRESHED directory — every completed
        # pull registers a new source, so capacity doubles per
        # generation instead of head-of-lineage serializing N readers.
        pass_num = 0
        # "busy" proves a live copy is actively streaming to someone —
        # re-arm the deadline on it (bounded by the hard cap) so a slow
        # early generation doesn't fail readers that WOULD be served
        hard_cap = time.monotonic() + 10 * self.config.arg_fetch_timeout_s
        while True:
            pass_num += 1
            if pass_num > 1:
                if time.monotonic() >= min(join_deadline, hard_cap):
                    break
                await asyncio.sleep(
                    random.uniform(0.2, min(0.3 * pass_num, 1.5)))
                try:
                    r = await self.gcs.call(
                        "get_object_locations",
                        {"object_id": oid.hex()})
                    locs = [l for l in r["locations"]
                            if l["node_id"] != self.node_id]
                except Exception as e:
                    last_err = e
                    continue
            random.shuffle(locs)
            saw_busy = False
            for loc in locs:
                try:
                    netx_addr = loc.get("netx_address") or ""
                    if netx_addr:
                        res = await self._netx_fetch(netx_addr, oid)
                        if res == "done":
                            return
                        if res == "busy":
                            saw_busy = True
                            continue
                        if res == "notfound":
                            continue
                        # res is None: the netx plane is unavailable for
                        # this peer (gated off, dial failed, transfer
                        # severed) — fall through to the asyncio path
                    remote = await protocol.connect(loc["raylet_address"])
                    try:
                        first = await remote.call("pull_object", {
                            "object_id": oid.hex(), "offset": 0, "length": CHUNK})
                        if first.get("busy"):
                            saw_busy = True
                            continue
                        if not first.get("found"):
                            continue
                        self._verify_chunk(first, first["data"], oid)
                        total = first["total_size"]
                        if self.store.contains(oid):
                            return
                        admitted = await self._admit_pull(total)
                        try:
                            if self.store.contains(oid):
                                return
                            try:
                                try:
                                    buf = self.store.create(oid, total)
                                except ValueError:
                                    # slot taken but object not sealed: an
                                    # interrupted inbound push holds it —
                                    # reap and take over (a LIVE push or a
                                    # concurrent fetch re-raises → handled
                                    # by the wait loop below)
                                    if not self._abort_stale_push(
                                            oid.hex(), max_age=10.0):
                                        raise
                                    buf = self.store.create(oid, total)
                            except ObjectStoreFullError:
                                await self._spill_until(total)
                                buf = self.store.create(oid, total,
                                                        allow_fallback=True)
                            try:
                                loop_ = asyncio.get_running_loop()

                                def _write(dst_off, d):
                                    buf[dst_off:dst_off + len(d)] = d

                                data = first["data"]
                                # chunk writes run in the executor — a GiB
                                # of inline memcpys stalls this raylet's
                                # loop just like inline serving stalls the
                                # holder's (see handle_pull_object)
                                await loop_.run_in_executor(
                                    None, _write, 0, data)
                                got = len(data)
                                while got < total:
                                    chunk = await remote.call("pull_object", {
                                        "object_id": oid.hex(), "offset": got,
                                        "length": CHUNK})
                                    d = chunk["data"]
                                    self._verify_chunk(chunk, d, oid)
                                    await loop_.run_in_executor(
                                        None, _write, got, d)
                                    got += len(d)
                            except BaseException:
                                # never leak an unsealed create: it would
                                # brick the object on this node
                                buf.release()
                                self.store.abort(oid)
                                raise
                            buf.release()
                            self.store.seal(oid)
                        finally:
                            await self._release_pull(admitted)
                        await self.gcs.call("add_object_location", {
                            "object_id": oid.hex(), "node_id": self.node_id})
                        return
                    finally:
                        remote.close()
                except ValueError as e:
                    # a LIVE inbound push holds the slot (same-process
                    # fetches are deduped above): JOIN it — wait for its
                    # seal as long as chunks keep arriving (a GiB push at
                    # contended bandwidth takes minutes; a fixed short cap
                    # abandoned pushes that were making steady progress),
                    # reaping only a STALE push so the pull can take over
                    while time.monotonic() < join_deadline:
                        if self.store.contains(oid):
                            return
                        if self._abort_stale_push(oid.hex(), max_age=10.0):
                            break  # interrupted push reaped — retry pull
                        await asyncio.sleep(0.5)
                    last_err = e
                except Exception as e:  # try next replica
                    last_err = e
            if saw_busy:
                join_deadline = max(
                    join_deadline,
                    time.monotonic() + self.config.arg_fetch_timeout_s)
                last_err = last_err or RuntimeError(
                    "all replicas at their serve cap")
            elif pass_num >= 2:
                # replicas genuinely failed twice (not merely busy):
                # give up — the old two-pass semantics
                break
        raise RuntimeError(f"could not fetch {oid}: no live copies "
                           f"({last_err})")

    async def _netx_fetch(self, address: str, oid: ObjectID
                          ) -> Optional[str]:
        """Pull one object through the netx plane: header via px_get,
        then px_chunk frames streamed by the holder's serve thread
        straight into our plasma create buffer on the netx IO thread —
        this loop only does admission/create/seal bookkeeping, so a GiB
        transfer costs it microseconds, not seconds of chunk RPCs.

        Returns "done"/"busy"/"notfound"; None means the transport is
        unavailable for this peer and the caller should fall back to
        the asyncio pull path. A ValueError from create (live inbound
        push holds the slot) propagates to the fetch loop's JOIN
        handler, and data errors (crc) propagate as replica failures —
        identical discipline to the asyncio path."""
        client = netx.get_client()
        if client is None:
            return None
        loop_ = asyncio.get_running_loop()
        hex_id = oid.hex()
        try:
            hdr = await loop_.run_in_executor(
                None, client.get_header, address, hex_id, 15.0)
        except protocol.RpcError:
            raise  # the peer answered and refused: failed replica
        except Exception:
            return None  # dial failure/backoff/timeout: no transport
        if hdr.get("busy"):
            return "busy"
        if not hdr.get("found"):
            return "notfound"
        total = int(hdr["total_size"])
        if self.store.contains(oid):
            return "done"
        admitted = await self._admit_pull(total)
        try:
            if self.store.contains(oid):
                return "done"
            try:
                try:
                    buf = self.store.create(oid, total)
                except ValueError:
                    # slot held by an interrupted inbound push: reap
                    # and take over (a LIVE push re-raises → JOINed by
                    # the fetch loop)
                    if not self._abort_stale_push(hex_id, max_age=10.0):
                        raise
                    buf = self.store.create(oid, total)
            except ObjectStoreFullError:
                await self._spill_until(total)
                buf = self.store.create(oid, total, allow_fallback=True)
            try:
                await loop_.run_in_executor(
                    None, client.pull_into, address, hex_id, buf, total)
            except BaseException:
                # never leak an unsealed create
                buf.release()
                self.store.abort(oid)
                raise
            buf.release()
            self.store.seal(oid)
        except netx.client.PullBusy:
            return "busy"
        except netx.client.PullNotFound:
            return "notfound"
        except (ConnectionError, TimeoutError):
            return None  # transfer severed past resume: asyncio fallback
        finally:
            await self._release_pull(admitted)
        await self.gcs.call("add_object_location", {
            "object_id": hex_id, "node_id": self.node_id})
        return "done"

    @staticmethod
    def _verify_chunk(reply: Dict[str, Any], data, oid: ObjectID):
        """End-to-end pull integrity: a chunk whose crc32 doesn't match
        what the sender computed is a failed replica (wire/storage
        corruption), not data — raise so the fetch loop retries against
        another copy instead of sealing a corrupt object. Replies from
        pre-1.2 peers carry no crc and pass through unchecked."""
        crc = reply.get("crc")
        if crc is not None and zlib.crc32(bytes(data)) != crc:
            raise IOError(
                f"pull chunk of {oid.hex()[:16]} failed crc verification")

    # -------------------------------------------------------- push manager

    async def push_object(self, oid: ObjectID, target_address: str,
                          target_node_id: str):
        """Proactively push a local object to a peer raylet (reference:
        push_manager.cc — chunked pushes with in-flight dedup). Used
        when this node spills a task to a peer whose args live here:
        the transfer overlaps the peer's worker startup instead of
        serializing behind its on-demand pull."""
        key = (oid.hex(), target_node_id)
        if key in self._pushes_inflight:
            return
        self._pushes_inflight.add(key)
        try:
            buf = self.store.get_buffer(oid)
            if buf is None:
                return
            try:
                total = len(buf)
                remote = await self._raylet_peer(target_address)
                offset = 0
                while offset < total:
                    n = min(CHUNK, total - offset)
                    r = await remote.call("receive_push", {
                        "object_id": oid.hex(), "offset": offset,
                        "total_size": total,
                        "data": bytes(buf[offset:offset + n])})
                    if not r.get("ok"):
                        return  # peer declined (full / already has it)
                    offset += n
            finally:
                buf.release()
                self.store.release(oid)
        except Exception:
            logger.debug("push of %s to %s failed", oid.hex()[:16],
                         target_node_id[:8], exc_info=True)
        finally:
            self._pushes_inflight.discard(key)

    def _abort_stale_push(self, hex_id: str, max_age: float) -> bool:
        """Abort an interrupted inbound push older than ``max_age`` so
        its unsealed create doesn't brick the object on this node.
        True if the slot is now free (no entry, or entry reaped)."""
        ent = self._inbound_pushes.get(hex_id)
        if ent is None:
            return True
        if time.monotonic() - ent[1] < max_age:
            return False  # still streaming
        self._inbound_pushes.pop(hex_id, None)
        try:
            ent[0].release()
        except Exception:
            pass
        self.store.abort(ObjectID.from_hex(hex_id))
        return True

    async def handle_receive_push(self, payload, conn):
        """Inbound proactive push: admit by byte budget, buffer chunks
        into an unsealed create, seal on the last one."""
        oid = ObjectID.from_hex(payload["object_id"])
        total = payload["total_size"]
        if self.store.contains(oid):
            return {"ok": False, "reason": "present"}
        if payload["offset"] == 0:
            # a retried push supersedes an interrupted predecessor
            if not self._abort_stale_push(oid.hex(), max_age=10.0):
                return {"ok": False, "reason": "push in progress"}
            admitted = await self._admit_pull(total)
            try:
                try:
                    self._inbound_pushes[oid.hex()] = \
                        [self.store.create(oid, total), time.monotonic()]
                except ObjectStoreFullError:
                    return {"ok": False, "reason": "full"}
                except ValueError:
                    return {"ok": False, "reason": "present"}
            finally:
                await self._release_pull(admitted)
        ent = self._inbound_pushes.get(oid.hex())
        if ent is None:
            return {"ok": False, "reason": "no create"}
        buf = ent[0]
        ent[1] = time.monotonic()
        data = payload["data"]
        buf[payload["offset"]:payload["offset"] + len(data)] = data
        if payload["offset"] + len(data) >= total:
            buf.release()
            self._inbound_pushes.pop(oid.hex(), None)
            self.store.seal(oid)
            await self.gcs.call("add_object_location", {
                "object_id": oid.hex(), "node_id": self.node_id})
        return {"ok": True}

    async def handle_fetch_object(self, payload, conn):
        await self._fetch_remote_object(ObjectID.from_hex(payload["object_id"]))
        return {}

    async def handle_pin_object(self, payload, conn):
        oid = ObjectID.from_hex(payload["object_id"])
        ok = self.store.pin(oid)
        if ok:
            self.pinned[oid.hex()] = {"owner": payload.get("owner")}
            await self.gcs.call("add_object_location", {
                "object_id": oid.hex(), "node_id": self.node_id,
                "owner": payload.get("owner")})
            self._maybe_spill_soon()
        return {"ok": ok}

    async def handle_free_objects(self, payload, conn):
        for hex_id in payload["object_ids"]:
            oid = ObjectID.from_hex(hex_id)
            if self.pinned.pop(hex_id, None) is not None:
                self.store.release(oid)  # drop pin
            self.store.delete(oid)
            ent = self.spilled.pop(hex_id, None)
            if ent is not None:
                try:
                    self.spill_storage.delete(ent[0])
                except Exception:
                    logger.debug("free: spill delete of %s failed "
                                 "(orphan file reaped by GC sweep)",
                                 hex_id, exc_info=True)
            try:
                await self.gcs.call("remove_object_location", {
                    "object_id": hex_id, "node_id": self.node_id})
            except Exception:
                logger.debug("free: remove_object_location %s failed; "
                             "the location table self-heals on next "
                             "report", hex_id, exc_info=True)
        return {}

    # ------------------------------------------------------------- spilling

    async def handle_request_spill(self, payload, conn):
        """Backpressure path: a worker's plasma create failed; make room.

        Reference: create_request_queue.cc backpressure +
        local_object_manager.h:206 SpillObjectsOfSize.
        """
        n = await self._spill_until(int(payload.get("bytes_needed", 0)))
        return {"spilled": n}

    async def handle_list_objects(self, payload, conn):
        """This node's slice of the cluster object listing: the
        per-raylet plasma index (pinned primaries + spilled primaries)
        as a bounded, id-sorted page. The GCS aggregates these instead
        of holding every object record itself (reference: the object
        directory is locations-only; per-object detail stays where the
        object lives)."""
        payload = payload or {}
        limit = max(1, min(int(payload.get("limit") or 1000), 10_000))
        token = payload.get("continuation_token") or ""
        rows: Dict[str, Dict[str, Any]] = {}
        for hex_id, meta in self.pinned.items():
            if hex_id <= token:
                continue
            rows[hex_id] = {"object_id": hex_id, "node_id": self.node_id,
                            "pinned": True, "spilled": False,
                            "owner": (meta or {}).get("owner")}
        for hex_id, (_uri, size) in self.spilled.items():
            if hex_id <= token:
                continue
            r = rows.setdefault(
                hex_id, {"object_id": hex_id, "node_id": self.node_id,
                         "pinned": False})
            r["spilled"] = True
            r["size_bytes"] = int(size)
        ordered = sorted(rows.values(), key=lambda r: r["object_id"])
        truncated = len(ordered) > limit
        page = ordered[:limit]
        # sizes for in-store objects: one bounded pass over the page
        for r in page:
            if r.get("size_bytes") is None:
                oid = ObjectID.from_hex(r["object_id"])
                buf = self.store.get_buffer(oid)
                if buf is not None:
                    r["size_bytes"] = len(buf)
                    buf.release()
                    self.store.release(oid)
        return {"node_id": self.node_id, "objects": page,
                "truncated": truncated}

    async def _task_events_loop(self):
        """Pump the process-local task-event ring to the GCS in batches
        (the raylet-side leg of the task-event pipeline; workers use
        the thread flusher in task_events.py)."""
        while not self._shutdown:
            await asyncio.sleep(tev._flush_interval())
            while True:
                batch, dropped = tev.drain()
                if not batch and not dropped:
                    break
                try:
                    await self.gcs.call(
                        "task_events",
                        {"events": batch, "dropped": dropped}, timeout=5)
                except Exception:
                    tev.requeue(batch, dropped)
                    break

    async def handle_contains_object(self, payload, conn):
        hex_id = payload["object_id"]
        present = (self.store.contains(ObjectID.from_hex(hex_id))
                   or hex_id in self.spilled)
        return {"present": present}

    def _maybe_spill_soon(self):
        """Proactive spill when the store crosses the threshold."""
        cap = self.store.capacity()
        if cap and self.store.used_bytes() > \
                self.config.object_spilling_threshold * cap:
            protocol.spawn(self._spill_until(0))

    def _get_spill_lock(self) -> asyncio.Lock:
        if self._spill_lock is None:
            self._spill_lock = asyncio.Lock()
        return self._spill_lock

    async def _spill_until(self, bytes_needed: int) -> int:
        async with self._get_spill_lock():
            return await self._spill_until_locked(bytes_needed)

    async def _spill_until_locked(self, bytes_needed: int) -> int:
        """Spill cold pinned primaries (FIFO = oldest first) to disk until
        `bytes_needed` could be allocated, or — if 0 — until usage drops
        below the spill threshold. Returns the number spilled. Caller must
        hold the spill lock."""
        cap = self.store.capacity()
        if bytes_needed:
            target_free = float(bytes_needed) + 64 * 1024  # block headers
        else:
            target_free = cap * (1.0 - self.config.object_spilling_threshold)
        os.makedirs(self.spill_dir, exist_ok=True)
        n = 0
        for hex_id in list(self.pinned.keys()):
            if cap - self.store.used_bytes() >= target_free:
                break
            if await self._spill_one(hex_id):
                n += 1
        return n

    async def _spill_one(self, hex_id: str) -> bool:
        oid = ObjectID.from_hex(hex_id)
        buf = self.store.get_buffer(oid)
        if buf is None:
            logger.debug("spill_one %s: no buffer", hex_id[:16])
            self.pinned.pop(hex_id, None)
            return False
        try:
            data = bytes(buf)
        finally:
            buf.release()
            self.store.release(oid)  # the get_buffer ref
        loop = asyncio.get_running_loop()
        try:
            uri = await loop.run_in_executor(
                None, self.spill_storage.spill, hex_id, data)
        except Exception:
            logger.warning("spill of %s failed", hex_id[:16],
                           exc_info=True)
            return False
        self.store.release(oid)  # the pin ref
        if not self.store.delete(oid):
            # a reader still maps it: leave it in shm, undo the spill
            logger.debug("spill_one %s: delete refused (readers)", hex_id[:16])
            self.store.pin(oid)
            await loop.run_in_executor(None, self.spill_storage.delete,
                                       uri)
            return False
        self.pinned.pop(hex_id, None)
        self.spilled[hex_id] = (uri, len(data))
        self._spill_count += 1
        self._spilled_bytes_total += len(data)
        # the GCS location entry stays: this node still owns the primary
        # copy (on disk); pulls/gets restore it transparently.
        return True

    async def _restore_spilled(self, oid: ObjectID) -> bool:
        async with self._get_spill_lock():
            if self.store.contains(oid):
                return True  # concurrent restore won
            ent = self.spilled.get(oid.hex())
            if ent is None:
                return False
            uri, size = ent
            loop = asyncio.get_running_loop()
            try:
                data = await loop.run_in_executor(
                    None, self.spill_storage.restore, uri)
            except Exception:
                logger.warning("restore of %s from %s failed",
                               oid.hex()[:16], uri, exc_info=True)
                return False
            try:
                self.store.put_bytes(oid, data)
            except ObjectStoreFullError:
                await self._spill_until_locked(len(data))
                try:
                    self.store.put_bytes(oid, data, allow_fallback=True)
                except ObjectStoreFullError:
                    return False
            except ValueError:
                pass  # already restored concurrently
            if self.store.pin(oid):
                self.pinned[oid.hex()] = {"owner": None}
            self.spilled.pop(oid.hex(), None)
            self._restore_count += 1
            self._restored_bytes_total += size
            await loop.run_in_executor(None, self.spill_storage.delete,
                                       uri)
            return True

    async def handle_get_info(self, payload, conn):
        return {
            "node_id": self.node_id,
            "resources": self.total_resources,
            "available": self.led.snapshot(),
            "store": self.store.stats(),
            "num_spilled_objects": len(self.spilled),
            "num_workers": len(self.workers),
            "num_pending_tasks": self.led.pending_count(),
            "tpu": self.tpu_info,
        }

    def _physical_stats(self) -> Dict[str, float]:
        """Host cpu/mem/disk readings from /proc — the per-node agent's
        reporter role (reference: dashboard/agent.py + modules/reporter
        reporter_agent.py, psutil there; /proc directly here)."""
        out: Dict[str, float] = {}
        try:
            with open("/proc/meminfo") as f:
                mem = {}
                for line in f:
                    k, _, rest = line.partition(":")
                    mem[k] = float(rest.split()[0]) * 1024  # kB -> bytes
            out["mem_total_bytes"] = mem.get("MemTotal", 0.0)
            out["mem_available_bytes"] = mem.get("MemAvailable", 0.0)
        except OSError:
            pass
        try:
            with open("/proc/stat") as f:
                parts = f.readline().split()[1:]
            vals = [float(x) for x in parts]
            busy, total = sum(vals) - vals[3] - vals[4], sum(vals)
            prev = self._prev_cpu_sample
            self._prev_cpu_sample = (busy, total)
            if prev and total > prev[1]:
                out["cpu_percent"] = 100.0 * (busy - prev[0]) \
                    / (total - prev[1])
        except (OSError, IndexError, ValueError):
            pass
        try:
            st = os.statvfs(self.spill_dir
                            if os.path.isdir(self.spill_dir)
                            else self.session_dir)
            out["disk_free_bytes"] = float(st.f_bavail * st.f_frsize)
        except OSError:
            pass
        try:
            out["load_avg_1m"] = os.getloadavg()[0]
        except OSError:
            pass
        return out

    async def handle_node_stats(self, payload, conn):
        """Per-node agent snapshot: physical + scheduler + object-plane
        gauges (reference: dashboard/agent.py reporting and the native
        metric set in src/ray/stats/metric_defs.cc — scheduler task
        counts, plasma usage, spill totals)."""
        idle = sum(len(v) for v in self.idle_workers.values())
        running = sum(1 for h in self.workers.values() if h.busy_task)
        actors = sum(1 for h in self.workers.values() if h.is_actor)
        store = self.store.stats()
        return {
            "node_id": self.node_id,
            "physical": self._physical_stats(),
            "scheduler": {
                "tasks_pending": self.led.pending_count(),
                "tasks_running": running,
                "tasks_dispatched_total": self._tasks_dispatched_total,
                "tasks_spilled_back_total": self._tasks_spilled_back_total,
                "workers_alive": len(self.workers),
                "workers_idle": idle,
                "actors_alive": actors,
                "resources_total": dict(self.total_resources),
                "resources_available": self.led.snapshot(),
                # versioned sync stream position (ray_syncer analogue)
                "sync_version": self._sync_version,
                "known_view_version": self._known_view_version,
                "cluster_view_nodes": len(self.cluster_view),
                # dispatch core + liveness observables (round 4: the
                # native schedcore ledger and the loop-lag that the
                # liveness thread attests to the GCS). Lag values come
                # from the OFF-LOOP liveness thread — a lag gauge
                # computed in this on-loop handler could never observe
                # a real stall (no responses during it; the tick timer
                # re-stamps before stats run after it)
                "sched_native": 1 if self.led.native else 0,
                "event_loop_lag_s": getattr(self, "_lag_last", 0.0),
                "event_loop_lag_peak_s": getattr(self, "_lag_peak", 0.0),
            },
            "object_store": {
                **{k: int(v) for k, v in store.items()},
                "pinned_objects": len(self.pinned),
                "spilled_objects": len(self.spilled),
                "spilled_bytes_current": sum(
                    s for _, s in self.spilled.values()),
                "spill_count_total": self._spill_count,
                "spilled_bytes_total": self._spilled_bytes_total,
                "restore_count_total": self._restore_count,
                "restored_bytes_total": self._restored_bytes_total,
                "pull_inflight_bytes": self._pull_inflight_bytes,
                "pushes_inflight": len(self._pushes_inflight),
            },
            "tpu": {
                "num_chips": int(self.total_resources.get("TPU", 0)),
                "chips_available": int(self.led.avail_get("TPU")),
                **(self.tpu_info or {}),
            },
        }

    # ---------------------------------------------------------------- report

    async def _log_monitor_loop(self):
        """Tail worker stdout/stderr files and publish new lines to the GCS
        'worker_logs' channel; the driver subscribes and mirrors them, so
        task/actor print() output appears at the driver.

        Role-equivalent to the reference's log_monitor process
        (python/ray/_private/log_monitor.py tail → GCS pubsub → driver);
        here the raylet owns the files, so the tail lives in-process.
        """
        # path -> [offset, worker_id, pid, is_err, job_id_getter]
        tracked: Dict[str, List[Any]] = {}
        while not self._shutdown:
            await asyncio.sleep(0.3)
            for h in list(self.workers.values()):
                for i, path in enumerate(h.log_paths):
                    if path and path not in tracked:
                        tracked[path] = [0, h.worker_id, h.proc.pid,
                                         i == 1, h]
            gone = []
            for path, ent in tracked.items():
                offset, worker_id, pid, is_err, h = ent
                try:
                    size = os.path.getsize(path)
                except OSError:
                    gone.append(path)
                    continue
                worker_dead = h.worker_id not in self.workers and \
                    h.proc.poll() is not None
                if size <= offset:
                    # drop tails of dead workers once fully drained
                    if worker_dead:
                        gone.append(path)
                    continue
                try:
                    with open(path, "rb") as f:
                        f.seek(offset)
                        data = f.read(256 * 1024)
                except OSError:
                    gone.append(path)
                    continue
                # consume only up to the last newline so a line mid-write
                # (or a multi-byte char straddling the chunk) is never torn;
                # a dead worker's final partial line flushes as-is
                last_nl = data.rfind(b"\n")
                if last_nl == -1:
                    if not worker_dead:
                        continue
                elif not worker_dead or last_nl != len(data) - 1:
                    data = data[:last_nl + 1]
                ent[0] = offset + len(data)
                lines = data.decode("utf-8", "replace").splitlines()
                for start in range(0, len(lines), 200):
                    try:
                        await self.gcs.notify("publish", {
                            "channel": "worker_logs",
                            "message": {"worker_id": worker_id, "pid": pid,
                                        "is_err": is_err, "job_id": h.job_id,
                                        "node_id": self.node_id,
                                        "lines": lines[start:start + 200]},
                        })
                    except Exception:
                        logger.debug("log monitor: publish failed; "
                                     "retrying worker %s on next scan",
                                     worker_id, exc_info=True)
                        break
            for path in gone:
                tracked.pop(path, None)

    # ------------------------------------------------------- memory monitor

    @staticmethod
    def _host_memory_fraction() -> float:
        """Used-memory fraction from /proc/meminfo (cgroup limit if lower).

        Reference: src/ray/common/memory_monitor.h:52 GetMemoryBytes — the
        min of cgroup and system capacity, usage = total - available."""
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, _, rest = line.partition(":")
                    info[k] = int(rest.strip().split()[0]) * 1024
            total = info.get("MemTotal", 0)
            avail = info.get("MemAvailable", 0)
            # cgroup v2 ceiling, when in a container
            try:
                with open("/sys/fs/cgroup/memory.max") as f:
                    raw = f.read().strip()
                if raw != "max":
                    limit = int(raw)
                    if 0 < limit < total:
                        with open("/sys/fs/cgroup/memory.current") as f:
                            cur = int(f.read().strip())
                        # reclaimable page cache must not count as pressure
                        # (reference: memory_monitor subtracts inactive_file)
                        try:
                            with open("/sys/fs/cgroup/memory.stat") as f:
                                for line in f:
                                    if line.startswith("inactive_file "):
                                        cur -= int(line.split()[1])
                                        break
                        except OSError:
                            pass
                        return max(0, cur) / limit
            except OSError:
                pass
            if total <= 0:
                return 0.0
            return 1.0 - avail / total
        except OSError:
            return 0.0

    def _pick_oom_victim(self) -> Optional[WorkerHandle]:
        """Worker-killing policy (reference: worker_killing_policy.h:30
        RetriableFIFO): prefer workers running retriable tasks, newest
        first — their work is recoverable via owner retries; then
        non-retriable tasks; restartable actors; detached/plain actors
        last."""
        retriable, tasks, actors = [], [], []
        for h in self.workers.values():
            if h.busy_task is None:
                continue
            entry = self._running_tasks.get(h.busy_task)
            if h.is_actor:
                actors.append(h)
            elif entry is not None and \
                    entry[1].spec.get("max_retries", 0) != 0:
                retriable.append(h)
            else:
                tasks.append(h)
        for group in (retriable, tasks, actors):
            if group:
                return max(group, key=lambda h: h.idle_since)
        return None

    async def _memory_monitor_loop(self):
        """Kill a worker (policy above) when host memory crosses the
        threshold, instead of letting the kernel OOM-killer pick a random
        victim (possibly the raylet or the model actor)."""
        period = self.config.memory_monitor_refresh_ms / 1000.0
        while not self._shutdown:
            await asyncio.sleep(period)
            frac = self._host_memory_fraction()
            if frac < self.config.memory_usage_threshold:
                continue
            victim = self._pick_oom_victim()
            if victim is None:
                continue
            logger.warning(
                "memory usage %.1f%% over threshold %.1f%%: killing worker "
                "%s (task %s) to relieve pressure", frac * 100,
                self.config.memory_usage_threshold * 100, victim.worker_id,
                victim.busy_task)
            self._oom_killed_workers.add(victim.worker_id)
            try:
                victim.proc.kill()
            except OSError:
                pass  # already exiting; the death path still runs
            # let the death path run before re-evaluating
            await asyncio.sleep(period)

    async def _send_report(self):
        """One tick of the versioned bidirectional sync stream
        (reference: ray_syncer.h — versioned snapshots up, cluster-view
        deltas down on the same exchange)."""
        self._sync_version += 1
        try:
            reply = await self.gcs.call("resource_report", {
                "node_id": self.node_id,
                "available": self.led.snapshot(),
                "total": self.total_resources,
                "sync_epoch": self._sync_epoch,
                "sync_version": self._sync_version,
                "known_view": self._known_view_version,
            })
        except Exception:
            return
        self._apply_view_delta(reply or {})

    def _apply_view_delta(self, reply: Dict[str, Any]):
        """Fold the GCS's cluster-view delta into the local cache and
        retire peer connections to nodes the view says are dead."""
        if reply.get("view_version", 0) <= self._known_view_version:
            return
        self._known_view_version = reply["view_version"]
        for ent in reply.get("delta") or ():
            self.cluster_view[ent["node_id"]] = ent
            if not ent["alive"]:
                conn = self._peer_raylets.pop(
                    ent["raylet_address"], None)
                if conn is not None:
                    try:
                        conn.close()
                    except Exception:
                        logger.debug("view delta: closing peer conn "
                                     "to dead node %s raised",
                                     ent["node_id"], exc_info=True)

    def report_soon(self):
        """Event-driven report push (debounced): resource releases reach
        the GCS scheduler immediately instead of at the next poll tick —
        a periodic-only view goes stale for seconds, which the cluster
        scheduler's locality/utilization scoring inherits (reference:
        ray_syncer's on-change broadcast vs pure polling)."""
        if getattr(self, "_report_pending", False) or self._shutdown:
            return
        self._report_pending = True

        async def _go():
            await asyncio.sleep(0.05)  # debounce bursts of releases
            self._report_pending = False
            await self._send_report()
        try:
            protocol.spawn(_go())
        except RuntimeError:
            self._report_pending = False

    async def _report_loop(self):
        while not self._shutdown:
            await self._send_report()
            await asyncio.sleep(self.config.health_check_period_s)

    # ------------------------------------------------------------ liveness

    async def _loop_tick_task(self):
        """Stamp event-loop progress for the liveness thread: the lag
        between now and this stamp is how far behind the loop is."""
        period = max(0.25, self.config.health_check_period_s / 2)
        while not self._shutdown:
            self._loop_tick = time.monotonic()
            await asyncio.sleep(period)

    def _start_liveness_thread(self):
        """Heartbeats from a DEDICATED thread + connection, so a busy
        event loop cannot read as node death (the 1 GiB-broadcast
        failure: the head raylet's loop spends >10s serving bulk pull
        chunks, its in-loop report misses the GCS health timeout, the
        GCS declares it dead and purges its object locations — every
        reader then sees "no live copies" for an object that is sitting
        pinned in shm).  The beat carries the loop's lag; a WEDGED loop
        (lag > loop_stall_death_s) stops refreshing last_seen, so true
        event-loop death is still detected — what this thread attests
        is "process up, loop merely behind", which the reference gets
        for free from its µs-latency C++ handlers
        (gcs_heartbeat_manager.cc)."""
        import threading

        self._loop_tick = time.monotonic()
        period = self.config.health_check_period_s

        def run():
            async def beat():
                conn = None
                while not self._shutdown:
                    lag = time.monotonic() - self._loop_tick
                    # off-loop lag observables for the stats agent
                    self._lag_last = lag
                    self._lag_peak = max(
                        lag, getattr(self, "_lag_peak", 0.0))
                    try:
                        if conn is None or conn._closed:
                            conn = await protocol.connect(self.gcs_address)
                        await conn.call("node_liveness", {
                            "node_id": self.node_id,
                            "loop_lag_s": lag,
                        }, timeout=period * 4)
                    except Exception:
                        if conn is not None:
                            conn.close()  # a timed-out call leaves the
                            conn = None   # socket open — don't leak it
                    await asyncio.sleep(period)
                if conn is not None:
                    conn.close()

            try:
                asyncio.run(beat())
            except Exception:
                pass

        threading.Thread(target=run, daemon=True,
                         name=f"liveness-{self.node_id[:8]}").start()

    def shutdown(self):
        self._shutdown = True
        if self._netx_server is not None:
            try:
                self._netx_server.close()
            except Exception:
                pass
        for h in self.workers.values():
            try:
                h.proc.kill()
            except OSError:
                pass  # already dead
        self.server.close()
        self.store.unlink()
        try:
            shutil.rmtree(self.spill_dir, ignore_errors=True)
        except Exception:
            pass


def _env_hash(runtime_env: Dict[str, Any]) -> str:
    from ray_tpu._private.runtime_env import env_hash
    return env_hash(runtime_env)
