"""The per-process core runtime: driver and worker share this.

Role-equivalent to the reference's CoreWorker + the Python worker layer
(reference: src/ray/core_worker/core_worker.cc SubmitTask:1621 / Get:1143 /
Put:936 / ExecuteTask:2235; python/ray/_private/worker.py). Every process —
driver or worker — embeds one ``Worker``:

  - an RPC server on a unix socket (the process's "core worker service";
    reference: core_worker.proto) handling task pushes, actor calls, result
    delivery, borrower registration, and object waits
  - an in-process memory store for small objects + a plasmax client for the
    node's shared-memory segment (reference: store_provider/)
  - the owner-side task manager: pending tasks, retries, and lineage for
    reconstruction (reference: task_manager.cc, max_retries semantics)
  - owner-side reference counting with a borrower protocol (simplified from
    reference_count.cc: borrowers register with the owner on deserialize and
    notify on release; owner frees cluster-wide when counts reach zero)
  - the task execution loop (workers) and the actor runtime with per-caller
    ordering and max_concurrency thread pools (reference:
    actor_scheduling_queue.cc / concurrency_group_manager.cc)
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import queue
import sys
import threading
import time
import traceback
import weakref
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import chaos, protocol, serialization
from ray_tpu._private import task_events as tev

# ray_tpu.util imports back into this module, so the timeline module is
# bound lazily on first task execution (cached here — a per-task
# ``from ray_tpu.util import timeline`` showed up in lane profiles)
_timeline = None
from ray_tpu._private.function_manager import FunctionManager
from ray_tpu._private.object_store import MemoryStore, PlasmaxStore
from ray_tpu.common.config import SystemConfig, global_config, set_global_config
from ray_tpu.common.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu import exceptions as exc

logger = logging.getLogger(__name__)

MODE_DRIVER = "driver"
MODE_WORKER = "worker"

# _execute_task reply sentinel: the direct lane (direct.py) executes on
# the receiving thread and wants the result dict RETURNED, not delivered
# through an asyncio future
DIRECT_REPLY = "direct"


# --------------------------------------------------------------------------
# ObjectRef


class ObjectRef:
    """A future for an object in the cluster.

    Carries the owner's address so any holder can reach the owner for the
    borrower protocol and result waiting (reference: ObjectRefs carry owner
    addresses in their custom reducer, SURVEY.md §8.4).
    """

    def __init__(self, oid: ObjectID, owner_address: str = "",
                 *, _register: bool = True):
        self._id = oid
        self._owner_address = owner_address
        self._held_buffer = None
        w = _global_worker
        self._worker = w if (w is not None and w.connected) else None
        if self._worker is not None and _register:
            self._worker.reference_counter.add_local(oid)

    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    def owner_address(self) -> str:
        return self._owner_address

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __del__(self):
        # GC can fire INSIDE a region that already holds the refcount
        # lock (observed: a dict resize in add_local triggered GC, which
        # collected a ref whose __del__ then re-took the non-reentrant
        # lock — a self-deadlock). __del__ therefore only enqueues; the
        # actual decrement runs from normal code paths.
        w = self._worker
        if w is not None and w.connected:
            try:
                w.reference_counter.defer_remove_local(
                    self._id, self._owner_address)
            except Exception:
                pass

    def __reduce__(self):
        from ray_tpu._private import ref_serialization
        ref_serialization.record_ref((self._id.hex(), self._owner_address))
        return (_deserialize_ref, (self._id.binary(), self._owner_address))

    def on_done(self, cb) -> bool:
        """Fire ``cb()`` (no value fetch) when the producing task
        completes. Returns False when completion can't be tracked (e.g.
        this process didn't submit the task) — caller must fall back."""
        w = self._worker
        if w is None or not w.connected:
            return False
        state = w.pending_tasks.get(self._id.task_id().hex())
        if state is not None:
            state.result_event.add_callback(cb)
            return True
        if (w.memory_store.contains(self._id)
                or w.plasma.contains(self._id)):
            cb()
            return True
        return False

    def future(self):
        """A concurrent.futures.Future resolved with the object's value."""
        from concurrent.futures import Future
        f: Future = Future()

        def _resolve():
            try:
                f.set_result(get(self))
            except BaseException as e:  # noqa: BLE001
                f.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return f

    def __await__(self):
        fut = asyncio.wrap_future(self.future())
        return fut.__await__()


def _deserialize_ref(binary: bytes, owner_address: str) -> ObjectRef:
    oid = ObjectID(binary)
    ref = ObjectRef(oid, owner_address, _register=False)
    w = _global_worker
    if w is not None and w.connected:
        w.reference_counter.add_borrowed(oid, owner_address)
    return ref


# --------------------------------------------------------------------------
# Reference counting (owner side + borrower side)


class ReferenceCounter:
    """Simplified distributed refcounting (reference: reference_count.cc).

    Owner tracks local refs, submitted-task refs, and registered borrowers.
    Borrowers count their local refs and tell the owner when they hit zero.
    When the owner's total reaches zero the object is freed cluster-wide.
    """

    def __init__(self, worker: "Worker"):
        self.worker = worker
        self.lock = threading.Lock()
        # oid -> [local, submitted, borrowers:set, owned:bool, spec|None]
        self.table: Dict[ObjectID, Dict[str, Any]] = {}
        # removals queued by ObjectRef.__del__ (GC-safe: deque.append is
        # atomic and takes no lock); drained by drain_deferred()
        self._deferred: collections.deque = collections.deque()

    def defer_remove_local(self, oid: ObjectID, owner_address: str):
        self._deferred.append((oid, owner_address))

    def drain_deferred(self):
        """Apply queued __del__ decrements. Called from ordinary (non-GC)
        code paths and a periodic io-loop tick; O(1) when empty."""
        while self._deferred:
            try:
                oid, owner = self._deferred.popleft()
            except IndexError:
                return
            self.remove_local(oid, owner)

    def _entry(self, oid: ObjectID):
        return self.table.setdefault(oid, {
            "local": 0, "submitted": 0, "borrowers": set(),
            "owned": False, "lineage": None, "in_plasma": False,
        })

    def mark_in_plasma(self, oid: ObjectID):
        """Flag an existing entry as plasma-backed (no-op if the ref was
        already freed)."""
        with self.lock:
            e = self.table.get(oid)
            if e is not None:
                e["in_plasma"] = True

    def add_owned(self, oid: ObjectID, in_plasma: bool = False,
                  lineage=None):
        with self.lock:
            e = self._entry(oid)
            e["owned"] = True
            e["in_plasma"] = e["in_plasma"] or in_plasma
            if lineage is not None:
                e["lineage"] = lineage

    def add_local(self, oid: ObjectID):
        with self.lock:
            self._entry(oid)["local"] += 1

    def remove_local(self, oid: ObjectID, owner_address: str):
        free = False
        notify_owner = False
        with self.lock:
            e = self.table.get(oid)
            if e is None:
                return
            e["local"] -= 1
            if e["local"] <= 0 and e["submitted"] <= 0:
                if e["owned"]:
                    if not e["borrowers"]:
                        free = True
                else:
                    notify_owner = True
        if free:
            self._free(oid)
        elif notify_owner and owner_address and \
                owner_address != self.worker.address:
            self.worker.try_notify(owner_address, "borrow_del",
                                   {"object_id": oid.hex(),
                                    "borrower": self.worker.address})

    def add_submitted(self, oid: ObjectID):
        with self.lock:
            self._entry(oid)["submitted"] += 1

    def remove_submitted(self, oid: ObjectID):
        free = False
        with self.lock:
            e = self.table.get(oid)
            if e is None:
                return
            e["submitted"] -= 1
            if e["local"] <= 0 and e["submitted"] <= 0 and e["owned"] and \
                    not e["borrowers"]:
                free = True
        if free:
            self._free(oid)

    def add_borrowed(self, oid: ObjectID, owner_address: str):
        """Called when a ref deserializes in this process."""
        with self.lock:
            e = self._entry(oid)
            e["local"] += 1
            registered = e.get("registered_borrow", False)
            e["registered_borrow"] = True
        if not registered and owner_address and \
                owner_address != self.worker.address:
            self.worker.try_notify(owner_address, "borrow_add",
                                   {"object_id": oid.hex(),
                                    "borrower": self.worker.address})

    def on_borrow_add(self, oid_hex: str, borrower: str):
        with self.lock:
            self._entry(ObjectID.from_hex(oid_hex))["borrowers"].add(borrower)

    def on_borrow_del(self, oid_hex: str, borrower: str):
        oid = ObjectID.from_hex(oid_hex)
        free = False
        with self.lock:
            e = self.table.get(oid)
            if e is None:
                return
            e["borrowers"].discard(borrower)
            if e["local"] <= 0 and e["submitted"] <= 0 and e["owned"] and \
                    not e["borrowers"]:
                free = True
        if free:
            self._free(oid)

    def set_lineage(self, oid: ObjectID, spec: Dict[str, Any]):
        with self.lock:
            self._entry(oid)["lineage"] = spec

    def get_lineage(self, oid: ObjectID):
        with self.lock:
            e = self.table.get(oid)
            return e.get("lineage") if e else None

    def _free(self, oid: ObjectID):
        with self.lock:
            e = self.table.pop(oid, None)
        if e is None:
            return
        self.worker.memory_store.delete(oid)
        if e.get("in_plasma"):
            self.worker.free_plasma([oid])


# --------------------------------------------------------------------------
# Worker


_global_worker: Optional["Worker"] = None


def global_worker() -> "Worker":
    if _global_worker is None or not _global_worker.connected:
        raise RuntimeError(
            "ray_tpu.init() must be called before using the API")
    return _global_worker


class _CallbackEvent(threading.Event):
    """threading.Event that also fires one-shot callbacks on set() —
    lets ObjectRef.on_done release resources (e.g. Serve router
    backpressure slots) without a waiter thread per ref."""

    def __init__(self):
        super().__init__()
        self._cbs: List = []
        self._cb_lock = threading.Lock()

    def add_callback(self, cb):
        fire = False
        with self._cb_lock:
            if self.is_set():
                fire = True
            else:
                self._cbs.append(cb)
        if fire:
            cb()

    def set(self):
        super().set()
        with self._cb_lock:
            cbs, self._cbs = self._cbs, []
        for cb in cbs:
            try:
                cb()
            except Exception:
                pass


class PendingTaskState:
    __slots__ = ("spec", "retries_left", "return_ids", "done",
                 "result_event", "worker_address", "attempt", "direct")

    def __init__(self, spec, retries_left, return_ids):
        self.spec = spec
        self.retries_left = retries_left
        self.return_ids = return_ids
        self.done = False
        self.result_event = _CallbackEvent()
        self.worker_address = None
        self.attempt = 0  # bumped per retry; rides spec["attempt"]
        self.direct = False  # in flight on the native direct lane


class _LeaseState:
    """Driver-side record of one worker lease (reference:
    normal_task_submitter.cc LeaseEntry).  `busy` is best-effort under
    the GIL — two racing callers both landing on the lease just queue
    serially at the worker, which is correct, only slower."""

    __slots__ = ("key", "lease_id", "addr", "inflight", "last_used",
                 "acquiring", "revoked")

    # pipeline depth per leased worker: execution is serial, so this
    # just hides the RPC round-trip, it does not add parallelism
    MAX_INFLIGHT = 8

    def __init__(self, key):
        self.key = key
        self.lease_id = None
        self.addr = None
        self.inflight = 0
        self.last_used = 0.0
        self.acquiring = True  # constructed on the way to acquisition
        self.revoked = False   # raylet revoked; ack once inflight drains


class Worker:
    def __init__(self):
        self.mode = MODE_DRIVER
        self.connected = False
        self.io: Optional[protocol.EventLoopThread] = None
        self.raylet: Optional[protocol.Connection] = None
        self.gcs: Optional[protocol.Connection] = None
        self.memory_store = MemoryStore()
        self.plasma: Optional[PlasmaxStore] = None
        self.node_id: str = ""
        self.worker_id = WorkerID.from_random()
        self.job_id = JobID.nil()
        self.address = ""  # this process's core-worker RPC address
        self.config: SystemConfig = global_config()
        self.function_manager: Optional[FunctionManager] = None
        self.reference_counter = ReferenceCounter(self)
        self.current_task_id: Optional[TaskID] = None
        self.current_actor_id: Optional[ActorID] = None
        self.log_to_driver = True
        self._prepared_envs: Dict[str, Any] = {}
        self.task_context = threading.local()
        self._put_counter = 0
        self._put_lock = threading.Lock()
        self.pending_tasks: Dict[str, PendingTaskState] = {}
        # fn_key -> (opts snapshot, shared spec fields); see submit_task
        self._shared_spec_cache: Dict[str, Tuple] = {}
        self._submit_buf: List[Tuple[Dict[str, Any], PendingTaskState]] = []
        self._submit_lock = threading.Lock()
        self._submit_flush_scheduled = False
        # io-loop only; see protocol.single_flight_connect
        self._peer_conns: Dict[str, protocol.Connection] = {}
        self._peer_pending: Dict[str, "asyncio.Future"] = {}
        # worker-lease pools for direct pushes, keyed by sorted resource
        # items; one pool entry per leased worker
        self._worker_leases: Dict[Tuple, List["_LeaseState"]] = {}
        self._lease_fail_at: Dict[Tuple, float] = {}
        self._lease_waiters: Dict[Tuple, List[Tuple]] = {}
        self.session_dir = ""
        self.namespace = ""
        self.runtime_context: Dict[str, Any] = {}
        # worker-mode execution state
        self._task_queue: "queue.Queue" = queue.Queue()
        self._leased_executed = 0
        self._leased_stats_scheduled = False
        self._actor_instance = None
        self._actor_threads: Optional[ThreadPoolExecutor] = None
        self._actor_lock = threading.Lock()
        self._actor_async_loop = None
        self._cancelled_tasks: set = set()
        self.tpu_chips: List[int] = []
        self._server: Optional[protocol.Server] = None
        # receive side: highest actor-call seq dispatched per caller +
        # parked out-of-order arrivals (reference:
        # actor_scheduling_queue.cc ordering by sequence_no)
        self._actor_seq: Dict[str, int] = {}
        self._actor_waiting: Dict[str, Dict[int, Any]] = {}
        # send side: per-actor monotone counters, program-order allocated,
        # plus the contiguous completed-prefix ("processed up to") that
        # rides every call so a restarted actor learns its baseline from
        # the first message instead of stalling on a phantom gap
        # (reference: actor_scheduling_queue.cc client_processed_up_to)
        self._actor_send_seq: Dict[str, int] = {}
        self._actor_done_seqs: Dict[str, set] = {}
        self._actor_processed_upto: Dict[str, int] = {}
        self._actor_send_lock = threading.Lock()
        # per-object location channels (long-poll pubsub): hex -> [Event,
        # waiter refcount]
        self._obj_channels: Dict[str, list] = {}
        self._obj_channel_lock = threading.Lock()
        # native direct-execution lane (direct.py; RTPU_NATIVE_RPC):
        # workers run a DirectServer beside the asyncio server, drivers
        # route qualifying leased tasks through a DirectClient
        self.direct_address = ""
        self.direct_tcp_address = ""
        self._direct_server = None
        self._direct_client = None

    # ------------------------------------------------------------- lifecycle

    def connect(self, mode: str, gcs_address: str, raylet_address: str,
                store_path: str, node_id: str, session_dir: str,
                namespace: str = "", job_id: Optional[JobID] = None):
        global _global_worker
        self.mode = mode
        self.session_dir = session_dir
        self.namespace = namespace
        self.node_id = node_id
        self.io = protocol.EventLoopThread()
        sock = os.path.join(session_dir,
                            f"cw_{self.worker_id.hex()[:12]}.sock")
        self._server = protocol.Server(self._handlers())
        self.io.run(self._server.start_unix(sock))
        self.address = f"unix:{sock}"
        if mode == MODE_WORKER:
            # direct-execution lane (perf; docs/WIRE_PROTOCOL.md
            # "Implementations"): a second listening socket served by the
            # native frame pump, where leased unary tasks run
            # recv→decode→execute→reply on one thread. Any failure here
            # (library didn't build, RTPU_NATIVE_RPC=0) just leaves the
            # asyncio path in charge.
            from ray_tpu._private import rpccore
            if rpccore.available():
                try:
                    from ray_tpu._private import direct
                    dsock = os.path.join(
                        session_dir,
                        f"cw_{self.worker_id.hex()[:12]}.direct.sock")
                    from ray_tpu._private import netx
                    self._direct_server = direct.DirectServer(
                        self, dsock,
                        tcp_host=netx.node_ip() if netx.enabled()
                        else None)
                    self.direct_address = self._direct_server.address
                    self.direct_tcp_address = \
                        self._direct_server.tcp_address
                except Exception:
                    logger.warning("direct lane unavailable; using the "
                                   "asyncio path", exc_info=True)
                    self._direct_server = None
                    self.direct_address = ""
                    self.direct_tcp_address = ""
        self.gcs_address = gcs_address
        # survives a GCS restart: calls retry after re-dial (GCS fault
        # tolerance; reference: gcs_rpc_client.h reconnection). The
        # constructor is loop-free; it dials lazily on first call.
        self.gcs = protocol.ReconnectingConnection(
            gcs_address, handler=self._handle_request)
        self.plasma = PlasmaxStore(store_path)
        self.function_manager = FunctionManager(
            lambda m, p: self.io.run(self.gcs.call(m, p)))
        if raylet_address:
            if mode == MODE_WORKER:
                # A worker whose raylet vanished (SIGKILL, node death) is an
                # orphan: nothing can ever schedule onto it again, and leaked
                # workers keep shm segments mapped. Exit hard.
                on_close = lambda _conn: os._exit(1)  # noqa: E731
            else:
                # Driver: batched submissions were acked by the raylet
                # and get their dispatch failures via notify — a dead
                # connection can deliver neither, so every still-pending
                # submission must fail (and retry/fatal-resolve) NOW or
                # ray_tpu.get() on those refs hangs forever.
                def on_close(_conn):
                    self._fail_pending_submissions("RAYLET_UNREACHABLE",
                                                   "raylet connection lost")
            self.raylet = self.io.run(protocol.connect(
                raylet_address, handler=self._handle_request,
                on_close=on_close))
            # negotiate on the long-lived raylet link: the raylet gates
            # minor-version features (batched dispatch statuses) on the
            # version we declare here; a pre-hello raylet answers "no
            # such method", which is fine — we just look legacy to it
            try:
                from ray_tpu._private import schema
                self.io.run(self.raylet.call(
                    "__hello__", schema.hello_payload(), timeout=10))
            except Exception:
                pass
        if mode == MODE_DRIVER:
            if self.raylet is not None:
                from ray_tpu._private import rpccore
                if rpccore.available():
                    try:
                        from ray_tpu._private import direct
                        self._direct_client = direct.DirectClient(self)
                    except Exception:
                        logger.warning(
                            "direct client unavailable; using the "
                            "asyncio lease pool", exc_info=True)
                        self._direct_client = None
            chaos.init_from_env("driver")
            r = self.io.run(self.gcs.call("next_job_id", {}))
            self.job_id = JobID.from_int(r["job_index"])
            self.io.run(self.gcs.call("add_job", {
                "job_id": self.job_id.hex(), "driver_pid": os.getpid(),
                "namespace": namespace}))
            self.current_task_id = TaskID.for_driver(self.job_id)
            if self.log_to_driver:
                # mirror worker stdout/stderr here (reference: log_monitor
                # pubsub → driver); re-subscribe after a GCS restart
                async def _resub(conn):
                    await conn.call("subscribe",
                                    {"channels": ["worker_logs"]})
                self.gcs.on_reconnect = _resub
                self.io.run(self.gcs.call("subscribe",
                                          {"channels": ["worker_logs"]}))
        elif job_id is not None:
            self.job_id = job_id
        self.connected = True

        # periodic drain of GC-deferred ref removals (ObjectRef.__del__
        # only enqueues — see ReferenceCounter.drain_deferred)
        async def _drain_loop():
            while self.connected:
                await asyncio.sleep(1.0)
                try:
                    self.reference_counter.drain_deferred()
                except Exception:
                    pass
        self.io.run_async(_drain_loop())
        _global_worker = self

    def disconnect(self):
        # flush deferred decrements BEFORE teardown: the borrow_del/free
        # notifies for refs dropped in the last drain interval must still
        # reach their owners or they leak cluster-wide
        try:
            self.reference_counter.drain_deferred()
        except Exception:
            pass
        # ship the last task-event + trace-span batches while the GCS
        # link still lives, then stop the background flusher threads —
        # _flusher_started flags never reset, so without the stop every
        # init/shutdown cycle (tests reconnect dozens of times) leaked
        # one timeline/tracing thread per cycle
        try:
            tev.flush_all(timeout=1.0)
        except Exception:
            pass
        try:
            from ray_tpu._private import tracing
            tracing.flush_all(timeout=1.0)
            tracing.stop_flusher()
        except Exception:
            pass
        try:
            from ray_tpu.util import timeline
            timeline.stop_flusher()
        except Exception:
            pass
        self.connected = False
        # native direct lane: stop the lane/delivery threads and free
        # the pumps before the io loop (their fallback resubmits and
        # lease releases ride it)
        dc, self._direct_client = self._direct_client, None
        if dc is not None:
            try:
                dc.close()
            except Exception:
                pass
        ds, self._direct_server = self._direct_server, None
        if ds is not None:
            try:
                ds.close()
            except Exception:
                pass
        self.direct_address = ""
        self.direct_tcp_address = ""
        # compiled-DAG channels: close the listener + stage sockets and
        # free the plasmax ring slots before the store goes away
        ep = getattr(self, "_dag_endpoint", None)
        if ep is not None:
            self._dag_endpoint = None
            try:
                ep.close()
            except Exception:
                pass
        if self._server is not None:
            self._server.close()
        if self.io is not None:
            self.io.stop()

    # -------------------------------------------------------------- plumbing

    def _handlers(self):
        return {
            "task_result": self._h_task_result,
            "task_failed": self._h_task_failed,
            "task_dispatch_status": self._h_task_dispatch_status,
            "task_dispatch_status_batch": self._h_task_dispatch_status_batch,
            "revoke_lease": self._h_revoke_lease,
            "push_task": self._h_push_task,
            "leased_task": self._h_leased_task,
            "become_actor": self._h_become_actor,
            "actor_call": self._h_actor_call,
            "cancel_task": self._h_cancel_task,
            "wait_object": self._h_wait_object,
            "borrow_add": self._h_borrow_add,
            "borrow_del": self._h_borrow_del,
            "exit_worker": self._h_exit_worker,
            "preemption_notice": self._h_preemption_notice,
            "dag_channel_open": self._h_dag_channel_open,
            "dag_channel_close": self._h_dag_channel_close,
            "dag_stage_error": self._h_dag_stage_error,
            "dag_peer_down": self._h_dag_peer_down,
            "ping": self._h_ping,
            "pubsub": self._h_pubsub,
            "dump_stacks": self._h_dump_stacks,
            "profile_worker": self._h_profile_worker,
        }

    async def _h_dump_stacks(self, payload, conn):
        """Live stack snapshot of every thread in this process
        (reference: dashboard/modules/reporter/profile_manager.py —
        py-spy there; faulthandler-style sys._current_frames here, no
        external tooling needed)."""
        import traceback as _tb
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        parts = []
        for tid, frame in frames.items():
            parts.append(
                f"--- thread {names.get(tid, '?')} ({tid}) ---\n"
                + "".join(_tb.format_stack(frame)))
        return {"pid": os.getpid(), "worker_id": self.worker_id.hex(),
                "current_task": self.current_task_id.hex()
                if self.current_task_id else None,
                "stacks": "\n".join(parts),
                # actor-call ordering state: dispatched watermark and any
                # parked out-of-order seqs per caller (a stuck parked seq
                # here is the first thing to look for in a wedge)
                "actor_seq": dict(self._actor_seq),
                "parked_seqs": {c: sorted(m) for c, m in
                                self._actor_waiting.items() if m}}

    async def _h_profile_worker(self, payload, conn):
        """Timed SAMPLING profile of this process -> folded stacks
        (flamegraph-collapsed format, speedscope-importable).
        Reference: dashboard/modules/reporter/profile_manager.py (py-spy
        there; a sys._current_frames sampler here — no external tools).
        The sampler runs on an executor thread so the io loop keeps
        serving while the profile is taken."""
        duration = min(float(payload.get("duration_s") or 2.0), 30.0)
        interval = max(0.001, float(payload.get("interval_s") or 0.01))

        def _sample():
            import collections
            folded: collections.Counter = collections.Counter()
            me = threading.get_ident()
            end = time.monotonic() + duration
            n = 0
            while time.monotonic() < end:
                for tid, frame in sys._current_frames().items():
                    if tid == me:
                        continue
                    stack = []
                    f = frame
                    while f is not None:
                        code = f.f_code
                        stack.append(
                            f"{code.co_name}@"
                            f"{os.path.basename(code.co_filename)}:"
                            f"{f.f_lineno}")
                        f = f.f_back
                    folded[";".join(reversed(stack))] += 1
                n += 1
                time.sleep(interval)
            return folded, n

        folded, n = await asyncio.get_running_loop().run_in_executor(
            None, _sample)
        # report the RAYLET-REGISTRY worker id (the one
        # profile_flamegraph(worker_id=...) filters by), not the
        # process's random uid
        return {"pid": os.getpid(),
                "worker_id": os.environ.get("RTPU_WORKER_ID")
                or self.worker_id.hex(),
                "samples": n, "duration_s": duration,
                "folded": "\n".join(f"{k} {v}"
                                    for k, v in folded.most_common())}

    async def _h_pubsub(self, payload, conn):
        """GCS pubsub push. Drivers mirror 'worker_logs' lines to their own
        stdout/stderr (reference: log_monitor → print_logs in worker.py);
        obj:* channels wake waiters blocked on an object's location."""
        channel = payload.get("channel") or ""
        if channel.startswith("obj:"):
            with self._obj_channel_lock:
                ent = self._obj_channels.get(channel[4:])
            if ent is not None:
                ent[0].set()
            return {}
        if channel != "worker_logs" or not self.log_to_driver:
            return {}
        msg = payload.get("message") or {}
        job = msg.get("job_id")
        if job and job != self.job_id.hex():
            return {}
        stream = sys.stderr if msg.get("is_err") else sys.stdout
        prefix = f"({msg.get('worker_id', '?')} pid={msg.get('pid', '?')})"
        for line in msg.get("lines", ()):
            print(f"{prefix} {line}", file=stream, flush=True)
        return {}

    async def _handle_request(self, method, payload, conn):
        fn = self._handlers().get(method)
        if fn is None:
            raise protocol.RpcError(f"core worker: no method {method}")
        return await fn(payload, conn)

    async def _peer(self, address: str) -> protocol.Connection:
        return await protocol.single_flight_connect(
            self._peer_conns, self._peer_pending, address,
            lambda a: protocol.connect(a, handler=self._handle_request))

    def prepare_runtime_env(self, runtime_env):
        """Upload local working_dir/py_modules to GCS KV, rewriting the env
        to content-addressed URIs (reference: packaging.py upload). Cached
        per env-json so repeated submits don't re-zip."""
        if not runtime_env:
            return runtime_env
        import json as _json
        from ray_tpu._private import runtime_env as renv
        # key includes a content fingerprint of local dirs so edits between
        # submits re-upload (interactive/notebook drivers)
        prints = []
        wd = runtime_env.get("working_dir")
        if isinstance(wd, str) and os.path.isdir(wd):
            prints.append(renv.dir_fingerprint(wd))
        for m in runtime_env.get("py_modules") or ():
            if isinstance(m, str) and os.path.exists(m):
                prints.append(renv.dir_fingerprint(m))
        key = _json.dumps([runtime_env, prints], sort_keys=True, default=str)
        cached = self._prepared_envs.get(key)
        if cached is not None:
            return cached

        def _kv_put(k: str, v: bytes):
            self.call_sync(self.gcs, "kv_put", {"key": k, "value": v})

        prepared = renv.upload_local_paths(runtime_env, _kv_put)
        self._prepared_envs[key] = prepared
        return prepared

    def try_notify(self, address: str, method: str, payload):
        """Fire-and-forget from any thread."""
        if self.io is None:
            return

        async def _go():
            try:
                conn = await self._peer(address)
                await conn.notify(method, payload)
            except Exception:
                pass
        try:
            self.io.run_async(_go())
        except Exception:
            pass

    def call_sync(self, conn: protocol.Connection, method: str, payload,
                  timeout=None):
        return self.io.run(conn.call(method, payload, timeout=timeout))

    # ------------------------------------------------------------------- put

    def next_put_id(self) -> ObjectID:
        with self._put_lock:
            self._put_counter += 1
            idx = self._put_counter
        task_id = self.current_task_id or TaskID.for_driver(self.job_id)
        return ObjectID.for_put(task_id, idx)

    def put_object(self, value: Any, owner_ref: Optional[ObjectRef] = None
                   ) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("Calling put() on an ObjectRef is not allowed")
        self.reference_counter.drain_deferred()
        oid = self.next_put_id()
        ser = serialization.serialize(value)
        self._store_serialized(oid, ser)
        self.reference_counter.add_owned(
            oid, in_plasma=ser.total_size > self.config.max_inline_object_size)
        return ObjectRef(oid, self.address)

    def _plasma_create_with_spill(self, oid: ObjectID, size: int):
        """plasma create with spill backpressure: a full store asks the
        raylet to spill cold primaries to disk and retries (reference:
        create_request_queue.cc retry-after-spill semantics)."""
        from ray_tpu.exceptions import ObjectStoreFullError
        attempts = 3
        for i in range(attempts):
            try:
                # last attempt (spilling couldn't make room) may overflow
                # into the disk-backed fallback segment — reference
                # plasma's spill-then-fallback ordering
                return self.plasma.create(
                    oid, size, allow_fallback=(i == attempts - 1))
            except ObjectStoreFullError:
                if self.raylet is None or i == attempts - 1:
                    raise
                try:
                    self.call_sync(self.raylet, "request_spill",
                                   {"bytes_needed": size}, timeout=30)
                except Exception:
                    raise ObjectStoreFullError(
                        f"store full and spill request failed for {oid}")

    def _store_serialized(self, oid: ObjectID, ser) -> Dict[str, Any]:
        """Store a SerializedObject; returns a result descriptor."""
        if ser.total_size <= self.config.max_inline_object_size:
            payload = ser.to_bytes()
            self.memory_store.put(oid, payload)
            return {"object_id": oid.hex(), "inline": payload,
                    "owner": self.address}
        buf = self._plasma_create_with_spill(oid, ser.total_size)
        ser.write_into(buf)
        buf.release()
        self.plasma.seal(oid)
        # pin the primary copy at this node's raylet + publish location
        if self.raylet is not None:
            try:
                self.call_sync(self.raylet, "pin_object",
                               {"object_id": oid.hex(), "owner": self.address})
            except Exception:
                pass
        return {"object_id": oid.hex(), "plasma": True, "node_id": self.node_id,
                "owner": self.address}

    def free_plasma(self, oids: List[ObjectID]):
        """Fire-and-forget: may be called from ANY thread, including the IO
        loop itself (refcounts hit zero inside result handlers), so this must
        never block on the loop."""
        if self.raylet is None or self.io is None:
            return

        async def _go():
            try:
                await self.raylet.call(
                    "free_objects", {"object_ids": [o.hex() for o in oids]})
            except Exception:
                pass
        try:
            self.io.run_async(_go())
        except Exception:
            pass

    # ------------------------------------------------------------------- get

    def get_objects(self, refs: List[ObjectRef],
                    timeout: Optional[float] = None) -> List[Any]:
        self.reference_counter.drain_deferred()
        deadline = None if timeout is None else time.monotonic() + timeout
        return [self._get_one(ref, deadline) for ref in refs]

    def _remaining(self, deadline) -> Optional[float]:
        if deadline is None:
            return None
        return max(0.0, deadline - time.monotonic())

    def _get_one(self, ref: ObjectRef, deadline) -> Any:
        oid = ref.id()
        recovery_attempts = 0
        while True:
            # 1. in-process memory store
            payload = self.memory_store.get(oid)
            if payload is not None:
                try:
                    return self._deserialize_payload(oid, payload)
                except exc.ObjectLostError:
                    # stale descriptor: the node holding the primary died.
                    # Drop it and fall through to recovery — if we own the
                    # object, lineage reconstruction resubmits the creating
                    # task (reference: object_recovery_manager.cc). Lineage
                    # re-execution assumes idempotent tasks, same as the
                    # reference's ownership model. Attempts are bounded so a
                    # persistently failing fetch path can't re-execute the
                    # task forever.
                    recovery_attempts += 1
                    if recovery_attempts > 3:
                        raise
                    self.memory_store.delete(oid)
                    if self.mode == MODE_DRIVER or not ref.owner_address() \
                            or ref.owner_address() == self.address:
                        self._maybe_reconstruct(oid)
                    if deadline is not None and \
                            self._remaining(deadline) <= 0:
                        raise exc.GetTimeoutError(
                            f"get() timed out during recovery of {oid}")
                    continue
            # 2. a task WE submitted that is still in flight: wait for
            # completion before probing plasma — the sync-get hot path
            # was paying two ctypes store probes per wait loop for an
            # object that cannot be sealed yet
            state = self.pending_tasks.get(oid.task_id().hex())
            if state is not None and not state.done:
                if not self._resolve_remote(ref, deadline):
                    raise exc.GetTimeoutError(
                        f"get() timed out waiting for {oid}")
                continue
            # 3. local plasma
            buf = self.plasma.get_buffer(oid)
            if buf is not None:
                return self._deserialize_plasma(oid, buf)
            # 4. ask the owner / locate
            if not self._resolve_remote(ref, deadline):
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {oid}")

    def _deserialize_payload(self, oid: ObjectID, payload: bytes) -> Any:
        value = serialization.deserialize(payload)
        if isinstance(value, _PlasmaIndirect):
            # owner sent us a descriptor: the real value sits in plasma
            self._ensure_local_plasma(oid)
            buf = self.plasma.get_buffer(oid)
            if buf is None:
                raise exc.ObjectLostError(oid)
            self.memory_store.delete(oid)
            return self._deserialize_plasma(oid, buf)
        return value

    def _ensure_local_plasma(self, oid: ObjectID) -> None:
        """Bring a plasma object referenced by a descriptor to this node.

        The descriptor (_PlasmaIndirect) names the node holding the primary;
        the local raylet pulls it chunk-wise (reference: object directory +
        PullManager; here raylet.handle_fetch_object)."""
        try:
            self._fetch_via_raylet(oid)
        except Exception as e:
            raise exc.ObjectLostError(
                oid, f"primary copy unreachable: {e}") from e

    def _deserialize_plasma(self, oid: ObjectID, buf) -> Any:
        try:
            value = serialization.deserialize(buf)
        except BaseException:
            buf.release()
            self.plasma.release(oid)
            raise
        # zero-copy values keep the store slot alive until GC'd
        try:
            weakref.finalize(value, _release_plasma, self.plasma, oid, buf)
        except TypeError:
            # not weakref-able: value cannot reference the buffer (envelope
            # copies scalars), safe to release now
            buf.release()
            self.plasma.release(oid)
        return value

    def _resolve_remote(self, ref: ObjectRef, deadline) -> bool:
        """Pull the object toward this process. True if progress was made."""
        oid = ref.id()
        owner = ref.owner_address()
        timeout = self._remaining(deadline)
        step = min(timeout, 2.0) if timeout is not None else 2.0
        if owner and owner != self.address:
            try:
                conn = self.io.run(self._peer(owner))
                r = self.call_sync(conn, "wait_object",
                                   {"object_id": oid.hex(), "timeout": step},
                                   timeout=step + 5)
            except Exception:
                r = None
            if r and r.get("ready"):
                if r.get("inline") is not None:
                    self.memory_store.put(oid, r["inline"])
                    return True
                # plasma object on some node: fetch into local store
                self._fetch_via_raylet(oid)
                return True
            if r is not None and not r.get("ready"):
                if r.get("lost"):
                    raise exc.ObjectLostError(oid, r.get("reason", ""))
                if timeout is not None and timeout <= 0:
                    return False
                return True  # keep waiting
            # owner unreachable
            if self._try_locations(oid):
                return True
            raise exc.ObjectLostError(
                oid, "owner is unreachable and no copies are registered "
                     "(owner failure is fatal for its objects, as in the "
                     "reference ownership model)")
        # we are the owner (or owner unknown): wait on local delivery
        state = self.pending_tasks.get(oid.task_id().hex())
        if state is not None and not state.done:
            dc = self._direct_client
            if dc is not None and state.direct and not dc._closed:
                # direct-lane task: reap the reply on THIS thread (the
                # getter pumps the native reactor; no delivery-thread
                # handoff on the sync path)
                dc.reap_result(state, step)
            else:
                state.result_event.wait(step)
            return timeout is None or self._remaining(deadline) > 0
        if self.memory_store.contains(oid) or self.plasma.contains(oid):
            return True
        if self._try_locations(oid):
            return True
        if self.mode == MODE_WORKER or not ref.owner_address():
            # borrower without owner info: long-poll the object channel
            # (reference: GCS pubsub object channels /
            # WORKER_OBJECT_LOCATIONS_CHANNEL) — subscribe, re-check
            # the directory to close the subscribe/add race, then block
            # on the notification instead of a poll loop
            ev = self._subscribe_object_channel(oid)
            try:
                if self._try_locations(oid):
                    return True
                ev.wait(step)
            finally:
                self._unsubscribe_object_channel(oid)
            return timeout is None or timeout > 0
        return self._maybe_reconstruct(oid)

    def _subscribe_object_channel(self, oid: ObjectID) -> threading.Event:
        """Subscribe to the per-object location channel; returns the
        event its pubsub notification sets. Refcounted: concurrent
        waiters on one object share a subscription."""
        hex_id = oid.hex()
        with self._obj_channel_lock:
            ent = self._obj_channels.get(hex_id)
            if ent is not None:
                ent[1] += 1
                return ent[0]
            ev = threading.Event()
            self._obj_channels[hex_id] = [ev, 1]
        try:
            self.call_sync(self.gcs, "subscribe",
                           {"channels": [f"obj:{hex_id}"]}, timeout=10)
        except Exception:
            pass  # degrade to the timed wait; re-check loop still runs
        return ev

    def _unsubscribe_object_channel(self, oid: ObjectID):
        hex_id = oid.hex()
        with self._obj_channel_lock:
            ent = self._obj_channels.get(hex_id)
            if ent is None:
                return
            ent[1] -= 1
            if ent[1] > 0:
                return
            self._obj_channels.pop(hex_id, None)
        try:
            self.io.run_async(self.gcs.call(
                "unsubscribe", {"channels": [f"obj:{hex_id}"]}))
        except Exception:
            pass

    def _try_locations(self, oid: ObjectID) -> bool:
        try:
            r = self.call_sync(self.gcs, "get_object_locations",
                               {"object_id": oid.hex()})
        except Exception:
            return False
        if r.get("locations"):
            self._fetch_via_raylet(oid)
            return True
        return False

    def _fetch_via_raylet(self, oid: ObjectID):
        if self.plasma.contains(oid):
            return
        if self.raylet is None:
            raise exc.ObjectLostError(oid, "no raylet to fetch through")
        # object-plane transfer span: a cross-node pull is the slow path
        # (chunked raylet↔raylet copy), exactly what latency attribution
        # must see; local hits returned above without touching tracing
        from ray_tpu._private import tracing
        cur = self._current_trace() if tracing.enabled() else None
        sp = tracing.span_if(cur and cur.get("trace_id"),
                             f"object.pull:{oid.hex()[:12]}",
                             parent_span_id=cur and cur.get("span_id"),
                             kind="object.pull", phase="transfer",
                             attrs={"object_id": oid.hex()})
        try:
            self.call_sync(self.raylet, "fetch_object",
                           {"object_id": oid.hex()})
        except BaseException:
            if sp is not None:
                sp.finish("error")
            raise
        if sp is not None:
            sp.finish()

    def _maybe_reconstruct(self, oid: ObjectID) -> bool:
        """Lineage reconstruction: resubmit the creating task (reference:
        object_recovery_manager.h RecoverObject → TaskManager::ResubmitTask)."""
        state = self.pending_tasks.get(oid.task_id().hex())
        if state is not None and not state.done:
            return True  # a resubmit is already in flight
        spec = self.reference_counter.get_lineage(oid)
        if spec is None:
            raise exc.ObjectLostError(oid, "no lineage recorded")
        logger.warning("reconstructing %s via lineage resubmit", oid)
        self.submit_spec(spec, reconstruction=True)
        return True

    # ------------------------------------------------------------------ wait

    def wait(self, refs: List[ObjectRef], num_returns: int,
             timeout: Optional[float]) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectRef] = []
        pending = list(refs)
        while len(ready) < num_returns:
            still = []
            for ref in pending:
                # cap at num_returns (reference ray.wait semantics):
                # extras stay pending for the next call
                if len(ready) < num_returns and self._is_ready(ref):
                    ready.append(ref)
                else:
                    still.append(ref)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.005)
        return ready, pending

    def _is_ready(self, ref: ObjectRef) -> bool:
        oid = ref.id()
        if self.memory_store.contains(oid) or self.plasma.contains(oid):
            return True
        state = self.pending_tasks.get(oid.task_id().hex())
        if state is not None:
            return state.done
        owner = ref.owner_address()
        if owner and owner != self.address:
            try:
                conn = self.io.run(self._peer(owner))
                r = self.call_sync(conn, "wait_object",
                                   {"object_id": oid.hex(), "timeout": 0},
                                   timeout=5)
                return bool(r.get("ready"))
            except Exception:
                return False
        return False

    # ------------------------------------------------------------ submit task

    def _shared_spec_fields(self, fn_key: str, fn_name: str,
                            opts: Dict[str, Any]) -> Dict[str, Any]:
        """Spec fields identical for every invocation of a function
        under one options set — the single source shared by the unary
        and batched submission paths (they must never drift)."""
        from ray_tpu.common.options import resource_dict_from_options
        num_returns = opts.get("num_returns")
        if num_returns is None:
            num_returns = 1
        spec = {
            "fn_key": fn_key,
            "fn_name": fn_name,
            "num_returns": num_returns,
            "owner_address": self.address,
            "job_id": self.job_id.hex(),
            "resources": resource_dict_from_options(opts, is_actor=False),
            "max_retries": opts.get("max_retries",
                                    self.config.task_max_retries_default),
        }
        # optional fields ride the wire only when set (every consumer
        # reads them with .get): at thousands of tasks/s the empty
        # runtime_env/scheduling/placement_group/retry_exceptions keys
        # were measurable pack+unpack weight on each leased frame
        runtime_env = self.prepare_runtime_env(opts.get("runtime_env"))
        if runtime_env:
            spec["runtime_env"] = runtime_env
        scheduling = self._scheduling_from_opts(opts)
        if scheduling:
            spec["scheduling"] = scheduling
        pg = self._pg_from_opts(opts)
        if pg is not None:
            spec["placement_group"] = pg
        if opts.get("retry_exceptions"):
            spec["retry_exceptions"] = True
        return spec

    def submit_task(self, fn_key: str, fn_name: str, args, kwargs,
                    opts: Dict[str, Any]) -> List[ObjectRef]:
        task_id = TaskID.for_task(self.current_task_id
                                  or TaskID.for_driver(self.job_id))
        arg_blob, plasma_deps, arg_refs = self._serialize_args(args, kwargs)
        # shared fields are identical for every call of one function
        # under one options dict — cache them (hot unary path; a
        # runtime_env opts set is excluded: its content fingerprint of
        # local dirs must be recomputed per submit)
        cached = self._shared_spec_cache.get(fn_key)
        if cached is not None and cached[0] == opts:
            shared = cached[1]
        else:
            shared = self._shared_spec_fields(fn_key, fn_name, opts)
            if not opts.get("runtime_env"):
                self._shared_spec_cache[fn_key] = (dict(opts), shared)
        spec = dict(shared, task_id=task_id.hex(), args=arg_blob,
                    plasma_deps=plasma_deps, arg_refs=arg_refs)
        return self.submit_spec(spec)

    def submit_task_batch(self, fn_key: str, fn_name: str, arg_tuples,
                          opts: Dict[str, Any]) -> List[List[ObjectRef]]:
        """Bulk submission fast path: shared spec fields are computed
        once, per-task work is only arg serialization + IDs + ownership,
        and the whole batch rides submit_task_batch RPCs. This is the
        >=10k tasks/s path of the scale envelope (reference:
        release/benchmarks/README.md:11; the reference reaches its rates
        the same way — amortizing per-task overhead across a batch)."""
        parent = self.current_task_id or TaskID.for_driver(self.job_id)
        shared = self._shared_spec_fields(fn_key, fn_name, opts)
        num_returns = shared["num_returns"]
        batch = []
        out: List[List[ObjectRef]] = []
        add_owned = self.reference_counter.add_owned
        for item in arg_tuples:
            # each item is a tuple of positional args (kwargs: use the
            # unary path — batch submission keeps the hot loop lean)
            arg_blob, plasma_deps, arg_refs = self._serialize_args(
                tuple(item), {})
            task_id = TaskID.for_task(parent)
            spec = dict(shared, task_id=task_id.hex(), args=arg_blob,
                        plasma_deps=plasma_deps, arg_refs=arg_refs,
                        trace_ctx=self._trace_ctx_for_submit())
            return_ids = [ObjectID.for_return(task_id, i)
                          for i in range(num_returns)]
            state = PendingTaskState(spec, spec["max_retries"], return_ids)
            self.pending_tasks[spec["task_id"]] = state
            for oid in return_ids:
                add_owned(oid, lineage=spec)
            tev.emit(spec["task_id"], tev.PENDING_SCHEDULING,
                     name=spec.get("fn_name"), job_id=spec.get("job_id"))
            batch.append((spec, state))
            out.append([ObjectRef(oid, self.address) for oid in return_ids])
        with self._submit_lock:
            self._submit_buf.extend(batch)
            scheduled = self._submit_flush_scheduled
            self._submit_flush_scheduled = True
        if not scheduled:
            self.io.call_soon(self._spawn_submit_flush)
        return out

    # ---- tracing: span propagation through task specs (reference:
    # util/tracing/tracing_helper.py:160 _DictPropagator — the context
    # rides the TaskSpec; here it lands in the chrome timeline args so
    # `ray-tpu timeline` reconstructs the driver→task→child tree) ----

    def _current_trace(self) -> Dict[str, str]:
        ctx = getattr(self.task_context, "trace", None)
        if ctx:
            return ctx
        if not hasattr(self, "_root_trace"):
            self._root_trace = {"trace_id": os.urandom(8).hex(),
                                "span_id": "root"}
        return self._root_trace

    def _trace_ctx_for_submit(self) -> Dict[str, str]:
        cur = self._current_trace()
        return {"trace_id": cur["trace_id"],
                "span_id": os.urandom(8).hex(),  # one urandom per submit
                "parent_span_id": cur["span_id"]}

    def submit_spec(self, spec, reconstruction: bool = False) -> List[ObjectRef]:
        if "trace_ctx" not in spec:
            spec["trace_ctx"] = self._trace_ctx_for_submit()
        task_id = TaskID(bytes.fromhex(spec["task_id"]))
        num_returns = spec["num_returns"]
        return_ids = [ObjectID.for_return(task_id, i)
                      for i in range(num_returns)]
        state = PendingTaskState(spec, spec.get("max_retries", 0), return_ids)
        state.attempt = int(spec.get("attempt") or 0)
        self.pending_tasks[spec["task_id"]] = state
        for oid in return_ids:
            self.reference_counter.add_owned(oid, lineage=spec)
        tev.emit(spec["task_id"], tev.PENDING_SCHEDULING,
                 name=spec.get("fn_name"), job_id=spec.get("job_id"),
                 attempt=state.attempt or None)
        if reconstruction:
            # the original submission's counts were already removed on the
            # first completion; count the resubmit's arg refs again
            for hex_ref, _owner in spec.get("arg_refs", []):
                self.reference_counter.add_submitted(ObjectID.from_hex(hex_ref))

        if not self._try_leased_submit(spec, state):
            self._enqueue_submit(spec, state)
        refs = [ObjectRef(oid, self.address) for oid in return_ids]
        return refs

    # ---- worker leases: direct owner->worker pushes (reference:
    # src/ray/core_worker/transport/normal_task_submitter.cc — the
    # reference's normal-task path IS lease-based; this recovers it as a
    # fast lane beside the GCS-routed default, cutting a no-dep CPU task
    # from 6 messages across 3 processes to 2 messages total) ----

    _LEASE_IDLE_RELEASE_S = 2.0
    _LEASE_RETRY_COOLDOWN_S = 5.0
    # the pool grows until the raylet denies the lease (LEASE_UNAVAILABLE),
    # so its size naturally tracks node capacity; the cap is a sanity bound
    _LEASE_POOL_MAX = 16
    _LEASE_MAX_WAITERS = 512

    def _lease_qualifies(self, spec) -> bool:
        # plain CPU-only demands: custom resources imply placement on
        # specific nodes (the local raylet may not even have them) and
        # TPU chips are granted per task
        return (not spec.get("plasma_deps")
                and not spec.get("runtime_env")
                and not spec.get("placement_group")
                and not spec.get("scheduling")
                and not spec.get("spilled_from")
                and all(k == "CPU"
                        for k in (spec.get("resources") or {})))

    def _try_leased_submit(self, spec, state) -> bool:
        """Caller-thread side: only qualification + cheap reads happen
        here.  ALL lease state (pool, waiters, inflight) is mutated on
        the io thread — a caller-thread append racing the io-side drain
        silently orphaned parked tasks (round-5 review finding)."""
        if not self._lease_qualifies(spec):
            return False
        dc = self._direct_client
        if dc is not None and dc.usable():
            # the native lane owns leasing for this process: when it
            # declines (lease denied recently, parked queue overflow)
            # the task goes to the BATCHED raylet path — never to the
            # asyncio lease pool, which would build a second pool
            # competing for the same node capacity and thrash the
            # raylet's lease-revoke logic
            return dc.submit(spec, state)
        key = tuple(sorted((spec.get("resources") or {}).items()))
        pool = self._worker_leases.get(key)
        if not pool and time.monotonic() - self._lease_fail_at.get(
                key, 0.0) <= self._LEASE_RETRY_COOLDOWN_S:
            return False  # leasing recently denied — normal path
        self.io.call_soon(self._park_lease_waiter, key, spec, state)
        return True

    def cancel_leased_task(self, task_id: str):
        """Cancel a task the raylet never saw: drop it from the parked
        waiters, or send cancel_task straight to the leased worker it
        was pushed to (runs the io-side work on the io thread)."""
        state = self.pending_tasks.get(task_id)
        if state is None or state.done:
            return
        self.io.call_soon(self._cancel_leased_io, task_id, state)

    def _resolve_cancelled(self, task_id, state):
        """Resolve a never-dispatched task as cancelled (refs get the
        TaskCancelledError envelope, the state table goes terminal)."""
        err = exc.TaskCancelledError(task_id)
        ser = serialization.serialize_error(err)
        for oid in state.return_ids:
            self.memory_store.put(oid, ser.to_bytes())
        tev.emit(task_id, tev.FAILED,
                 name=state.spec.get("fn_name"),
                 job_id=state.spec.get("job_id"),
                 error="CANCELLED: never dispatched")
        state.done = True
        state.result_event.set()
        self.pending_tasks.pop(task_id, None)

    def _cancel_leased_io(self, task_id, state):
        dc = self._direct_client
        if dc is not None and dc.cancel(task_id, state):
            return
        for key, waiters in list(self._lease_waiters.items()):
            for item in waiters:
                if item[0]["task_id"] == task_id:
                    waiters.remove(item)
                    self._resolve_cancelled(task_id, state)
                    return
        if state.worker_address:
            async def _send():
                try:
                    conn = await self._peer(state.worker_address)
                    await conn.notify("cancel_task", {"task_id": task_id})
                except Exception:
                    pass  # worker gone — the task is dead anyway
            protocol.spawn(_send())

    def _park_lease_waiter(self, key, spec, state):
        """io thread: grow the pool if useful, park the task, drain."""
        pool = self._worker_leases.get(key)
        if pool is None:
            pool = []
            self._worker_leases[key] = pool
        best = None
        acquiring = False
        for L in pool:
            if L.acquiring:
                acquiring = True
            elif L.addr is not None and (best is None
                                         or L.inflight < best.inflight):
                best = L
        # grow when empty or saturated (each lease is one serial worker;
        # grow-until-denied sizes the pool to node capacity)
        if (best is None or best.inflight >= 2) \
                and len(pool) < self._LEASE_POOL_MAX and not acquiring:
            if time.monotonic() - self._lease_fail_at.get(key, 0.0) > \
                    self._LEASE_RETRY_COOLDOWN_S:
                L = _LeaseState(key)
                pool.append(L)
                protocol.spawn(self._acquire_lease(
                    L, dict(spec.get("resources") or {})))
        waiters = self._lease_waiters.setdefault(key, [])
        if len(waiters) >= self._LEASE_MAX_WAITERS:
            self._enqueue_submit(spec, state)  # overflow: batched path
            return
        waiters.append((spec, state))
        self._drain_lease_waiters(key)

    async def _acquire_lease(self, L, resources):
        try:
            r = await self.raylet.call("lease_worker",
                                       {"resources": resources})
        except Exception as e:  # noqa: BLE001
            r = {"error": "LEASE_RPC_FAILED", "message": str(e)}
        L.acquiring = False
        if r.get("error"):
            self._lease_fail_at[L.key] = time.monotonic()
            pool = self._worker_leases.get(L.key)
            if pool and L in pool:
                pool.remove(L)
            self._drain_lease_waiters(L.key)
            return
        L.lease_id = r["lease_id"]
        L.addr = r["worker_address"]
        L.last_used = time.monotonic()
        self.io.loop.call_later(self._LEASE_IDLE_RELEASE_S,
                                self._lease_idle_check, L)
        self._drain_lease_waiters(L.key)

    def _drain_lease_waiters(self, key):
        """Route parked tasks (io thread only).  Feed ready leases up to
        their pipeline depth; keep the rest parked while an acquisition
        is in flight or any lease exists (completions re-drain); flush
        to the normal path only when the pool is gone."""
        waiters = self._lease_waiters.get(key)
        if not waiters:
            return
        pool = self._worker_leases.get(key) or []
        ready = [L for L in pool if L.addr is not None]
        if not ready:
            if any(L.acquiring for L in pool):
                return  # stay parked; the acquisition settles the drain
            self._lease_waiters.pop(key, None)
            for spec, state in waiters:
                self._enqueue_submit(spec, state)
            return
        while waiters:
            L = min(ready, key=lambda x: x.inflight)
            if L.inflight >= L.MAX_INFLIGHT:
                break  # completions call back into this drain
            spec, state = waiters.pop(0)
            L.inflight += 1
            L.last_used = time.monotonic()
            protocol.spawn(self._leased_call(L, spec, state))
        if not waiters:
            self._lease_waiters.pop(key, None)

    def _lease_idle_check(self, L):
        """Release an idle lease so it stops pinning cluster capacity."""
        if L.addr is None:
            return
        idle = time.monotonic() - L.last_used
        if L.inflight or idle < self._LEASE_IDLE_RELEASE_S:
            self.io.loop.call_later(
                max(0.2, self._LEASE_IDLE_RELEASE_S - idle),
                self._lease_idle_check, L)
            return
        self._drop_lease(L, release=True)

    def _drop_lease(self, L, release: bool = False):
        lease_id, L.lease_id, L.addr = L.lease_id, None, None
        pool = self._worker_leases.get(L.key)
        if pool and L in pool:
            pool.remove(L)
        self._drain_lease_waiters(L.key)  # re-route or flush parked tasks
        if release and lease_id is not None:
            async def _rel():
                try:
                    await self.raylet.call("release_lease",
                                           {"lease_id": lease_id})
                except Exception:
                    pass  # raylet-side conn cleanup is the backstop
            protocol.spawn(_rel())

    async def _leased_call(self, L, spec, state):
        state.worker_address = L.addr
        try:
            conn = await self._peer(L.addr)
            reply = await conn.call("leased_task", {"spec": spec})
        except Exception:
            # lease broken (worker died / revoked / dial failed): drop
            # it — WITH a release RPC, which is idempotent raylet-side
            # and reclaims the resources when only the owner->worker
            # dial was at fault — and fall back to the normal path
            # (at-least-once, same as the task-retry contract)
            L.inflight -= 1
            self._drop_lease(L, release=True)
            state.worker_address = None  # else _fail_pending skips it
            self._enqueue_submit(spec, state)
            return
        L.inflight -= 1
        L.last_used = time.monotonic()
        if L.revoked and L.inflight == 0:
            self._ack_revoked_lease(L)
        self._drain_lease_waiters(L.key)
        await self._h_task_result(reply, None)

    # Micro-batched submission: specs enqueued between IO-loop ticks ride
    # ONE submit_task_batch RPC (reference gets its tasks/s the same way —
    # batched TaskSpec pushes). Dispatch failures come back as
    # task_dispatch_status notifies handled by _h_task_dispatch_status.
    _SUBMIT_BATCH_MAX = 2000

    def _enqueue_submit(self, spec, state):
        with self._submit_lock:
            self._submit_buf.append((spec, state))
            if self._submit_flush_scheduled:
                return
            self._submit_flush_scheduled = True
        self.io.call_soon(self._spawn_submit_flush)

    def _spawn_submit_flush(self):
        from ray_tpu._private.protocol import spawn
        spawn(self._flush_submits())

    async def _flush_submits(self):
        while True:
            with self._submit_lock:
                batch = self._submit_buf[:self._SUBMIT_BATCH_MAX]
                del self._submit_buf[:self._SUBMIT_BATCH_MAX]
                if not batch:
                    self._submit_flush_scheduled = False
                    return
            try:
                await self.raylet.call(
                    "submit_task_batch",
                    {"specs": [spec for spec, _ in batch]})
            except Exception as e:
                reply = {"error": "RAYLET_UNREACHABLE", "message": str(e)}
                for _, state in batch:
                    self._on_submit_reply(state, dict(reply))

    async def _h_task_dispatch_status(self, payload, conn):
        """Raylet-side dispatch outcome for a batched submission; feed it
        through the same retry/fatal machinery as a unary submit reply
        (success carries worker_address, errors drive retries)."""
        state = self.pending_tasks.get(payload.get("task_id"))
        if state is not None and not state.done:
            self._on_submit_reply(state, payload)
        return {}

    async def _h_revoke_lease(self, payload, conn):
        """The raylet reclaims a lease under contention: stop routing new
        tasks through it, let in-flight calls finish on the worker's
        serial queue, then ACK the drain with a release_lease carrying
        ``inflight=0`` — the raylet defers re-idling the worker until
        that ack, so it never hands the dispatch loop a worker that is
        still executing our leased tasks."""
        lease_id = payload.get("lease_id")
        dc = self._direct_client
        if dc is not None and dc.on_revoke(lease_id):
            return {}
        for pool in self._worker_leases.values():
            for L in list(pool):
                if L.lease_id == lease_id:
                    self._lease_fail_at[L.key] = time.monotonic()
                    L.revoked = True
                    if L in pool:
                        pool.remove(L)
                    L.addr = None  # stop routing; in-flight calls
                    # already hold their worker connection
                    self._drain_lease_waiters(L.key)
                    if L.inflight == 0:
                        self._ack_revoked_lease(L)
                    return {}
        return {}

    def _ack_revoked_lease(self, L):
        """io thread: the revoked lease's in-flight calls drained —
        tell the raylet (inflight=0) so it reclaims the worker."""
        lease_id, L.lease_id = L.lease_id, None
        if lease_id is None:
            return

        async def _rel():
            try:
                await self.raylet.call("release_lease",
                                       {"lease_id": lease_id,
                                        "inflight": 0})
            except Exception:
                pass  # raylet-side revoke-ack timeout is the backstop
        protocol.spawn(_rel())

    async def _h_task_dispatch_status_batch(self, payload, conn):
        """Coalesced form: one notify carrying many statuses (the raylet
        batches success statuses per flush tick)."""
        for status in payload.get("statuses") or ():
            await self._h_task_dispatch_status(status, conn)
        return {}

    def _fail_pending_submissions(self, err: str, message: str):
        """The raylet connection died: every submission not yet known to
        be dispatched (no worker_address) can neither run nor report —
        push it through the standard error path so gets don't hang.
        Runs on the io loop (connection on_close)."""
        for state in list(self.pending_tasks.values()):
            if not state.done and state.worker_address is None:
                try:
                    self._on_submit_reply(
                        state, {"error": err, "message": message})
                except Exception:
                    logger.exception("failing pending submission")

    def _on_submit_reply(self, state: PendingTaskState, reply):
        err = reply.get("error")
        if err is None:
            state.worker_address = reply.get("worker_address")
            return
        if err in ("WORKER_DIED", "WORKER_START_FAILED",
                   "OBJECT_FETCH_FAILED", "RAYLET_UNREACHABLE",
                   "NODE_DRAINING") and \
                state.retries_left != 0:
            state.retries_left -= 1
            self._bump_attempt(state)
            logger.warning("task %s failed (%s), retrying (%d left)",
                           state.spec["fn_name"], err, state.retries_left)

            async def _resub():
                if err == "NODE_DRAINING":
                    # the draining raylet spills the resubmit to a peer;
                    # a beat of backoff keeps retries from burning out
                    # before peer capacity shows up in the scheduler
                    await asyncio.sleep(0.25)
                try:
                    reply = await self.raylet.call("submit_task", state.spec)
                except Exception as e:
                    reply = {"error": "RAYLET_UNREACHABLE", "message": str(e)}
                self._on_submit_reply(state, reply)
            self.io.run_async(_resub())
            return
        # fatal: store error into all return objects
        e: Exception
        if err == "CANCELLED":
            e = exc.TaskCancelledError(state.spec["task_id"])
        elif err == "WORKER_DIED":
            e = exc.WorkerCrashedError(reply.get("message", ""))
        else:
            e = exc.RayTpuError(f"{err}: {reply.get('message', '')}")
        ser = serialization.serialize_error(e)
        payload = ser.to_bytes()
        for oid in state.return_ids:
            self.memory_store.put(oid, payload)
        for hex_ref, _ in state.spec.get("arg_refs", []):
            self.reference_counter.remove_submitted(ObjectID.from_hex(hex_ref))
        # owner-side fatal resolution (cancel, retries exhausted,
        # unreachable raylet): the task must land terminal in the
        # state table even when no worker/raylet could report it
        tev.emit(state.spec.get("task_id"), tev.FAILED,
                 name=state.spec.get("fn_name"),
                 job_id=state.spec.get("job_id"),
                 attempt=state.attempt or None,
                 error=f"{err}: {reply.get('message', '')}"[:200])
        state.done = True
        state.result_event.set()
        self.pending_tasks.pop(state.spec.get("task_id"), None)

    _SCALAR_ARG_TYPES = (type(None), bool, int, float, str, bytes)

    def _serialize_args(self, args, kwargs):
        """Serialize task args. Large arg values are promoted to plasma
        objects (implicit put) so they ride the object plane; refs are listed
        as dependencies for the executing raylet to pre-fetch."""
        if not kwargs and all(type(a) in self._SCALAR_ARG_TYPES
                              for a in args):
            # scalar fast path: an msgpack-inline envelope — no pickle,
            # no ref collection (scalars can't contain ObjectRefs), no
            # deps. serialization.deserialize takes its existing
            # "inline" branch, so the executing worker skips
            # pickle.loads too. Exact-type checks keep user containers
            # (whose tuples must survive round-trip) on the pickle path.
            try:
                import struct as _struct
                import msgpack as _msgpack
                header = _msgpack.packb({"inline": [list(args), {}],
                                         "v": 1}, use_bin_type=True)
                return (_struct.pack("<I", len(header)) + header, [], [])
            except (OverflowError, ValueError, TypeError):
                pass  # e.g. an int beyond 64-bit: take the pickle path
        promoted_args = []
        for a in args:
            promoted_args.append(self._promote_arg(a))
        promoted_kwargs = {k: self._promote_arg(v) for k, v in kwargs.items()}
        ser = serialization.serialize((promoted_args, promoted_kwargs))
        arg_refs = list(ser.contained_refs)
        # Count submitted-task references NOW, before promoted ObjectRefs can
        # be GC'd (the matching remove_submitted runs at task completion).
        for hex_ref, _owner in arg_refs:
            self.reference_counter.add_submitted(ObjectID.from_hex(hex_ref))
        plasma_deps = []
        for hex_ref, owner in arg_refs:
            oid = ObjectID.from_hex(hex_ref)
            e = self.reference_counter.table.get(oid)
            if (e and e.get("in_plasma")) or self.plasma.contains(oid):
                plasma_deps.append(hex_ref)
        return ser.to_bytes(), plasma_deps, arg_refs

    def _promote_arg(self, value):
        if isinstance(value, ObjectRef):
            return value
        try:
            import numpy as np
            if isinstance(value, np.ndarray) and \
                    value.nbytes > self.config.max_inline_object_size:
                return self.put_object(value)
        except ImportError:
            pass
        return value

    @staticmethod
    def _scheduling_from_opts(opts) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        strategy = opts.get("scheduling_strategy")
        if strategy == "SPREAD":
            out["spread"] = True
        elif strategy is not None and not isinstance(strategy, str):
            # NodeAffinitySchedulingStrategy / PlacementGroup strategy objects
            node_id = getattr(strategy, "node_id", None)
            if node_id is not None:
                out["node_id"] = node_id
                out["soft"] = getattr(strategy, "soft", False)
        if opts.get("tpu_topology"):
            out["tpu_topology"] = opts["tpu_topology"]
        return out

    @staticmethod
    def _pg_from_opts(opts) -> Optional[Dict[str, Any]]:
        strategy = opts.get("scheduling_strategy")
        pg = getattr(strategy, "placement_group", None)
        if pg is None:
            return None
        return {"pg_id": pg.id_hex,
                "bundle_index": getattr(strategy,
                                        "placement_group_bundle_index", 0)}

    # --------------------------------------------------- result delivery (owner)

    async def _h_task_result(self, payload, conn):
        self._apply_task_result(payload)
        return {}

    def _apply_task_result(self, payload):
        """Store a task's returns and wake its getters.  Thread-safe
        (memory store / refcounter / result event all take their own
        locks): the asyncio handler above and the direct lane's
        delivery thread (direct.py) both land here — the latter is what
        lets a leased round trip complete without ever scheduling onto
        the io loop."""
        task_hex = payload["task_id"]
        state = self.pending_tasks.get(task_hex)
        for ret in payload["returns"]:
            oid = ObjectID.from_hex(ret["object_id"])
            if ret.get("inline") is not None:
                self.memory_store.put(oid, ret["inline"])
            else:
                # descriptor: value lives in plasma (possibly another node)
                ind = _PlasmaIndirect(ret.get("node_id", ""))
                ser = serialization.serialize(ind)
                if not self.plasma.contains(oid):
                    self.memory_store.put(oid, ser.to_bytes())
                # the owner's table must know this ref is plasma-backed —
                # downstream tasks list it in plasma_deps (prefetch +
                # locality-aware scheduling) even when the primary copy
                # is on another node
                self.reference_counter.mark_in_plasma(oid)
        if state is not None:
            if payload.get("app_error") and state.retries_left != 0 and \
                    state.spec.get("retry_exceptions"):
                state.retries_left -= 1
                self._bump_attempt(state)
                self.io.run_async(self._retry(state))
                return
            state.done = True
            state.result_event.set()
            for hex_ref, _ in state.spec.get("arg_refs", []):
                self.reference_counter.remove_submitted(
                    ObjectID.from_hex(hex_ref))
            # terminal: drop the tracking entry (the result lives in the
            # memory store / plasma, lineage lives in the refcounter's
            # table). Without this the dict grew one spec+state per task
            # for the process lifetime — real memory AND a growing gen-2
            # GC sweep that visibly decayed sustained task throughput.
            self.pending_tasks.pop(task_hex, None)

    async def _h_task_failed(self, payload, conn):
        """The raylet reports the executing worker died mid-task."""
        state = self.pending_tasks.get(payload["task_id"])
        if state is None or state.done:
            return {}
        self._on_submit_reply(state, payload)
        return {}

    def _bump_attempt(self, state: PendingTaskState):
        """A retry restarts the task lifecycle: stamp the new attempt
        number into the spec (raylet + worker events inherit it) and
        report the transition back to PENDING_SCHEDULING."""
        state.attempt += 1
        state.spec["attempt"] = state.attempt
        tev.emit(state.spec["task_id"], tev.PENDING_SCHEDULING,
                 name=state.spec.get("fn_name"),
                 job_id=state.spec.get("job_id"), attempt=state.attempt)

    async def _retry(self, state):
        try:
            reply = await self.raylet.call("submit_task", state.spec)
        except Exception as e:
            reply = {"error": "RAYLET_UNREACHABLE", "message": str(e)}
        self._on_submit_reply(state, reply)

    async def _h_wait_object(self, payload, conn):
        """Owner-side long poll: is this object ready? (borrowers call this)"""
        oid = ObjectID.from_hex(payload["object_id"])
        timeout = payload.get("timeout", 0)
        payload_bytes = self.memory_store.get(oid)
        if payload_bytes is None and not self.plasma.contains(oid):
            state = self.pending_tasks.get(oid.task_id().hex())
            if state is not None and not state.done and timeout:
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(
                    None, state.result_event.wait, timeout)
            payload_bytes = self.memory_store.get(oid)
        if payload_bytes is not None:
            value = None
            try:
                value = serialization.deserialize(payload_bytes)
            except BaseException:
                pass  # error envelope: still ship it raw
            if isinstance(value, _PlasmaIndirect):
                return {"ready": True, "plasma": True,
                        "node_id": value.node_id}
            return {"ready": True, "inline": payload_bytes}
        if self.plasma.contains(oid):
            return {"ready": True, "plasma": True, "node_id": self.node_id}
        # the primary may have been spilled to disk by our raylet — still
        # ready; borrowers restore it via the pull path
        if self.raylet is not None:
            try:
                r = await self.raylet.call(
                    "contains_object", {"object_id": oid.hex()})
                if r.get("present"):
                    return {"ready": True, "plasma": True,
                            "node_id": self.node_id}
            except Exception:
                pass
        return {"ready": False}

    async def _h_borrow_add(self, payload, conn):
        self.reference_counter.on_borrow_add(payload["object_id"],
                                             payload["borrower"])
        return {}

    async def _h_borrow_del(self, payload, conn):
        self.reference_counter.on_borrow_del(payload["object_id"],
                                             payload["borrower"])
        return {}

    async def _h_ping(self, payload, conn):
        return {"worker_id": self.worker_id.hex(), "mode": self.mode}

    async def _h_exit_worker(self, payload, conn):
        os._exit(0)

    # ---- compiled-DAG channels (ray_tpu/dag/channel.py; schema 1.5) ----

    async def _h_dag_channel_open(self, payload, conn):
        """Pre-wire one compiled-DAG stage in this (actor) worker: build
        the stage runtime, dial its downstream peers, and hand back this
        process's channel address. The raylet learns about the stage so
        a worker death reaches the compiling owner (dag_peer_down)
        without waiting out an execute timeout."""
        from ray_tpu.dag import channel as dagch
        loop = asyncio.get_running_loop()
        ep = dagch.get_endpoint(self)
        # dialing downstream peers is blocking socket work — keep it off
        # the io loop
        r = await loop.run_in_executor(None, ep.open_stage, payload)
        if self.raylet is not None:
            try:
                await self.raylet.notify("dag_register", {
                    "dag_id": payload["dag_id"],
                    "owner_address": payload["owner_address"]})
            except Exception:
                pass
        return r

    async def _h_dag_channel_close(self, payload, conn):
        ep = getattr(self, "_dag_endpoint", None)
        if ep is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, ep.close_stage, payload["dag_id"],
                payload.get("stage_id"))
        if self.raylet is not None:
            try:
                await self.raylet.notify(
                    "dag_unregister", {"dag_id": payload["dag_id"]})
            except Exception:
                pass
        return {}

    async def _h_dag_stage_error(self, payload, conn):
        """A stage's forward send broke (downstream peer died): the
        compiling owner tears the graph down and falls back."""
        from ray_tpu.dag import compiled_dag
        compiled_dag.on_stage_error(payload)
        return {}

    async def _h_dag_peer_down(self, payload, conn):
        """Raylet-side death detection for a worker hosting compiled-DAG
        stages (raylet.py _handle_worker_death)."""
        from ray_tpu.dag import compiled_dag
        compiled_dag.on_peer_down(payload)
        return {}

    async def _h_preemption_notice(self, payload, conn):
        """The raylet is draining (TPU preemption): surface the deadline
        to any train session in this process so the train loop commits
        an out-of-band checkpoint before the node dies."""
        from ray_tpu.air import session as air_session
        air_session.mark_preempted(
            deadline_unix=payload.get("deadline_unix"),
            grace_s=payload.get("grace_s"))
        return {}

    # ----------------------------------------------------- task execution side

    async def _h_push_task(self, payload, conn):
        self._task_queue.put(payload)
        return {}

    async def _h_leased_task(self, payload, conn):
        """Direct owner->worker execution under a lease: the reply IS
        the result delivery (2 messages/task; no raylet involvement —
        the lease holds the resources)."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._task_queue.put({"spec": payload["spec"], "tpu_chips": [],
                              "reply": (loop, fut)})
        result = await fut
        # leased tasks bypass the raylet, so its tasks_dispatched gauge
        # would go dark — coalesce executed-count deltas into one
        # task_stats notify per 0.3 s tick
        self._leased_executed += 1
        if not self._leased_stats_scheduled:
            self._leased_stats_scheduled = True
            loop.call_later(0.3, self._flush_leased_stats)
        return result

    def _flush_leased_stats(self):
        self._leased_stats_scheduled = False
        delta, self._leased_executed = self._leased_executed, 0
        if delta and self.raylet is not None:
            protocol.spawn(self.raylet.notify(
                "task_stats", {"executed": delta}))

    async def _h_cancel_task(self, payload, conn):
        self._cancelled_tasks.add(payload["task_id"])
        return {}

    def task_execution_loop(self):
        """Main loop of a worker process (reference:
        core_worker.cc:2180 RunTaskExecutionLoop → task_execution_handler)."""
        while True:
            item = self._task_queue.get()
            if item is None:
                break
            self._execute_task(item["spec"], item.get("tpu_chips") or [],
                               reply=item.get("reply"))

    def _execute_task(self, spec, tpu_chips, reply=None):
        if chaos._ENGINE is not None:
            # chaos injection point: "kill" at the N-th task this worker
            # starts executing (SIGKILL — the task dies mid-flight and
            # the owner's retry machinery takes over)
            chaos.hit("worker.execute", spec.get("fn_name"))
        task_hex = spec["task_id"]
        self.current_task_id = TaskID(bytes.fromhex(task_hex))
        self.tpu_chips = tpu_chips
        owner = spec["owner_address"]
        returns = []
        app_error = False
        global _timeline
        if _timeline is None:
            from ray_tpu.util import timeline as _tl
            _timeline = _tl
        _t0 = time.time()
        _task_err: Optional[str] = None
        tev.emit(task_hex, tev.RUNNING, name=spec.get("fn_name"),
                 job_id=spec.get("job_id"), node_id=self.node_id,
                 worker_pid=os.getpid(), attempt=spec.get("attempt"),
                 trace_ctx=spec.get("trace_ctx"))
        # adopt the propagated span: child submits from inside this task
        # will parent to it
        self.task_context.trace = spec.get("trace_ctx")
        _deser_s = _ship_t0 = None
        try:
            if task_hex in self._cancelled_tasks:
                raise exc.TaskCancelledError(task_hex)
            fn = self.function_manager.fetch(spec["fn_key"])
            _td0 = time.time()
            args, kwargs = serialization.deserialize(spec["args"])
            args = [self._resolve_arg(a) for a in args]
            kwargs = {k: self._resolve_arg(v) for k, v in kwargs.items()}
            # arg deserialization + dependency resolution: the
            # "deserialize" phase of the synthesized task trace
            _deser_s = round(time.time() - _td0, 6)
            result = fn(*args, **kwargs)
            _ship_t0 = time.time()  # result shipping = "transfer" phase
            num_returns = spec["num_returns"]
            if num_returns == 1:
                values = [result]
            elif num_returns == 0:
                values = []
            else:
                values = list(result)
                if len(values) != num_returns:
                    raise ValueError(
                        f"task declared num_returns={num_returns} but "
                        f"returned {len(values)} values")
            for i, v in enumerate(values):
                oid = ObjectID.for_return(self.current_task_id, i)
                ser = serialization.serialize(v)
                returns.append(self._ship_return(oid, ser))
        except BaseException as e:  # noqa: BLE001
            logger.debug("task %s raised: %s", spec["fn_name"],
                         traceback.format_exc())
            app_error = True
            err = exc.TaskError.capture(spec["fn_name"], e) \
                if not isinstance(e, exc.RayTpuError) else e
            _task_err = f"{type(e).__name__}: {e}"
            ser = serialization.serialize_error(err)
            for i in range(max(1, spec["num_returns"])):
                oid = ObjectID.for_return(self.current_task_id, i)
                returns.append({"object_id": oid.hex(),
                                "inline": ser.to_bytes()})
        finally:
            self.current_task_id = None
            self.task_context.trace = None
            _timeline.record_task(spec.get("fn_name", "task"), _t0,
                                  time.time(), pid=os.getpid(),
                                  failed=app_error,
                                  trace_ctx=spec.get("trace_ctx"))
            tev.emit(task_hex,
                     tev.FAILED if app_error else tev.FINISHED,
                     name=spec.get("fn_name"), job_id=spec.get("job_id"),
                     node_id=self.node_id, worker_pid=os.getpid(),
                     attempt=spec.get("attempt"), error=_task_err,
                     deser_s=_deser_s,
                     ship_s=(round(time.time() - _ship_t0, 6)
                             if _ship_t0 is not None else None))
        if reply is not None:
            # leased task: the RPC reply carries the result (no owner
            # notify, no task_done — the lease holds the resources)
            result = {"task_id": task_hex, "returns": returns,
                      "app_error": app_error}
            if reply == DIRECT_REPLY:
                # direct lane: the caller (direct.py's one-thread
                # recv→execute→reply loop) frames and sends this itself
                return result
            loop, fut = reply
            loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result(result))
            return
        # Deliver the result BEFORE task_done: for TPU tasks the raylet
        # retires (kills) this worker as soon as task_done arrives, so a
        # fire-and-forget result here races worker death and the owner would
        # wait out its full timeout (flaky PG tests, round 3). A drained
        # notify is on the wire even if we die right after.
        async def _deliver():
            conn = await self._peer(owner)
            await conn.notify("task_result", {
                "task_id": task_hex, "returns": returns,
                "app_error": app_error})
        try:
            self.io.run(_deliver(), timeout=30)
        except Exception:
            logger.warning("result delivery for %s failed", task_hex,
                           exc_info=True)
        if self.raylet is not None:
            # notify, not call: the raylet never replies with anything —
            # a request would cost an extra send + seq bookkeeping per task
            self.io.run_async(self.raylet.notify("task_done",
                                                 {"task_id": task_hex}))

    def _ship_return(self, oid: ObjectID, ser) -> Dict[str, Any]:
        if ser.total_size <= self.config.max_inline_object_size:
            return {"object_id": oid.hex(), "inline": ser.to_bytes()}
        buf = self._plasma_create_with_spill(oid, ser.total_size)
        ser.write_into(buf)
        buf.release()
        self.plasma.seal(oid)
        if self.raylet is not None:
            try:
                self.call_sync(self.raylet, "pin_object",
                               {"object_id": oid.hex()})
            except Exception:
                pass
        return {"object_id": oid.hex(), "plasma": True,
                "node_id": self.node_id}

    def _resolve_arg(self, value):
        if isinstance(value, ObjectRef):
            # bounded: a lost/freed arg object must surface as a task
            # error, not wedge the executor thread forever
            deadline = time.monotonic() + self.config.arg_fetch_timeout_s
            return self._get_one(value, deadline=deadline)
        return value

    # -------------------------------------------------------------- actor side

    async def _h_become_actor(self, payload, conn):
        spec = payload["create_spec"]
        self.tpu_chips = payload.get("tpu_chips") or []
        loop = asyncio.get_running_loop()
        err = await loop.run_in_executor(None, self._init_actor, spec)
        if err is not None:
            raise protocol.RpcError(err)
        return {}

    def _init_actor(self, spec) -> Optional[str]:
        try:
            cls = self.function_manager.fetch(spec["class_key"])
            args, kwargs = serialization.deserialize(spec["init_args"])
            args = [self._resolve_arg(a) for a in args]
            kwargs = {k: self._resolve_arg(v) for k, v in kwargs.items()}
            self.current_actor_id = ActorID(bytes.fromhex(spec["actor_id"]))
            self.current_task_id = TaskID.for_actor_task(
                self.current_actor_id, 0)
            max_concurrency = spec.get("max_concurrency") or 1
            self._actor_threads = ThreadPoolExecutor(
                max_workers=max_concurrency,
                thread_name_prefix="actor-exec")
            # concurrency groups (reference: actor concurrency groups,
            # core_worker/transport/concurrency_group_manager): named
            # executors so e.g. "io" calls never starve "compute" calls
            self._actor_group_threads = {
                name: ThreadPoolExecutor(
                    max_workers=int(n),
                    thread_name_prefix=f"actor-{name}")
                for name, n in (spec.get("concurrency_groups")
                                or {}).items()}
            self._actor_instance = cls(*args, **kwargs)
            self.mode = MODE_WORKER
            return None
        except BaseException as e:  # noqa: BLE001
            logger.error("actor init failed: %s", traceback.format_exc())
            return f"{type(e).__name__}: {e}"

    def _executor_for(self, method) -> ThreadPoolExecutor:
        group = getattr(method, "__rtpu_method_opts__",
                        {}).get("concurrency_group")
        if group:
            groups = getattr(self, "_actor_group_threads", {})
            ex = groups.get(group)
            if ex is None:
                # silently landing on the default executor would recreate
                # exactly the starvation the group was meant to prevent
                raise ValueError(
                    f"method declares concurrency_group={group!r} but the "
                    f"actor defined groups {sorted(groups)}")
            return ex
        return self._actor_threads

    def enqueue_actor_call(self, actor_id_hex: str, payload: Dict[str, Any],
                           coro_factory) -> int:
        """Stamp ``payload`` with the next per-(process, actor) sequence
        number and enqueue its send coroutine — ATOMICALLY. All handles
        share the counter (__reduce__-recreated handles must not restart
        the numbering), and because run_coroutine_threadsafe preserves
        enqueue order, holding the lock across both steps means frames
        leave in seq order on the cached fast path; out-of-order
        delivery then only happens on cold starts and retries, where the
        receiver's parking backstop absorbs it."""
        with self._actor_send_lock:
            n = self._actor_send_seq.get(actor_id_hex, 0) + 1
            self._actor_send_seq[actor_id_hex] = n
            payload["seq"] = n
            payload["processed_up_to"] = \
                self._actor_processed_upto.get(actor_id_hex, 0)
            self.io.run_async(coro_factory())
            return n

    def mark_actor_seq_done(self, actor_id_hex: str, seq: int):
        """A call completed (result or error): advance the contiguous
        processed prefix that future calls advertise."""
        with self._actor_send_lock:
            done = self._actor_done_seqs.setdefault(actor_id_hex, set())
            done.add(seq)
            upto = self._actor_processed_upto.get(actor_id_hex, 0)
            while upto + 1 in done:
                upto += 1
                done.discard(upto)
            self._actor_processed_upto[actor_id_hex] = upto

    async def _order_actor_call(self, caller: str, seq: int,
                                processed_up_to: int = 0):
        """Park until every lower seq from this caller has been
        dispatched (per-caller ordering — without it the async send
        tasks race and e.g. train() can reach the actor before
        create()). A timeout keeps a gap from wedging the queue: a
        predecessor that died before sending (send-side failure) or a
        counter carried across an actor restart both resolve by
        skipping forward — best-effort beats deadlock. 30 s errs toward
        ordering: under a saturated host a predecessor's send can lag
        seconds, and skipping early re-creates the reorder bug."""
        if not caller or not seq:
            return
        loop = asyncio.get_running_loop()
        waiting = self._actor_waiting.setdefault(caller, {})
        if processed_up_to > self._actor_seq.get(caller, 0):
            # the caller says everything ≤ processed_up_to already
            # completed (possibly against a previous incarnation of this
            # actor): fast-forward instead of waiting on phantom gaps
            self._actor_seq[caller] = processed_up_to
            self._release_actor_call(caller, processed_up_to)
        while seq > self._actor_seq.get(caller, 0) + 1:
            fut = loop.create_future()
            waiting[seq] = fut
            try:
                await asyncio.wait_for(fut, timeout=30.0)
            except asyncio.TimeoutError:
                break
            finally:
                waiting.pop(seq, None)
        if seq > self._actor_seq.get(caller, 0):
            self._actor_seq[caller] = seq

    def _release_actor_call(self, caller: str, seq: int):
        if not caller or not seq:
            return
        nxt = self._actor_waiting.get(caller, {}).get(seq + 1)
        if nxt is not None and not nxt.done():
            nxt.set_result(None)

    def _actor_task_events_on(self) -> bool:
        """RTPU_ACTOR_TASK_EVENTS=1 extends the task-event pipeline to
        actor method calls (the direct-call fast lane skips the normal
        execute path). Off by default: steady-state actor chatter
        (health probes, long-polls) would crowd the bounded task table;
        the game-day harness turns it on for its cluster so the state
        engine can be reconciled per request against client ledgers."""
        on = getattr(self, "_actor_tev_on", None)
        if on is None:
            on = bool(os.environ.get("RTPU_ACTOR_TASK_EVENTS"))
            self._actor_tev_on = on
        return on

    async def _h_actor_call(self, payload, conn):
        loop = asyncio.get_running_loop()
        method_name = payload["method"]
        # ordering FIRST: every error path below must still consume this
        # seq (and release its successor), or calls pipelined behind a
        # bad one stall on a phantom gap until the parking timeout
        await self._order_actor_call(payload.get("caller"),
                                     payload.get("seq") or 0,
                                     payload.get("processed_up_to") or 0)
        inst = self._actor_instance
        method = getattr(inst, method_name, None) \
            if inst is not None else None
        if inst is None or method is None:
            self._release_actor_call(payload.get("caller"),
                                     payload.get("seq") or 0)
            raise protocol.RpcError(
                "not an actor worker" if inst is None else
                f"{type(inst).__name__} has no method {method_name}")

        emit_tev = self._actor_task_events_on()
        fn_label = f"{type(inst).__name__}.{method_name}"

        def _run():
            seq = TaskID(bytes.fromhex(payload["task_id"]))
            if emit_tev:
                tev.emit(payload["task_id"], tev.RUNNING, name=fn_label,
                         node_id=self.node_id, worker_pid=os.getpid(),
                         trace_ctx=payload.get("trace_ctx"))
            # adopt the caller's propagated span (nested-parent fix):
            # without this, a task submitted from inside an actor
            # method — including every serve replica's user code —
            # parented to this worker's _root_trace instead of its
            # caller, severing the trace tree at the actor boundary.
            # Saved/restored, not cleared: actor executor threads are
            # pooled and a replica may have installed a serve span.
            prev_trace = getattr(self.task_context, "trace", None)
            self.task_context.trace = payload.get("trace_ctx")
            try:
                args, kwargs = serialization.deserialize(payload["args"])
                args = [self._resolve_arg(a) for a in args]
                kwargs = {k: self._resolve_arg(v) for k, v in kwargs.items()}
                result = method(*args, **kwargs)
                if asyncio.iscoroutine(result):
                    result = asyncio.run(result)
                ser = serialization.serialize(result)
                oid = ObjectID.for_return(seq, 0)
                if emit_tev:
                    tev.emit(payload["task_id"], tev.FINISHED,
                             name=fn_label, node_id=self.node_id,
                             worker_pid=os.getpid())
                return self._ship_return(oid, ser)
            except BaseException as e:  # noqa: BLE001
                if emit_tev:
                    tev.emit(payload["task_id"], tev.FAILED,
                             name=fn_label, node_id=self.node_id,
                             worker_pid=os.getpid(),
                             error=f"{type(e).__name__}: {e}"[:200])
                err = exc.ActorError.capture(
                    f"{type(inst).__name__}.{method_name}", e)
                ser = serialization.serialize_error(err)
                oid = ObjectID.for_return(seq, 0)
                return {"object_id": oid.hex(), "inline": ser.to_bytes(),
                        "app_error": True}
            finally:
                self.task_context.trace = prev_trace

        try:
            executor = self._executor_for(method)
        except ValueError as e:
            self._release_actor_call(payload.get("caller"),
                                     payload.get("seq") or 0)
            # surface as an application error on the return object, not a
            # transport failure (which would look like an actor death)
            err = exc.ActorError.capture(
                f"{type(inst).__name__}.{method_name}", e)
            ser = serialization.serialize_error(err)
            oid = ObjectID.for_return(
                TaskID(bytes.fromhex(payload["task_id"])), 0)
            return {"object_id": oid.hex(), "inline": ser.to_bytes(),
                    "app_error": True}
        # enqueue BEFORE releasing the successor: the executor's FIFO
        # queue then preserves seq order within each concurrency group
        fut = loop.run_in_executor(executor, _run)
        self._release_actor_call(payload.get("caller"),
                                 payload.get("seq") or 0)
        return await fut


class _PlasmaIndirect:
    """Marker stored in a memory store slot: the value is in plasma."""

    def __init__(self, node_id: str):
        self.node_id = node_id


def _release_plasma(plasma: PlasmaxStore, oid: ObjectID, buf):
    try:
        buf.release()
        plasma.release(oid)
    except Exception:
        pass


# --------------------------------------------------------------------------
# Module-level convenience used by the public API

def get(ref_or_refs, *, timeout: Optional[float] = None):
    w = global_worker()
    if isinstance(ref_or_refs, ObjectRef):
        return w.get_objects([ref_or_refs], timeout)[0]
    if isinstance(ref_or_refs, list):
        return w.get_objects(ref_or_refs, timeout)
    raise TypeError("get() expects an ObjectRef or a list of ObjectRefs")
