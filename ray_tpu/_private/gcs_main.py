"""GCS server process entrypoint (reference: gcs_server_main.cc)."""

import asyncio
import logging
import os

from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.node import restore_tpu_plugin_env
from ray_tpu.common.config import SystemConfig

restore_tpu_plugin_env()


async def main():
    logging.basicConfig(level=os.environ.get("RTPU_LOG_LEVEL", "INFO"))
    session_dir = os.environ["RTPU_SESSION_DIR"]
    port = int(os.environ.get("RTPU_GCS_PORT", "0"))
    cfg_json = os.environ.get("RTPU_SYSTEM_CONFIG")
    config = SystemConfig.from_json(cfg_json) if cfg_json else SystemConfig()
    store_dir = os.environ.get("RTPU_GCS_STORE_DIR") or \
        os.path.join(session_dir, "gcs_store")
    from ray_tpu.util import events
    events.init_emitter("gcs", session_dir)
    from ray_tpu._private import chaos
    eng = chaos.init_from_env("gcs")
    gcs = GcsServer(config, store_path=store_dir)
    if eng is not None:
        eng.set_notifier(gcs.events.append)
    actual = await gcs.start("127.0.0.1", port)
    tmp = os.path.join(session_dir, ".gcs_port.tmp")
    with open(tmp, "w") as f:
        f.write(str(actual))
    os.replace(tmp, os.path.join(session_dir, "gcs_port"))
    await asyncio.Event().wait()


if __name__ == "__main__":
    asyncio.run(main())
