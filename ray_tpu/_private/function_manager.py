"""Function/actor-class distribution via GCS KV.

Reference semantics: python/ray/_private/function_manager.py +
_private/import_thread.py — functions are cloudpickled once, exported to the
GCS KV keyed by hash, and lazily imported (and cached) in workers. Here the
fetch is pull-based at first use instead of an import thread; the cache is
per-process.
"""

from __future__ import annotations

import hashlib
import os
import sys
import sysconfig
import threading
from typing import Any, Dict

import cloudpickle

_STDLIB = sysconfig.get_paths().get("stdlib", "")
_SITE = sysconfig.get_paths().get("purelib", "")
_by_value_registered: set = set()


def _ensure_serializable_by_value(obj: Any, _depth: int = 0):
    """Functions/classes from user script modules (not site-packages or
    the framework itself) are pickled BY VALUE, so workers that can't
    import the driver's script still execute them (the reference ships
    the working_dir runtime env instead; by-value is the zero-install
    equivalent for single-file drivers). Closure cells and defaults are
    walked so captured user functions get the same treatment."""
    if _depth > 2:
        return
    closure = getattr(obj, "__closure__", None)
    if closure:
        for cell in closure:
            try:
                v = cell.cell_contents
            except ValueError:
                continue
            if callable(v) or isinstance(v, type):
                _ensure_serializable_by_value(v, _depth + 1)
    for d in (getattr(obj, "__defaults__", None) or ()):
        if callable(d) or isinstance(d, type):
            _ensure_serializable_by_value(d, _depth + 1)
    mod_name = getattr(obj, "__module__", None)
    if not mod_name or mod_name in ("__main__", "builtins"):
        return  # cloudpickle already handles __main__ by value
    if mod_name in _by_value_registered:
        return
    if mod_name == "ray_tpu" or mod_name.startswith("ray_tpu."):
        return
    mod = sys.modules.get(mod_name)
    mod_file = getattr(mod, "__file__", None)
    if mod is None or not mod_file:
        return
    mod_file = os.path.abspath(mod_file)
    # installed packages (any site-packages/dist-packages, incl. --user
    # installs) and the stdlib are importable on workers → by reference
    if ("site-packages" in mod_file or "dist-packages" in mod_file
            or (_STDLIB and mod_file.startswith(_STDLIB + os.sep))):
        return
    try:
        cloudpickle.register_pickle_by_value(mod)
        _by_value_registered.add(mod_name)
    except Exception:
        pass


class FunctionManager:
    def __init__(self, kv_call):
        """kv_call(method, payload) -> reply; bound to the process's GCS conn."""
        self._kv_call = kv_call
        self._cache: Dict[str, Any] = {}
        self._exported: set[str] = set()
        self._lock = threading.Lock()

    def export(self, obj: Any, kind: str = "fn") -> str:
        _ensure_serializable_by_value(obj)
        try:
            blob = cloudpickle.dumps(obj, protocol=5)
        except Exception:
            # a module registered by value may hold unpicklable state;
            # fall back to by-reference for everything we registered
            for m in list(_by_value_registered):
                mod = sys.modules.get(m)
                if mod is not None:
                    try:
                        cloudpickle.unregister_pickle_by_value(mod)
                    except Exception:
                        pass
                _by_value_registered.discard(m)
            blob = cloudpickle.dumps(obj, protocol=5)
        key = f"{kind}:{hashlib.sha1(blob).hexdigest()}"
        with self._lock:
            if key in self._exported:
                return key
        self._kv_call("kv_put", {"key": "@fn/" + key, "value": blob,
                                 "overwrite": False})
        with self._lock:
            self._exported.add(key)
            self._cache[key] = obj
        return key

    def fetch(self, key: str) -> Any:
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        reply = self._kv_call("kv_get", {"key": "@fn/" + key})
        blob = reply.get("value")
        if blob is None:
            raise KeyError(f"function {key} not found in GCS")
        obj = cloudpickle.loads(blob)
        with self._lock:
            self._cache[key] = obj
        return obj
