"""Function/actor-class distribution via GCS KV.

Reference semantics: python/ray/_private/function_manager.py +
_private/import_thread.py — functions are cloudpickled once, exported to the
GCS KV keyed by hash, and lazily imported (and cached) in workers. Here the
fetch is pull-based at first use instead of an import thread; the cache is
per-process.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict

import cloudpickle


class FunctionManager:
    def __init__(self, kv_call):
        """kv_call(method, payload) -> reply; bound to the process's GCS conn."""
        self._kv_call = kv_call
        self._cache: Dict[str, Any] = {}
        self._exported: set[str] = set()
        self._lock = threading.Lock()

    def export(self, obj: Any, kind: str = "fn") -> str:
        blob = cloudpickle.dumps(obj, protocol=5)
        key = f"{kind}:{hashlib.sha1(blob).hexdigest()}"
        with self._lock:
            if key in self._exported:
                return key
        self._kv_call("kv_put", {"key": "@fn/" + key, "value": blob,
                                 "overwrite": False})
        with self._lock:
            self._exported.add(key)
            self._cache[key] = obj
        return key

    def fetch(self, key: str) -> Any:
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        reply = self._kv_call("kv_get", {"key": "@fn/" + key})
        blob = reply.get("value")
        if blob is None:
            raise KeyError(f"function {key} not found in GCS")
        obj = cloudpickle.loads(blob)
        with self._lock:
            self._cache[key] = obj
        return obj
