"""Runtime environment materialization.

Role-equivalent to the reference's runtime_env stack
(reference: python/ray/_private/runtime_env/pip.py — venv per env,
packaging.py — working_dir/py_modules URI upload + content-addressed cache,
dashboard/modules/runtime_env/runtime_env_agent.py — per-node installer).

TPU-first redesign: no separate agent process — the raylet materializes
environments inline (venv creation offloaded to a thread) into a
content-addressed per-host cache, and workers are pooled keyed by env hash
(already the case in the worker pool). Fields supported:

  env_vars:    dict[str, str]                 (merged into worker env)
  working_dir: local dir (driver packs+uploads) or package URI
  py_modules:  list of local dirs/files or URIs (prepended to PYTHONPATH)
  pip:         list of requirement specs / local wheel paths
               (installed into a venv with --system-site-packages)
  conda:       env name / env dir / {"dependencies": [...]} spec
               (gated on a conda binary being installed on the host)
  container:   {"image": ..., "run_options": [...]} — worker runs inside
               the image via podman/docker (gated on the runtime binary;
               reference: runtime_env/container.py worker_setup_hook)
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import subprocess
import sys
import tempfile
import zipfile
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

PKG_PREFIX = "@pkg/"
URI_SCHEME = "gcs://"


def env_hash(runtime_env: Optional[Dict[str, Any]]) -> str:
    if not runtime_env:
        return ""
    return hashlib.sha1(
        json.dumps(runtime_env, sort_keys=True).encode()).hexdigest()[:12]


def dir_fingerprint(path: str) -> str:
    """Cheap content fingerprint (mtime_ns + size over the tree) so driver-
    side upload caching notices edits between submits."""
    h = hashlib.sha1()
    if os.path.isfile(path):
        st = os.stat(path)
        h.update(f"{path}:{st.st_mtime_ns}:{st.st_size}".encode())
    else:
        for root, dirs, files in sorted(os.walk(path)):
            dirs.sort()
            for name in sorted(files):
                full = os.path.join(root, name)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                h.update(f"{os.path.relpath(full, path)}:"
                         f"{st.st_mtime_ns}:{st.st_size}".encode())
    return h.hexdigest()[:16]


# --------------------------------------------------------------- packaging

def _zip_dir(path: str, include_base: bool = False) -> bytes:
    """Deterministic zip of a directory tree (or single file).

    include_base=True keeps the top-level directory name in the archive —
    needed for py_modules (the extracted parent goes on PYTHONPATH, so the
    package dir itself must exist); working_dir extracts contents at the
    root (it becomes the cwd)."""
    buf = tempfile.SpooledTemporaryFile()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(path):
            zf.write(path, os.path.basename(path))
        else:
            base = os.path.abspath(path)
            arc_root = os.path.basename(base.rstrip(os.sep)) \
                if include_base else ""
            for root, dirs, files in sorted(os.walk(base)):
                dirs.sort()
                if "__pycache__" in root:
                    continue
                for name in sorted(files):
                    full = os.path.join(root, name)
                    rel = os.path.relpath(full, base)
                    zf.write(full, os.path.join(arc_root, rel))
    buf.seek(0)
    return buf.read()


def upload_local_paths(runtime_env: Dict[str, Any],
                       kv_put: Callable[[str, bytes], None]
                       ) -> Dict[str, Any]:
    """Driver side: replace local working_dir/py_modules paths with
    content-addressed URIs backed by GCS KV blobs (reference:
    packaging.py upload_package_to_gcs). Idempotent: URIs pass through."""
    if not runtime_env:
        return runtime_env
    out = dict(runtime_env)

    def _pack(path: str, include_base: bool) -> str:
        if path.startswith(URI_SCHEME):
            return path
        data = _zip_dir(path, include_base=include_base)
        digest = hashlib.sha1(data).hexdigest()[:20]
        uri = f"{URI_SCHEME}{digest}"
        kv_put(PKG_PREFIX + digest, data)
        return uri

    if isinstance(out.get("working_dir"), str) and \
            not out["working_dir"].startswith(URI_SCHEME) and \
            os.path.exists(out["working_dir"]):
        out["working_dir"] = _pack(out["working_dir"], include_base=False)
    if out.get("py_modules"):
        out["py_modules"] = [
            _pack(m, include_base=True)
            if os.path.exists(m) or m.startswith(URI_SCHEME) else m
            for m in out["py_modules"]]
    return out


# ------------------------------------------------------------ materialize

@dataclass
class MaterializedEnv:
    python_exe: str = sys.executable
    env_vars: Dict[str, str] = field(default_factory=dict)
    cwd: Optional[str] = None
    pythonpath: List[str] = field(default_factory=list)


def _extract_uri(uri: str, cache_dir: str,
                 kv_get: Callable[[str], Optional[bytes]]) -> str:
    digest = uri[len(URI_SCHEME):]
    dest = os.path.join(cache_dir, "pkg", digest)
    if os.path.isdir(dest):
        return dest
    blob = kv_get(PKG_PREFIX + digest)
    if blob is None:
        raise RuntimeError(f"package {uri} not found in GCS")
    # unique tmp per caller: concurrent materializations of the same URI
    # (batch submits) must not fight over one .tmp dir
    os.makedirs(os.path.join(cache_dir, "pkg"), exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=f".{digest}-", dir=os.path.join(
        cache_dir, "pkg"))
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, dest)
    except OSError:
        shutil.rmtree(tmp, ignore_errors=True)
        if not os.path.isdir(dest):  # a concurrent extract won; else re-raise
            raise
    return dest


def _ensure_pip_venv(reqs: List[str], cache_dir: str) -> str:
    """Create (or reuse) a venv with the given requirements installed.
    Returns its python executable (reference: pip.py PipProcessor)."""
    import fcntl
    key = hashlib.sha1(json.dumps(sorted(reqs)).encode()).hexdigest()[:16]
    venv_dir = os.path.join(cache_dir, "venvs", key)
    py = os.path.join(venv_dir, "bin", "python")
    marker = os.path.join(venv_dir, ".ready")
    if os.path.exists(marker):
        return py
    # cross-process/thread lock: the cache dir is shared by all raylets on
    # the host; without it two materializations rmtree each other mid-install
    # and a .ready marker could bless a corrupted venv
    os.makedirs(os.path.join(cache_dir, "venvs"), exist_ok=True)
    lock_path = os.path.join(cache_dir, "venvs", f".{key}.lock")
    with open(lock_path, "w") as lock_f:
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        try:
            return _build_pip_venv_locked(reqs, venv_dir, py, marker)
        finally:
            fcntl.flock(lock_f, fcntl.LOCK_UN)


def _build_pip_venv_locked(reqs: List[str], venv_dir: str, py: str,
                           marker: str) -> str:
    if os.path.exists(marker):  # re-check under the lock
        return py
    shutil.rmtree(venv_dir, ignore_errors=True)
    subprocess.check_call(
        [sys.executable, "-m", "venv", "--system-site-packages", venv_dir],
        stdout=subprocess.DEVNULL)
    # --system-site-packages chains to the BASE interpreter; when this
    # process itself runs in a venv (typical), the parent venv's packages
    # (numpy, jax, ...) would be invisible. Expose them via a .pth — the
    # new venv's own site-packages still shadows these (it sorts first).
    import sysconfig
    new_site = sysconfig.get_path(
        "purelib", vars={"base": venv_dir, "platbase": venv_dir})
    parent_sites = [p for p in sys.path
                    if p.endswith("site-packages") and os.path.isdir(p)]
    if parent_sites:
        with open(os.path.join(new_site, "_rtpu_parent_env.pth"), "w") as f:
            f.write("\n".join(parent_sites) + "\n")
    if reqs:
        # local wheel/sdist paths install with --no-index (offline);
        # name-based specs go through the configured index
        offline = all(os.path.exists(r) or r.startswith(("/", "."))
                      for r in reqs)
        cmd = [py, "-m", "pip", "install", "--no-input", "--quiet",
               "--disable-pip-version-check"]
        if offline:
            cmd.append("--no-index")
        subprocess.check_call(cmd + reqs, stdout=subprocess.DEVNULL)
    open(marker, "w").close()
    return py


def _conda_exe() -> Optional[str]:
    """The host's conda binary, if any (reference: conda.py get_conda_activate_commands
    resolving $CONDA_EXE). None means the feature is unavailable here."""
    exe = os.environ.get("CONDA_EXE") or shutil.which("conda")
    return exe if exe and os.path.exists(exe) else None


def _ensure_conda_env(spec: Any, cache_dir: str) -> str:
    """Resolve/create a conda env and return its python executable
    (reference: runtime_env/conda.py — named envs activate in place,
    dict specs materialize a content-addressed env). Raises RuntimeError
    when conda isn't installed — the feature is gated, not stubbed."""
    conda = _conda_exe()
    if conda is None:
        raise RuntimeError(
            "runtime_env['conda'] requires a conda installation "
            "(none found via $CONDA_EXE or PATH)")
    if isinstance(spec, str):
        if os.path.isdir(spec):  # explicit env dir
            return os.path.join(spec, "bin", "python")
        base = subprocess.check_output(
            [conda, "info", "--base"], text=True).strip()
        env_dir = os.path.join(base, "envs", spec)
        py = os.path.join(env_dir, "bin", "python")
        if not os.path.exists(py):
            raise RuntimeError(f"conda env {spec!r} not found at {env_dir}")
        return py
    # dict spec: content-addressed env under the shared cache, guarded by
    # the same flock discipline as pip venvs
    import fcntl
    key = hashlib.sha1(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]
    env_dir = os.path.join(cache_dir, "conda", key)
    py = os.path.join(env_dir, "bin", "python")
    marker = os.path.join(env_dir, ".ready")
    if os.path.exists(marker):
        return py
    os.makedirs(os.path.join(cache_dir, "conda"), exist_ok=True)
    lock_path = os.path.join(cache_dir, "conda", f".{key}.lock")
    with open(lock_path, "w") as lock_f:
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        if os.path.exists(marker):
            return py
        shutil.rmtree(env_dir, ignore_errors=True)
        yml = os.path.join(cache_dir, "conda", f"{key}.yml")
        with open(yml, "w") as f:
            json.dump(spec, f)  # YAML is a JSON superset
        subprocess.check_call(
            [conda, "env", "create", "-p", env_dir, "-f", yml, "--yes"],
            stdout=subprocess.DEVNULL)
        open(marker, "w").close()
    return py


def container_command(container: Dict[str, Any], session_dir: str,
                      cache_dir: str,
                      env_keys: Optional[List[str]] = None) -> List[str]:
    """Command prefix that wraps the worker in a container (reference:
    runtime_env/container.py — podman run with the session dir mounted).
    Gated on a runtime binary: $RTPU_CONTAINER_RUNTIME overrides the
    podman/docker PATH lookup (and is how tests inject a fake).
    ``env_keys`` are forwarded with bare ``-e KEY`` (both podman and
    docker then read the value from the spawning environment, which the
    raylet populates via Popen(env=...))."""
    image = container.get("image")
    if not image:
        raise RuntimeError("runtime_env['container'] requires 'image'")
    runtime = os.environ.get("RTPU_CONTAINER_RUNTIME") or \
        shutil.which("podman") or shutil.which("docker")
    if not runtime:
        raise RuntimeError(
            "runtime_env['container'] requires podman or docker "
            "(none found; set RTPU_CONTAINER_RUNTIME to override)")
    cmd = [runtime, "run", "--rm", "--network=host", "--ipc=host",
           "-v", f"{session_dir}:{session_dir}",
           "-v", f"{cache_dir}:{cache_dir}"]
    for k in env_keys or ():
        cmd += ["-e", k]
    cmd += list(container.get("run_options") or [])
    cmd.append(image)
    return cmd


def materialize(runtime_env: Optional[Dict[str, Any]], cache_dir: str,
                kv_get: Callable[[str], Optional[bytes]]
                ) -> MaterializedEnv:
    """Node side: turn a runtime_env spec into concrete process parameters.
    Safe to call repeatedly — every artifact is content-addressed."""
    m = MaterializedEnv()
    if not runtime_env:
        return m
    os.makedirs(cache_dir, exist_ok=True)
    m.env_vars.update(runtime_env.get("env_vars") or {})
    wd = runtime_env.get("working_dir")
    if wd:
        if wd.startswith(URI_SCHEME):
            m.cwd = _extract_uri(wd, cache_dir, kv_get)
        elif os.path.isdir(wd):
            m.cwd = wd  # local path (same-host dev convenience)
        m.pythonpath.append(m.cwd or "")
    for mod in runtime_env.get("py_modules") or ():
        if mod.startswith(URI_SCHEME):
            m.pythonpath.append(_extract_uri(mod, cache_dir, kv_get))
        elif os.path.exists(mod):
            m.pythonpath.append(os.path.abspath(
                os.path.dirname(mod) if os.path.isfile(mod) else mod))
    if runtime_env.get("pip") and runtime_env.get("conda"):
        # the reference rejects this combination too (validation.py):
        # pip packages inside a conda env go in the conda spec's
        # nested {"dependencies": [..., {"pip": [...]}]} form
        raise ValueError(
            "runtime_env cannot specify both 'pip' and 'conda'; put pip "
            "packages inside the conda spec's dependencies.pip list")
    if runtime_env.get("pip"):
        reqs = list(runtime_env["pip"]) if not isinstance(
            runtime_env["pip"], dict) else \
            list(runtime_env["pip"].get("packages", []))
        m.python_exe = _ensure_pip_venv(reqs, cache_dir)
    if runtime_env.get("conda"):
        m.python_exe = _ensure_conda_env(runtime_env["conda"], cache_dir)
    m.pythonpath = [p for p in m.pythonpath if p]
    return m
