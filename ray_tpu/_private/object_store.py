"""Python client for the plasmax shared-memory object store.

Role-equivalent to the reference's plasma client
(reference: src/ray/object_manager/plasma/client.cc and
core_worker/store_provider/plasma_store_provider.cc), plus the in-process
memory store for small objects
(reference: core_worker/store_provider/memory_store/memory_store.cc).

The store is a single mmap'd segment in /dev/shm created by the node process;
every worker attaches by path. Reads are zero-copy: ``get_buffer`` returns a
memoryview straight into shared memory.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import threading
from typing import Dict, Optional

from ray_tpu.common.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError

_LIB = None


def _lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        path = os.path.join(os.path.dirname(__file__), "..", "core", "libplasmax.so")
        path = os.path.abspath(path)
        src = os.path.abspath(os.path.join(
            os.path.dirname(path), "..", "..", "src", "plasmax", "store.cc"))
        if not os.path.exists(path) or (
                os.path.exists(src)
                and os.path.getmtime(src) > os.path.getmtime(path)):
            _build_lib(path)
        lib = ctypes.CDLL(path)
        lib.px_segment_size.restype = ctypes.c_uint64
        lib.px_segment_size.argtypes = [ctypes.c_uint64, ctypes.c_uint32]
        lib.px_init.restype = ctypes.c_int
        lib.px_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32]
        lib.px_attach_check.restype = ctypes.c_int
        lib.px_attach_check.argtypes = [ctypes.c_void_p]
        for name in ("px_create", "px_get"):
            getattr(lib, name).restype = ctypes.c_int
        lib.px_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.POINTER(ctypes.c_uint64)]
        lib.px_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_uint64),
                               ctypes.POINTER(ctypes.c_uint64)]
        for name in ("px_seal", "px_abort", "px_release", "px_delete",
                     "px_contains", "px_pin", "px_refcount"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        for name in ("px_used_bytes", "px_capacity", "px_num_objects",
                     "px_num_evicted"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_uint64
            fn.argtypes = [ctypes.c_void_p]
        lib.px_stats.restype = None
        lib.px_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
        _LIB = lib
    return _LIB


def _build_lib(out_path: str):
    """Build libplasmax.so from source on first use (source ships in src/)."""
    import subprocess
    src = os.path.join(os.path.dirname(out_path), "..", "..", "src", "plasmax",
                       "store.cc")
    src = os.path.abspath(src)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    subprocess.check_call(
        ["g++", "-O2", "-fPIC", "-shared", "-o", out_path, src, "-lpthread"])


DEFAULT_NSLOTS = 1 << 16


class PlasmaxStore:
    """Handle to one shared-memory segment (create or attach by path)."""

    def __init__(self, path: str, capacity: int = 0, create: bool = False,
                 nslots: int = DEFAULT_NSLOTS):
        self.path = path
        self._lib = _lib()
        if create:
            seg_size = self._lib.px_segment_size(capacity, nslots)
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            try:
                os.ftruncate(fd, seg_size)
                self._mm = mmap.mmap(fd, seg_size)
            finally:
                os.close(fd)
            self._base = ctypes.addressof(ctypes.c_char.from_buffer(self._mm))
            rc = self._lib.px_init(self._base, seg_size, nslots)
            if rc != 0:
                raise RuntimeError(f"px_init failed: {rc}")
        else:
            fd = os.open(path, os.O_RDWR)
            try:
                seg_size = os.fstat(fd).st_size
                self._mm = mmap.mmap(fd, seg_size)
            finally:
                os.close(fd)
            self._base = ctypes.addressof(ctypes.c_char.from_buffer(self._mm))
            if self._lib.px_attach_check(self._base) != 0:
                raise RuntimeError(f"not a plasmax segment: {path}")
        self._size = seg_size

    # -- write path --

    def create(self, oid: ObjectID, size: int) -> memoryview:
        """Allocate and return a writable view; caller must seal()."""
        off = ctypes.c_uint64()
        rc = self._lib.px_create(self._base, oid.binary(), size, ctypes.byref(off))
        if rc == -1:
            raise ValueError(f"object {oid} already exists")
        if rc == -2:
            raise ObjectStoreFullError(
                f"cannot allocate {size} bytes (capacity {self.capacity()}, "
                f"used {self.used_bytes()})")
        if rc == -3:
            raise ObjectStoreFullError("object index full")
        return memoryview(self._mm)[off.value:off.value + size]

    def seal(self, oid: ObjectID):
        rc = self._lib.px_seal(self._base, oid.binary())
        if rc != 0:
            raise ValueError(f"seal failed for {oid}: {rc}")
        # creator's implicit ref is dropped; raylet pins primaries separately
        self._lib.px_release(self._base, oid.binary())

    def put_bytes(self, oid: ObjectID, data) -> None:
        buf = self.create(oid, len(data))
        buf[:] = data
        self.seal(oid)

    def abort(self, oid: ObjectID):
        self._lib.px_abort(self._base, oid.binary())

    # -- read path --

    def get_buffer(self, oid: ObjectID) -> Optional[memoryview]:
        """Zero-copy read view, or None if absent. Caller should release()."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.px_get(self._base, oid.binary(), ctypes.byref(off),
                              ctypes.byref(size))
        if rc != 0:
            return None
        return memoryview(self._mm)[off.value:off.value + size.value]

    def release(self, oid: ObjectID):
        self._lib.px_release(self._base, oid.binary())

    def delete(self, oid: ObjectID) -> bool:
        return self._lib.px_delete(self._base, oid.binary()) == 0

    def contains(self, oid: ObjectID) -> bool:
        return bool(self._lib.px_contains(self._base, oid.binary()))

    def refcount(self, oid: ObjectID) -> int:
        """Debug: shared refcount of the slot, -1 if absent."""
        return int(self._lib.px_refcount(self._base, oid.binary()))

    def pin(self, oid: ObjectID) -> bool:
        return self._lib.px_pin(self._base, oid.binary()) == 0

    # -- stats --

    def used_bytes(self) -> int:
        return self._lib.px_used_bytes(self._base)

    def capacity(self) -> int:
        return self._lib.px_capacity(self._base)

    def num_objects(self) -> int:
        return self._lib.px_num_objects(self._base)

    def stats(self) -> Dict[str, int]:
        arr = (ctypes.c_uint64 * 6)()
        self._lib.px_stats(self._base, arr)
        keys = ("used_bytes", "capacity", "num_objects", "num_created",
                "num_evicted", "bytes_evicted")
        return dict(zip(keys, arr))

    def close(self):
        # Views into the mmap must be gone before closing; callers own that.
        self._base = None

    def unlink(self):
        try:
            os.unlink(self.path)
        except OSError:
            pass


class MemoryStore:
    """In-process store for small/inlined objects.

    Reference analogue: CoreWorkerMemoryStore
    (core_worker/store_provider/memory_store/memory_store.cc) — small results
    skip shared memory and travel inline through the control plane.
    """

    def __init__(self):
        self._store: Dict[ObjectID, bytes] = {}
        self._lock = threading.Lock()
        self._waiters: Dict[ObjectID, threading.Event] = {}

    def put(self, oid: ObjectID, payload: bytes):
        with self._lock:
            self._store[oid] = payload
            ev = self._waiters.pop(oid, None)
        if ev is not None:
            ev.set()

    def get(self, oid: ObjectID) -> Optional[bytes]:
        with self._lock:
            return self._store.get(oid)

    def wait_for(self, oid: ObjectID, timeout: Optional[float]) -> Optional[bytes]:
        with self._lock:
            if oid in self._store:
                return self._store[oid]
            ev = self._waiters.setdefault(oid, threading.Event())
        if not ev.wait(timeout):
            return None
        return self.get(oid)

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._store

    def delete(self, oid: ObjectID):
        with self._lock:
            self._store.pop(oid, None)
