"""Python client for the plasmax shared-memory object store.

Role-equivalent to the reference's plasma client
(reference: src/ray/object_manager/plasma/client.cc and
core_worker/store_provider/plasma_store_provider.cc), plus the in-process
memory store for small objects
(reference: core_worker/store_provider/memory_store/memory_store.cc).

The store is a single mmap'd segment in /dev/shm created by the node process;
every worker attaches by path. Reads are zero-copy: ``get_buffer`` returns a
memoryview straight into shared memory.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import threading
from typing import Dict, Optional

from ray_tpu.common.ids import ObjectID
from ray_tpu.exceptions import ObjectStoreFullError

_LIB = None


def _lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is None:
        path = os.path.join(os.path.dirname(__file__), "..", "core", "libplasmax.so")
        path = os.path.abspath(path)
        src = os.path.abspath(os.path.join(
            os.path.dirname(path), "..", "..", "src", "plasmax", "store.cc"))
        if not os.path.exists(path) or (
                os.path.exists(src)
                and os.path.getmtime(src) > os.path.getmtime(path)):
            _build_lib(path)
        lib = ctypes.CDLL(path)
        lib.px_segment_size.restype = ctypes.c_uint64
        lib.px_segment_size.argtypes = [ctypes.c_uint64, ctypes.c_uint32]
        lib.px_init.restype = ctypes.c_int
        lib.px_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32]
        lib.px_attach_check.restype = ctypes.c_int
        lib.px_attach_check.argtypes = [ctypes.c_void_p]
        for name in ("px_create", "px_get"):
            getattr(lib, name).restype = ctypes.c_int
        lib.px_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.POINTER(ctypes.c_uint64)]
        lib.px_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_uint64),
                               ctypes.POINTER(ctypes.c_uint64)]
        lib.px_debug_lock.restype = ctypes.c_int
        lib.px_debug_lock.argtypes = [ctypes.c_void_p]
        for name in ("px_seal", "px_abort", "px_release", "px_delete",
                     "px_contains", "px_pin", "px_refcount"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.px_unseal.restype = ctypes.c_int
        lib.px_unseal.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.POINTER(ctypes.c_uint64)]
        for name in ("px_used_bytes", "px_capacity", "px_num_objects",
                     "px_num_evicted"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_uint64
            fn.argtypes = [ctypes.c_void_p]
        lib.px_stats.restype = None
        lib.px_stats.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
        _LIB = lib
    return _LIB


def _build_lib(out_path: str):
    """Build libplasmax.so from source on first use (source ships in src/)."""
    import subprocess
    src = os.path.join(os.path.dirname(out_path), "..", "..", "src", "plasmax",
                       "store.cc")
    src = os.path.abspath(src)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    subprocess.check_call(
        ["g++", "-O2", "-fPIC", "-shared", "-o", out_path, src, "-lpthread"])


DEFAULT_NSLOTS = 1 << 16


class PlasmaxStore:
    """Handle to one shared-memory segment (create or attach by path).

    ``fallback_path`` names a second, disk-backed segment used when the
    shm segment cannot satisfy an allocation even after spilling
    (reference: plasma fallback allocation,
    object_manager/plasma/create_request_queue.cc +
    plasma_allocator.cc mmapping under /tmp when /dev/shm is
    exhausted). The raylet creates it eagerly as a SPARSE file (no
    disk used until pages are written); workers attach lazily on first
    need, so the common path never touches it."""

    def __init__(self, path: str, capacity: int = 0, create: bool = False,
                 nslots: int = DEFAULT_NSLOTS,
                 fallback_path: Optional[str] = None,
                 fallback_capacity: int = 0):
        self.path = path
        self._lib = _lib()
        self.fallback_path = fallback_path
        self._fallback: Optional["PlasmaxStore"] = None
        # oids created-but-not-yet-sealed in the fallback segment: routes
        # the seal/abort that follows a create to the right segment
        self._fb_creating: set = set()
        if create:
            seg_size = self._lib.px_segment_size(capacity, nslots)
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            try:
                os.ftruncate(fd, seg_size)
                self._mm = mmap.mmap(fd, seg_size)
            finally:
                os.close(fd)
            self._base = ctypes.addressof(ctypes.c_char.from_buffer(self._mm))
            rc = self._lib.px_init(self._base, seg_size, nslots)
            if rc != 0:
                raise RuntimeError(f"px_init failed: {rc}")
            if fallback_path:
                self._fallback = PlasmaxStore(
                    fallback_path,
                    capacity=fallback_capacity or capacity,
                    create=True)
                # sidecar makes the pair self-describing: attachers
                # (workers, drivers) discover the overflow segment from
                # the shm path alone — no plumbing through connect()
                with open(path + ".fbpath", "w") as f:
                    f.write(fallback_path)
        else:
            fd = os.open(path, os.O_RDWR)
            try:
                seg_size = os.fstat(fd).st_size
                self._mm = mmap.mmap(fd, seg_size)
            finally:
                os.close(fd)
            self._base = ctypes.addressof(ctypes.c_char.from_buffer(self._mm))
            if self._lib.px_attach_check(self._base) != 0:
                raise RuntimeError(f"not a plasmax segment: {path}")
            if self.fallback_path is None:
                try:
                    with open(path + ".fbpath") as f:
                        self.fallback_path = f.read().strip() or None
                except OSError:
                    pass
        self._size = seg_size

    def _fb(self) -> Optional["PlasmaxStore"]:
        """The fallback segment, attaching lazily (readers)."""
        if self._fallback is None and self.fallback_path and \
                os.path.exists(self.fallback_path):
            try:
                self._fallback = PlasmaxStore(self.fallback_path)
            except (OSError, RuntimeError):
                self.fallback_path = None
        return self._fallback

    # -- write path --

    def create(self, oid: ObjectID, size: int,
               allow_fallback: bool = False) -> memoryview:
        """Allocate and return a writable view; caller must seal().

        ``allow_fallback`` is the last-resort switch: reference plasma
        only fallback-allocates AFTER spilling failed to make room
        (create_request_queue.cc), so callers opt in once their
        spill-and-retry path is exhausted."""
        if self._fallback is not None and self._fallback.contains(oid):
            raise ValueError(f"object {oid} already exists")
        off = ctypes.c_uint64()
        rc = self._lib.px_create(self._base, oid.binary(), size, ctypes.byref(off))
        if rc == -1:
            raise ValueError(f"object {oid} already exists")
        if rc in (-2, -3):
            fb = self._fb() if allow_fallback else None
            if fb is not None:
                buf = fb.create(oid, size)  # disk-backed overflow
                self._fb_creating.add(oid.binary())
                return buf
            raise ObjectStoreFullError(
                "object index full" if rc == -3 else
                f"cannot allocate {size} bytes (capacity {self.capacity()},"
                f" used {self.used_bytes()})")
        return memoryview(self._mm)[off.value:off.value + size]

    def seal(self, oid: ObjectID):
        if oid.binary() in self._fb_creating:
            self._fb_creating.discard(oid.binary())
            self._fallback.seal(oid)
            return
        rc = self._lib.px_seal(self._base, oid.binary())
        if rc != 0:
            raise ValueError(f"seal failed for {oid}: {rc}")
        # creator's implicit ref is dropped; raylet pins primaries separately
        self._lib.px_release(self._base, oid.binary())

    def put_bytes(self, oid: ObjectID, data,
                  allow_fallback: bool = False) -> None:
        buf = self.create(oid, len(data), allow_fallback=allow_fallback)
        buf[:] = data
        self.seal(oid)

    def abort(self, oid: ObjectID):
        if oid.binary() in self._fb_creating:
            self._fb_creating.discard(oid.binary())
            self._fallback.abort(oid)
            return
        self._lib.px_abort(self._base, oid.binary())

    # -- read path --

    def get_buffer(self, oid: ObjectID) -> Optional[memoryview]:
        """Zero-copy read view, or None if absent. Caller should release()."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        rc = self._lib.px_get(self._base, oid.binary(), ctypes.byref(off),
                              ctypes.byref(size))
        if rc != 0:
            fb = self._fb()
            return fb.get_buffer(oid) if fb is not None else None
        return memoryview(self._mm)[off.value:off.value + size.value]

    def release(self, oid: ObjectID):
        if self._lib.px_release(self._base, oid.binary()) != 0:
            fb = self._fb()
            if fb is not None:
                fb.release(oid)

    def delete(self, oid: ObjectID) -> bool:
        if self._lib.px_delete(self._base, oid.binary()) == 0:
            return True
        fb = self._fb()
        return fb.delete(oid) if fb is not None else False

    def contains(self, oid: ObjectID) -> bool:
        if self._lib.px_contains(self._base, oid.binary()):
            return True
        fb = self._fb()
        return fb.contains(oid) if fb is not None else False

    def refcount(self, oid: ObjectID) -> int:
        """Debug: shared refcount of the slot, -1 if absent."""
        rc = int(self._lib.px_refcount(self._base, oid.binary()))
        if rc < 0:
            fb = self._fb()
            if fb is not None:
                return fb.refcount(oid)
        return rc

    def pin(self, oid: ObjectID) -> bool:
        if self._lib.px_pin(self._base, oid.binary()) == 0:
            return True
        fb = self._fb()
        return fb.pin(oid) if fb is not None else False

    # -- ring buffers (compiled-DAG channels) --
    #
    # A ring slot is a plasmax object the WRITER owns for the lifetime of a
    # compiled graph: created once (keeping the creator's pin so LRU eviction
    # can never reclaim it), then cycled seal→unseal→refill→seal per
    # invocation instead of create-per-object. px_unseal rewrites in place —
    # no allocator traffic, so used_bytes/num_created stay flat across
    # repeated graph executions (the property tests/test_compiled_dag.py
    # gates). Readers use the normal get_buffer/release pair; unseal refuses
    # (-2) while any reader still holds a ref and the writer retries.

    def ring_create(self, oid: ObjectID, size: int) -> memoryview:
        """Allocate a reusable slot; the creator pin is KEPT across seal."""
        off = ctypes.c_uint64()
        rc = self._lib.px_create(self._base, oid.binary(), size,
                                 ctypes.byref(off))
        if rc == -1:
            raise ValueError(f"ring slot {oid} already exists")
        if rc in (-2, -3):
            raise ObjectStoreFullError(
                f"cannot allocate {size}-byte ring slot")
        return memoryview(self._mm)[off.value:off.value + size]

    def ring_seal(self, oid: ObjectID):
        """Seal WITHOUT dropping the creator pin (unlike seal())."""
        rc = self._lib.px_seal(self._base, oid.binary())
        if rc != 0:
            raise ValueError(f"ring seal failed for {oid}: {rc}")

    def ring_recycle(self, oid: ObjectID,
                     timeout: float = 5.0) -> Optional[memoryview]:
        """Unseal a slot for rewrite; blocks until readers release (or
        timeout → None, caller falls back to an inline send)."""
        import time as _time
        off = ctypes.c_uint64()
        deadline = _time.monotonic() + timeout
        while True:
            rc = self._lib.px_unseal(self._base, oid.binary(),
                                     ctypes.byref(off))
            if rc == 0:
                # slot size is fixed at ring_create; callers slice the view
                # to the size they tracked
                return memoryview(self._mm)[off.value:]
            if rc == -1:
                return None  # gone (evicted segment teardown) — inline
            if _time.monotonic() >= deadline:
                return None  # reader wedged: skip the slot this round
            _time.sleep(0.0002)

    def ring_free(self, oid: ObjectID):
        """Teardown: drop the creator pin; delete if no readers remain
        (otherwise the slot becomes ordinary evictable garbage)."""
        self._lib.px_release(self._base, oid.binary())
        self._lib.px_delete(self._base, oid.binary())

    # -- stats --

    def used_bytes(self) -> int:
        return self._lib.px_used_bytes(self._base)

    def capacity(self) -> int:
        return self._lib.px_capacity(self._base)

    def num_objects(self) -> int:
        return self._lib.px_num_objects(self._base)

    def stats(self) -> Dict[str, int]:
        arr = (ctypes.c_uint64 * 6)()
        self._lib.px_stats(self._base, arr)
        keys = ("used_bytes", "capacity", "num_objects", "num_created",
                "num_evicted", "bytes_evicted")
        out = dict(zip(keys, arr))
        # shm-segment numbers stay primary-only (the raylet's spill
        # thresholds act on shm health); disk overflow reports separately
        fb = self._fb()
        if fb is not None:
            fbs = fb.stats()
            out["fallback_used_bytes"] = fbs["used_bytes"]
            out["fallback_capacity"] = fbs["capacity"]
            out["fallback_objects"] = fbs["num_objects"]
        return out

    def close(self):
        # Views into the mmap must be gone before closing; callers own that.
        self._base = None

    def unlink(self):
        for p in (self.path, self.path + ".fbpath",
                  self.fallback_path or ""):
            if p:
                try:
                    os.unlink(p)
                except OSError:
                    pass


class MemoryStore:
    """In-process store for small/inlined objects.

    Reference analogue: CoreWorkerMemoryStore
    (core_worker/store_provider/memory_store/memory_store.cc) — small results
    skip shared memory and travel inline through the control plane.
    """

    def __init__(self):
        self._store: Dict[ObjectID, bytes] = {}
        self._lock = threading.Lock()
        self._waiters: Dict[ObjectID, threading.Event] = {}

    def put(self, oid: ObjectID, payload: bytes):
        with self._lock:
            self._store[oid] = payload
            ev = self._waiters.pop(oid, None)
        if ev is not None:
            ev.set()

    def get(self, oid: ObjectID) -> Optional[bytes]:
        with self._lock:
            return self._store.get(oid)

    def wait_for(self, oid: ObjectID, timeout: Optional[float]) -> Optional[bytes]:
        with self._lock:
            if oid in self._store:
                return self._store[oid]
            ev = self._waiters.setdefault(oid, threading.Event())
        if not ev.wait(timeout):
            return None
        return self.get(oid)

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._store

    def delete(self, oid: ObjectID):
        with self._lock:
            self._store.pop(oid, None)
