"""Task lifecycle event pipeline (worker/raylet side).

Reference analogue: the task-event backend behind `ray list tasks`
(src/ray/core_worker/task_event_buffer.cc shipping batched
TaskEventData to gcs_task_manager.cc). Every process that observes a
task-state transition — the owner at submit, the raylet at queue/death,
the executing worker at run/finish — records it into a process-local
bounded ring and a background flusher ships batches to the GCS, which
folds them into a bounded, indexed table (`gcs.TaskEventTable`).

Design constraints (the whole point of this pipeline):
  - recording is O(1), lock-append, never an RPC: safe inside the
    hot submit loop (`submit_task_batch`) and the worker execute path;
  - memory is bounded end-to-end: the ring drops oldest events past
    ``RTPU_TASK_EVENTS_BUFFER`` (drop count ships with each batch so
    the head's table can report lossiness instead of lying), the GCS
    table evicts oldest-finished past its own cap;
  - shipping is batched: one ``task_events`` RPC per flush tick
    (``RTPU_TASK_EVENTS_FLUSH_S``, default 0.5 s), never per event.

States (reference: src/ray/protobuf/gcs.proto TaskStatus):
  PENDING_SCHEDULING -> PENDING_NODE_ASSIGNMENT -> RUNNING ->
  FINISHED | FAILED
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

PENDING_SCHEDULING = "PENDING_SCHEDULING"
PENDING_NODE_ASSIGNMENT = "PENDING_NODE_ASSIGNMENT"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"

TERMINAL_STATES = (FINISHED, FAILED)

# Later states win a merge race at the GCS (events from different
# processes arrive out of order); FAILED outranks FINISHED so a
# worker-death report isn't papered over by a stale success.
STATE_RANK = {
    PENDING_SCHEDULING: 0,
    PENDING_NODE_ASSIGNMENT: 1,
    RUNNING: 2,
    FINISHED: 3,
    FAILED: 4,
}


_RING_CAP: Optional[int] = None


def _ring_cap() -> int:
    # cached: this sits on the per-task emit path and an environ read
    # per event is measurable there (tests that change the env call
    # _reset_ring_cap / set the module global directly)
    global _RING_CAP
    if _RING_CAP is None:
        _RING_CAP = int(os.environ.get("RTPU_TASK_EVENTS_BUFFER", 8192))
    return _RING_CAP


def _reset_ring_cap():
    global _RING_CAP
    _RING_CAP = None


def _flush_interval() -> float:
    return float(os.environ.get("RTPU_TASK_EVENTS_FLUSH_S", 0.5))


_lock = threading.Lock()
_buf: List[Dict[str, Any]] = []
_dropped = 0          # ring overflow since the last shipped batch
_flusher_started = False
# raylets pump the buffer from their own asyncio loop (set_external_
# flusher); worker/driver processes start the default thread flusher
_external = False
_sender: Optional[Callable[[Dict[str, Any]], bool]] = None

_BATCH_MAX = 4000  # events per task_events RPC


def emit(task_id: str, state: str, **fields) -> None:
    """Record one lifecycle transition. O(1); never blocks on I/O.

    ``fields``: name, job_id, node_id, worker_pid, attempt, error,
    trace_ctx, plus the tracing-plane stamps the GCS synthesizes task
    phase spans from (docs/TRACING.md): ``dispatch_ts`` (raylet, at
    worker handoff), ``deser_s`` / ``ship_s`` (worker, arg
    deserialization and return shipping). Only non-None values ride
    the wire.
    """
    if not task_id:
        return
    ev = {"task_id": task_id, "state": state, "ts": time.time()}
    for k, v in fields.items():
        if v is not None:
            ev[k] = v
    global _dropped
    with _lock:
        _buf.append(ev)
        over = len(_buf) - _ring_cap()
        if over > 0:
            del _buf[:over]
            _dropped += over
    if not _external:
        _ensure_flusher()


def drain(max_n: int = _BATCH_MAX) -> Tuple[List[Dict[str, Any]], int]:
    """Take up to ``max_n`` buffered events (+ the drop count accrued
    since the last drain). Used by external pumps (the raylet loop)."""
    global _dropped
    with _lock:
        batch = _buf[:max_n]
        del _buf[:max_n]
        dropped, _dropped = _dropped, 0
    return batch, dropped


def requeue(events: List[Dict[str, Any]], dropped: int = 0) -> None:
    """Put a failed batch back at the front (bounded: oldest events past
    the ring cap are dropped and counted — a dead GCS must not grow an
    unbounded retry queue in every process)."""
    global _dropped
    if not events and not dropped:
        return
    with _lock:
        _buf[:0] = events
        _dropped += dropped
        over = len(_buf) - _ring_cap()
        if over > 0:
            del _buf[:over]
            _dropped += over


def pending_count() -> int:
    with _lock:
        return len(_buf)


def set_external_flusher() -> None:
    """The raylet owns flushing on its asyncio loop; don't start the
    thread flusher in this process."""
    global _external
    _external = True


def set_sender(fn: Optional[Callable[[Dict[str, Any]], bool]]) -> None:
    """Override the default ship-via-global-worker sender (tests)."""
    global _sender
    _sender = fn


def _default_send(payload: Dict[str, Any],
                  timeout: float = 5.0) -> bool:
    from ray_tpu._private import worker as worker_mod
    w = worker_mod._global_worker
    if w is None or not w.connected:
        return False
    try:
        w.call_sync(w.gcs, "task_events", payload, timeout=timeout)
        return True
    except Exception:
        return False


def flush(send_timeout: float = 5.0) -> bool:
    """Ship one batch to the GCS. Returns False when nothing could be
    sent (batch is requeued — cursor semantics: events are only dropped
    by the bounded ring, never by a failed send)."""
    batch, dropped = drain()
    if not batch and not dropped:
        return True
    payload = {"events": batch, "dropped": dropped}
    if _sender is not None:
        ok = _sender(payload)
    else:
        ok = _default_send(payload, timeout=send_timeout)
    if ok:
        return True
    requeue(batch, dropped)
    return False


def flush_all(timeout: float = 2.0) -> None:
    """Best-effort full drain (process teardown): each send is capped
    by the remaining budget so a dead GCS can't stall shutdown."""
    deadline = time.monotonic() + timeout
    while pending_count():
        left = deadline - time.monotonic()
        if left <= 0 or not flush(send_timeout=max(0.1, left)):
            return


def _ensure_flusher() -> None:
    global _flusher_started
    if _flusher_started:
        return
    _flusher_started = True

    def loop():
        while True:
            time.sleep(_flush_interval())
            try:
                flush()
            except Exception:
                pass

    threading.Thread(target=loop, daemon=True,
                     name="rtpu-task-events").start()
