"""Scheduling ledger: resource accounting + per-class pending queues +
the dispatch poll, behind one interface with two implementations.

``NativeLedger`` drives ``src/schedcore/schedcore.cc`` — the dispatch
hot loop in C++ (reference analogue: raylet/scheduling's fixed-point
``ClusterResourceData`` + ``LocalTaskManager``'s per-SchedulingClass
queues and ``DispatchScheduledTasksToWorkers``,
local_task_manager.cc:99).  ``PyLedger`` is the pure-Python fallback
(used when the C++ toolchain is unavailable, or under
``RTPU_NATIVE_SCHED=0``) with identical ACCOUNTING semantics — same
feasibility, acquisition atomicity, bundle lifecycle, and FIFO order
within a scheduling class; the relative order in which DIFFERENT
classes win contended resources is unspecified and may differ between
the two (both are valid schedules; tests assert accounting invariants,
not cross-class interleavings).

The split of responsibilities: the ledger owns MECHANISM — atomic
feasibility/acquire over the node pool, per-bundle pools and concrete
TPU chip sets, and the head-of-class scan that turns freed capacity
into a batch of dispatch decisions.  The raylet above owns POLICY —
spillback of stuck classes, worker-pool choice, and all RPC plumbing.
Chip IDs are concrete (two committed bundles own disjoint chip sets;
reference: placement_group_resource_manager.cc converts bundle
resources into node-local instances).
"""

from __future__ import annotations

import ctypes
import logging
import os
import tempfile
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

_LIB = None
_LIB_FAILED = False

POLL_MAX = 1024
# per-poll chip buffer; also the max TPU demand of a single dispatchable
# task under the native ledger (a head demanding more is reported
# blocked for spillback, never dispatched — real TPU hosts top out at
# 8 chips, so the bound is three orders of magnitude of headroom)
POLL_MAXCHIPS = 4096
POLL_MAXBLOCKED = 512

_u64p = ctypes.POINTER(ctypes.c_uint64)
_i32p = ctypes.POINTER(ctypes.c_int32)

logger = logging.getLogger(__name__)
_f64p = ctypes.POINTER(ctypes.c_double)


def _lib():
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    try:
        path = os.path.abspath(os.path.join(
            os.path.dirname(__file__), "..", "core", "libschedcore.so"))
        src = os.path.abspath(os.path.join(
            os.path.dirname(path), "..", "..", "src", "schedcore",
            "schedcore.cc"))
        if not os.path.exists(path) or (
                os.path.exists(src)
                and os.path.getmtime(src) > os.path.getmtime(path)):
            _build(src, path)
        lib = ctypes.CDLL(path)
        lib.scx_create.restype = ctypes.c_void_p
        lib.scx_destroy.argtypes = [ctypes.c_void_p]
        lib.scx_set_tpu_res.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.scx_node_add.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_double]
        lib.scx_node_get.restype = ctypes.c_double
        lib.scx_node_get.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.scx_node_chips_add.argtypes = [ctypes.c_void_p, _i32p,
                                           ctypes.c_int]
        lib.scx_node_chips.restype = ctypes.c_int
        lib.scx_node_chips.argtypes = [ctypes.c_void_p, _i32p, ctypes.c_int]
        lib.scx_class.restype = ctypes.c_int
        lib.scx_class.argtypes = [ctypes.c_void_p, _i32p, _f64p, ctypes.c_int,
                                  ctypes.c_int, ctypes.c_longlong]
        lib.scx_push.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                 ctypes.c_uint64]
        lib.scx_push_front.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                       ctypes.c_uint64]
        lib.scx_remove.restype = ctypes.c_int
        lib.scx_remove.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                   ctypes.c_uint64]
        lib.scx_head.restype = ctypes.c_uint64
        lib.scx_head.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.scx_pop_head.restype = ctypes.c_uint64
        lib.scx_pop_head.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.scx_pending.restype = ctypes.c_longlong
        lib.scx_pending.argtypes = [ctypes.c_void_p]
        lib.scx_feasible.restype = ctypes.c_int
        lib.scx_feasible.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.scx_acquire.restype = ctypes.c_int
        lib.scx_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int, _i32p,
                                    ctypes.c_int]
        lib.scx_gc.restype = ctypes.c_int
        lib.scx_gc.argtypes = [ctypes.c_void_p, _i32p, ctypes.c_int]
        lib.scx_release.argtypes = [ctypes.c_void_p, ctypes.c_int, _i32p,
                                    ctypes.c_int]
        lib.scx_prepare.restype = ctypes.c_int
        lib.scx_prepare.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                    _i32p, _f64p, ctypes.c_int, ctypes.c_int]
        for name in ("scx_commit", "scx_cancel_bundle", "scx_return_bundle",
                     "scx_has_bundle"):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.scx_drain_bundle.restype = ctypes.c_int
        lib.scx_drain_bundle.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                         _u64p, ctypes.c_int]
        lib.scx_poll.restype = ctypes.c_int
        lib.scx_poll.argtypes = [
            ctypes.c_void_p, _u64p, _i32p, _i32p, _i32p, _i32p,
            ctypes.c_int, ctypes.c_int, _u64p, _i32p,
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        _LIB = lib
    except Exception:
        _LIB_FAILED = True
        _LIB = None
    return _LIB


def _build(src: str, out_path: str):
    import subprocess
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    # build to a temp path + atomic rename: many raylet processes may
    # race to build on a fresh checkout
    fd, tmp = tempfile.mkstemp(suffix=".so",
                               dir=os.path.dirname(out_path))
    os.close(fd)
    try:
        subprocess.check_call(
            ["g++", "-O2", "-fPIC", "-shared", "-o", tmp, src])
        os.replace(tmp, out_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


class PendingTask:
    __slots__ = ("spec", "reply_fut", "demand", "tpu_demand", "submitted_at",
                 "sched_class", "tag")

    def __init__(self, spec, reply_fut):
        self.spec = spec
        self.reply_fut = reply_fut
        self.demand: Dict[str, float] = dict(spec.get("resources", {}))
        self.tpu_demand = int(self.demand.get("TPU", 0))
        self.submitted_at = time.monotonic()
        self.tag = 0
        # scheduling class: tasks in one class are interchangeable for
        # feasibility (same demand, same PG bundle), so the dispatch loop
        # can skip a whole class once its head is blocked (reference:
        # cluster_task_manager's per-SchedulingClass queues).  Spilled-in
        # tasks get their own class: they must not block the spillback
        # drain of plain tasks queued behind them.
        pg = spec.get("placement_group") or None
        bundle = (pg["pg_id"], pg.get("bundle_index", 0)) if pg else None
        self.sched_class = (tuple(sorted(self.demand.items())), bundle,
                            bool(spec.get("spilled_from")))


def bundle_key_of(spec) -> Optional[Tuple[str, int]]:
    pg = spec.get("placement_group")
    if not pg:
        return None
    return (pg["pg_id"], pg.get("bundle_index", 0))


class PyLedger:
    """Pure-Python ledger (the pre-schedcore raylet logic, verbatim)."""

    native = False

    def __init__(self, totals: Dict[str, float], chips: List[int]):
        self.available = dict(totals)
        self.free_chips = list(chips)
        self.prepared_bundles: Dict[Tuple[str, int], Dict[str, float]] = {}
        self.committed_bundles: Dict[Tuple[str, int], Dict[str, float]] = {}
        self.pg_available: Dict[Tuple[str, int], Dict[str, float]] = {}
        self.prepared_bundle_chips: Dict[Tuple[str, int], List[int]] = {}
        self.pg_chips: Dict[Tuple[str, int], List[int]] = {}
        self._classes: Dict[tuple, deque] = {}
        self._count = 0

    # ------------------------------------------------------------- queue

    def append(self, ptask: PendingTask):
        q = self._classes.get(ptask.sched_class)
        if q is None:
            q = self._classes[ptask.sched_class] = deque()
        q.append(ptask)
        self._count += 1

    def remove(self, ptask: PendingTask) -> bool:
        q = self._classes.get(ptask.sched_class)
        if q is None:
            return False
        try:
            q.remove(ptask)
        except ValueError:
            return False
        self._count -= 1
        return True

    def requeue_front(self, ptask: PendingTask):
        q = self._classes.get(ptask.sched_class)
        if q is None:
            q = self._classes[ptask.sched_class] = deque()
        q.appendleft(ptask)
        self._count += 1

    def head(self, sched_class) -> Optional[PendingTask]:
        q = self._classes.get(sched_class)
        return q[0] if q else None

    def pop_head(self, sched_class) -> Optional[PendingTask]:
        q = self._classes.get(sched_class)
        if not q:
            return None
        self._count -= 1
        return q.popleft()

    def pending_count(self) -> int:
        return self._count

    def pending_tasks(self) -> List[PendingTask]:
        return [pt for q in self._classes.values() for pt in q]

    def poll(self):
        """Scan class heads; atomically acquire + emit every dispatchable
        task.  Returns (dispatches, blocked_heads, more)."""
        dispatches: List[Tuple[PendingTask, Tuple[int, ...]]] = []
        blocked: List[PendingTask] = []
        dead = [c for c, q in self._classes.items() if not q]
        for c in dead:
            del self._classes[c]
        for cls, q in list(self._classes.items()):
            while q:
                head = q[0]
                chips = self.acquire(head)
                if chips is None:
                    blocked.append(head)
                    break
                q.popleft()
                self._count -= 1
                dispatches.append((head, chips))
        return dispatches, blocked, False

    # --------------------------------------------------------- resources

    def feasible(self, ptask: PendingTask) -> bool:
        key = bundle_key_of(ptask.spec)
        if key is not None:
            pool = self.pg_available.get(key)
            if pool is None:
                return False
            return all(pool.get(k, 0) + 1e-9 >= v
                       for k, v in ptask.demand.items() if k != "TPU") and \
                len(self.pg_chips.get(key, ())) >= ptask.tpu_demand
        for k, v in ptask.demand.items():
            if self.available.get(k, 0) + 1e-9 < v:
                return False
        # invariant: available["TPU"] == len(free_chips); check both so
        # feasibility can never say yes while the concrete chip pool is
        # short (the round-2 PG race)
        return len(self.free_chips) >= ptask.tpu_demand

    def acquire(self, ptask: PendingTask) -> Optional[Tuple[int, ...]]:
        key = bundle_key_of(ptask.spec)
        if key is not None:
            pool = self.pg_available.get(key)
            if pool is None:  # bundle returned while the task waited
                return None
            chip_src = self.pg_chips.setdefault(key, [])
        else:
            pool = self.available
            chip_src = self.free_chips
        if len(chip_src) < ptask.tpu_demand:
            return None
        for k, v in ptask.demand.items():
            if pool.get(k, 0) + 1e-9 < v:
                return None
        for k, v in ptask.demand.items():
            pool[k] = pool.get(k, 0) - v
        chips = tuple(chip_src[:ptask.tpu_demand])
        del chip_src[:ptask.tpu_demand]
        return chips

    def release(self, ptask: PendingTask, chips: Tuple[int, ...] = ()):
        key = bundle_key_of(ptask.spec)
        if key is not None:
            pool = self.pg_available.get(key)
            if pool is not None:
                for k, v in ptask.demand.items():
                    pool[k] = pool.get(k, 0) + v
                chip_dst = self.pg_chips.setdefault(key, [])
                chip_dst.extend(chips)
                chip_dst.sort()
            else:
                # bundle already returned: chips rejoin the NODE pool, and
                # the node's TPU count must follow them here
                self.free_chips.extend(chips)
                self.free_chips.sort()
                self.available["TPU"] = \
                    self.available.get("TPU", 0) + len(chips)
            return
        for k, v in ptask.demand.items():
            self.available[k] = self.available.get(k, 0) + v
        self.free_chips.extend(chips)
        self.free_chips.sort()

    # ----------------------------------------------------------- bundles

    def prepare_bundle(self, key, res: Dict[str, float]) -> bool:
        if key in self.prepared_bundles or key in self.committed_bundles:
            return True  # idempotent under GCS-restart retries
        n_tpu = int(res.get("TPU", 0))
        for k, v in res.items():
            if self.available.get(k, 0) + 1e-9 < v:
                return False
        if len(self.free_chips) < n_tpu:
            return False
        for k, v in res.items():
            self.available[k] = self.available.get(k, 0) - v
        self.prepared_bundle_chips[key] = self.free_chips[:n_tpu]
        del self.free_chips[:n_tpu]
        self.prepared_bundles[key] = res
        return True

    def commit_bundle(self, key) -> bool:
        if key in self.committed_bundles:
            return True  # idempotent retry
        res = self.prepared_bundles.pop(key, None)
        if res is None:
            return False
        self.committed_bundles[key] = res
        self.pg_available[key] = dict(res)
        self.pg_chips[key] = self.prepared_bundle_chips.pop(key, [])
        return True

    def cancel_bundle(self, key) -> bool:
        res = self.prepared_bundles.pop(key, None)
        if res is None:
            return False
        for k, v in res.items():
            self.available[k] = self.available.get(k, 0) + v
        self.free_chips.extend(self.prepared_bundle_chips.pop(key, []))
        self.free_chips.sort()
        return True

    def return_bundle(self, key) -> bool:
        res = self.committed_bundles.pop(key, None)
        self.pg_available.pop(key, None)
        if res is None:
            return False
        returned = self.pg_chips.pop(key, [])
        for k, v in res.items():
            if k == "TPU":
                continue
            self.available[k] = self.available.get(k, 0) + v
        # only chips physically back in hand rejoin the node pool (and
        # its TPU count) now; chips held by a still-running task of this
        # PG come back via release() when that task finishes
        self.free_chips.extend(returned)
        self.free_chips.sort()
        if "TPU" in res:
            self.available["TPU"] = \
                self.available.get("TPU", 0) + len(returned)
        return True

    def drain_bundle(self, key) -> List[PendingTask]:
        """Pop every queued task bound to this bundle (the PG is gone;
        they can never run)."""
        out: List[PendingTask] = []
        for cls, q in list(self._classes.items()):
            if cls[1] != key:
                continue
            out.extend(q)
            self._count -= len(q)
            del self._classes[cls]
        return out

    def drain_pg(self, pg_id: str) -> List[PendingTask]:
        """Drain every bundle of a placement group, including bundles
        this node never hosted (tasks can queue before prepare)."""
        out: List[PendingTask] = []
        for cls, q in list(self._classes.items()):
            if cls[1] is not None and cls[1][0] == pg_id:
                out.extend(q)
                self._count -= len(q)
                del self._classes[cls]
        return out

    def has_bundle(self, key) -> bool:
        return key in self.prepared_bundles or key in self.committed_bundles

    # ----------------------------------------------------- introspection

    def snapshot(self) -> Dict[str, float]:
        return dict(self.available)

    def avail_get(self, name: str) -> float:
        return self.available.get(name, 0.0)

    def node_chips_count(self) -> int:
        return len(self.free_chips)


class NativeLedger:
    """ctypes facade over the C++ schedcore.  Python retains only the
    tag→PendingTask map and the name/bundle interning tables; all
    accounting and queueing state lives in native memory."""

    native = True

    def __init__(self, totals: Dict[str, float], chips: List[int]):
        lib = _lib()
        assert lib is not None
        self._lib = lib
        self._h = lib.scx_create()
        self._res_ids: Dict[str, int] = {}
        self._res_names: List[str] = []
        self._report_keys = list(totals)
        self._bundle_ids: Dict[Tuple[str, int], int] = {}
        self._next_bundle = 0
        self._cls_ids: Dict[tuple, int] = {}
        self._cls_rev: Dict[int, tuple] = {}
        self._tags: Dict[int, PendingTask] = {}
        self._next_tag = 1
        # reusable poll buffers
        self._b_tags = (ctypes.c_uint64 * POLL_MAX)()
        self._b_cls = (ctypes.c_int32 * POLL_MAX)()
        self._b_off = (ctypes.c_int32 * POLL_MAX)()
        self._b_cnt = (ctypes.c_int32 * POLL_MAX)()
        self._b_chips = (ctypes.c_int32 * POLL_MAXCHIPS)()
        self._b_btags = (ctypes.c_uint64 * POLL_MAXBLOCKED)()
        self._b_bcls = (ctypes.c_int32 * POLL_MAXBLOCKED)()
        # sized for the node's whole chip pool: scx_acquire bounds its
        # write by this capacity, never past it
        self._chipbuf = (ctypes.c_int32 * max(4096, len(chips) + 8))()
        lib.scx_set_tpu_res(self._h, self._res("TPU"))
        for k, v in totals.items():
            lib.scx_node_add(self._h, self._res(k), float(v))
        if chips:
            arr = (ctypes.c_int32 * len(chips))(*chips)
            lib.scx_node_chips_add(self._h, arr, len(chips))

    def __del__(self):
        try:
            self._lib.scx_destroy(self._h)
        except Exception:
            pass

    def _res(self, name: str) -> int:
        rid = self._res_ids.get(name)
        if rid is None:
            rid = len(self._res_names)
            self._res_ids[name] = rid
            self._res_names.append(name)
        return rid

    def _bundle(self, key: Tuple[str, int]) -> int:
        bid = self._bundle_ids.get(key)
        if bid is None:
            bid = self._next_bundle
            self._next_bundle += 1
            self._bundle_ids[key] = bid
        return bid

    _GC_THRESHOLD = 512

    def _cls(self, ptask: PendingTask) -> int:
        cid = self._cls_ids.get(ptask.sched_class)
        if cid is None:
            if len(self._cls_ids) >= self._GC_THRESHOLD:
                self._gc_classes()
            names = list(ptask.demand)
            n = len(names)
            res = (ctypes.c_int32 * n)(*[self._res(k) for k in names])
            amt = (ctypes.c_double * n)(*[float(ptask.demand[k])
                                          for k in names])
            key = bundle_key_of(ptask.spec)
            bid = self._bundle(key) if key is not None else -1
            cid = self._lib.scx_class(self._h, res, amt, n,
                                      ptask.tpu_demand, bid)
            self._cls_ids[ptask.sched_class] = cid
            self._cls_rev[cid] = ptask.sched_class
        return cid

    def _gc_classes(self):
        """Tombstone empty native classes + drop the interning entries
        (a long-lived raylet seeing many distinct demand vectors must
        not grow state without bound).  Safe for in-flight tasks: a
        later release() re-interns an identical class by demand."""
        maxn = len(self._cls_ids)
        buf = (ctypes.c_int32 * maxn)()
        n = self._lib.scx_gc(self._h, buf, maxn)
        for i in range(n):
            sc = self._cls_rev.pop(buf[i], None)
            if sc is not None:
                self._cls_ids.pop(sc, None)

    def _res_arrays(self, res: Dict[str, float]):
        names = list(res)
        n = len(names)
        ids = (ctypes.c_int32 * n)(*[self._res(k) for k in names])
        amt = (ctypes.c_double * n)(*[float(res[k]) for k in names])
        return ids, amt, n

    # ------------------------------------------------------------- queue

    def append(self, ptask: PendingTask):
        cid = self._cls(ptask)
        tag = self._next_tag
        self._next_tag += 1
        ptask.tag = tag
        self._tags[tag] = ptask
        self._lib.scx_push(self._h, cid, tag)

    def remove(self, ptask: PendingTask) -> bool:
        tag = ptask.tag
        if tag not in self._tags:
            return False
        ok = self._lib.scx_remove(self._h, self._cls(ptask), tag)
        if ok:
            del self._tags[tag]
        return bool(ok)

    def requeue_front(self, ptask: PendingTask):
        cid = self._cls(ptask)
        if ptask.tag == 0 or ptask.tag not in self._tags:
            tag = self._next_tag
            self._next_tag += 1
            ptask.tag = tag
            self._tags[tag] = ptask
        self._lib.scx_push_front(self._h, cid, ptask.tag)

    def head(self, sched_class) -> Optional[PendingTask]:
        cid = self._cls_ids.get(sched_class)
        if cid is None:
            return None
        tag = self._lib.scx_head(self._h, cid)
        return self._tags.get(tag) if tag else None

    def pop_head(self, sched_class) -> Optional[PendingTask]:
        cid = self._cls_ids.get(sched_class)
        if cid is None:
            return None
        tag = self._lib.scx_pop_head(self._h, cid)
        if not tag:
            return None
        return self._tags.pop(tag, None)

    def pending_count(self) -> int:
        return int(self._lib.scx_pending(self._h))

    def pending_tasks(self) -> List[PendingTask]:
        return list(self._tags.values())

    def poll(self):
        lib = self._lib
        nblocked = ctypes.c_int(0)
        more = ctypes.c_int(0)
        n = lib.scx_poll(self._h, self._b_tags, self._b_cls, self._b_off,
                         self._b_cnt, self._b_chips, POLL_MAX,
                         POLL_MAXCHIPS, self._b_btags, self._b_bcls,
                         ctypes.byref(nblocked), POLL_MAXBLOCKED,
                         ctypes.byref(more))
        dispatches = []
        tags = self._tags
        for i in range(n):
            pt = tags.pop(self._b_tags[i], None)
            off, cnt = self._b_off[i], self._b_cnt[i]
            if pt is None:
                # Tag-map desync: the C++ ledger already deducted resources
                # and chips for this head.  Refund the orphaned acquire so
                # capacity is not leaked, and log the desync.
                chips = (ctypes.c_int32 * cnt)(*self._b_chips[off:off + cnt]) \
                    if cnt else ctypes.cast(None, _i32p)
                lib.scx_release(self._h, self._b_cls[i], chips, cnt)
                logger.warning("NativeLedger.poll: unknown tag %r from "
                               "scx_poll; refunded class %d (%d chips)",
                               self._b_tags[i], self._b_cls[i], cnt)
                continue
            dispatches.append((pt, tuple(self._b_chips[off:off + cnt])))
        blocked = []
        for i in range(nblocked.value):
            pt = tags.get(self._b_btags[i])
            if pt is not None:
                blocked.append(pt)
        return dispatches, blocked, bool(more.value)

    # --------------------------------------------------------- resources

    def feasible(self, ptask: PendingTask) -> bool:
        return bool(self._lib.scx_feasible(self._h, self._cls(ptask)))

    def acquire(self, ptask: PendingTask) -> Optional[Tuple[int, ...]]:
        got = self._lib.scx_acquire(self._h, self._cls(ptask),
                                    self._chipbuf, len(self._chipbuf))
        if got < 0:
            return None
        return tuple(self._chipbuf[:got])

    def release(self, ptask: PendingTask, chips: Tuple[int, ...] = ()):
        n = len(chips)
        arr = (ctypes.c_int32 * n)(*chips) if n else \
            ctypes.cast(None, _i32p)
        self._lib.scx_release(self._h, self._cls(ptask), arr, n)

    # ----------------------------------------------------------- bundles

    def prepare_bundle(self, key, res: Dict[str, float]) -> bool:
        ids, amt, n = self._res_arrays(res)
        return bool(self._lib.scx_prepare(
            self._h, self._bundle(key), ids, amt, n,
            int(res.get("TPU", 0))))

    def commit_bundle(self, key) -> bool:
        return bool(self._lib.scx_commit(self._h, self._bundle(key)))

    def cancel_bundle(self, key) -> bool:
        return bool(self._lib.scx_cancel_bundle(self._h, self._bundle(key)))

    def return_bundle(self, key) -> bool:
        return bool(self._lib.scx_return_bundle(self._h, self._bundle(key)))

    def drain_bundle(self, key) -> List[PendingTask]:
        """Pop every queued task bound to this bundle AND free the
        bundle's scheduling classes + interning entries (a PG-churning
        raylet must not accumulate dead classes — native Class structs
        are tombstoned, the id is never reused)."""
        bid = self._bundle_ids.get(key)
        if bid is None:
            return []
        maxn = max(16, self.pending_count())
        buf = (ctypes.c_uint64 * maxn)()
        n = self._lib.scx_drain_bundle(self._h, bid, buf, maxn)
        out = []
        for i in range(n):
            pt = self._tags.pop(buf[i], None)
            if pt is not None:
                out.append(pt)
        # drop interning entries for the dead classes; a later task for
        # the same (pg, bundle) re-interns cleanly
        for sc in [sc for sc, cid in self._cls_ids.items()
                   if sc[1] == key]:
            self._cls_rev.pop(self._cls_ids.pop(sc), None)
        # the bundle id must SURVIVE while native state (a committed
        # pool or a prepared reservation) still exists — drain_pg dooms
        # sibling-bundle tasks before those bundles' own return_bundle
        # arrives, and dropping the id here would orphan the pool (its
        # return would re-intern a fresh id, find no pool, and leak the
        # bundle's resources and chips permanently)
        if not self._lib.scx_has_bundle(self._h, bid):
            del self._bundle_ids[key]
        return out

    def drain_pg(self, pg_id: str) -> List[PendingTask]:
        """Drain EVERY bundle of a placement group — including bundles
        this node never hosted: tasks may queue against a bundle before
        its prepare lands, and a removed PG's return_bundle only arrives
        for bundles assigned here (the sibling-bundle hang)."""
        out: List[PendingTask] = []
        for key in [k for k in self._bundle_ids if k[0] == pg_id]:
            out.extend(self.drain_bundle(key))
        return out

    def has_bundle(self, key) -> bool:
        return bool(self._lib.scx_has_bundle(self._h, self._bundle(key)))

    # ----------------------------------------------------- introspection

    def snapshot(self) -> Dict[str, float]:
        g = self._lib.scx_node_get
        h = self._h
        out = {k: g(h, self._res_ids[k]) for k in self._report_keys}
        # custom resources that appeared after init (dynamic demands)
        for k, rid in self._res_ids.items():
            if k not in out and k != "TPU":
                v = g(h, rid)
                if v:
                    out[k] = v
        return out

    def avail_get(self, name: str) -> float:
        rid = self._res_ids.get(name)
        if rid is None:
            return 0.0
        return float(self._lib.scx_node_get(self._h, rid))

    def node_chips_count(self) -> int:
        return int(self._lib.scx_node_chips(
            self._h, ctypes.cast(None, _i32p), 0))


def make_ledger(totals: Dict[str, float], chips: List[int]):
    if os.environ.get("RTPU_NATIVE_SCHED", "1") != "0" and _lib() is not None:
        try:
            return NativeLedger(totals, chips)
        except Exception:
            pass
    return PyLedger(totals, chips)
