"""Node bootstrap: starts/owns the head and per-node processes.

Reference analogue: python/ray/_private/node.py (start_gcs_server:895,
start_raylet:928, start_head_processes:1045) + services.py. A head node runs
{GCS, raylet}; non-head nodes run {raylet}. Each service is a subprocess with
its own event loop; readiness is signaled through small files in the session
directory.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Any, Dict, Optional

from ray_tpu.common.config import SystemConfig
from ray_tpu.common.ids import NodeID


def new_session_dir() -> str:
    base = os.path.join(tempfile.gettempdir(), "ray_tpu")
    os.makedirs(base, exist_ok=True)
    session = os.path.join(
        base, f"session_{time.strftime('%Y%m%d-%H%M%S')}_{os.getpid()}_"
              f"{uuid.uuid4().hex[:6]}")
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    return session


def _wait_file(path: str, timeout: float = 30.0) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                data = f.read().strip()
            if data:
                return data
        time.sleep(0.02)
    raise TimeoutError(f"timed out waiting for {path}")


class NodeProcesses:
    """Handles to the subprocesses this driver started (for shutdown)."""

    def __init__(self):
        self.gcs_proc: Optional[subprocess.Popen] = None
        self.raylet_proc: Optional[subprocess.Popen] = None
        self.session_dir: str = ""
        self.gcs_address: str = ""
        self.raylet_address: str = ""
        self.node_id: str = ""
        self.store_path: str = ""

    def kill_all(self):
        for proc in (self.raylet_proc, self.gcs_proc):
            if proc is not None and proc.poll() is None:
                proc.terminate()
        for proc in (self.raylet_proc, self.gcs_proc):
            if proc is not None:
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
        # The raylet unlinks its shm segment in its SIGTERM handler; if it had
        # to be SIGKILLed the segment would leak into /dev/shm — unlink here
        # as a fallback (idempotent).
        if self.store_path:
            try:
                os.unlink(self.store_path)
            except OSError:
                pass


def sweep_stale_segments():
    """Remove plasmax segments in /dev/shm whose creating session is gone.

    A segment is stale when no live process has it mapped (checked via
    /proc/*/maps). Sessions killed with SIGKILL can leak segments; /dev/shm is
    a fixed-size tmpfs, so leaks eventually starve every later session.
    """
    import glob
    import time as _time
    now = _time.time()
    segs = []
    for seg in glob.glob("/dev/shm/rtpu_plasmax_*"):
        try:
            # skip very fresh segments: a concurrently starting raylet sits
            # between O_CREAT and mmap, so it appears in the glob but in no
            # /proc/*/maps yet
            if now - os.path.getmtime(seg) > 30.0:
                segs.append(seg)
        except OSError:
            pass
    if not segs:
        return
    mapped = set()
    for maps in glob.glob("/proc/[0-9]*/maps"):
        try:
            with open(maps) as f:
                data = f.read()
        except OSError:
            continue
        for seg in segs:
            if seg in data:
                mapped.add(seg)
    for seg in segs:
        if seg not in mapped:
            try:
                os.unlink(seg)
            except OSError:
                pass


def _defer_tpu_plugin(env: Dict[str, str]) -> None:
    """Strip the axon PJRT trigger for node-service children. The
    sitecustomize on TPU-tunnel boxes imports jax (~1.8s) in EVERY
    python process whose env carries PALLAS_AXON_POOL_IPS; raylets and
    the GCS never run jax, so parking the var makes node spawn ~10x
    faster (50-raylet sim clusters in seconds, not minutes). raylet_main
    and gcs_main restore it at startup so WORKER children still inherit
    the TPU tunnel normally."""
    saved = env.pop("PALLAS_AXON_POOL_IPS", None)
    if saved is not None:
        env["RTPU_SAVED_AXON_POOL_IPS"] = saved


def restore_tpu_plugin_env() -> None:
    """Counterpart of _defer_tpu_plugin, called by raylet_main/gcs_main."""
    saved = os.environ.pop("RTPU_SAVED_AXON_POOL_IPS", None)
    if saved is not None:
        os.environ["PALLAS_AXON_POOL_IPS"] = saved


def start_gcs(session_dir: str, config: SystemConfig,
              port: int = 0) -> subprocess.Popen:
    env = dict(os.environ)
    env["RTPU_SESSION_DIR"] = session_dir
    env["RTPU_GCS_PORT"] = str(port)
    env["RTPU_SYSTEM_CONFIG"] = config.to_json()
    _defer_tpu_plugin(env)
    log = open(os.path.join(session_dir, "logs", "gcs.log"), "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.gcs_main"],
        env=env, stdout=log, stderr=subprocess.STDOUT,
        start_new_session=True)


def start_raylet(session_dir: str, gcs_address: str, node_id: str,
                 resources: Dict[str, float], labels: Dict[str, str],
                 is_head: bool,
                 object_store_memory: Optional[int] = None,
                 env_overrides: Optional[Dict[str, str]] = None
                 ) -> subprocess.Popen:
    env = dict(os.environ)
    if env_overrides:
        # per-node env (simulated multi-"host" clusters: a distinct
        # RTPU_NODE_IP per raylet + RTPU_NET_FORCE_TCP makes two local
        # raylets talk to each other exclusively over TCP)
        env.update(env_overrides)
    env["RTPU_SESSION_DIR"] = session_dir
    env["RTPU_GCS_ADDRESS"] = gcs_address
    env["RTPU_NODE_ID"] = node_id
    env["RTPU_RESOURCES"] = json.dumps(resources)
    env["RTPU_LABELS"] = json.dumps(labels)
    env["RTPU_IS_HEAD"] = "1" if is_head else "0"
    if object_store_memory:
        env["RTPU_OBJECT_STORE_BYTES"] = str(object_store_memory)
    _defer_tpu_plugin(env)
    log = open(os.path.join(session_dir, "logs", f"raylet_{node_id[:8]}.log"),
               "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.raylet_main"],
        env=env, stdout=log, stderr=subprocess.STDOUT,
        start_new_session=True)


def start_head(config: SystemConfig,
               resources: Optional[Dict[str, float]] = None,
               labels: Optional[Dict[str, str]] = None,
               object_store_memory: Optional[int] = None,
               session_dir: Optional[str] = None) -> NodeProcesses:
    np_ = NodeProcesses()
    sweep_stale_segments()
    np_.session_dir = session_dir or new_session_dir()
    np_.gcs_proc = start_gcs(np_.session_dir, config)
    gcs_port = _wait_file(os.path.join(np_.session_dir, "gcs_port"))
    np_.gcs_address = f"127.0.0.1:{gcs_port}"
    node_id = NodeID.from_random().hex()
    np_.node_id = node_id
    np_.raylet_proc = start_raylet(np_.session_dir, np_.gcs_address, node_id,
                                   resources or {}, labels or {},
                                   is_head=True,
                                   object_store_memory=object_store_memory)
    info = _wait_file(os.path.join(np_.session_dir,
                                   f"raylet_{node_id[:8]}.json"))
    info = json.loads(info)
    np_.raylet_address = info["unix_address"]
    np_.store_path = info["store_path"]
    return np_


def preempt_raylet(proc: subprocess.Popen) -> bool:
    """Deliver a preemption notice to a raylet process the way a TPU
    spot/maintenance notice reaches the host: SIGUSR2. The raylet drains
    gracefully for its configured grace window (see
    raylet._preempt_drain), then exits — unlike ``kill_all``, which
    models an unannounced death. Returns False if the process is gone."""
    if proc is None or proc.poll() is not None:
        return False
    try:
        proc.send_signal(signal.SIGUSR2)
        return True
    except OSError:
        return False


def add_node(session_dir: str, gcs_address: str,
             resources: Optional[Dict[str, float]] = None,
             labels: Optional[Dict[str, str]] = None,
             object_store_memory: Optional[int] = None,
             env_overrides: Optional[Dict[str, str]] = None
             ) -> Dict[str, Any]:
    node_id = NodeID.from_random().hex()
    proc = start_raylet(session_dir, gcs_address, node_id, resources or {},
                        labels or {}, is_head=False,
                        object_store_memory=object_store_memory,
                        env_overrides=env_overrides)
    info = json.loads(_wait_file(
        os.path.join(session_dir, f"raylet_{node_id[:8]}.json")))
    info["proc"] = proc
    info["node_id"] = node_id
    return info
