"""Two-layer wire format: msgpack envelope + pickle5 out-of-band buffers.

Reference semantics: python/ray/_private/serialization.py — a msgpack envelope
for cheap primitives with an embedded cloudpickle payload whose pickle-protocol-5
out-of-band buffers enable zero-copy reads of numpy (and here, host-staged
jax.Array) data straight out of shared memory (SURVEY.md §8.4).

Wire layout:
    [uint32 header_len][msgpack header][buffer 0][buffer 1]...
header = {
    "inline": optional msgpack-native value (fast path, no pickle)
    "pickle": offset/len of the cloudpickle payload within the buffer region
    "buffers": list of (offset, len) for out-of-band buffers, 64-byte aligned
    "error": optional — marks the payload as a serialized exception
    "refs": list of serialized ObjectRefs contained in the value (for the
            borrower protocol: the deserializing process registers as a
            borrower with each ref's owner)
}
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Optional, Tuple

import cloudpickle
import msgpack

_ALIGN = 64

# msgpack-native types that skip pickle entirely
_INLINE_TYPES = (type(None), bool, int, float, str, bytes)


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


class SerializedObject:
    """A serialization result: header bytes + list of payload buffers."""

    __slots__ = ("header", "buffers", "total_size", "contained_refs")

    def __init__(self, header: bytes, buffers: List, total_size: int,
                 contained_refs: List):
        self.header = header
        self.buffers = buffers
        self.total_size = total_size
        self.contained_refs = contained_refs

    def to_bytes(self) -> bytes:
        if not self.buffers:
            # inline fast path (scalars, small replies): one concat
            # instead of bytearray + memoryview + write_into
            hdr = self.header
            return (struct.pack("<I", len(hdr)) + hdr
                    + b"\x00" * (self.total_size - 4 - len(hdr)))
        out = bytearray(self.total_size)
        self.write_into(memoryview(out))
        return bytes(out)

    def write_into(self, dest: memoryview):
        hlen = len(self.header)
        struct.pack_into("<I", dest, 0, hlen)
        dest[4:4 + hlen] = self.header
        off = _aligned(4 + hlen)
        for buf in self.buffers:
            b = memoryview(buf)
            if b.format != "B":
                b = b.cast("B")
            n = b.nbytes
            dest[off:off + n] = b
            off = _aligned(off + n)


def serialize(value: Any, is_error: bool = False) -> SerializedObject:
    contained_refs: List = []
    if not is_error and type(value) in _INLINE_TYPES:
        header = msgpack.packb({"inline": value, "v": 1},
                               use_bin_type=True)
        hlen = len(header)
        return SerializedObject(header, [], _aligned(4 + hlen), [])

    oob: List[pickle.PickleBuffer] = []

    def buffer_cb(pb: pickle.PickleBuffer) -> bool:
        raw = pb.raw()
        if raw.nbytes < 1024:
            return True  # tiny buffers: keep in-band
        oob.append(pb)
        return False

    from ray_tpu._private import ref_serialization
    with ref_serialization.collecting_refs(contained_refs):
        payload = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_cb)

    # layout: [pickle payload][oob buffers...]; offsets relative to the start
    # of the buffer region (which begins at aligned(4 + header_len))
    metas: List[Tuple[int, int]] = []
    off = _aligned(len(payload))
    raws = []
    for pb in oob:
        raw = pb.raw()
        if raw.format != "B":
            raw = raw.cast("B")
        metas.append((off, raw.nbytes))
        raws.append(raw)
        off = _aligned(off + raw.nbytes)
    header = msgpack.packb({
        "pickle": len(payload),
        "buffers": metas,
        "error": is_error,
        "refs": [r for r in contained_refs],
        "v": 1,
    }, use_bin_type=True)
    hlen = len(header)
    total = _aligned(4 + hlen) + off
    return SerializedObject(header, [payload] + raws, total, contained_refs)


def deserialize(data, out_of_band_ok: bool = True) -> Any:
    """Deserialize from bytes or a (shared-memory) memoryview.

    When ``data`` is a memoryview into the object store and the payload holds
    aligned numpy buffers, the arrays returned are zero-copy views; callers
    that outlive the view must copy (worker task args are copied by default
    only when the object may be evicted mid-task — primaries are pinned for
    the task's duration by the raylet, so views are safe there).
    """
    view = memoryview(data)
    hlen = struct.unpack_from("<I", view, 0)[0]
    header = msgpack.unpackb(bytes(view[4:4 + hlen]), raw=False)
    if "inline" in header:
        return header["inline"]
    region = view[_aligned(4 + hlen):]
    plen = header["pickle"]
    payload = region[:plen]
    buffers = [region[off:off + n] for off, n in header["buffers"]]
    value = pickle.loads(payload, buffers=buffers)
    if header.get("error"):
        raise value
    return value


def peek_is_error(data) -> bool:
    view = memoryview(data)
    hlen = struct.unpack_from("<I", view, 0)[0]
    header = msgpack.unpackb(bytes(view[4:4 + hlen]), raw=False)
    return bool(header.get("error"))


def serialize_error(exc: BaseException) -> SerializedObject:
    try:
        return serialize(exc, is_error=True)
    except Exception:
        from ray_tpu.exceptions import TaskError
        return serialize(TaskError("<unserializable>", None, repr(exc)),
                         is_error=True)
