"""The direct-execution lane: leased unary tasks over the native pump.

Closes the unary-task gap left after the round-5 lease work (PERF.md
"The unary task path"): the residual ~420 µs/round-trip was Python
asyncio handler work plus two thread handoffs inside the worker
(io loop -> exec thread -> io loop).  This module removes both:

* **DirectServer** (worker side): a second listening socket served by
  the native frame pump (src/rpccore/).  ONE thread runs
  recv -> decode -> execute -> reply; the user function's return value
  is msgpack-framed and written by the native sender without another
  thread or the event loop touching it.  Non-leased work (pushed tasks,
  actor calls, async handlers) keeps the asyncio path untouched.
* **DirectClient** (driver side): a native lease pool beside the asyncio
  one.  Submissions are sent from the CALLER's thread (no io-loop
  handoff); replies land on one delivery thread that stores results and
  wakes getters directly (``Worker._apply_task_result``) — asyncio never
  runs on the steady-state round trip.

Wire bytes are identical to the asyncio implementation
(docs/WIRE_PROTOCOL.md "Implementations"): the same ``leased_task``
REQUEST/REPLY frames, the same ``__hello__`` negotiation, the same
``cancel_task`` notify.  Chaos frame-fault sites (``protocol.send`` /
``protocol.recv``; docs/FAULT_TOLERANCE.md) are applied at the frame
boundary on both sides with the same semantics as
``protocol.Connection``, so a seeded fault schedule replays identically
against either implementation.

Failure contract (matches ``Worker._leased_call``): any transport
failure — send to a dead peer, connection close with calls in flight,
an ERROR reply — drops the lease and resubmits the task through the
batched raylet path (at-least-once, the task-retry contract).

Selection: ``RTPU_NATIVE_RPC=0`` (or a failed library build/load)
disables this module entirely; workers then skip the direct listener,
drivers fall back to the asyncio lease pool, and — mixed clusters — a
lease grant whose worker reports no ``direct_address`` permanently
reverts the driver to the asyncio pool.
"""

from __future__ import annotations

import asyncio
import collections
import itertools
import logging
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

import msgpack

from ray_tpu._private import chaos, protocol, rpccore, schema

logger = logging.getLogger(__name__)

_REQUEST, _REPLY, _ERROR, _NOTIFY = (protocol.REQUEST, protocol.REPLY,
                                     protocol.ERROR, protocol.NOTIFY)


def _pack(body) -> bytes:
    return msgpack.packb(body, use_bin_type=True)


def _chaos_send(pump: rpccore.Pump, cid: int, method: str,
                data: bytes, peer_host: str = "") -> bool:
    """Send one frame through the outbound chaos sites (same semantics
    as protocol.Connection._send: net.partition when the peer is
    off-box, then drop/delay/dup/reset).  Returns False when the
    connection is gone (incl. a chaos reset/partition)."""
    eng = chaos._ENGINE
    if eng is not None and peer_host:
        from ray_tpu._private import netx
        if netx.partitioned(peer_host):
            pump.close_conn(cid)  # an unplugged cable, not a FIN
            return False
    if eng is not None:
        act = eng.hit("protocol.send", method)
        if act is not None:
            op = act["op"]
            if op == "drop":
                return True  # frame lost on the wire; peer never sees it
            if op == "delay":
                time.sleep(float(act.get("delay_s", eng.delay_s)))
            elif op == "reset":
                pump.close_conn(cid)
                return False
            elif op == "dup":
                pump.send(cid, data)
    return pump.send(cid, data)


# --------------------------------------------------------------------------
# Worker side


class DirectServer:
    """The worker's direct-call lane: one thread, zero handoffs.

    Serves ``leased_task`` (execute inline, reply inline), ``__hello__``,
    ``ping`` and ``cancel_task`` on a dedicated unix socket owned by the
    native pump.  Anything else arriving here is bridged onto the
    worker's asyncio handler table (rare — owners only dial this socket
    for the leased fast path)."""

    def __init__(self, worker, path: str,
                 tcp_host: Optional[str] = None):
        self.worker = worker
        self.pump = rpccore.Pump()
        self.pump.listen(path)
        self.address = "unix:" + path
        # 1.8: the lane's host:port twin — same pump, same frames, so
        # an off-box owner pushes leased tasks and actor calls with
        # identical semantics (advertised via worker_register)
        self.tcp_address = ""
        if tcp_host:
            try:
                port = self.pump.listen_tcp(tcp_host, 0)
                self.tcp_address = f"{tcp_host}:{port}"
            except OSError:
                logger.warning("direct lane: TCP listener on %s failed; "
                               "lane stays unix-only", tcp_host)
        self.executed = 0  # direct tasks run (tests/bench introspection)
        self._stats_delta = 0
        self._stats_last = time.monotonic()
        self._validate = schema.validation_enabled()
        self._thread = threading.Thread(
            target=self._serve, name="rtpu-direct-exec", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- lane loop

    def _serve(self):
        import os as _os
        prof = None
        prof_dir = _os.environ.get("RTPU_CPROFILE_DIR")
        if prof_dir and "direct" in _os.environ.get(
                "RTPU_CPROFILE_PROCS", ""):
            # perf-debug aid (cProfile is per-thread — the worker
            # main-thread profiler can't see the lane)
            import cProfile
            prof = cProfile.Profile()
            prof.enable()
        try:
            self._serve_loop(prof, prof_dir)
        finally:
            if prof is not None:
                prof.disable()
                prof.dump_stats(_os.path.join(
                    prof_dir, f"direct_{_os.getpid()}.pstats"))

    def _serve_loop(self, prof=None, prof_dir=None):
        import os as _os
        last_dump = time.monotonic()
        while True:
            if prof is not None and time.monotonic() - last_dump > 3.0:
                # workers die via os._exit: flush the profile mid-run
                last_dump = time.monotonic()
                prof.dump_stats(_os.path.join(
                    prof_dir, f"direct_{_os.getpid()}.pstats"))
            try:
                evs = self.pump.next_batch(500)
            except Exception:
                return  # pump destroyed under us (disconnect)
            if evs is None:
                return  # shutdown
            for cid, kind, body in evs:
                if kind != rpccore.KIND_FRAME:
                    continue
                try:
                    self._on_frame(cid, body)
                except Exception:
                    logger.exception("direct lane: frame handling failed")
            self._flush_stats()

    def _on_frame(self, cid: int, body: bytes):
        try:
            frame = msgpack.unpackb(body, raw=False)
            mtype, seq, method, payload = frame
        except Exception:
            self.pump.close_conn(cid)  # garbage on the wire: drop peer
            return
        eng = chaos._ENGINE
        if eng is not None and mtype in (_REQUEST, _NOTIFY):
            # inbound chaos site at the frame boundary — identical
            # semantics to Connection._read_loop (replies are exempt:
            # reply loss is modeled on the sender side)
            act = eng.hit("protocol.recv", method)
            if act is not None:
                op = act["op"]
                if op == "drop":
                    return
                if op == "delay":
                    time.sleep(float(act.get("delay_s", eng.delay_s)))
                elif op == "reset":
                    self.pump.close_conn(cid)
                    return
                elif op == "dup":
                    self._dispatch(cid, mtype, seq, method, payload)
        self._dispatch(cid, mtype, seq, method, payload)

    def _dispatch(self, cid, mtype, seq, method, payload):
        from ray_tpu._private import worker as worker_mod
        w = self.worker
        if chaos._ENGINE is not None:
            # server-side kill site, parity with protocol.Server._handle
            chaos.hit("rpc.request", method)
        if method == "leased_task":
            try:
                if self._validate:
                    errors = schema.validate(method, payload)
                    if errors:
                        raise protocol.RpcError(
                            "wire schema violation: " + "; ".join(errors))
                result = w._execute_task(payload["spec"], [],
                                         reply=worker_mod.DIRECT_REPLY)
            except Exception as e:  # noqa: BLE001 — errors cross the wire
                self._reply(cid, seq, method,
                            f"{type(e).__name__}: {e}", error=True)
                return
            self.executed += 1
            self._stats_delta += 1
            self._reply(cid, seq, method, result)
        elif method == "__hello__":
            err = schema.check_hello(payload or {})
            if err:
                self._reply(cid, seq, method,
                            f"RpcError: protocol negotiation failed: {err}",
                            error=True)
            else:
                self._reply(cid, seq, method, schema.hello_payload())
        elif method == "ping":
            self._reply(cid, seq, method,
                        {"worker_id": w.worker_id.hex(), "mode": w.mode})
        elif method == "cancel_task":
            w._cancelled_tasks.add(payload["task_id"])
            if seq is not None and mtype == _REQUEST:
                self._reply(cid, seq, method, {})
        else:
            # bridge: run it on the asyncio handler table; the reply (if
            # requested) is sent from the future's callback — the lane
            # never blocks on slow-path work
            fut = asyncio.run_coroutine_threadsafe(
                w._handle_request(method, payload, None), w.io.loop)
            if mtype == _REQUEST and seq is not None:
                def _done(f, cid=cid, seq=seq, method=method):
                    try:
                        self._reply(cid, seq, method, f.result())
                    except Exception as e:  # noqa: BLE001
                        self._reply(cid, seq, method,
                                    f"{type(e).__name__}: {e}", error=True)
                fut.add_done_callback(_done)

    def _reply(self, cid, seq, method, result, error: bool = False):
        if seq is None:
            return
        body = _pack([_ERROR if error else _REPLY, seq, method, result])
        _chaos_send(self.pump, cid, method, body)

    def _flush_stats(self):
        """Leased workers bypass the raylet; keep its dispatch gauge
        truthful with one coalesced task_stats notify per 0.3 s of
        activity (same contract as _flush_leased_stats)."""
        if not self._stats_delta or self.worker.raylet is None:
            return
        now = time.monotonic()
        if now - self._stats_last < 0.3:
            return
        delta, self._stats_delta = self._stats_delta, 0
        self._stats_last = now
        try:
            self.worker.io.run_async(self.worker.raylet.notify(
                "task_stats", {"executed": delta}))
        except Exception:
            pass

    def close(self):
        self.pump.shutdown()
        self._thread.join(timeout=2)
        if not self._thread.is_alive():
            self.pump.destroy()


# --------------------------------------------------------------------------
# Driver side


class _DLease:
    __slots__ = ("key", "lease_id", "cid", "addr", "peer_host",
                 "inflight", "last_used", "acquiring", "revoked",
                 "released")

    def __init__(self, key):
        self.key = key
        self.lease_id: Optional[str] = None
        self.cid: Optional[int] = None
        self.addr: Optional[str] = None
        self.peer_host = ""  # '' = on-box (unix) lane
        self.inflight = 0
        self.last_used = 0.0
        self.acquiring = True
        self.revoked = False
        self.released = False


class DirectClient:
    """Owner-side lease pool over the native pump.

    All state lives behind one ``threading.Lock`` (NOT confined to the
    io thread like the asyncio pool — that confinement is exactly the
    handoff this lane removes).  Sends happen on whatever thread submits
    or delivers; the raylet RPCs (lease acquire/release) still ride the
    io loop, off the hot path."""

    MAX_INFLIGHT = 8           # mirrors _LeaseState.MAX_INFLIGHT
    POOL_MAX = 16
    MAX_WAITERS = 512
    IDLE_RELEASE_S = 2.0
    RETRY_COOLDOWN_S = 5.0

    def __init__(self, worker):
        self.worker = worker
        self.pump = rpccore.Pump()
        self.lock = threading.Lock()
        self.pools: Dict[Tuple, List[_DLease]] = {}
        self.parked: Dict[Tuple, Deque] = {}
        self.pending: Dict[Tuple[int, int], Tuple[dict, Any, _DLease]] = {}
        self.by_cid: Dict[int, _DLease] = {}
        self.fail_at: Dict[Tuple, float] = {}
        self.unsupported = False  # cluster's workers predate the lane
        self.submitted = 0        # tasks sent down the direct lane
        self._seq = itertools.count(1)
        self._closed = False
        self._idle_last = time.monotonic()
        # reactor handover: whoever holds _pump_lock runs the reactor.
        # A getter blocked on a direct task's result takes it over
        # (reap_result) so the reply is decoded ON the getter's thread —
        # no delivery-thread hop on the sync path; the background thread
        # parks on _no_getters while any getter is pumping.
        self._pump_lock = threading.Lock()
        self._getter_lock = threading.Lock()
        self._getters = 0
        self._last_getter = 0.0
        self._delivery_in_reactor = False
        self._no_getters = threading.Event()
        self._no_getters.set()
        self._thread = threading.Thread(
            target=self._deliver, name="rtpu-direct-recv", daemon=True)
        self._thread.start()

    def usable(self) -> bool:
        return not (self._closed or self.unsupported)

    # ------------------------------------------------------------- submit

    def submit(self, spec, state) -> bool:
        """Route a qualifying task down the direct lane.  True = this
        lane owns the task now (sent or parked); False = caller should
        use the asyncio lease pool / batched path."""
        if not self.usable():
            return False
        key = tuple(sorted((spec.get("resources") or {}).items()))
        now = time.monotonic()
        actions: List = []
        fast = None
        with self.lock:
            pool = self.pools.setdefault(key, [])
            # hot fast path: one live lease with capacity and nothing
            # parked — a single dict insert instead of the deque/drain
            # machinery (this is the steady state of a sync-unary loop)
            if len(pool) == 1 and not self.parked:
                L = pool[0]
                if L.cid is not None and not L.revoked \
                        and L.inflight < self.MAX_INFLIGHT:
                    seq = next(self._seq)
                    L.inflight += 1
                    L.last_used = now
                    self.pending[(L.cid, seq)] = (spec, state, L)
                    fast = (L, L.cid, seq)
            if fast is None:
                ready = [x for x in pool
                         if x.cid is not None and not x.revoked]
                acquiring = any(x.acquiring for x in pool)
                if not ready and not acquiring and \
                        now - self.fail_at.get(key, 0.0) <= \
                        self.RETRY_COOLDOWN_S:
                    return False  # leasing recently denied: normal path
                q = self.parked.setdefault(key, collections.deque())
                if len(q) >= self.MAX_WAITERS:
                    return False  # overflow: batched path absorbs bursts
                q.append((spec, state))
                # grow when empty or saturated (grow-until-denied sizes
                # the pool to node capacity, same policy as
                # _park_lease_waiter)
                if (not ready or min(x.inflight for x in ready) >= 2) \
                        and len(pool) < self.POOL_MAX and not acquiring \
                        and now - self.fail_at.get(key, 0.0) > \
                        self.RETRY_COOLDOWN_S:
                    L = _DLease(key)
                    pool.append(L)
                    self.worker.io.run_async(self._acquire(L))
                self._drain_locked(key, now, actions)
        if fast is not None:
            self._send_task(fast[0], fast[1], fast[2], spec, state)
        else:
            self._run_actions(actions)
        return True

    def _drain_locked(self, key, now, actions: List):
        """Feed parked tasks to ready leases (lock held).  Appends
        ("send", ...) / ("flush", items) work items for the caller to
        run after releasing the lock."""
        q = self.parked.get(key)
        if not q:
            self.parked.pop(key, None)
            return
        pool = self.pools.get(key) or []
        ready = [x for x in pool if x.cid is not None and not x.revoked]
        if not ready:
            if any(x.acquiring for x in pool):
                return  # stay parked; the acquisition settles the drain
            self.parked.pop(key, None)
            actions.append(("flush", list(q)))
            return
        while q:
            L = min(ready, key=lambda x: x.inflight)
            if L.inflight >= self.MAX_INFLIGHT:
                break  # completions re-drain
            spec, state = q.popleft()
            seq = next(self._seq)
            L.inflight += 1
            L.last_used = now
            self.pending[(L.cid, seq)] = (spec, state, L)
            actions.append(("send", L, L.cid, seq, spec, state))
        if not q:
            self.parked.pop(key, None)

    def _run_actions(self, actions: List):
        for item in actions:
            if item[0] == "send":
                _, L, cid, seq, spec, state = item
                self._send_task(L, cid, seq, spec, state)
            else:  # flush to the batched submission path
                for spec, state in item[1]:
                    state.worker_address = None
                    self.worker._enqueue_submit(spec, state)

    def _send_task(self, L: _DLease, cid: int, seq: int, spec, state):
        state.worker_address = L.addr
        state.direct = True
        self.submitted += 1
        data = _pack([_REQUEST, seq, "leased_task", {"spec": spec}])
        if not _chaos_send(self.pump, cid, "leased_task", data,
                           L.peer_host):
            self._fail_pending(cid, seq, spec, state)

    def _fail_pending(self, cid, seq, spec, state):
        """A send found the connection dead: resubmit through the
        batched path (once — the close event skips entries we popped)."""
        with self.lock:
            ent = self.pending.pop((cid, seq), None)
            if ent is not None:
                ent[2].inflight -= 1
        if ent is not None:
            state.worker_address = None
            state.direct = False
            self.worker._enqueue_submit(spec, state)

    # ----------------------------------------------------------- acquire

    async def _acquire(self, L: _DLease):
        """io thread: lease a worker, dial its direct socket."""
        w = self.worker
        try:
            r = await w.raylet.call("lease_worker",
                                    {"resources": dict(L.key)})
        except Exception as e:  # noqa: BLE001
            r = {"error": "LEASE_RPC_FAILED", "message": str(e)}
        now = time.monotonic()
        direct_addr = ""
        peer_host = ""
        if not r.get("error"):
            # 1.8: the lease reply advertises both lane endpoints; dial
            # unix when the worker is on this box, TCP otherwise
            from ray_tpu._private import netx
            direct_addr = netx.pick(r.get("direct_address"),
                                    r.get("direct_tcp_address"))
            peer_host = netx.host_of(direct_addr)
        cid = None
        if direct_addr:
            try:
                cid = self.pump.dial(direct_addr)
            except Exception:
                cid = None
        if cid is None:
            # denied, unreachable, or a worker without the direct lane
            if not r.get("error"):
                if not direct_addr:
                    # mixed cluster: this raylet's workers predate the
                    # lane — stop burning lease grants on probes and
                    # leave leasing to the asyncio pool
                    self.unsupported = True
                try:
                    await w.raylet.call(
                        "release_lease", {"lease_id": r["lease_id"]})
                except Exception:
                    pass
            actions: List = []
            with self.lock:
                if r.get("error"):
                    self.fail_at[L.key] = now
                L.acquiring = False
                pool = self.pools.get(L.key)
                if pool and L in pool:
                    pool.remove(L)
                self._drain_locked(L.key, now, actions)
            self._run_actions(actions)
            return
        # negotiation on the direct link (reply is discarded — seq 0 is
        # never a pending entry; an incompatible-major worker cannot
        # exist inside one session, the hello is for wire parity)
        _chaos_send(self.pump, cid, "__hello__",
                    _pack([_REQUEST, 0, "__hello__", schema.hello_payload()]),
                    peer_host)
        actions = []
        with self.lock:
            L.acquiring = False
            L.lease_id = r["lease_id"]
            L.addr = r["worker_address"]
            L.peer_host = peer_host
            L.cid = cid
            L.last_used = now
            self.by_cid[cid] = L
            self._drain_locked(L.key, now, actions)
        self._run_actions(actions)

    # ---------------------------------------------------------- delivery

    _GETTER_GRACE_S = 0.02

    def _deliver(self):
        while not self._closed:
            # park while a getter owns the reactor (it does our job)
            if not self._no_getters.wait(timeout=0.5):
                continue
            # resume grace: in a sync get loop the next getter arrives
            # within microseconds — re-entering the reactor here would
            # force a wake/bounce handover on EVERY round trip
            left = self._GETTER_GRACE_S - \
                (time.monotonic() - self._last_getter)
            if left > 0:
                time.sleep(left)
                continue
            if not self._pump_lock.acquire(timeout=0.2):
                continue
            try:
                self._delivery_in_reactor = True
                evs = self.pump.next_batch(500)
            except Exception:
                return  # pump destroyed under us
            finally:
                self._delivery_in_reactor = False
                self._pump_lock.release()
            if evs is None:
                return
            self._process_events(evs)
            self._idle_scan()

    def _process_events(self, evs) -> None:
        for cid, kind, body in evs:
            if kind == rpccore.KIND_CLOSED:
                self._on_closed(cid)
            elif kind == rpccore.KIND_FRAME:
                try:
                    self._on_frame(cid, body)
                except Exception:
                    logger.exception("direct delivery: frame failed")
            # KIND_WAKE: reactor-handover nudge, nothing to process

    def reap_result(self, state, timeout: float) -> bool:
        """Pump the reactor from the GETTER's thread until ``state`` is
        done (True) or ``timeout`` elapses (False).  Decoding the reply
        on the thread that wants it removes the delivery-thread hop —
        with the worker's one-thread lane, a sync round trip is then
        caller-thread → worker lane → caller-thread.  Concurrent getters
        contend on the pump lock; losers fall back to short event waits
        (the winner completes their tasks too)."""
        deadline = time.monotonic() + timeout
        with self._getter_lock:
            self._getters += 1
            self._no_getters.clear()
        try:
            if self._delivery_in_reactor:
                # bounce the delivery thread out of its epoll; when it is
                # already parked (steady sync loop) the wake — and the
                # synthetic event the getter would then have to drain —
                # is skipped entirely
                self.pump.wake()
            while not state.done:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                if self._pump_lock.acquire(timeout=min(left, 0.05)):
                    try:
                        if state.done:
                            return True
                        try:
                            evs = self.pump.next_batch(
                                int(min(left, 0.2) * 1000) or 1)
                        except Exception:
                            return state.result_event.wait(left)
                        if evs is None:  # pump shut down mid-get
                            return state.result_event.wait(left)
                        self._process_events(evs)
                    finally:
                        self._pump_lock.release()
                else:
                    # another getter is pumping; it processes all
                    # inbound replies, ours included
                    if state.result_event.wait(0.005):
                        return True
            return True
        finally:
            with self._getter_lock:
                self._getters -= 1
                self._last_getter = time.monotonic()
                if self._getters == 0:
                    self._no_getters.set()

    def _on_frame(self, cid: int, body: bytes):
        try:
            frame = msgpack.unpackb(body, raw=False)
            mtype, seq, _method, payload = frame
        except Exception:
            self.pump.close_conn(cid)
            return
        if mtype not in (_REPLY, _ERROR):
            return  # workers never initiate requests on this lane
        now = time.monotonic()
        actions: List = []
        with self.lock:
            ent = self.pending.pop((cid, seq), None)
            if ent is None:
                return  # __hello__ reply, dup, or already failed over
            spec, state, L = ent
            L.inflight -= 1
            L.last_used = now
            ack = L.revoked and L.inflight == 0
            if self.parked:
                self._drain_locked(L.key, now, actions)
        if actions:
            self._run_actions(actions)
        if ack:
            self._release(L, inflight0=True)
        if mtype == _REPLY:
            # result delivery runs HERE, on the delivery thread —
            # stores returns and wakes the getter without the io loop
            self.worker._apply_task_result(payload)
        else:
            # ERROR reply = transport-level failure (parity with
            # _leased_call's except branch): drop the lease, resubmit
            self._drop_lease(L, release=True)
            state.worker_address = None
            state.direct = False
            self.worker._enqueue_submit(spec, state)

    def _on_closed(self, cid: int):
        resubmit = []
        actions: List = []
        with self.lock:
            L = self.by_cid.pop(cid, None)
            for k in [k for k in self.pending if k[0] == cid]:
                spec, state, _L = self.pending.pop(k)
                resubmit.append((spec, state))
            if L is not None:
                L.cid = None
                if L.inflight:
                    L.inflight = 0
                pool = self.pools.get(L.key)
                if pool and L in pool:
                    pool.remove(L)
                self._drain_locked(L.key, time.monotonic(), actions)
        for spec, state in resubmit:
            state.worker_address = None
            state.direct = False
            self.worker._enqueue_submit(spec, state)
        self._run_actions(actions)
        if L is not None:
            self._release(L)  # idempotent raylet-side; reclaims capacity

    def _idle_scan(self):
        now = time.monotonic()
        if now - self._idle_last < 0.5:
            return
        self._idle_last = now
        drops: List[_DLease] = []
        with self.lock:
            for pool in self.pools.values():
                for L in pool:
                    if L.cid is not None and not L.revoked \
                            and L.inflight == 0 \
                            and now - L.last_used > self.IDLE_RELEASE_S:
                        drops.append(L)
        for L in drops:
            self._drop_lease(L, release=True)

    # ------------------------------------------------- revoke/cancel/drop

    def on_revoke(self, lease_id: str) -> bool:
        """io thread (revoke_lease handler): stop routing through this
        lease; ack the drain (release inflight=0) once in-flight calls
        complete — the raylet defers re-idling until then."""
        actions: List = []
        with self.lock:
            L = None
            for pool in self.pools.values():
                for x in pool:
                    if x.lease_id == lease_id:
                        L = x
                        break
                if L is not None:
                    break
            if L is None:
                return False
            L.revoked = True
            self.fail_at[L.key] = time.monotonic()
            pool = self.pools.get(L.key)
            if pool and L in pool:
                pool.remove(L)
            ack = L.inflight == 0
            self._drain_locked(L.key, time.monotonic(), actions)
        self._run_actions(actions)
        if ack:
            self._release(L, inflight0=True)
        return True

    def cancel(self, task_id: str, state) -> bool:
        """Cancel a task this lane owns: unpark it (resolving the refs
        cancelled), or notify the executing worker."""
        target_cid = None
        unparked = False
        with self.lock:
            for q in self.parked.values():
                for item in q:
                    if item[0]["task_id"] == task_id:
                        q.remove(item)
                        unparked = True
                        break
                if unparked:
                    break
            target_host = ""
            if not unparked:
                for (cid, _seq), (spec, _st, _L) in self.pending.items():
                    if spec["task_id"] == task_id:
                        target_cid = cid
                        target_host = _L.peer_host
                        break
        if unparked:
            # outside the lock: resolving fires result-event callbacks
            # (e.g. serve router slot release) that may re-enter submit
            self.worker._resolve_cancelled(task_id, state)
            return True
        if target_cid is not None:
            _chaos_send(self.pump, target_cid, "cancel_task",
                        _pack([_NOTIFY, None, "cancel_task",
                               {"task_id": task_id}]), target_host)
            return True
        return False

    def _drop_lease(self, L: _DLease, release: bool = False):
        actions: List = []
        with self.lock:
            pool = self.pools.get(L.key)
            if pool and L in pool:
                pool.remove(L)
            cid, L.cid = L.cid, None
            if cid is not None:
                self.by_cid.pop(cid, None)
            self._drain_locked(L.key, time.monotonic(), actions)
        if cid is not None:
            self.pump.close_conn(cid)
        self._run_actions(actions)
        if release:
            self._release(L)

    def _release(self, L: _DLease, inflight0: bool = False):
        with self.lock:
            if L.released or L.lease_id is None:
                return
            L.released = True
            lease_id = L.lease_id
        payload = {"lease_id": lease_id}
        if inflight0:
            payload["inflight"] = 0

        async def _rel():
            try:
                await self.worker.raylet.call("release_lease", payload)
            except Exception:
                pass  # raylet-side conn cleanup is the backstop
        try:
            self.worker.io.run_async(_rel())
        except Exception:
            pass

    # ------------------------------------------------------------- close

    def close(self):
        self._closed = True
        flush = []
        with self.lock:
            for q in self.parked.values():
                flush.extend(q)
            self.parked.clear()
        for spec, state in flush:
            state.worker_address = None
            self.worker._enqueue_submit(spec, state)
        self.pump.shutdown()
        self._thread.join(timeout=2)
        if not self._thread.is_alive():
            self.pump.destroy()
