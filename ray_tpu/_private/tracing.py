"""End-to-end distributed tracing: typed spans + critical-path analysis.

Reference analogue: Dapper-style request tracing
(util/tracing/tracing_helper.py propagates OpenTelemetry context through
TaskSpecs in the reference; the dashboard's timeline only ever renders
flat events). Here the ``trace_ctx`` that already rides every task spec
(trace_id/span_id/parent_span_id, worker.py ``_trace_ctx_for_submit``)
becomes queryable: every subsystem records *typed spans* into a bounded
per-process buffer, a background flusher ships them in batches to the
GCS (``trace_spans`` RPC → ``gcs.TraceTable``, bounded + indexed + a
visible drop counter, the PR-6 pattern), and ``get_trace`` merges them
with task-lifecycle spans synthesized from the state engine's task
records — no new instrumentation on the task hot path.

Span shape (one dict per span; only non-None fields ride the wire)::

    {"trace_id", "span_id", "parent_span_id",   # linkage
     "name",                                    # human label
     "kind",     # serve.request|serve.replica|task|dag.hop|object.pull
     "phase",    # queue|schedule|dispatch|transfer|execute|deserialize
     "start_ts", "end_ts",                      # wall-clock seconds
     "status",   # ok | error | shed
     "node_id", "pid", "attrs"}

Sampling (bounds overhead end to end):
  - head sampling: ``RTPU_TRACE_SAMPLE`` in [0,1] (default 0.1, the
    Dapper stance: production tracing is sampled) decides per *trace
    id* with a deterministic hash, so every process agrees on whether
    a trace is recorded without coordination. Unsampled serve requests
    skip span recording AND context propagation — their only cost is
    two clock reads on the root span. Task-lifecycle spans are NOT
    subject to this rate: they are synthesized from the state engine's
    task events, so ``get_trace`` always explains a task.
  - tail keep: spans that FAILED or ran longer than
    ``RTPU_TRACE_SLOW_S`` (default 1.0 s) are always recorded, even
    when head-sampled out — the slow/broken tail is exactly what the
    critical-path analyzer exists for;
  - ``RTPU_TRACING=0`` disables recording entirely (the overhead gate
    in ``_BENCH_TRACE`` compares default sampling against this).

The critical-path analyzer (``critical_path``) attributes a root span's
wall time to named phases with a deepest-active-span sweep: at every
instant of the root's interval the deepest span covering it wins, so
overlapping parent/child spans never double-count and uncovered gaps
fall to the nearest enclosing span's phase. ``aggregate_critical_path``
sums the same attribution across a cohort (e.g. a game day's p99
requests).
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

PHASES = ("queue", "schedule", "dispatch", "transfer", "execute",
          "deserialize", "submit", "other")

_ROOT_PARENTS = (None, "", "root")

# ------------------------------------------------------------------ config

_enabled: Optional[bool] = None
_sample_rate: Optional[float] = None
_slow_s: Optional[float] = None
_node_id: str = ""

DEFAULT_SAMPLE_RATE = 0.1


def refresh() -> None:
    """Re-read the env knobs (tests and the bench toggle them within
    one process; the hot path must not touch os.environ per span)."""
    global _enabled, _sample_rate, _slow_s, _node_id
    _enabled = os.environ.get("RTPU_TRACING", "1") not in ("0", "false")
    try:
        _sample_rate = min(1.0, max(0.0, float(
            os.environ.get("RTPU_TRACE_SAMPLE", DEFAULT_SAMPLE_RATE))))
    except ValueError:
        _sample_rate = DEFAULT_SAMPLE_RATE
    try:
        _slow_s = float(os.environ.get("RTPU_TRACE_SLOW_S", 1.0))
    except ValueError:
        _slow_s = 1.0
    _node_id = (os.environ.get("RTPU_NODE_ID") or "")[:12]


def enabled() -> bool:
    if _enabled is None:
        refresh()
    return _enabled


def sampled(trace_id: Optional[str]) -> bool:
    """Deterministic head-sampling decision for one trace id: every
    process hashes the id the same way, so a trace is either recorded
    by ALL its participants or by none (no half-traces from skewed
    coin flips)."""
    if _enabled is None:
        refresh()
    if not _enabled:
        return False
    if _sample_rate >= 1.0:
        return True
    if _sample_rate <= 0.0 or not trace_id:
        return False
    h = zlib.crc32(trace_id.encode()) & 0xFFFFFFFF
    return h / 4294967296.0 < _sample_rate


# span ids: a per-process random salt + counter instead of an
# os.urandom syscall per span (several spans per serve request ride
# the hot path; the 1-core overhead gate counts every microsecond)
_id_salt = os.urandom(5).hex()
_id_lock = threading.Lock()
_id_n = 0


def new_span_id() -> str:
    global _id_n
    with _id_lock:
        _id_n += 1
        n = _id_n
    return f"{_id_salt}{n:06x}"


def new_trace_id() -> str:
    return new_span_id()


# ------------------------------------------------------------------ buffer

def _ring_cap() -> int:
    return int(os.environ.get("RTPU_TRACE_BUFFER", 8192))


def _flush_interval() -> float:
    return float(os.environ.get("RTPU_TRACE_FLUSH_S", 0.5))


_lock = threading.Lock()
_buf: List[Dict[str, Any]] = []
_dropped = 0
_flusher_started = False
_flusher_stop: Optional[threading.Event] = None
_sender: Optional[Callable[[Dict[str, Any]], bool]] = None

_BATCH_MAX = 4000


def record_span(trace_id: str, span_id: str, name: str, *,
                parent_span_id: Optional[str] = None,
                kind: str = "span", phase: str = "other",
                start_ts: float, end_ts: float,
                status: str = "ok",
                attrs: Optional[Dict[str, Any]] = None) -> None:
    """Record one finished span. O(1) lock-append, never an RPC.

    Head-sampled-out spans are still kept when they are slow or broken
    (tail keep) — a partial trace for the p99.9 straggler beats a
    complete trace for the median request."""
    if not enabled():
        return
    if not sampled(trace_id) and status == "ok" \
            and (end_ts - start_ts) < _slow_s:
        return
    span = {"trace_id": trace_id, "span_id": span_id, "name": name,
            "kind": kind, "phase": phase,
            "start_ts": start_ts, "end_ts": end_ts,
            "status": status, "pid": os.getpid()}
    if parent_span_id is not None:
        span["parent_span_id"] = parent_span_id
    if attrs:
        span["attrs"] = attrs
    if _node_id:
        span["node_id"] = _node_id
    global _dropped
    with _lock:
        _buf.append(span)
        over = len(_buf) - _ring_cap()
        if over > 0:
            del _buf[:over]
            _dropped += over
    _ensure_flusher()


class Span:
    """A live span handle: start now, ``finish()`` records it.

    ``child_ctx()`` is the propagation payload (what rides a task spec,
    a serve kwarg, or a dag frame) — the receiving side parents its own
    spans under this span."""

    __slots__ = ("trace_id", "span_id", "parent_span_id", "name",
                 "kind", "phase", "start_ts", "attrs", "_done")

    def __init__(self, trace_id: str, name: str, *,
                 parent_span_id: Optional[str] = None,
                 kind: str = "span", phase: str = "other",
                 attrs: Optional[Dict[str, Any]] = None,
                 start_ts: Optional[float] = None):
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_span_id = parent_span_id
        self.name = name
        self.kind = kind
        self.phase = phase
        self.attrs = attrs
        self.start_ts = time.time() if start_ts is None else start_ts
        self._done = False

    def child_ctx(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def trace_ctx(self) -> Dict[str, str]:
        """worker.task_context-compatible ctx: submits made while this
        span is current parent under it."""
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_span_id": self.parent_span_id or "root"}

    def finish(self, status: str = "ok",
               end_ts: Optional[float] = None) -> None:
        if self._done:  # idempotent: error paths may double-finish
            return
        self._done = True
        record_span(self.trace_id, self.span_id, self.name,
                    parent_span_id=self.parent_span_id, kind=self.kind,
                    phase=self.phase, start_ts=self.start_ts,
                    end_ts=time.time() if end_ts is None else end_ts,
                    status=status, attrs=self.attrs)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.finish("error" if exc_type is not None else "ok")


def span_if(trace_id: Optional[str], name: str, **kw) -> Optional[Span]:
    """A Span when tracing is on and the trace is worth starting, else
    None (callers guard each touch with ``if s is not None``). Unlike
    ``record_span``'s tail keep, a *head* decision must be made here —
    slow/failed spans under a sampled-out trace are still caught
    because ``Span.finish`` routes through ``record_span``."""
    if not enabled() or not trace_id:
        return None
    return Span(trace_id, name, **kw)


# ------------------------------------------------------------------ flush

def drain(max_n: int = _BATCH_MAX) -> Tuple[List[Dict[str, Any]], int]:
    global _dropped
    with _lock:
        batch = _buf[:max_n]
        del _buf[:max_n]
        dropped, _dropped = _dropped, 0
    return batch, dropped


def requeue(spans: List[Dict[str, Any]], dropped: int = 0) -> None:
    global _dropped
    if not spans and not dropped:
        return
    with _lock:
        _buf[:0] = spans
        _dropped += dropped
        over = len(_buf) - _ring_cap()
        if over > 0:
            del _buf[:over]
            _dropped += over


def pending_count() -> int:
    with _lock:
        return len(_buf)


def set_sender(fn: Optional[Callable[[Dict[str, Any]], bool]]) -> None:
    global _sender
    _sender = fn


def _default_send(payload: Dict[str, Any], timeout: float = 5.0) -> bool:
    from ray_tpu._private import worker as worker_mod
    w = worker_mod._global_worker
    if w is None or not w.connected:
        return False
    try:
        w.call_sync(w.gcs, "trace_spans", payload, timeout=timeout)
        return True
    except Exception:
        return False


def flush(send_timeout: float = 5.0) -> bool:
    batch, dropped = drain()
    if not batch and not dropped:
        return True
    payload = {"spans": batch, "dropped": dropped}
    ok = (_sender(payload) if _sender is not None
          else _default_send(payload, timeout=send_timeout))
    if ok:
        return True
    requeue(batch, dropped)
    return False


def flush_all(timeout: float = 2.0) -> None:
    """Best-effort full drain (process teardown), bounded by ``timeout``
    so a dead GCS cannot stall shutdown."""
    deadline = time.monotonic() + timeout
    while pending_count():
        left = deadline - time.monotonic()
        if left <= 0 or not flush(send_timeout=max(0.1, left)):
            return


def _ensure_flusher() -> None:
    global _flusher_started, _flusher_stop
    if _flusher_started:
        return
    _flusher_started = True
    stop = _flusher_stop = threading.Event()

    def loop():
        while not stop.wait(_flush_interval()):
            try:
                flush()
            except Exception:
                pass

    threading.Thread(target=loop, daemon=True,
                     name="rtpu-trace-spans").start()


def stop_flusher() -> None:
    """Worker shutdown: stop the flusher thread and allow a later
    reconnect to start a fresh one (leaving ``_flusher_started`` set
    leaks one thread per init/shutdown cycle in tests)."""
    global _flusher_started, _flusher_stop
    if _flusher_stop is not None:
        _flusher_stop.set()
    _flusher_stop = None
    _flusher_started = False


# ------------------------------------------------- task-span synthesis

def synthesize_task_spans(rec: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Task-lifecycle phase spans from ONE state-engine task record —
    no extra instrumentation on the submit/execute hot paths; the
    task-event pipeline already carries every timestamp this needs.

    Layout (ids derive from the propagated span id, so the task span
    slots into the trace tree exactly where ``_trace_ctx_for_submit``
    said it would)::

        <span_id>            name=<fn>      phase=submit  (whole task)
          <span_id>:queue    owner submit -> raylet queue
          <span_id>:schedule raylet queue -> worker picked
          <span_id>:dispatch worker picked -> RUNNING (push + args)
          <span_id>:execute  RUNNING -> terminal
            <span_id>:deser    arg deserialization (deser_s)
            <span_id>:ship     return shipping     (ship_s)
    """
    tc = rec.get("trace_ctx") or {}
    trace_id, span_id = tc.get("trace_id"), tc.get("span_id")
    if not trace_id or not span_id:
        return []
    st = rec.get("state_ts") or {}
    submit = st.get("PENDING_SCHEDULING") or rec.get("created_ts")
    queued = st.get("PENDING_NODE_ASSIGNMENT")
    dispatched = rec.get("dispatch_ts")
    running = st.get("RUNNING") or rec.get("start_ts")
    end = rec.get("end_ts")
    if submit is None:
        return []
    last = max(v for v in (submit, queued, dispatched, running, end)
               if v is not None)
    status = "error" if rec.get("state") == "FAILED" else (
        "ok" if end is not None else "running")
    name = rec.get("name") or rec.get("task_id", "")[:12]
    base = {"trace_id": trace_id, "kind": "task",
            "node_id": rec.get("node_id"), "pid": rec.get("worker_pid")}
    spans = [{**base, "span_id": span_id,
              "parent_span_id": tc.get("parent_span_id"),
              "name": name, "phase": "submit",
              "start_ts": submit, "end_ts": last, "status": status,
              "attrs": {"task_id": rec.get("task_id"),
                        "state": rec.get("state"),
                        "attempt": rec.get("attempt", 0)}}]

    def child(suffix, phase, t0, t1, parent=span_id):
        if t0 is None or t1 is None or t1 < t0:
            return
        spans.append({**base, "span_id": f"{span_id}:{suffix}",
                      "parent_span_id": parent,
                      "name": f"{name}:{suffix}", "phase": phase,
                      "start_ts": t0, "end_ts": t1, "status": "ok"})

    child("queue", "queue", submit, queued)
    child("schedule", "schedule", queued, dispatched or running)
    if dispatched is not None:
        child("dispatch", "dispatch", dispatched, running)
    child("execute", "execute", running, end)
    if running is not None and rec.get("deser_s"):
        child("deser", "deserialize", running,
              running + float(rec["deser_s"]), parent=f"{span_id}:execute")
    if end is not None and rec.get("ship_s"):
        child("ship", "transfer", end - float(rec["ship_s"]), end,
              parent=f"{span_id}:execute")
    return spans


# ------------------------------------------------- tree / critical path

def build_tree(spans: List[Dict[str, Any]]
               ) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """(roots, orphans). A root's parent is absent-by-design
    (None/""/"root"); an orphan names a parent that is not in the span
    set — the reconcile completeness check fails on orphans."""
    ids = {s.get("span_id") for s in spans}
    roots, orphans = [], []
    for s in spans:
        p = s.get("parent_span_id")
        if p in _ROOT_PARENTS:
            roots.append(s)
        elif p not in ids:
            orphans.append(s)
    return roots, orphans


def tree_complete(spans: List[Dict[str, Any]]) -> Tuple[bool, str]:
    """Is this span set a well-formed tree? (>=1 root, no orphans)."""
    if not spans:
        return False, "no spans"
    roots, orphans = build_tree(spans)
    if not roots:
        return False, "no root span"
    if orphans:
        return False, (f"{len(orphans)} orphan spans, e.g. "
                       f"{orphans[0].get('name')} -> missing parent "
                       f"{orphans[0].get('parent_span_id')}")
    return True, f"{len(spans)} spans, {len(roots)} root(s)"


def _depths(spans: List[Dict[str, Any]]) -> Dict[str, int]:
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    depths: Dict[str, int] = {}

    def depth(sid: str, hop: int = 0) -> int:
        if sid in depths:
            return depths[sid]
        s = by_id.get(sid)
        if s is None or hop > len(by_id):  # cycle guard
            return 0
        p = s.get("parent_span_id")
        d = 0 if p in _ROOT_PARENTS or p not in by_id \
            else depth(p, hop + 1) + 1
        depths[sid] = d
        return d

    for sid in by_id:
        depth(sid)
    return depths


def critical_path(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Attribute the root span's wall time to named phases.

    Sweep attribution: sort all span starts/ends; inside the root's
    interval, each time slice charges the DEEPEST active span's phase
    (ties: the most recently started). Overlap never double-counts and
    gaps fall to the enclosing span — so ``attributed_s`` always equals
    the root interval and the phase table sums to 100% of it.
    """
    spans = [s for s in spans
             if s.get("start_ts") is not None
             and s.get("end_ts") is not None
             and s["end_ts"] >= s["start_ts"]]
    if not spans:
        return {"total_s": 0.0, "phases": {}, "segments": [],
                "attributed_s": 0.0}
    roots, _ = build_tree(spans)
    if not roots:  # orphan-only set: attribute over the envelope
        t0 = min(s["start_ts"] for s in spans)
        t1 = max(s["end_ts"] for s in spans)
    else:
        t0 = min(r["start_ts"] for r in roots)
        t1 = max(r["end_ts"] for r in roots)
    depths = _depths(spans)
    events: List[Tuple[float, int, int]] = []
    for i, s in enumerate(spans):
        events.append((max(s["start_ts"], t0), 0, i))
        events.append((min(s["end_ts"], t1), 1, i))
    events.sort(key=lambda e: (e[0], e[1]))
    active: Dict[int, None] = {}
    phases: Dict[str, float] = {}
    segments: List[Dict[str, Any]] = []
    prev = t0

    def charge(upto: float):
        nonlocal prev
        if upto <= prev or not active:
            prev = max(prev, upto)
            return
        # deepest active span wins; among equals the latest start
        i = max(active, key=lambda j: (depths.get(
            spans[j].get("span_id", ""), 0), spans[j]["start_ts"]))
        s = spans[i]
        phase = s.get("phase") or "other"
        phases[phase] = phases.get(phase, 0.0) + (upto - prev)
        if segments and segments[-1]["span_id"] == s.get("span_id") \
                and abs(segments[-1]["t1"] - prev) < 1e-9:
            segments[-1]["t1"] = upto  # coalesce adjacent slices
        else:
            segments.append({"t0": prev, "t1": upto,
                             "span_id": s.get("span_id"),
                             "name": s.get("name"), "phase": phase})
        prev = upto

    for ts, kind, i in events:
        charge(min(max(ts, t0), t1))
        if kind == 0:
            active[i] = None
        else:
            active.pop(i, None)
    charge(t1)
    total = t1 - t0
    attributed = sum(phases.values())
    return {
        "total_s": round(total, 6),
        "attributed_s": round(attributed, 6),
        "attributed_frac": round(attributed / total, 4) if total else 0.0,
        "phases": {k: round(v, 6)
                   for k, v in sorted(phases.items(),
                                      key=lambda kv: -kv[1])},
        "segments": [{**seg, "t0": round(seg["t0"], 6),
                      "t1": round(seg["t1"], 6)} for seg in segments],
    }


def aggregate_critical_path(traces: List[List[Dict[str, Any]]]
                            ) -> Dict[str, Any]:
    """Phase attribution summed over a cohort of traces (the p99 slice
    of a game day): where does the tail actually spend its time?"""
    phases: Dict[str, float] = {}
    total = 0.0
    n = 0
    for spans in traces:
        cp = critical_path(spans)
        if not cp["phases"]:
            continue
        n += 1
        total += cp["total_s"]
        for k, v in cp["phases"].items():
            phases[k] = phases.get(k, 0.0) + v
    out = {"traces": n, "total_s": round(total, 6),
           "phases": {k: round(v, 6)
                      for k, v in sorted(phases.items(),
                                         key=lambda kv: -kv[1])}}
    if total > 0:
        out["phase_frac"] = {k: round(v / total, 4)
                             for k, v in out["phases"].items()}
    return out


# ------------------------------------------------------ chrome export

def chrome_events(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Spans -> chrome-trace 'X' events (one row per process, nested by
    tree depth), wall-clock microseconds — the same time axis
    ``util/timeline.py`` and the XLA device spans merged by
    ``util/tpu_profiler.py`` already use, so the outputs concatenate
    into one chrome://tracing document."""
    depths = _depths(spans)
    out = []
    for s in spans:
        if s.get("start_ts") is None or s.get("end_ts") is None:
            continue
        out.append({
            "name": s.get("name", "?"), "ph": "X", "cat": "trace",
            "ts": s["start_ts"] * 1e6,
            "dur": max(s["end_ts"] - s["start_ts"], 0) * 1e6,
            "pid": s.get("pid") or 0,
            "tid": depths.get(s.get("span_id", ""), 0),
            "cname": "terrible" if s.get("status") == "error" else None,
            "args": {"trace_id": s.get("trace_id"),
                     "span_id": s.get("span_id"),
                     "parent_span_id": s.get("parent_span_id"),
                     "phase": s.get("phase"),
                     "kind": s.get("kind")},
        })
    return [{k: v for k, v in e.items() if v is not None} for e in out]


def export_chrome(spans: List[Dict[str, Any]],
                  device_events: Optional[List[Dict[str, Any]]] = None,
                  pad_s: float = 0.05) -> List[Dict[str, Any]]:
    """One chrome-trace document for a trace: its spans plus any XLA
    device spans (``tpu_profiler`` rows in the merged timeline, pids >=
    ``_XLA_PID_BASE``) that overlap the trace window. Pass
    ``device_events=None`` to pull the merged timeline automatically."""
    out = chrome_events(spans)
    if not out:
        return out
    t0 = min(e["ts"] for e in out) - pad_s * 1e6
    t1 = max(e["ts"] + e.get("dur", 0) for e in out) + pad_s * 1e6
    if device_events is None:
        try:
            from ray_tpu.util import timeline
            device_events = timeline.timeline_dump()
        except Exception:
            device_events = []
    from ray_tpu.util.tpu_profiler import _XLA_PID_BASE
    for e in device_events or ():
        pid = e.get("pid", 0)
        if not isinstance(pid, int) or pid < _XLA_PID_BASE:
            continue
        if e.get("ph") == "M":  # process_name rows label the XLA lanes
            out.append(e)
        elif e.get("ph") == "X" and t0 <= e.get("ts", 0) <= t1:
            out.append(e)
    return out
