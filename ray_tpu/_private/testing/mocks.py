"""Mock doubles of the framework's internal interfaces.

Reference analogue: ``src/mock/ray/raylet_client/raylet_client.h`` and
friends (gmock), plus ``mock_worker.cc`` — scriptable stand-ins so unit
tests exercise one component's logic in isolation.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.common.ids import ObjectID


class MockConnection:
    """Scriptable double of ``protocol.Connection`` /
    ``ReconnectingConnection``.

    ``replies`` maps method name → canned reply, or a callable
    ``(payload) -> reply`` (which may raise to script failures). Every
    call is recorded in ``calls`` for assertions.
    """

    def __init__(self, replies: Optional[Dict[str, Any]] = None):
        self.replies = replies or {}
        self.calls: List[Tuple[str, Any]] = []
        self.notifications: List[Tuple[str, Any]] = []
        self._closed = False
        self.meta: Dict[str, Any] = {}

    def _reply_for(self, method: str, payload: Any) -> Any:
        r = self.replies.get(method, {})
        return r(payload) if callable(r) else r

    async def call(self, method: str, payload: Any = None,
                   timeout: Optional[float] = None) -> Any:
        self.calls.append((method, payload))
        return self._reply_for(method, payload)

    async def notify(self, method: str, payload: Any = None):
        self.notifications.append((method, payload))

    def close(self):
        self._closed = True

    def calls_to(self, method: str) -> List[Any]:
        return [p for m, p in self.calls if m == method]


class MockStore:
    """In-memory double of the plasmax ``PlasmaxStore`` surface
    (create/seal/get_buffer/pin/release/delete/contains/stats)."""

    def __init__(self, capacity: int = 64 * 1024 * 1024):
        self._capacity = capacity
        self._objects: Dict[bytes, bytearray] = {}
        self._sealed: Dict[bytes, bool] = {}
        self._refs: Dict[bytes, int] = {}
        self.num_created = 0

    def _used(self) -> int:
        return sum(len(b) for b in self._objects.values())

    def create(self, oid: ObjectID, size: int,
               allow_fallback: bool = False) -> memoryview:
        from ray_tpu.exceptions import ObjectStoreFullError
        key = oid.binary()
        if key in self._objects:
            raise ValueError(f"object {oid} already exists")
        if self._used() + size > self._capacity:
            raise ObjectStoreFullError(f"mock store full ({size} bytes)")
        buf = bytearray(size)
        self._objects[key] = buf
        self._sealed[key] = False
        self._refs[key] = 1
        self.num_created += 1
        return memoryview(buf)

    def seal(self, oid: ObjectID):
        self._sealed[oid.binary()] = True
        self._refs[oid.binary()] -= 1

    def abort(self, oid: ObjectID):
        key = oid.binary()
        if not self._sealed.get(key):
            self._objects.pop(key, None)
            self._sealed.pop(key, None)
            self._refs.pop(key, None)

    def put_bytes(self, oid: ObjectID, data,
                  allow_fallback: bool = False):
        buf = self.create(oid, len(data))
        buf[:] = data
        self.seal(oid)

    def get_buffer(self, oid: ObjectID) -> Optional[memoryview]:
        key = oid.binary()
        if not self._sealed.get(key):
            return None
        self._refs[key] += 1
        return memoryview(self._objects[key])

    def release(self, oid: ObjectID):
        key = oid.binary()
        if key in self._refs:
            self._refs[key] -= 1

    def pin(self, oid: ObjectID) -> bool:
        key = oid.binary()
        if not self._sealed.get(key):
            return False
        self._refs[key] += 1
        return True

    def delete(self, oid: ObjectID) -> bool:
        key = oid.binary()
        if self._refs.get(key, 0) > 0:
            return False
        self._objects.pop(key, None)
        self._sealed.pop(key, None)
        self._refs.pop(key, None)
        return True

    def contains(self, oid: ObjectID) -> bool:
        return bool(self._sealed.get(oid.binary()))

    def capacity(self) -> int:
        return self._capacity

    def used_bytes(self) -> int:
        return self._used()

    def stats(self) -> Dict[str, int]:
        return {"used_bytes": self._used(), "capacity": self._capacity,
                "num_objects": len(self._objects),
                "num_created": self.num_created,
                "num_evicted": 0, "bytes_evicted": 0}


def make_bare(cls, **attrs):
    """Instantiate ``cls`` WITHOUT running ``__init__`` and set just
    the attributes a unit test needs — the mock-worker pattern for
    components whose constructors bind sockets/shm (Raylet, Worker,
    GcsServer)."""
    obj = object.__new__(cls)
    for k, v in attrs.items():
        setattr(obj, k, v)
    return obj
