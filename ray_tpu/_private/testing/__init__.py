"""Interface mocks for unit tests.

Role-equivalent to the reference's ``src/mock/ray/**`` gmock library
(header-for-header doubles of gcs_client, raylet_client, core_worker,
pubsub, rpc — used by the C++ unit tests to test components in
isolation) and ``core_worker/test/mock_worker.cc``. The integration
suite drives real local clusters; these mocks let the *logic* inside a
component (ordering, admission, scheduling, validation) be unit-tested
without processes, sockets, or shared memory.
"""

from ray_tpu._private.testing.mocks import (  # noqa: F401
    MockConnection, MockStore, make_bare)
