"""GCS — the head-node control plane.

Role-equivalent to the reference's gcs_server (reference:
src/ray/gcs/gcs_server/gcs_server.cc wiring gcs_node_manager,
gcs_actor_manager + gcs_actor_scheduler, gcs_job_manager,
gcs_placement_group_manager/_scheduler, gcs_kv_manager, pubsub_handler,
gcs_health_check_manager). One asyncio process holds:

  - node table + resource view (raylets report periodically — the analogue of
    the ray_syncer resource gossip, common/ray_syncer/ray_syncer.h)
  - cluster scheduler: hybrid pack/spread node selection for spillback and
    actor placement, TPU-slice aware
  - actor directory + state machine (DEPENDENCIES_UNREADY → PENDING_CREATION →
    ALIVE ⇄ RESTARTING → DEAD, reference: src/ray/design_docs/actor_states.rst)
  - placement groups with 2-phase commit against raylets (reference:
    gcs_placement_group_scheduler.cc Prepare/Commit/CancelResourceReserve)
  - KV store (function blobs, runtime-env URIs, cluster metadata, rendezvous)
  - pubsub channels (actor state, node events, logs, errors)
  - object directory (object -> node locations, for inter-node transfer)
  - job table
  - health: an active disconnect/heartbeat monitor; node death is broadcast

State is held in plain dicts; a `StoreClient` abstraction (in-memory default,
file-backed snapshot optional) mirrors the reference's pluggable gcs storage
(gcs/store_client/) so GCS fault tolerance can be added without changing
managers.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private import protocol
from ray_tpu._private import task_events as tev
from ray_tpu._private.gcs_store import StoreClient, make_store
from ray_tpu.common.config import SystemConfig

logger = logging.getLogger(__name__)


# ------------------------------------------------------------------
# List pagination + server-side filtering (shared by every list_*
# handler; reference: the dashboard state_aggregator's ListApiOptions
# — limit / server-side filters / a continuation token so no RPC ever
# carries the full table of a large cluster in one response).

def _match_row(row: Dict[str, Any], filters: Optional[Dict[str, Any]]
               ) -> bool:
    """Equality filter pushdown; a list/tuple value means membership."""
    if not filters:
        return True
    for k, v in filters.items():
        have = row.get(k)
        if isinstance(v, (list, tuple)):
            if have not in v:
                return False
        elif have != v:
            return False
    return True


_LIST_LIMIT_DEFAULT = 1000
_LIST_LIMIT_MAX = 10_000


def paginate(rows, payload: Dict[str, Any], id_key: str):
    """Apply filters, then (when the client asked for the paged shape)
    sort by the stable ``id_key`` and cut a cursor page.

    Legacy callers (no ``paged`` flag) get the old bare-list reply, so
    every pre-pagination peer keeps working; paged callers get
    ``{"items", "next_token", "total"}`` where ``next_token`` is the
    last id of the page — pass it back to resume strictly after it
    (ids are unique + the sort is stable, so pages never overlap and
    their union is the full filtered set even as rows churn).
    """
    payload = payload or {}
    filters = payload.get("filters")
    rows = [r for r in rows if _match_row(r, filters)]
    if not payload.get("paged"):
        return rows
    rows.sort(key=lambda r: str(r.get(id_key, "")))
    total = len(rows)
    token = payload.get("continuation_token")
    if token:
        rows = [r for r in rows if str(r.get(id_key, "")) > str(token)]
    limit = int(payload.get("limit") or _LIST_LIMIT_DEFAULT)
    limit = max(1, min(limit, _LIST_LIMIT_MAX))
    page = rows[:limit]
    next_token = str(page[-1].get(id_key, "")) \
        if len(rows) > limit and page else None
    return {"items": page, "next_token": next_token, "total": total}


class TaskEventTable:
    """Bounded, indexed task table fed by the task-event pipeline
    (reference: gcs_task_manager.cc GcsTaskManager — same contract:
    RAY_task_events_max_num_task_in_gcs cap, oldest-finished evicted
    first, a visible drop counter instead of silent loss).

    Never O(all-tasks-ever): memory is ``cap`` records; everything
    beyond it increments ``dropped`` and disappears.
    """

    def __init__(self, cap: Optional[int] = None):
        if cap is None:
            cap = int(os.environ.get("RTPU_TASK_TABLE_MAX", 32768))
        self.cap = max(1, int(cap))
        self.records: Dict[str, Dict[str, Any]] = {}
        from collections import deque
        self._terminal_order: "deque[str]" = deque()
        self.dropped = 0          # records evicted past the cap
        self.events_dropped = 0   # process-ring overflow (reported in)
        self.state_counts: Dict[str, int] = {}
        self.total_seen = 0       # records ever created
        # trace_id -> {task_id}: lets get_trace synthesize task-phase
        # spans without scanning the whole table (bounded by the same
        # cap — entries die with their records)
        self.trace_index: Dict[str, set] = {}

    def _count(self, state: Optional[str], delta: int):
        if state:
            self.state_counts[state] = \
                self.state_counts.get(state, 0) + delta

    def apply(self, ev: Dict[str, Any]):
        tid = ev.get("task_id")
        state = ev.get("state")
        if not tid or state not in tev.STATE_RANK:
            return
        rec = self.records.get(tid)
        if rec is None:
            rec = {"task_id": tid, "state": state, "attempt": 0,
                   "created_ts": ev.get("ts")}
            self.records[tid] = rec
            self.total_seen += 1
            self._count(state, +1)
            self._evict()
        # fields merge regardless of ordering (a late PENDING event
        # still fills in name/job_id it uniquely knows)
        for k in ("name", "job_id", "node_id", "worker_pid",
                  "trace_ctx", "dispatch_ts", "deser_s", "ship_s"):
            if ev.get(k) is not None:
                rec[k] = ev[k]
        tc = rec.get("trace_ctx")
        if tc and tc.get("trace_id"):
            self.trace_index.setdefault(tc["trace_id"], set()).add(tid)
        attempt = int(ev.get("attempt") or 0)
        old_rank = tev.STATE_RANK.get(rec["state"], -1)
        new_rank = tev.STATE_RANK[state]
        if attempt > rec["attempt"]:
            # a retry restarts the lifecycle: state may regress (and
            # the previous attempt's phase timestamps no longer
            # describe this lifecycle)
            rec["attempt"] = attempt
            rec.pop("state_ts", None)
            rec.pop("dispatch_ts", None)
            advance = True
        elif attempt < rec["attempt"]:
            # stale attempt (flush ticks race across processes): its
            # terminal state must not override the newer attempt
            advance = False
        else:
            advance = new_rank >= old_rank
        if advance and rec["state"] != state:
            self._count(rec["state"], -1)
            self._count(state, +1)
            rec["state"] = state
        if attempt == rec["attempt"] and ev.get("ts") is not None:
            # per-state wall clock: what get_trace synthesizes the
            # task's queue/schedule/dispatch/execute spans from.
            # Recorded REGARDLESS of advance — flush ticks from
            # different processes race, so a worker's FINISHED often
            # lands before the raylet's queue stamp; each state's ts is
            # a fact of this attempt, not a merge-ordering outcome
            # (first event per state wins: the raylet re-emits its
            # state at dispatch time to carry dispatch_ts).
            rec.setdefault("state_ts", {}).setdefault(state, ev["ts"])
        if advance:
            if state == tev.RUNNING:
                rec["start_ts"] = ev.get("ts")
            elif state in tev.TERMINAL_STATES:
                rec["end_ts"] = ev.get("ts")
                if rec.get("start_ts") and ev.get("ts"):
                    rec["duration_s"] = round(
                        ev["ts"] - rec["start_ts"], 6)
                if ev.get("error") is not None:
                    rec["error"] = str(ev["error"])[:500]
                self._terminal_order.append(tid)

    def _evict(self):
        while len(self.records) > self.cap:
            victim = None
            # oldest-terminal first: live tasks are what an operator is
            # debugging; history is what we can afford to forget
            while self._terminal_order:
                cand = self._terminal_order.popleft()
                rec = self.records.get(cand)
                if rec is not None and \
                        rec["state"] in tev.TERMINAL_STATES:
                    victim = cand
                    break
            if victim is None:
                victim = next(iter(self.records))
            rec = self.records.pop(victim, None)
            if rec is not None:
                self._count(rec["state"], -1)
                self.dropped += 1
                tc = rec.get("trace_ctx") or {}
                tids = self.trace_index.get(tc.get("trace_id"))
                if tids is not None:
                    tids.discard(victim)
                    if not tids:
                        self.trace_index.pop(tc["trace_id"], None)

    def summary(self) -> Dict[str, Any]:
        return {"total": len(self.records),
                "total_seen": self.total_seen,
                "by_state": dict(self.state_counts),
                "dropped": self.dropped,
                "events_dropped": self.events_dropped,
                "cap": self.cap}


class TraceTable:
    """Bounded, indexed span store fed by the ``trace_spans`` pipeline
    (the TaskEventTable contract applied to traces: a hard span cap,
    oldest-updated trace evicted first, a visible drop counter instead
    of silent loss or OOM).

    Spans group by trace_id; insertion order of the ``traces`` dict is
    maintained as LRU-by-last-update so eviction is O(1) amortized.
    """

    def __init__(self, cap: Optional[int] = None,
                 per_trace_cap: Optional[int] = None):
        if cap is None:
            cap = int(os.environ.get("RTPU_TRACE_TABLE_MAX", 65536))
        if per_trace_cap is None:
            per_trace_cap = int(os.environ.get(
                "RTPU_TRACE_MAX_SPANS", 512))
        self.cap = max(1, int(cap))
        self.per_trace_cap = max(1, int(per_trace_cap))
        # trace_id -> {"spans": [...], "updated_ts", "start_ts",
        # "end_ts", "root_name", "error"}  (dict preserves insertion
        # order; re-insert on update = LRU)
        self.traces: Dict[str, Dict[str, Any]] = {}
        self.total_spans = 0
        self.dropped_spans = 0      # evicted/over-cap spans
        self.spans_dropped_rings = 0  # process-ring overflow (reported)
        self.total_seen = 0

    def apply(self, span: Dict[str, Any]):
        tid = span.get("trace_id")
        if not tid or span.get("span_id") is None:
            return
        self.total_seen += 1
        ent = self.traces.pop(tid, None)
        if ent is None:
            ent = {"spans": [], "start_ts": span.get("start_ts"),
                   "end_ts": span.get("end_ts"), "root_name": None,
                   "error": False}
        self.traces[tid] = ent  # re-insert: newest at the end
        ent["updated_ts"] = time.time()
        if len(ent["spans"]) >= self.per_trace_cap:
            self.dropped_spans += 1
            return
        ent["spans"].append(span)
        self.total_spans += 1
        ts0, ts1 = span.get("start_ts"), span.get("end_ts")
        if ts0 is not None and (ent["start_ts"] is None
                                or ts0 < ent["start_ts"]):
            ent["start_ts"] = ts0
        if ts1 is not None and (ent["end_ts"] is None
                                or ts1 > ent["end_ts"]):
            ent["end_ts"] = ts1
        if span.get("status") == "error":
            ent["error"] = True
        if span.get("parent_span_id") in (None, "", "root"):
            ent["root_name"] = span.get("name")
        self._evict()

    def _evict(self):
        while self.total_spans > self.cap and len(self.traces) > 1:
            victim_id = next(iter(self.traces))  # oldest-updated
            victim = self.traces.pop(victim_id)
            self.total_spans -= len(victim["spans"])
            self.dropped_spans += len(victim["spans"])

    def get(self, trace_id: str) -> List[Dict[str, Any]]:
        ent = self.traces.get(trace_id)
        return list(ent["spans"]) if ent else []

    def summary_rows(self) -> List[Dict[str, Any]]:
        rows = []
        for tid, ent in self.traces.items():
            t0, t1 = ent.get("start_ts"), ent.get("end_ts")
            rows.append({
                "trace_id": tid,
                "root": ent.get("root_name"),
                "spans": len(ent["spans"]),
                "start_ts": t0,
                "duration_s": (round(t1 - t0, 6)
                               if t0 is not None and t1 is not None
                               else None),
                "status": "error" if ent.get("error") else "ok",
            })
        return rows

    def summary(self) -> Dict[str, Any]:
        return {"traces": len(self.traces),
                "spans": self.total_spans,
                "total_seen": self.total_seen,
                "dropped_spans": self.dropped_spans,
                "spans_dropped_rings": self.spans_dropped_rings,
                "cap": self.cap}


# Actor states (reference: design_docs/actor_states.rst)
DEPS_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class NodeInfo:
    def __init__(self, node_id: str, payload: Dict[str, Any],
                 conn: protocol.Connection):
        self.node_id = node_id
        self.raylet_address: str = payload["raylet_address"]
        # 1.8: netx transfer endpoint ('' = node serves asyncio-only)
        self.netx_address: str = payload.get("netx_address", "")
        self.object_store_path: str = payload["object_store_path"]
        self.hostname: str = payload.get("hostname", "")
        self.total_resources: Dict[str, float] = dict(payload["resources"])
        self.available_resources: Dict[str, float] = dict(payload["resources"])
        self.labels: Dict[str, str] = dict(payload.get("labels", {}))
        # TPU topology: e.g. {"slice": "v5e-8-abc", "topology": "v5e-8",
        # "worker_index": 0, "num_slice_hosts": 2}
        self.tpu: Dict[str, Any] = dict(payload.get("tpu", {}))
        self.conn = conn
        self.alive = True
        # draining: preemption notice received; still alive (in-flight
        # work finishing) but the scheduler must not place onto it
        self.draining = False
        self.drain_deadline_unix = 0.0
        self.last_seen = time.monotonic()
        self.is_head = bool(payload.get("is_head"))
        # versioned sync state (reference: ray_syncer.h — per-node
        # versioned snapshots; stale versions dropped, epoch guards
        # against a restarted raylet's counter reset)
        self.sync_epoch: float = float(payload.get("sync_epoch", 0.0))
        self.sync_version: int = int(payload.get("sync_version", 0))
        self.view_stamp: int = 0  # cluster-view version this entry last changed at


class GcsServer:
    def __init__(self, config: SystemConfig,
                 store_path: Optional[str] = None):
        self.config = config
        # Persistence (reference: gcs/store_client/ + gcs_init_data.cc):
        # live state stays in dicts (hot path), every mutation writes through
        # to the store, and start() replays the store so a restarted GCS
        # rebuilds actors/PGs/jobs/KV. In-memory backend when no path given.
        self.store: StoreClient = make_store(store_path)
        self.nodes: Dict[str, NodeInfo] = {}
        self._view_version = 0  # cluster-view sync version (ray_syncer)
        self.kv: Dict[str, bytes] = {}
        self.actors: Dict[str, Dict[str, Any]] = {}
        self.named_actors: Dict[Tuple[str, str], str] = {}  # (ns, name) -> id
        self.jobs: Dict[str, Dict[str, Any]] = {}
        self.placement_groups: Dict[str, Dict[str, Any]] = {}
        self.object_locations: Dict[bytes, Set[str]] = {}
        self.object_owners: Dict[bytes, str] = {}  # object hex -> worker addr
        self.subscribers: Dict[str, Set[protocol.Connection]] = {}
        # bounded structured-event ring (reference: RAY_EVENT framework;
        # browsable via the state API / dashboard /api/events)
        from collections import deque
        self.events: "deque" = deque(maxlen=1000)
        # bounded task table fed by the task-event pipeline (reference:
        # gcs_task_manager.cc); cap via RTPU_TASK_TABLE_MAX
        self.task_table = TaskEventTable()
        # bounded span store fed by the trace-span pipeline (cap via
        # RTPU_TRACE_TABLE_MAX); get_trace merges it with task-phase
        # spans synthesized from the task table's per-state timestamps
        self.trace_table = TraceTable()
        # scheduler's pessimistic view of its own in-flight placements:
        # node_id -> [(expiry, demand)] (see _utilization)
        self._ephemeral_allocs: Dict[str, List[Tuple[float, Dict[str,
                                                                 float]]]] = {}
        self._spread_rr = -1
        self.next_job_index = 1
        self._server = protocol.Server(self._handlers())
        self._actor_creation_waiters: Dict[str, List[asyncio.Future]] = {}
        self._shutdown = asyncio.Event()

    # ------------------------------------------------------------------ wiring

    def _handlers(self):
        h = {
            "_on_connect": self._on_connect,
            "_on_disconnect": self._on_disconnect,
            "register_node": self.register_node,
            "resource_report": self.resource_report,
            "node_liveness": self.node_liveness,
            "get_nodes": self.get_nodes,
            "profile_stacks": self.profile_stacks,
            "profile_flamegraph": self.profile_flamegraph,
            "get_node_stats": self.get_node_stats,
            "drain_node": self.drain_node,
            "node_draining": self.node_draining,
            "node_drained": self.node_drained,
            "preempt_node": self.preempt_node,
            "kv_put": self.kv_put,
            "kv_get": self.kv_get,
            "kv_get_prefix": self.kv_get_prefix,
            "kv_del": self.kv_del,
            "kv_keys": self.kv_keys,
            "kv_exists": self.kv_exists,
            "next_job_id": self.next_job_id,
            "add_job": self.add_job,
            "get_jobs": self.get_jobs,
            "register_actor": self.register_actor,
            "create_actor": self.create_actor,
            "get_actor": self.get_actor,
            "get_named_actor": self.get_named_actor,
            "list_named_actors": self.list_named_actors,
            "actor_state_update": self.actor_state_update,
            "kill_actor": self.kill_actor,
            "wait_actor_alive": self.wait_actor_alive,
            "list_actors": self.list_actors,
            "add_event": self.add_event,
            "list_events": self.list_events,
            "task_events": self.task_events,
            "trace_spans": self.trace_spans,
            "get_trace": self.get_trace,
            "list_traces": self.list_traces,
            "list_tasks": self.list_tasks,
            "list_objects": self.list_objects,
            "summarize": self.summarize,
            "summarize_tasks": self.summarize_tasks,
            "configure_state": self.configure_state,
            "schedule": self.schedule,
            "create_placement_group": self.create_placement_group,
            "remove_placement_group": self.remove_placement_group,
            "get_placement_group": self.get_placement_group,
            "list_placement_groups": self.list_placement_groups,
            "subscribe": self.subscribe,
            "unsubscribe": self.unsubscribe,
            "publish": self.publish,
            "add_object_location": self.add_object_location,
            "remove_object_location": self.remove_object_location,
            "get_object_locations": self.get_object_locations,
            "cluster_resources": self.cluster_resources,
            "available_resources": self.available_resources,
            "ping": self.ping,
        }
        return h

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._load_persisted()
        self.port = await self._server.start_tcp(host, port)
        protocol.spawn(self._health_loop())
        self._resume_interrupted()
        logger.info("GCS listening on %s:%s", host, self.port)
        return self.port

    # ------------------------------------------------------------ persistence

    def _load_persisted(self):
        """Rebuild manager state from the store (reference:
        gcs_init_data.cc LoadJobTableData/LoadActorTableData/...)."""
        restart_actors: List[str] = []
        restart_pgs: List[str] = []
        for table, key, value in self.store.load_all():
            if table == "kv":
                self.kv[key] = value
            elif table == "jobs":
                self.jobs[key] = value
            elif table == "actors":
                self.actors[key] = value
                if value.get("state") in (PENDING_CREATION, RESTARTING,
                                          DEPS_UNREADY):
                    restart_actors.append(key)
            elif table == "named_actors":
                ns, _, name = key.partition("\x00")
                self.named_actors[(ns, name)] = value
            elif table == "pgs":
                self.placement_groups[key] = value
                if value.get("state") == "PENDING":
                    restart_pgs.append(key)
            elif table == "meta":
                if key == "next_job_index":
                    self.next_job_index = int(value)
        if self.actors or self.placement_groups or self.kv:
            logger.info(
                "GCS state rebuilt from store: %d actors, %d PGs, %d jobs, "
                "%d kv keys", len(self.actors), len(self.placement_groups),
                len(self.jobs), len(self.kv))
        self._pending_restart_actors = restart_actors
        self._pending_restart_pgs = restart_pgs

    def _resume_interrupted(self):
        """Re-kick scheduling work that was in flight when the GCS died.
        Called once the server is accepting raylet re-registrations."""
        for aid in getattr(self, "_pending_restart_actors", []):
            protocol.spawn(self._schedule_actor(aid))
        for pg_id in getattr(self, "_pending_restart_pgs", []):
            protocol.spawn(self._retry_pg(pg_id))
        self._pending_restart_actors = []
        self._pending_restart_pgs = []
        if self.actors or self.placement_groups:
            protocol.spawn(
                self._reconcile_after_restart())

    async def _reconcile_after_restart(self):
        """Nodes that died while the GCS was down never re-register, so
        persisted ALIVE actors / CREATED PGs pointing at them would hang
        forever. After a re-registration grace period, fail those actors
        over (restart policy applies) and re-place those PGs."""
        await asyncio.sleep(self.config.health_check_timeout_s)
        live = {nid for nid, n in self.nodes.items() if n.alive}
        for aid, info in list(self.actors.items()):
            if info["state"] in (ALIVE, RESTARTING, PENDING_CREATION) and \
                    info.get("node_id") and info["node_id"] not in live:
                await self._handle_actor_failure(
                    aid, "node lost during GCS downtime")
        for pg_id, pg in list(self.placement_groups.items()):
            if pg.get("state") == "CREATED" and pg.get("assignment") and \
                    any(nid not in live for nid in pg["assignment"]):
                pg["state"] = "PENDING"
                pg["assignment"] = None
                self._persist_pg(pg_id)
                protocol.spawn(self._retry_pg(pg_id))

    def _persist_actor(self, aid: str):
        info = self.actors.get(aid)
        if info is not None:
            self.store.put("actors", aid, info)

    def _persist_pg(self, pg_id: str):
        pg = self.placement_groups.get(pg_id)
        if pg is not None:
            self.store.put("pgs", pg_id, pg)

    async def _on_connect(self, conn):
        pass

    async def _on_disconnect(self, conn):
        # raylet connection drop == node death (active health check analogue,
        # reference: gcs_health_check_manager.cc). A raylet that re-dialed
        # (ReconnectingConnection) re-registers with a NEW conn before the
        # old one's EOF is processed — only the node's current conn counts.
        node_id = conn.meta.get("node_id")
        if node_id and node_id in self.nodes and self.nodes[node_id].alive \
                and self.nodes[node_id].conn is conn:
            await self._mark_node_dead(node_id, "raylet disconnected")
        for channel in list(self.subscribers):
            subs = self.subscribers[channel]
            subs.discard(conn)
            if not subs:
                self.subscribers.pop(channel, None)

    # ------------------------------------------------------------- events

    def _event(self, severity: str, label: str, message: str, **fields):
        from ray_tpu.util import events as ev
        event = ev.report(severity, label, message, **fields)
        event["source"] = "gcs"
        self.events.append(event)

    async def add_event(self, payload, conn):
        self.events.append(payload)
        return {}

    async def list_events(self, payload, conn):
        payload = payload or {}
        limit = payload.get("limit", 200)
        sev = payload.get("severity")
        out = [e for e in self.events
               if sev is None or e.get("severity") == sev]
        out = [e for e in out if _match_row(e, payload.get("filters"))]
        return out[-limit:] if limit and limit > 0 else []

    # ------------------------------------------------------ task state

    async def task_events(self, payload, conn):
        """Batched task lifecycle events from workers/raylets — folded
        into the bounded task table (never stored raw)."""
        payload = payload or {}
        self.task_table.events_dropped += int(payload.get("dropped") or 0)
        for ev in payload.get("events") or ():
            try:
                self.task_table.apply(ev)
            except Exception:
                logger.debug("bad task event dropped: %r", ev,
                             exc_info=True)
        return {}

    async def trace_spans(self, payload, conn):
        """Batched spans from the per-process tracing buffers — folded
        into the bounded trace table (never stored raw)."""
        payload = payload or {}
        self.trace_table.spans_dropped_rings += \
            int(payload.get("dropped") or 0)
        for span in payload.get("spans") or ():
            try:
                self.trace_table.apply(span)
            except Exception:
                logger.debug("bad span dropped: %r", span, exc_info=True)
        return {}

    async def get_trace(self, payload, conn):
        """One trace's full span set: explicit spans (serve, dag hops,
        object pulls) merged with task-lifecycle spans synthesized from
        the state engine's task records — assembled HERE, where both
        tables live, in one RPC."""
        from ray_tpu._private import tracing
        trace_id = (payload or {}).get("trace_id") or ""
        spans = self.trace_table.get(trace_id)
        for task_id in sorted(
                self.task_table.trace_index.get(trace_id) or ()):
            rec = self.task_table.records.get(task_id)
            if rec is not None:
                spans.extend(tracing.synthesize_task_spans(rec))
        return {"trace_id": trace_id, "spans": spans,
                **{k: v for k, v in self.trace_table.summary().items()
                   if k in ("dropped_spans", "spans_dropped_rings")}}

    async def list_traces(self, payload, conn):
        """Cursor-paginated trace summaries: explicit-span traces plus
        task-only traces (a task tree whose trace never recorded an
        explicit span is still browsable)."""
        rows = self.trace_table.summary_rows()
        seen = {r["trace_id"] for r in rows}
        for tid, task_ids in self.task_table.trace_index.items():
            if tid in seen:
                continue
            recs = [self.task_table.records[t] for t in task_ids
                    if t in self.task_table.records]
            if not recs:
                continue
            starts = [r.get("created_ts") for r in recs
                      if r.get("created_ts") is not None]
            ends = [r.get("end_ts") for r in recs
                    if r.get("end_ts") is not None]
            rows.append({
                "trace_id": tid,
                "root": min(recs, key=lambda r: r.get("created_ts")
                            or 0).get("name"),
                "spans": len(recs),
                "start_ts": min(starts) if starts else None,
                "duration_s": (round(max(ends) - min(starts), 6)
                               if starts and ends else None),
                "status": ("error" if any(r.get("state") == "FAILED"
                                          for r in recs) else "ok"),
            })
        reply = paginate(rows, payload, "trace_id")
        if isinstance(reply, dict):
            reply["dropped"] = self.trace_table.dropped_spans
        return reply

    async def list_tasks(self, payload, conn):
        rows = [dict(r) for r in self.task_table.records.values()]
        reply = paginate(rows, payload, "task_id")
        if isinstance(reply, dict):
            reply["dropped"] = self.task_table.dropped
            reply["events_dropped"] = self.task_table.events_dropped
        return reply

    async def list_objects(self, payload, conn):
        """Cluster object listing: aggregates the PER-RAYLET plasma
        indexes (each raylet reports its own bounded page) instead of
        the GCS centralizing every object record — the head holds only
        the location directory, and one listing RPC never materializes
        more than ~limit rows per node."""
        payload = payload or {}
        limit = int(payload.get("limit") or _LIST_LIMIT_DEFAULT)
        limit = max(1, min(limit, _LIST_LIMIT_MAX))
        req = {"limit": limit,
               "continuation_token": payload.get("continuation_token")}
        fan = await self._fanout_to_raylets(
            "list_objects", req, node_id=payload.get("node_id"))
        merged: Dict[str, Dict[str, Any]] = {}
        truncated = False
        for node_reply in fan["nodes"]:
            if node_reply.get("error"):
                continue
            truncated = truncated or bool(node_reply.get("truncated"))
            for row in node_reply.get("objects") or ():
                oid = row["object_id"]
                have = merged.get(oid)
                if have is None:
                    have = merged[oid] = dict(row)
                    have["locations"] = []
                else:
                    # keep the richer copy's size/pinned/spilled bits
                    for k in ("size_bytes", "pinned", "spilled"):
                        if row.get(k):
                            have[k] = row[k]
                have["locations"].append(row.get("node_id"))
        for oid, rec in merged.items():
            rec["owner"] = self.object_owners.get(oid)
        rows = list(merged.values())
        reply = paginate(rows, payload, "object_id")
        if isinstance(reply, dict) and truncated and \
                not reply.get("next_token") and reply["items"]:
            # a raylet clipped its page at the limit: there IS more
            # even though the merged cut didn't overflow
            reply["next_token"] = reply["items"][-1]["object_id"]
        return reply

    async def summarize(self, payload, conn):
        """Cluster summary in ONE rpc — counts computed where the
        tables live instead of shipping full node/actor tables to the
        client just to len() them."""
        actors_by_state: Dict[str, int] = {}
        for info in self.actors.values():
            s = info.get("state") or "?"
            actors_by_state[s] = actors_by_state.get(s, 0) + 1
        return {
            "nodes_total": len(self.nodes),
            "nodes_alive": sum(1 for n in self.nodes.values() if n.alive),
            "nodes_draining": sum(1 for n in self.nodes.values()
                                  if n.alive and n.draining),
            "actors_total": len(self.actors),
            "actors_alive": actors_by_state.get(ALIVE, 0),
            "actors_by_state": actors_by_state,
            "jobs_total": len(self.jobs),
            "placement_groups_total": len(self.placement_groups),
            "objects_tracked": len(self.object_locations),
            "cluster_resources": await self.cluster_resources({}, conn),
            "available_resources": await self.available_resources({},
                                                                  conn),
            "tasks": self.task_table.summary(),
            "traces": self.trace_table.summary(),
        }

    async def summarize_tasks(self, payload, conn):
        """`ray-tpu summary tasks`: per-function aggregation over the
        bounded table (reference: `ray summary tasks`)."""
        by_func: Dict[str, Dict[str, Any]] = {}
        for rec in self.task_table.records.values():
            name = rec.get("name") or "(unknown)"
            agg = by_func.get(name)
            if agg is None:
                agg = by_func[name] = {"name": name, "count": 0,
                                       "by_state": {},
                                       "duration_sum_s": 0.0,
                                       "finished": 0}
            agg["count"] += 1
            st = rec["state"]
            agg["by_state"][st] = agg["by_state"].get(st, 0) + 1
            if rec.get("duration_s") is not None:
                agg["duration_sum_s"] += rec["duration_s"]
                agg["finished"] += 1
        for agg in by_func.values():
            if agg["finished"]:
                agg["mean_duration_s"] = round(
                    agg["duration_sum_s"] / agg["finished"], 6)
        return {"summary": sorted(by_func.values(),
                                  key=lambda a: -a["count"]),
                **self.task_table.summary()}

    async def configure_state(self, payload, conn):
        """Operator/test knob: resize the task table cap live (shrink
        evicts immediately, drop counter visible)."""
        cap = (payload or {}).get("task_table_max")
        if cap is not None:
            self.task_table.cap = max(1, int(cap))
            self.task_table._evict()
        tcap = (payload or {}).get("trace_table_max")
        if tcap is not None:
            self.trace_table.cap = max(1, int(tcap))
            self.trace_table._evict()
        return {"task_table_max": self.task_table.cap,
                "trace_table_max": self.trace_table.cap}

    async def _health_loop(self):
        period = self.config.health_check_period_s
        while not self._shutdown.is_set():
            await asyncio.sleep(period)
            now = time.monotonic()
            for node in list(self.nodes.values()):
                if node.alive and now - node.last_seen > \
                        self.config.health_check_timeout_s:
                    await self._mark_node_dead(node.node_id, "heartbeat timeout")

    async def _mark_node_dead(self, node_id: str, reason: str):
        node = self.nodes.get(node_id)
        if node is None:
            return
        node.alive = False
        self._bump_view(node)
        logger.warning("node %s dead: %s", node_id[:8], reason)
        self._event("ERROR", "NODE_DEAD",
                    f"node {node_id[:8]} died: {reason}",
                    node_id=node_id, reason=reason)
        await self._publish("node_events",
                            {"event": "dead", "node_id": node_id,
                             "reason": reason})
        # fail actors on that node; restart where policy allows
        for aid, info in list(self.actors.items()):
            if info.get("node_id") == node_id and info["state"] in (
                    ALIVE, PENDING_CREATION, RESTARTING):
                await self._handle_actor_failure(
                    aid, f"node {node_id[:8]} died: {reason}")
        # drop object locations + the scheduler's in-flight accounting
        for oid, locs in list(self.object_locations.items()):
            locs.discard(node_id)
        self._ephemeral_allocs.pop(node_id, None)

    # ------------------------------------------------------------------- nodes

    def _bump_view(self, node: "NodeInfo"):
        """Advance the cluster-view version and stamp the changed entry
        (the delta unit of the bidirectional sync stream)."""
        self._view_version += 1
        node.view_stamp = self._view_version

    def _view_delta(self, since: int) -> Dict[str, Any]:
        """Entries that changed after ``since`` — piggybacked on report
        replies so every raylet converges on the cluster view without a
        second RPC (reference: ray_syncer bidirectional stream)."""
        delta = [{
            "node_id": n.node_id,
            "alive": n.alive,
            "draining": n.draining,
            "raylet_address": n.raylet_address,
            "available": n.available_resources,
            "total": n.total_resources,
        } for n in self.nodes.values() if n.view_stamp > since]
        return {"view_version": self._view_version, "delta": delta}

    async def register_node(self, payload, conn):
        node_id = payload["node_id"]
        info = NodeInfo(node_id, payload, conn)
        self.nodes[node_id] = info
        self._bump_view(info)
        conn.meta["node_id"] = node_id
        # (re-)registration carries the node's primary object copies so a
        # restarted GCS rebuilds its object directory
        for hex_id in payload.get("objects", ()):  # volatile directory state
            self.object_locations.setdefault(hex_id, set()).add(node_id)
        self._event("INFO", "NODE_ADDED",
                    f"node {node_id[:8]} registered",
                    node_id=node_id, resources=info.total_resources,
                    hostname=info.hostname)
        await self._publish("node_events", {"event": "alive",
                                            "node_id": node_id,
                                            "resources": info.total_resources})
        return {"config": self.config.to_json()}

    async def node_liveness(self, payload, conn):
        """Thread-side heartbeat (see raylet._start_liveness_thread):
        refreshes last_seen while the raylet's EVENT LOOP may be busy
        with bulk work — a loaded node is not a dead node.  A loop
        wedged past loop_stall_death_s stops counting as alive: the
        beat attests the process, the lag bounds the loop."""
        node = self.nodes.get(payload["node_id"])
        if node is None:
            return {}
        lag = float(payload.get("loop_lag_s", 0.0))
        if node.alive and lag < self.config.loop_stall_death_s:
            node.last_seen = time.monotonic()
        return {}

    async def resource_report(self, payload, conn):
        node = self.nodes.get(payload["node_id"])
        if node is None:
            return {}
        # versioned stream: drop stale/reordered reports (same epoch,
        # older version); a NEW epoch (restarted raylet, counter reset)
        # always supersedes (reference: ray_syncer.h version filtering)
        epoch = float(payload.get("sync_epoch", 0.0))
        version = int(payload.get("sync_version", 0))
        if epoch == node.sync_epoch and version <= node.sync_version \
                and version:
            node.last_seen = time.monotonic()
            return self._view_delta(int(payload.get("known_view", 0)))
        node.sync_epoch, node.sync_version = epoch, version
        node.available_resources = payload["available"]
        node.total_resources = payload.get("total", node.total_resources)
        node.last_seen = time.monotonic()
        self._bump_view(node)
        # a fresh report supersedes older ephemeral allocations (the task
        # is either reflected in it or already finished) — keeping them
        # would double-count against the node
        allocs = self._ephemeral_allocs.get(payload["node_id"])
        if allocs:
            cutoff = time.monotonic() - 0.25
            allocs[:] = [(t, d) for t, d in allocs if t > cutoff]
        return self._view_delta(int(payload.get("known_view", 0)))

    async def get_nodes(self, payload, conn):
        rows = [{
            "node_id": n.node_id,
            "alive": n.alive,
            "draining": n.draining,
            "drain_deadline_unix": n.drain_deadline_unix,
            "raylet_address": n.raylet_address,
            "netx_address": n.netx_address,
            "object_store_path": n.object_store_path,
            "resources": n.total_resources,
            "available": n.available_resources,
            "labels": n.labels,
            "tpu": n.tpu,
            "is_head": n.is_head,
        } for n in self.nodes.values()]
        return paginate(rows, payload, "node_id")

    async def _fanout_to_raylets(self, method: str, payload: Dict[str, Any],
                                 node_id: Optional[str] = None,
                                 timeout: float = 10.0) -> Dict[str, Any]:
        """Concurrent RPC to every (or one) alive raylet; per-node
        errors are folded into the result list rather than failing the
        whole fan-out."""
        targets = [n for n in list(self.nodes.values())
                   if n.alive and (not node_id or n.node_id == node_id)]

        async def one(n):
            try:
                return await asyncio.wait_for(
                    n.conn.call(method, payload), timeout=timeout)
            except Exception as e:
                return {"node_id": n.node_id,
                        "error": f"{type(e).__name__}: {e}"}

        return {"nodes": list(await asyncio.gather(
            *[one(n) for n in targets]))}

    async def profile_stacks(self, payload, conn):
        """Fan a live-stack snapshot request out to raylets (reference:
        the dashboard reporter's profile endpoints); node_id narrows to
        one node, worker_id to one worker."""
        return await self._fanout_to_raylets(
            "dump_worker_stacks",
            {"worker_id": payload.get("worker_id")},
            node_id=payload.get("node_id"))

    async def profile_flamegraph(self, payload, conn):
        """Timed sampling profiles (folded stacks) of workers across
        the cluster (reference: profile_manager.py py-spy flamegraphs).
        node_id/worker_id narrow the fan-out."""
        duration = min(float(payload.get("duration_s") or 2.0), 30.0)
        req = {"duration_s": duration}
        for k in ("worker_id", "interval_s"):
            if payload.get(k) is not None:
                req[k] = payload[k]
        return await self._fanout_to_raylets(
            "profile_workers", req,
            node_id=payload.get("node_id"),
            timeout=duration + 15.0)

    async def get_node_stats(self, payload, conn):
        """Fan a node-stats snapshot out to raylet agents (reference:
        dashboard head scraping per-node agents, dashboard/agent.py);
        node_id narrows to one node."""
        return await self._fanout_to_raylets(
            "node_stats", {}, node_id=payload.get("node_id"))

    async def drain_node(self, payload, conn):
        await self._mark_node_dead(payload["node_id"], "drained")
        return {}

    async def node_draining(self, payload, conn):
        """A raylet received a preemption notice: mark it draining in
        the node table so the scheduler (spillback, actors, PGs) stops
        placing onto it, and broadcast for anyone watching node state."""
        node = self.nodes.get(payload["node_id"])
        if node is None:
            return {}
        node.draining = True
        node.drain_deadline_unix = float(
            payload.get("deadline_unix") or 0.0)
        self._bump_view(node)
        self._event("WARNING", "NODE_DRAINING",
                    f"node {node.node_id[:8]} draining: "
                    f"{payload.get('reason') or 'preemption notice'}",
                    node_id=node.node_id,
                    grace_s=payload.get("grace_s"),
                    deadline_unix=node.drain_deadline_unix)
        await self._publish("node_events", {
            "event": "draining", "node_id": node.node_id,
            "grace_s": payload.get("grace_s"),
            "deadline_unix": node.drain_deadline_unix})
        return {}

    async def node_drained(self, payload, conn):
        """Graceful end of a drain: the raylet is about to exit — mark
        the node dead NOW (fast failover) instead of waiting out the
        heartbeat timeout."""
        await self._mark_node_dead(
            payload["node_id"],
            f"preempted ({payload.get('reason') or 'drained'})")
        return {}

    async def preempt_node(self, payload, conn):
        """Deliver a preemption notice to a raylet (the test/operator
        entry; real TPU spot notices arrive as SIGUSR2 on the host)."""
        node = self.nodes.get(payload["node_id"])
        if node is None or not node.alive:
            return {"error": "unknown or dead node"}
        try:
            return await node.conn.call("preempt", {
                "grace_s": payload.get("grace_s"),
                "reason": payload.get("reason")}, timeout=10)
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}

    async def cluster_resources(self, payload, conn):
        out: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.total_resources.items():
                out[k] = out.get(k, 0) + v
        return out

    async def available_resources(self, payload, conn):
        out: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.available_resources.items():
                out[k] = out.get(k, 0) + v
        return out

    # --------------------------------------------------------------------- kv

    async def kv_put(self, payload, conn):
        key = payload["key"]
        overwrite = payload.get("overwrite", True)
        if not overwrite and key in self.kv:
            return {"added": False}
        self.kv[key] = payload["value"]
        self.store.put("kv", key, payload["value"])
        return {"added": True}

    async def kv_get(self, payload, conn):
        return {"value": self.kv.get(payload["key"])}

    async def kv_del(self, payload, conn):
        prefix = payload.get("prefix", False)
        key = payload["key"]
        if prefix:
            n = 0
            for k in [k for k in self.kv if k.startswith(key)]:
                del self.kv[k]
                self.store.delete("kv", k)
                n += 1
            return {"deleted": n}
        deleted = self.kv.pop(key, None) is not None
        if deleted:
            self.store.delete("kv", key)
        return {"deleted": int(deleted)}

    async def kv_keys(self, payload, conn):
        prefix = payload.get("prefix", "")
        return {"keys": [k for k in self.kv if k.startswith(prefix)]}

    async def kv_get_prefix(self, payload, conn):
        """Bulk fetch of every key under a prefix in ONE round-trip — the
        recovery read path (e.g. a restarted serve controller loading its
        whole journal) must not pay a kv_get per key."""
        prefix = payload.get("prefix", "")
        return {"items": [[k, v] for k, v in self.kv.items()
                          if k.startswith(prefix)]}

    async def kv_exists(self, payload, conn):
        return {"exists": payload["key"] in self.kv}

    # -------------------------------------------------------------------- jobs

    async def next_job_id(self, payload, conn):
        idx = self.next_job_index
        self.next_job_index += 1
        self.store.put("meta", "next_job_index", self.next_job_index)
        return {"job_index": idx}

    async def add_job(self, payload, conn):
        self.jobs[payload["job_id"]] = {
            "job_id": payload["job_id"],
            "driver_pid": payload.get("driver_pid"),
            "start_time": time.time(),
            "namespace": payload.get("namespace", ""),
            "metadata": payload.get("metadata", {}),
            "status": "RUNNING",
        }
        self.store.put("jobs", payload["job_id"],
                       self.jobs[payload["job_id"]])
        return {}

    async def get_jobs(self, payload, conn):
        return paginate(list(self.jobs.values()), payload, "job_id")

    # ----------------------------------------------------------------- pubsub

    async def subscribe(self, payload, conn):
        for channel in payload["channels"]:
            self.subscribers.setdefault(channel, set()).add(conn)
        return {}

    async def unsubscribe(self, payload, conn):
        """Drop channel subscriptions (and empty channel sets — the
        per-object channels below would otherwise accumulate one entry
        per ever-waited-on object)."""
        for channel in payload["channels"]:
            subs = self.subscribers.get(channel)
            if subs is not None:
                subs.discard(conn)
                if not subs:
                    self.subscribers.pop(channel, None)
        return {}

    async def publish(self, payload, conn):
        await self._publish(payload["channel"], payload["message"])
        return {}

    async def _publish(self, channel: str, message):
        dead = []
        for sub in self.subscribers.get(channel, ()):  # push-based pubsub
            try:
                await sub.notify("pubsub", {"channel": channel,
                                            "message": message})
            except Exception:
                dead.append(sub)
        for d in dead:
            self.subscribers.get(channel, set()).discard(d)

    # ---------------------------------------------------------------- actors

    async def register_actor(self, payload, conn):
        """Persist registration before scheduling (reference semantics:
        RegisterActor persists before dependency resolution so the actor
        survives owner-failure windows; actor_states.rst)."""
        aid = payload["actor_id"]
        if aid in self.actors:
            # idempotent under ReconnectingConnection retry: the first
            # attempt registered before the GCS died mid-reply
            return {"actor_id": aid, "existing": False}
        name = payload.get("name")
        ns = payload.get("namespace", "")
        if name:
            key = (ns, name)
            if key in self.named_actors and self.named_actors[key] != aid:
                existing = self.named_actors[key]
                # a mapping whose actor record is missing (crash between
                # the two persists) counts as DEAD — the name is free
                if self.actors.get(existing,
                                   {"state": DEAD}).get("state") != DEAD:
                    if payload.get("get_if_exists"):
                        return {"actor_id": existing, "existing": True}
                    return {"error": f"actor name {name!r} taken in "
                                     f"namespace {ns!r}"}
        self.actors[aid] = {
            "actor_id": aid,
            "name": name,
            "namespace": ns,
            "state": DEPS_UNREADY,
            "class_name": payload.get("class_name", ""),
            "owner_address": payload.get("owner_address"),
            "detached": payload.get("detached", False),
            "resources": payload.get("resources", {}),
            "max_restarts": payload.get("max_restarts", 0),
            "num_restarts": 0,
            "node_id": None,
            "worker_address": None,
            "create_spec": payload.get("create_spec"),
            "scheduling": payload.get("scheduling", {}),
            "death_cause": None,
        }
        # actor record first, THEN the name mapping: a crash between the two
        # fsync points must not leave a name pointing at a missing actor
        self._persist_actor(aid)
        if name:
            self.named_actors[(ns, name)] = aid
            self.store.put("named_actors", f"{ns}\x00{name}", aid)
        return {"actor_id": aid, "existing": False}

    async def create_actor(self, payload, conn):
        """Dependency-resolved: schedule and start the actor process-side.

        Reference: GcsActorScheduler::Schedule (gcs_actor_scheduler.cc:49) —
        GCS picks a node, leases a worker from that raylet, pushes creation.
        """
        aid = payload["actor_id"]
        info = self.actors.get(aid)
        if info is None:
            return {"error": "unknown actor"}
        if info["state"] != DEPS_UNREADY:
            # retried create (GCS restart mid-reply): scheduling is already
            # in flight or done — kicking it again would lease a 2nd worker
            return {}
        info["create_spec"] = payload.get("create_spec", info.get("create_spec"))
        self._persist_actor(aid)
        protocol.spawn(self._schedule_actor(aid))
        return {}

    async def _schedule_actor(self, aid: str):
        info = self.actors.get(aid)
        if info is None or info["state"] == DEAD:
            return
        info["state"] = PENDING_CREATION
        demand = info.get("resources", {})
        sched = info.get("scheduling", {})
        deadline = time.monotonic() + self.config.worker_lease_timeout_s * 10
        while time.monotonic() < deadline:
            node_id = self._pick_node(demand, sched)
            if node_id is None:
                await asyncio.sleep(0.2)  # wait for resources/nodes
                continue
            node = self.nodes[node_id]
            try:
                reply = await node.conn.call("create_actor_worker", {
                    "actor_id": aid,
                    "create_spec": info["create_spec"],
                    "resources": demand,
                }, timeout=self.config.worker_start_timeout_s)
            except Exception as e:
                logger.warning("actor %s creation on %s failed: %s",
                               aid[:8], node_id[:8], e)
                await asyncio.sleep(0.2)
                continue
            if reply.get("error"):
                err = reply["error"]
                if reply.get("retryable", True):
                    await asyncio.sleep(0.2)
                    continue
                await self._mark_actor_dead(aid, err)
                return
            info["node_id"] = node_id
            info["worker_address"] = reply["worker_address"]
            # 1.8: the worker's direct-lane endpoints ride the actor
            # record (get_actor / wait_actor_alive) so any caller in
            # the fleet can push actor_call down the native lane
            info["direct_address"] = reply.get("direct_address", "")
            info["direct_tcp_address"] = reply.get(
                "direct_tcp_address", "")
            info["state"] = ALIVE
            self._persist_actor(aid)
            await self._publish("actor_events",
                                {"actor_id": aid, "state": ALIVE,
                                 "worker_address": reply["worker_address"]})
            for fut in self._actor_creation_waiters.pop(aid, []):
                if not fut.done():
                    fut.set_result(info)
            return
        await self._mark_actor_dead(aid, "actor creation timed out (resources "
                                         "never became available)")

    async def _handle_actor_failure(self, aid: str, reason: str):
        info = self.actors.get(aid)
        if info is None:
            return
        max_restarts = info.get("max_restarts", 0)
        if max_restarts == -1 or info["num_restarts"] < max_restarts:
            info["num_restarts"] += 1
            info["state"] = RESTARTING
            self._event("WARNING", "ACTOR_RESTARTING",
                        f"actor {aid[:8]} ({info.get('class_name')}) "
                        f"restarting: {reason}",
                        actor_id=aid, restarts=info["num_restarts"])
            self._persist_actor(aid)
            await self._publish("actor_events",
                                {"actor_id": aid, "state": RESTARTING})
            protocol.spawn(self._schedule_actor(aid))
        else:
            await self._mark_actor_dead(aid, reason)

    async def _mark_actor_dead(self, aid: str, reason: str):
        info = self.actors.get(aid)
        if info is None:
            return
        info["state"] = DEAD
        info["death_cause"] = reason
        self._event("ERROR", "ACTOR_DEAD",
                    f"actor {aid[:8]} ({info.get('class_name')}) died: "
                    f"{reason}", actor_id=aid, reason=reason)
        self._persist_actor(aid)
        await self._publish("actor_events",
                            {"actor_id": aid, "state": DEAD, "reason": reason})
        for fut in self._actor_creation_waiters.pop(aid, []):
            if not fut.done():
                fut.set_result(info)

    async def actor_state_update(self, payload, conn):
        aid = payload["actor_id"]
        state = payload["state"]
        if state == DEAD:
            info = self.actors.get(aid)
            if info is None:
                return {}
            if payload.get("restart", False) and not payload.get("intended"):
                await self._handle_actor_failure(aid, payload.get("reason", ""))
            else:
                await self._mark_actor_dead(aid, payload.get("reason", ""))
        return {}

    async def kill_actor(self, payload, conn):
        aid = payload["actor_id"]
        info = self.actors.get(aid)
        if info is None or info["state"] == DEAD:
            return {}
        node = self.nodes.get(info.get("node_id") or "")
        info["max_restarts"] = 0 if payload.get("no_restart", True) else \
            info["max_restarts"]
        self._persist_actor(aid)
        if node is not None and info.get("worker_address"):
            try:
                await node.conn.call("kill_actor_worker", {
                    "actor_id": aid,
                    "worker_address": info["worker_address"],
                })
            except Exception:
                pass
        await self._mark_actor_dead(aid, "ray_tpu.kill() called")
        return {}

    async def get_actor(self, payload, conn):
        info = self.actors.get(payload["actor_id"])
        if info is None:
            return {"error": "unknown actor"}
        return {k: v for k, v in info.items() if k != "create_spec"}

    async def get_named_actor(self, payload, conn):
        key = (payload.get("namespace", ""), payload["name"])
        aid = self.named_actors.get(key)
        if aid is None:
            return {"error": f"no actor named {payload['name']!r}"}
        return await self.get_actor({"actor_id": aid}, conn)

    async def list_named_actors(self, payload, conn):
        ns = payload.get("namespace")
        out = []
        for (actor_ns, name), aid in self.named_actors.items():
            if ns is not None and actor_ns != ns:
                continue
            if self.actors.get(aid, {}).get("state") != DEAD:
                out.append({"name": name, "namespace": actor_ns})
        return out

    async def list_actors(self, payload, conn):
        rows = [{k: v for k, v in info.items() if k != "create_spec"}
                for info in self.actors.values()]
        return paginate(rows, payload, "actor_id")

    async def wait_actor_alive(self, payload, conn):
        aid = payload["actor_id"]
        info = self.actors.get(aid)
        if info is None:
            return {"error": "unknown actor"}
        if info["state"] == ALIVE or info["state"] == DEAD:
            return {k: v for k, v in info.items() if k != "create_spec"}
        fut = asyncio.get_running_loop().create_future()
        self._actor_creation_waiters.setdefault(aid, []).append(fut)
        timeout = payload.get("timeout", 120.0)
        try:
            info = await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return {"error": "timeout waiting for actor to start"}
        return {k: v for k, v in info.items() if k != "create_spec"}

    # ------------------------------------------------------------- scheduling

    def _feasible(self, node: NodeInfo, demand: Dict[str, float],
                  strict_labels: Dict[str, str] | None = None) -> bool:
        if not node.alive or node.draining:
            return False
        for k, v in (strict_labels or {}).items():
            if node.labels.get(k) != v and str(node.tpu.get(k)) != str(v):
                return False
        for k, v in demand.items():
            if node.available_resources.get(k, 0.0) + 1e-9 < v:
                return False
        return True

    def _pick_node(self, demand: Dict[str, float],
                   sched: Dict[str, Any] | None = None,
                   deps: Optional[List[str]] = None) -> Optional[str]:
        """Hybrid policy + locality (reference:
        hybrid_scheduling_policy.cc, scheduling_policy.cc scorer, and
        lease_policy.cc locality): prefer the preferred/local node until
        its utilization crosses scheduler_spread_threshold, then score
        the feasible nodes — dependencies already present beat lower
        utilization, so data-heavy tasks run where their args live.
        NodeAffinity and TPU-slice constraints are strict filters."""
        sched = sched or {}
        if sched.get("node_id"):
            node = self.nodes.get(sched["node_id"])
            if node is not None and self._feasible(node, demand):
                return node.node_id
            if not sched.get("soft", False):
                return None
        labels = {}
        if sched.get("tpu_topology"):
            labels["topology"] = sched["tpu_topology"]
        candidates = [n for n in self.nodes.values()
                      if self._feasible(n, demand, labels)]
        if not candidates:
            return None
        util = {n.node_id: self._utilization(n) for n in candidates}
        chosen: Optional[str] = None
        if sched.get("spread"):
            # utilization is report-driven (stale between polls): a burst
            # of SPREAD tasks all see identical numbers, so break ties
            # round-robin or they all land on one node
            candidates.sort(key=lambda n: (util[n.node_id], n.node_id))
            low = util[candidates[0].node_id]
            tied = [n for n in candidates
                    if util[n.node_id] - low < 0.05]
            self._spread_rr += 1
            chosen = tied[self._spread_rr % len(tied)].node_id
        if chosen is None:
            preferred = sched.get("preferred_node")
            if preferred:
                node = self.nodes.get(preferred)
                if node is not None and node in candidates and \
                        util[preferred] < \
                        self.config.scheduler_spread_threshold:
                    chosen = preferred
        if chosen is None:
            def score(n: NodeInfo):
                # deps-local first — but only while the holder can take
                # this demand under the pessimistic view (locality must
                # not pile a burst onto a node that, once spilled-to,
                # cannot re-spill) — then lower utilization, stable by id
                loc = (self._locality(n.node_id, deps)
                       if self._pessimistic_headroom(n, demand) else 0)
                return (-loc, util[n.node_id], n.node_id)

            chosen = min(candidates, key=score).node_id
        # pessimistic self-accounting: this placement occupies resources
        # NOW even though the node's next report hasn't seen it yet
        self._ephemeral_allocs.setdefault(chosen, []).append(
            (time.monotonic(), dict(demand)))
        return chosen

    def _locality(self, node_id: str, deps: Optional[List[str]]) -> int:
        """How many of the task's plasma dependencies this node already
        holds (object-size-weighted in the reference; the directory here
        tracks locations, not sizes — count is the proxy)."""
        if not deps:
            return 0
        return sum(1 for hex_id in deps
                   if node_id in self.object_locations.get(hex_id, ()))

    _EPHEMERAL_TTL = 3.0

    def _pending_for(self, node_id: str) -> Dict[str, float]:
        """Sum of this scheduler's unexpired in-flight placements."""
        now = time.monotonic()
        pending: Dict[str, float] = {}
        allocs = self._ephemeral_allocs.get(node_id)
        if allocs:
            allocs[:] = [(t, d) for t, d in allocs
                         if now - t < self._EPHEMERAL_TTL]
            for _t, demand in allocs:
                for k, v in demand.items():
                    pending[k] = pending.get(k, 0.0) + v
        return pending

    def _effective_avail(self, node: NodeInfo, key: str,
                         pending: Dict[str, float]) -> float:
        """The reported availability (lags node state) and total-minus-
        recent-placements (this scheduler's own view) are EACH an upper
        bound on what's free; take the min — summing them double-counts
        any task that is both reported-running and still in the
        ephemeral window."""
        reported = node.available_resources.get(key, 0.0)
        own_view = node.total_resources.get(key, 0.0) - \
            pending.get(key, 0.0)
        return max(0.0, min(reported, own_view))

    def _pessimistic_headroom(self, node: NodeInfo,
                              demand: Dict[str, float]) -> bool:
        pending = self._pending_for(node.node_id)
        return all(self._effective_avail(node, k, pending) >= v
                   for k, v in demand.items())

    def _utilization(self, node: NodeInfo) -> float:
        # node reports are poll-driven and lag the GCS's own decisions;
        # fold in this scheduler's recent placements (ephemeral
        # allocations, reference: cluster_resource_manager's local view)
        # or a burst of schedule() calls piles onto one node
        pending = self._pending_for(node.node_id)
        worst = 0.0
        for k, total in node.total_resources.items():
            if total <= 0:
                continue
            avail = self._effective_avail(node, k, pending)
            worst = max(worst, 1.0 - avail / total)
        return worst

    async def schedule(self, payload, conn):
        """Spillback scheduling for tasks a raylet can't place locally."""
        node_id = self._pick_node(payload.get("demand", {}),
                                  payload.get("scheduling"),
                                  deps=payload.get("deps"))
        if node_id is None:
            return {"node_id": None}
        return {"node_id": node_id,
                "raylet_address": self.nodes[node_id].raylet_address}

    # ------------------------------------------------------ placement groups

    async def create_placement_group(self, payload, conn):
        """2-phase commit of bundles (reference:
        gcs_placement_group_scheduler.cc Prepare/Commit/CancelResourceReserve
        over node_manager.proto:377-384). STRICT_PACK over a TPU slice
        co-schedules all hosts of that slice — the ICI domain is the locality
        unit (SURVEY.md §7 phase 1)."""
        pg_id = payload["pg_id"]
        bundles: List[Dict[str, float]] = payload["bundles"]
        strategy = payload.get("strategy", "PACK")

        def park_pending():
            # a PG that can't place NOW is queued and retried as
            # resources free up — it must never be dropped (a burst of
            # creations all reading the same stale reports routinely
            # fails the 2-phase prepare; reference:
            # gcs_placement_group_manager's pending queue)
            self.placement_groups[pg_id] = {
                "pg_id": pg_id, "state": "PENDING", "bundles": bundles,
                "strategy": strategy, "assignment": None,
                "name": payload.get("name"),
            }
            self._persist_pg(pg_id)
            protocol.spawn(
                self._retry_pg(pg_id))
            return {"state": "PENDING"}

        assignment = self._place_bundles(bundles, strategy)
        if assignment is None:
            return park_pending()
        ok = await self._commit_bundles(pg_id, bundles, assignment)
        if not ok:
            return park_pending()
        self.placement_groups[pg_id] = {
            "pg_id": pg_id, "state": "CREATED", "bundles": bundles,
            "strategy": strategy, "assignment": assignment,
            "name": payload.get("name"),
        }
        self._persist_pg(pg_id)
        return {"state": "CREATED", "assignment": assignment}

    def _pg_ever_feasible(self, bundles) -> bool:
        """Can the CURRENT cluster's totals ever host every bundle?
        (Pending PGs demanding more than any node will ever have back
        off hard instead of re-running placement every interval.)"""
        totals = [dict(n.total_resources) for n in self.nodes.values()
                  if n.alive and not n.draining]
        for b in bundles:
            if not any(all(t.get(k, 0) >= v for k, v in b.items())
                       for t in totals):
                return False
        return True

    async def _retry_pg(self, pg_id: str):
        # retries until the PG places or is removed (pending PGs are
        # legitimate under autoscaling — capacity may yet arrive); the
        # interval backs off so hundreds of pending PGs cost the loop
        # little, and never-satisfiable ones poll at the slowest rate
        delay = 0.25
        while True:
            await asyncio.sleep(delay)
            delay = min(delay * 1.5, 2.0)
            pg = self.placement_groups.get(pg_id)
            if pg is None:
                return
            if pg["state"] != "PENDING":
                return
            if not self._pg_ever_feasible(pg["bundles"]):
                delay = 10.0
                continue
            assignment = self._place_bundles(pg["bundles"], pg["strategy"])
            if assignment is None:
                continue
            if await self._commit_bundles(pg_id, pg["bundles"], assignment):
                pg["state"] = "CREATED"
                pg["assignment"] = assignment
                self._persist_pg(pg_id)
                await self._publish("pg_events",
                                    {"pg_id": pg_id, "state": "CREATED"})
                return

    def _place_bundles(self, bundles, strategy) -> Optional[List[str]]:
        # pessimistic view: reported availability folded with this
        # scheduler's own recent placements, so a burst of concurrent
        # creations doesn't stampede one node on stale reports
        avail = {}
        for nid, n in self.nodes.items():
            if not n.alive or n.draining:
                continue
            pending = self._pending_for(nid)
            avail[nid] = {
                k: self._effective_avail(n, k, pending)
                for k in set(n.total_resources) | set(pending)}

        def fits(nid, bundle):
            return all(avail[nid].get(k, 0) + 1e-9 >= v
                       for k, v in bundle.items())

        def take(nid, bundle):
            for k, v in bundle.items():
                avail[nid][k] = avail[nid].get(k, 0) - v

        assignment: List[str] = []
        node_ids = list(avail)
        if strategy in ("PACK", "STRICT_PACK"):
            # try to fit all on one node first
            for nid in node_ids:
                ok = True
                tmp = dict(avail[nid])
                for b in bundles:
                    if all(tmp.get(k, 0) + 1e-9 >= v for k, v in b.items()):
                        for k, v in b.items():
                            tmp[k] = tmp.get(k, 0) - v
                    else:
                        ok = False
                        break
                if ok:
                    return [nid] * len(bundles)
            if strategy == "STRICT_PACK":
                return None
        used_nodes: Set[str] = set()
        for b in bundles:
            placed = None
            for nid in sorted(node_ids,
                              key=lambda n: (n in used_nodes)
                              if strategy in ("SPREAD", "STRICT_SPREAD")
                              else (n not in used_nodes)):
                if strategy == "STRICT_SPREAD" and nid in used_nodes:
                    continue
                if fits(nid, b):
                    placed = nid
                    break
            if placed is None:
                return None
            take(placed, b)
            used_nodes.add(placed)
            assignment.append(placed)
        return assignment

    async def _commit_bundles(self, pg_id, bundles, assignment) -> bool:
        # phase 1: prepare (reserve) on each raylet
        prepared: List[Tuple[str, int]] = []
        ok = True
        for idx, (bundle, nid) in enumerate(zip(bundles, assignment)):
            node = self.nodes.get(nid)
            if node is None or not node.alive:
                ok = False
                break
            try:
                r = await node.conn.call("prepare_bundle", {
                    "pg_id": pg_id, "bundle_index": idx, "resources": bundle})
                if not r.get("ok"):
                    ok = False
                    break
                prepared.append((nid, idx))
            except Exception:
                ok = False
                break
        if not ok:
            for nid, idx in prepared:
                node = self.nodes.get(nid)
                if node is not None:
                    try:
                        await node.conn.call("cancel_bundle",
                                             {"pg_id": pg_id,
                                              "bundle_index": idx})
                    except Exception:
                        logger.debug(
                            "pg %s: cancel_bundle %d on %s failed "
                            "(node dying? resources refund on its "
                            "death path)", pg_id, idx, nid,
                            exc_info=True)
            return False
        # phase 2: commit; record the reservations in the ephemeral view
        # so concurrent placements see them before the next node report
        for (nid, idx), bundle in zip(prepared, bundles):
            self._ephemeral_allocs.setdefault(nid, []).append(
                (time.monotonic(), dict(bundle)))
            try:
                await self.nodes[nid].conn.call(
                    "commit_bundle", {"pg_id": pg_id, "bundle_index": idx})
            except Exception:
                logger.warning(
                    "pg %s: commit_bundle %d on %s failed after a "
                    "successful prepare; bundle rides on the prepare "
                    "reservation until the node report reconciles",
                    pg_id, idx, nid, exc_info=True)
        return True

    async def remove_placement_group(self, payload, conn):
        pg = self.placement_groups.pop(payload["pg_id"], None)
        if pg is None:
            return {}
        self.store.delete("pgs", payload["pg_id"])
        if pg.get("assignment"):
            for idx, nid in enumerate(pg["assignment"]):
                node = self.nodes.get(nid)
                if node is not None and node.alive:
                    try:
                        await node.conn.call("return_bundle", {
                            "pg_id": pg["pg_id"], "bundle_index": idx})
                    except Exception:
                        logger.debug(
                            "pg %s: return_bundle %d on %s failed "
                            "(node death refunds it)", pg["pg_id"],
                            idx, nid, exc_info=True)
        return {}

    async def get_placement_group(self, payload, conn):
        pg = self.placement_groups.get(payload["pg_id"])
        if pg is None:
            return {"error": "unknown placement group"}
        return pg

    async def list_placement_groups(self, payload, conn):
        return paginate(list(self.placement_groups.values()), payload,
                        "pg_id")

    # -------------------------------------------------------- object registry

    async def add_object_location(self, payload, conn):
        oid = payload["object_id"]
        self.object_locations.setdefault(oid, set()).add(payload["node_id"])
        if payload.get("owner"):
            self.object_owners[oid] = payload["owner"]
        # long-poll object channel (reference: GCS pubsub
        # WORKER_OBJECT_LOCATIONS_CHANNEL): borrowers waiting on this
        # object wake on the notification instead of polling the
        # directory
        if f"obj:{oid}" in self.subscribers:
            await self._publish(f"obj:{oid}",
                                {"object_id": oid,
                                 "node_id": payload["node_id"]})
        return {}

    async def remove_object_location(self, payload, conn):
        locs = self.object_locations.get(payload["object_id"])
        if locs:
            locs.discard(payload["node_id"])
        return {}

    async def get_object_locations(self, payload, conn):
        oid = payload["object_id"]
        locs = self.object_locations.get(oid, set())
        out = []
        for nid in locs:
            node = self.nodes.get(nid)
            if node is not None and node.alive:
                out.append({"node_id": nid,
                            "raylet_address": node.raylet_address,
                            # 1.8: pullers prefer the netx plane
                            "netx_address": node.netx_address})
        return {"locations": out, "owner": self.object_owners.get(oid)}

    async def ping(self, payload, conn):
        return {"t": time.time()}


async def run_gcs(config: SystemConfig, host: str, port: int,
                  ready_cb=None) -> GcsServer:
    gcs = GcsServer(config)
    actual = await gcs.start(host, port)
    if ready_cb:
        ready_cb(actual)
    return gcs
