"""Control-plane RPC: msgpack-framed messages over unix/TCP sockets.

Role-equivalent to the reference's gRPC plumbing (reference: src/ray/rpc/ —
client_call.h, grpc_server.cc) redesigned lighter: the control plane here is a
msgpack-over-socket protocol with request/reply correlation and one-way
notifications. Bulk data never rides this plane — large payloads go through
the plasmax shared-memory store (intra-node) or the chunked object-transfer
path (inter-node), exactly like the reference splits control (gRPC) from data
(plasma/object_manager).

Frame: [uint32 length][msgpack body]
Body:  [msg_type, seq, method, payload]
  msg_type: 0 = request (expects reply), 1 = reply, 2 = error reply,
            3 = one-way notification
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import struct
import threading
from typing import Any, Awaitable, Callable, Dict, Optional

import msgpack

from ray_tpu._private import chaos

REQUEST, REPLY, ERROR, NOTIFY = 0, 1, 2, 3

_MAX_FRAME = 256 * 1024 * 1024


def pack_frame(body) -> bytes:
    data = msgpack.packb(body, use_bin_type=True)
    return struct.pack("<I", len(data)) + data


async def read_frame(reader: asyncio.StreamReader):
    hdr = await reader.readexactly(4)
    (n,) = struct.unpack("<I", hdr)
    if n > _MAX_FRAME:
        raise ConnectionError(f"frame too large: {n}")
    data = await reader.readexactly(n)
    return msgpack.unpackb(data, raw=False)


def read_frame_sync(sock) -> Any:
    """Blocking-socket twin of read_frame — same framing, no event loop.
    The compiled-DAG channel threads (ray_tpu/dag/channel.py) speak the
    wire protocol over dedicated sockets owned by plain threads, so the
    forward path never touches an asyncio loop."""
    hdr = _recv_exact(sock, 4)
    (n,) = struct.unpack("<I", hdr)
    if n > _MAX_FRAME:
        raise ConnectionError(f"frame too large: {n}")
    return msgpack.unpackb(_recv_exact(sock, n), raw=False)


def _recv_exact(sock, n: int) -> bytes:
    parts = []
    while n:
        chunk = sock.recv(n)
        if not chunk:
            raise ConnectionError("peer closed")
        parts.append(chunk)
        n -= len(chunk)
    return b"".join(parts) if len(parts) != 1 else parts[0]


class RpcError(Exception):
    pass


_BG_TASKS: set = set()


def spawn(coro) -> "asyncio.Task":
    """``create_task`` with a strong reference held until completion.
    The loop only weak-refs tasks; a discarded handle lets the GC close
    the coroutine mid-await (GeneratorExit) — fire-and-forget work must
    go through here (or EventLoopThread.run_async, which does the
    same)."""
    task = asyncio.get_running_loop().create_task(coro)
    _BG_TASKS.add(task)
    task.add_done_callback(_BG_TASKS.discard)
    return task


class Connection:
    """A bidirectional RPC connection. Either side can issue requests."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 handler: Optional[Callable[[str, Any, "Connection"],
                                            Awaitable[Any]]] = None,
                 on_close: Optional[Callable[["Connection"], None]] = None):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.on_close = on_close
        self._seq = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self._send_lock = asyncio.Lock()
        self._task = asyncio.get_running_loop().create_task(self._read_loop())
        # opaque per-connection state the server attaches (e.g. worker id)
        self.meta: Dict[str, Any] = {}
        # remote IP for TCP links ('' = unix/unknown): keys the
        # net.partition chaos site in _send — see netx.endpoints
        self.peer_host: str = ""

    async def _read_loop(self):
        try:
            while True:
                frame = await read_frame(self.reader)
                mtype, seq, method, payload = frame
                eng = chaos._ENGINE
                if eng is not None and mtype in (REQUEST, NOTIFY):
                    # chaos injection point (inbound): drop/delay/dup a
                    # frame or reset the link — restricted to
                    # request/notify frames (swallowing a reply wedges
                    # the peer's pending future; model that by dropping
                    # the reply on ITS send side instead)
                    act = eng.hit("protocol.recv", method)
                    if act is not None:
                        op = act["op"]
                        if op == "drop":
                            continue
                        if op == "delay":
                            await asyncio.sleep(
                                float(act.get("delay_s", eng.delay_s)))
                        elif op == "reset":
                            raise ConnectionError("chaos: reset (recv)")
                        elif op == "dup":
                            spawn(self._dispatch(
                                seq if mtype == REQUEST else None,
                                method, payload))
                if mtype == REQUEST:
                    spawn(self._dispatch(seq, method, payload))
                elif mtype == NOTIFY:
                    spawn(self._dispatch(None, method, payload))
                elif mtype in (REPLY, ERROR):
                    fut = self._pending.pop(seq, None)
                    if fut is not None and not fut.done():
                        if mtype == REPLY:
                            fut.set_result(payload)
                        else:
                            fut.set_exception(RpcError(payload))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self._closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("connection closed"))
            self._pending.clear()
            try:
                self.writer.close()
            except Exception:
                pass
            if self.on_close:
                self.on_close(self)

    async def _dispatch(self, seq, method, payload):
        try:
            result = await self.handler(method, payload, self)
            if seq is not None:
                await self._send([REPLY, seq, method, result])
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            if seq is not None:
                try:
                    await self._send([ERROR, seq, method,
                                      f"{type(e).__name__}: {e}"])
                except Exception:
                    pass

    async def _send(self, body):
        dup = False
        eng = chaos._ENGINE
        if eng is not None and self.peer_host:
            # one-direction partition: every frame toward the severed
            # host is lost and the link dies (an unplugged cable, not a
            # polite FIN) — lazy import, netx.client imports this module
            from ray_tpu._private.netx import endpoints as _nx
            if _nx.partitioned(self.peer_host):
                self.close()
                raise ConnectionError("chaos: network partition")
        if eng is not None:
            # chaos injection point (outbound): body[2] is the method
            act = eng.hit("protocol.send", body[2])
            if act is not None:
                op = act["op"]
                if op == "drop":
                    return
                if op == "delay":
                    await asyncio.sleep(
                        float(act.get("delay_s", eng.delay_s)))
                elif op == "reset":
                    self.close()
                    raise ConnectionError("chaos: reset (send)")
                elif op == "dup":
                    dup = True
        async with self._send_lock:
            self.writer.write(pack_frame(body))
            if dup:
                self.writer.write(pack_frame(body))
            await self.writer.drain()

    async def call(self, method: str, payload: Any = None,
                   timeout: Optional[float] = None) -> Any:
        if self._closed:
            raise ConnectionError("connection closed")
        seq = next(self._seq)
        fut = asyncio.get_running_loop().create_future()
        self._pending[seq] = fut
        await self._send([REQUEST, seq, method, payload])
        if timeout is not None:
            return await asyncio.wait_for(fut, timeout)
        return await fut

    async def notify(self, method: str, payload: Any = None):
        if self._closed:
            raise ConnectionError("connection closed")
        await self._send([NOTIFY, None, method, payload])

    def close(self):
        self._closed = True
        try:
            self.writer.close()
        except Exception:
            pass
        # Cancel the read loop so the task isn't abandoned pending — an
        # un-cancelled _read_loop is GC'd later as "Task was destroyed
        # but it is pending!", masking real errors in every log.
        task = self._task
        if task is not None and not task.done():
            loop = task.get_loop()
            if loop.is_running():
                loop.call_soon_threadsafe(task.cancel)
            else:
                task.cancel()

    async def aclose(self):
        """Close and wait for the read loop to finish unwinding."""
        self.close()
        task = self._task
        if task is not None:
            try:
                await task
            except asyncio.CancelledError:
                cur = asyncio.current_task()
                # Task.cancelling() is 3.11+; on 3.10 there is no way to
                # tell "our cancellation" from the read loop's — swallow,
                # matching pre-3.11 semantics (the read loop's cancel is
                # the overwhelmingly common case here)
                if cur is not None and \
                        getattr(cur, "cancelling", lambda: 0)():
                    raise  # OUR cancellation, not the read loop's
            except Exception:  # noqa: BLE001 — read-loop teardown errors
                pass


class Server:
    """Accepts connections; dispatches to a method-name handler table."""

    def __init__(self, handlers: Dict[str, Callable]):
        self.handlers = handlers
        self.connections: set[Connection] = set()
        self._server: Optional[asyncio.base_events.Server] = None

    async def _on_connect(self, reader, writer):
        conn = Connection(reader, writer, handler=self._handle,
                          on_close=self._on_close)
        peer = writer.get_extra_info("peername")
        if isinstance(peer, tuple) and peer:
            conn.peer_host = str(peer[0])
        self.connections.add(conn)
        if "_on_connect" in self.handlers:
            await self.handlers["_on_connect"](conn)

    def _on_close(self, conn):
        self.connections.discard(conn)
        cb = self.handlers.get("_on_disconnect")
        if cb is not None:
            spawn(cb(conn))

    async def _handle(self, method, payload, conn):
        if chaos._ENGINE is not None:
            # chaos injection point: "kill" at the N-th served request
            # (executed inside the engine — SIGKILL, no cleanup)
            chaos.hit("rpc.request", method)
        if method == "__hello__":
            # version negotiation (schema.py — the protobuf-package
            # role): reply with our version + schema hash; reject
            # incompatible majors so drift fails at connect, not mid-RPC
            from ray_tpu._private import schema
            ver = (payload or {}).get("protocol_version")
            if conn is not None and isinstance(ver, (list, tuple)) \
                    and len(ver) == 2:
                try:
                    # remember what the peer negotiated: handlers gate
                    # minor-version features (e.g. batched dispatch
                    # statuses) on this instead of assuming the newest.
                    # conn is None for in-process dispatch (tests);
                    # there is no peer to remember then
                    conn.meta["peer_protocol_version"] = (
                        int(ver[0]), int(ver[1]))
                except (TypeError, ValueError):
                    pass
            err = schema.check_hello(payload or {})
            if err:
                raise RpcError(f"protocol negotiation failed: {err}")
            return schema.hello_payload()
        fn = self.handlers.get(method)
        if fn is None:
            raise RpcError(f"no such method: {method}")
        from ray_tpu._private import schema
        if schema.validation_enabled():
            errors = schema.validate(method, payload)
            if errors:
                raise RpcError("wire schema violation: "
                               + "; ".join(errors))
        return await fn(payload, conn)

    async def start_unix(self, path: str):
        self._server = await asyncio.start_unix_server(self._on_connect, path=path)

    async def start_tcp(self, host: str, port: int) -> int:
        self._server = await asyncio.start_server(self._on_connect, host, port)
        return self._server.sockets[0].getsockname()[1]

    def close(self):
        if self._server is not None:
            self._server.close()
        for c in list(self.connections):
            c.close()


async def single_flight_connect(cache: Dict[str, "Connection"],
                                pending: Dict[str, "asyncio.Future"],
                                address: str,
                                dial: Callable[[str], Awaitable["Connection"]]
                                ) -> "Connection":
    """Cached, single-flight dialing: concurrent callers of the same
    address share one in-flight dial instead of racing N parallel
    connects where every Connection but the last-stored leaks an open
    read loop (GC'd later as "Task was destroyed but it is pending!").

    Must be called from the loop that owns `cache`/`pending`.  A failed
    leader dial wakes the waiters, and one of them retries as leader;
    a caller's own cancellation propagates (it is never confused with
    the leader's failure — leader cancellation is translated to
    ConnectionError on the shared future)."""
    while True:
        conn = cache.get(address)
        if conn is not None and not conn._closed:
            return conn
        fut = pending.get(address)
        if fut is not None:
            try:
                return await asyncio.shield(fut)
            except asyncio.CancelledError:
                raise  # our own cancellation — the shared fut is never
                # cancelled and leader cancellation arrives as
                # ConnectionError below
            except Exception:
                continue  # leader's dial failed — retry as leader
        fut = asyncio.get_running_loop().create_future()
        pending[address] = fut
        try:
            conn = await dial(address)
        except BaseException as e:
            pending.pop(address, None)
            if isinstance(e, asyncio.CancelledError):
                fut.set_exception(ConnectionError("dial cancelled"))
            else:
                fut.set_exception(e)
            fut.exception()  # consumed here: waiters retry via the loop
            raise
        cache[address] = conn
        pending.pop(address, None)
        fut.set_result(conn)
        return conn


async def connect(address: str,
                  handler: Optional[Callable] = None,
                  on_close: Optional[Callable] = None) -> Connection:
    """address: 'unix:/path' or 'host:port'."""
    peer_host = ""
    if address.startswith("unix:"):
        reader, writer = await asyncio.open_unix_connection(address[5:])
    else:
        host, port = address.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(host, int(port))
        peer_host = host
    if handler is None:
        async def handler(method, payload, conn):  # noqa: ARG001
            raise RpcError(f"unexpected request {method}")
    conn = Connection(reader, writer, handler=handler, on_close=on_close)
    conn.peer_host = peer_host
    return conn


class ReconnectingConnection:
    """A Connection facade that transparently re-dials on failure.

    Used for links to the GCS so a GCS restart (fault tolerance, reference:
    gcs_rpc_client.h retry semantics) is invisible to raylets and workers:
    calls made while the GCS is down retry with backoff until
    `reconnect_timeout_s` elapses; `on_reconnect` (e.g. node re-registration,
    pubsub re-subscription) runs after each successful re-dial.
    """

    def __init__(self, address: str, handler=None,
                 on_reconnect=None, reconnect_timeout_s: float = 30.0):
        self.address = address
        self.handler = handler
        self.on_reconnect = on_reconnect
        self.reconnect_timeout_s = reconnect_timeout_s
        self._conn: Optional[Connection] = None
        self._lock: Optional[asyncio.Lock] = None
        self.meta: Dict[str, Any] = {}

    async def _ensure(self) -> Connection:
        if self._conn is not None and not self._conn._closed:
            return self._conn
        if self._lock is None:
            self._lock = asyncio.Lock()
        async with self._lock:
            if self._conn is not None and not self._conn._closed:
                return self._conn
            deadline = (asyncio.get_running_loop().time()
                        + self.reconnect_timeout_s)
            delay = 0.05
            first = self._conn is None
            while True:
                try:
                    self._conn = await connect(self.address,
                                               handler=self.handler)
                    break
                except OSError:
                    if asyncio.get_running_loop().time() >= deadline:
                        raise ConnectionError(
                            f"cannot reach {self.address}")
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 1.0)
            # version negotiation on the long-lived links (schema.py):
            # an incompatible MAJOR fails here, at connect time. A peer
            # predating __hello__ replies "no such method" — compatible.
            try:
                from ray_tpu._private import schema
                await self._conn.call("__hello__",
                                      schema.hello_payload(),
                                      timeout=10)
            except RpcError as e:
                if "negotiation failed" in str(e):
                    self._conn.close()
                    self._conn = None
                    raise ConnectionError(
                        f"protocol negotiation with {self.address} "
                        f"failed: {e}")
            except Exception:
                pass  # hello is best-effort beyond the version check
            if not first and self.on_reconnect is not None:
                await self.on_reconnect(self._conn)
            return self._conn

    async def call(self, method: str, payload: Any = None,
                   timeout: Optional[float] = None) -> Any:
        # Retrying after a mid-call connection loss re-executes the RPC on
        # the restarted peer, so GCS handlers are written to be idempotent
        # keyed on caller-supplied unique IDs (actor_id, pg_id, kv key) —
        # the same contract the reference's gcs_rpc_client retry layer
        # assumes.
        attempts = 2
        for i in range(attempts):
            conn = await self._ensure()
            try:
                return await conn.call(method, payload, timeout=timeout)
            except ConnectionError:
                if i == attempts - 1:
                    raise
                # peer went away mid-call: reconnect and retry once
                continue

    async def notify(self, method: str, payload: Any = None):
        conn = await self._ensure()
        await conn.notify(method, payload)

    def close(self):
        if self._conn is not None:
            self._conn.close()


class EventLoopThread:
    """A dedicated asyncio loop on a background thread.

    Every process (driver, worker, raylet, GCS) runs exactly one of these for
    its control-plane IO — the analogue of the reference's per-process
    instrumented_io_context (reference: src/ray/common/asio/). Blocking user
    threads interact via run()/run_async().
    """

    def __init__(self, name: str = "rtpu-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._started = threading.Event()
        self._stop_called = False
        self._inflight: set = set()  # strong refs to fire-and-forget tasks
        # stall detector (reference: the asio event-loop instrumentation
        # in common/asio/ + the debug loop-lag monitors): a heartbeat
        # callback stamps the clock; a watchdog thread flags the loop as
        # stalled — with the loop thread's live stack — when the stamp
        # goes stale. Enabled via RTPU_LOOP_STALL_S (seconds; 0 = off).
        self._hb = 0.0
        self.stalls_detected = 0
        import os as _os
        try:
            self._stall_s = float(
                _os.environ.get("RTPU_LOOP_STALL_S", "0") or 0)
        except ValueError:
            # a typo in an optional debug knob must not kill every
            # process at startup
            logging.getLogger(__name__).warning(
                "ignoring malformed RTPU_LOOP_STALL_S=%r",
                _os.environ.get("RTPU_LOOP_STALL_S"))
            self._stall_s = 0.0
        self._thread.start()
        self._started.wait()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        import os as _os
        prof_dir = _os.environ.get("RTPU_CPROFILE_DIR")
        prof = None
        if prof_dir and "loop" not in _os.environ.get(
                "RTPU_CPROFILE_PROCS", "loop"):
            prof_dir = None
        if prof_dir:
            # perf-debug aid: profile THIS loop thread (cProfile is
            # per-thread; the main-thread profilers can't see handler
            # work running here)
            import cProfile
            prof = cProfile.Profile()
            prof.enable()
        self.loop.call_soon(self._started.set)
        if self._stall_s > 0:
            self._start_stall_detector()
        self.loop.run_forever()
        if prof is not None:
            prof.disable()
            prof.dump_stats(_os.path.join(
                prof_dir,
                f"loop_{_os.getpid()}_{self._thread.name}.pstats"))

    def _start_stall_detector(self):
        import sys
        import time as _time
        import traceback as _tb
        period = self._stall_s / 2

        def beat():
            self._hb = _time.monotonic()
            self.loop.call_later(period, beat)
        self.loop.call_soon(beat)
        loop_tid = threading.get_ident()

        def watch():
            warned_hb = -1.0
            while self.loop.is_running() or self._hb == 0.0:
                _time.sleep(period)
                stale = _time.monotonic() - self._hb
                if self._hb and stale > self._stall_s \
                        and self._hb != warned_hb:
                    # one count + one stack per DISTINCT stall: during
                    # an ongoing stall the heartbeat stamp is frozen,
                    # so remembering it both dedups the log and keeps
                    # stalls_detected an event count
                    warned_hb = self._hb
                    self.stalls_detected += 1
                    frame = sys._current_frames().get(loop_tid)
                    stack = "".join(_tb.format_stack(frame)) \
                        if frame else "<no frame>"
                    logging.getLogger(__name__).warning(
                        "event loop %s stalled %.1fs (a blocking call "
                        "on the IO loop starves ALL control-plane "
                        "RPCs):\n%s", self._thread.name, stale, stack)

        threading.Thread(target=watch, daemon=True,
                         name=f"{self._thread.name}-stallwatch").start()

    def run(self, coro, timeout: Optional[float] = None):
        """Run coroutine on the IO loop, block until done, return result."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_soon(self, fn, *args):
        """Schedule a plain callback on the loop from any thread.  Much
        lighter than run_coroutine_threadsafe (~no Future chaining) —
        the submit hot path uses this to wake the flusher."""
        self.loop.call_soon_threadsafe(fn, *args)

    def run_async(self, coro):
        """Fire-and-forget — but with a STRONG reference held until
        completion: the event loop only weak-refs its tasks, so a
        discarded future lets the GC close the coroutine mid-await
        (observed as GeneratorExit killing in-flight actor-call sends
        under allocation pressure)."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        self._inflight.add(fut)
        fut.add_done_callback(self._inflight.discard)
        return fut

    def stop(self):
        """Drain-and-stop: cancel every pending task on the loop, await
        the unwinds, then stop and close the loop.  Skipping the drain
        leaves tasks to be GC'd pending ("Task was destroyed!") and
        callbacks to fire on a closed loop ("Event loop is closed").

        Idempotent: a second call must not schedule a drain onto a loop
        that already stopped (the coroutine would never be awaited) —
        it only finishes the close if the first call's join timed out."""
        if self._stop_called:
            if not self._thread.is_alive() and not self.loop.is_closed():
                self.loop.close()
            return
        self._stop_called = True

        async def _drain():
            me = asyncio.current_task()
            tasks = [t for t in asyncio.all_tasks() if t is not me]
            for t in tasks:
                t.cancel()
            if tasks:
                await asyncio.gather(*tasks, return_exceptions=True)
            self.loop.stop()

        coro = _drain()
        if not self._thread.is_alive():
            # the loop thread already exited (loop crashed or stopped):
            # scheduling the drain would park the coroutine forever on a
            # dead loop — never awaited, flagged at GC. Close it unrun
            # and finish the loop teardown directly.
            coro.close()
            if not self.loop.is_closed():
                self.loop.close()
            return
        try:
            asyncio.run_coroutine_threadsafe(coro, self.loop)
        except RuntimeError:
            # loop stopped/closed between the aliveness check and the
            # schedule: close the never-started coroutine so the
            # conftest leak gate stays clean
            coro.close()
            self._thread.join(timeout=5)
            if not self._thread.is_alive() and not self.loop.is_closed():
                self.loop.close()
            return
        self._thread.join(timeout=5)
        if not self._thread.is_alive() and not self.loop.is_closed():
            self.loop.close()
