"""GCS persistence backends.

Role-equivalent to the reference's pluggable GCS storage
(reference: src/ray/gcs/store_client/ — in_memory_store_client.cc,
redis_store_client.cc) and the restart rebuild path
(gcs/gcs_server/gcs_init_data.cc LoadActorData/LoadJobData/...).

Design: managers keep their live state in plain dicts (the hot path stays
allocation-free), but every mutation is written through a StoreClient. On
startup the GCS replays the store into the dicts, so killing and restarting
the GCS process preserves actors, placement groups, jobs, KV (including
exported function blobs) and named actors.

The file backend is a msgpack write-ahead log with snapshot compaction —
crash-safe without an external Redis: each record is
``(table, key, value | None)``; None is a tombstone. On load, the snapshot
is read first, then the WAL replayed; when the WAL grows past a threshold
it is folded into a new snapshot (write-to-temp + rename).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Iterator, Optional, Tuple

import msgpack


class StoreClient:
    """Persistence seam: tables of key -> msgpack-able value."""

    def put(self, table: str, key: str, value: Any) -> None:
        raise NotImplementedError

    def delete(self, table: str, key: str) -> None:
        raise NotImplementedError

    def load_all(self) -> Iterator[Tuple[str, str, Any]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStoreClient(StoreClient):
    def __init__(self):
        self.tables: Dict[str, Dict[str, Any]] = {}

    def put(self, table: str, key: str, value: Any) -> None:
        self.tables.setdefault(table, {})[key] = value

    def delete(self, table: str, key: str) -> None:
        self.tables.get(table, {}).pop(key, None)

    def load_all(self):
        for t, kv in self.tables.items():
            for k, v in kv.items():
                yield (t, k, v)


class FileStoreClient(StoreClient):
    """Snapshot + WAL on the local filesystem."""

    WAL_COMPACT_BYTES = 8 * 1024 * 1024

    def __init__(self, dir_path: str):
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        self.snap_path = os.path.join(dir_path, "snapshot.msgpack")
        self.wal_path = os.path.join(dir_path, "wal.msgpack")
        self._lock = threading.Lock()
        self._tables: Dict[str, Dict[str, Any]] = {}
        self._load_into_memory()
        self._wal = open(self.wal_path, "ab")

    # -- internal --------------------------------------------------------

    def _load_into_memory(self):
        for path in (self.snap_path, self.wal_path):
            if not os.path.exists(path):
                continue
            with open(path, "rb") as f:
                unpacker = msgpack.Unpacker(f, raw=False)
                try:
                    for rec in unpacker:
                        table, key, value = rec
                        if value is None:
                            self._tables.get(table, {}).pop(key, None)
                        else:
                            self._tables.setdefault(table, {})[key] = value
                # torn tail write after a crash: keep what replayed;
                # expected on every recovery, so nothing to report
                except Exception:  # rtpulint: ignore[RTPU007]
                    pass

    def _append(self, rec) -> None:
        # flush (not fsync) per record: the GCS runs _append inside async
        # handlers, and a per-mutation fsync would stall the whole control
        # plane. A GCS *process* crash loses nothing (page cache survives);
        # only a host power loss can drop the un-synced tail — the same
        # trade Redis makes with appendfsync everysec.
        data = msgpack.packb(rec, use_bin_type=True)
        self._wal.write(data)
        self._wal.flush()
        if self._wal.tell() > self.WAL_COMPACT_BYTES:
            self._compact()

    def _compact(self):
        tmp = self.snap_path + ".tmp"
        with open(tmp, "wb") as f:
            for t, kv in self._tables.items():
                for k, v in kv.items():
                    f.write(msgpack.packb((t, k, v), use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snap_path)
        self._wal.close()
        self._wal = open(self.wal_path, "wb")

    # -- StoreClient -----------------------------------------------------

    def put(self, table: str, key: str, value: Any) -> None:
        with self._lock:
            self._tables.setdefault(table, {})[key] = value
            self._append((table, key, value))

    def delete(self, table: str, key: str) -> None:
        with self._lock:
            if self._tables.get(table, {}).pop(key, None) is not None:
                self._append((table, key, None))

    def load_all(self):
        with self._lock:
            for t, kv in self._tables.items():
                for k, v in list(kv.items()):
                    yield (t, k, v)

    def close(self):
        with self._lock:
            try:
                self._wal.close()
            except Exception:
                pass


def make_store(path: Optional[str]) -> StoreClient:
    if path:
        return FileStoreClient(path)
    return InMemoryStoreClient()
