"""Raylet process entrypoint (reference: src/ray/raylet/main.cc)."""

import asyncio
import json
import logging
import os
import signal

from ray_tpu._private.node import restore_tpu_plugin_env
from ray_tpu._private.raylet import Raylet
from ray_tpu.common.config import SystemConfig

# this process skipped the TPU-plugin sitecustomize; worker children
# must still see the tunnel env (see node._defer_tpu_plugin)
restore_tpu_plugin_env()


async def main():
    logging.basicConfig(level=os.environ.get("RTPU_LOG_LEVEL", "INFO"))
    session_dir = os.environ["RTPU_SESSION_DIR"]
    profiler = None
    if os.environ.get("RTPU_CPROFILE_DIR") and \
            "raylet" in os.environ.get("RTPU_CPROFILE_PROCS", "raylet"):
        # perf-debug aid: RTPU_CPROFILE_DIR=/tmp/prof dumps a pstats
        # file per process at exit (the driver can't see inside the
        # raylet hot path any other way)
        import cProfile
        profiler = cProfile.Profile()
        profiler.enable()
        import atexit
        atexit.register(lambda: profiler.dump_stats(os.path.join(
            os.environ["RTPU_CPROFILE_DIR"],
            f"raylet_{os.getpid()}.pstats")))
    from ray_tpu.util import events
    events.init_emitter("raylet", session_dir)
    node_id = os.environ["RTPU_NODE_ID"]
    from ray_tpu._private import chaos
    chaos.init_from_env("raylet",
                        is_head=os.environ.get("RTPU_IS_HEAD") == "1")
    raylet = Raylet(
        config=SystemConfig().apply_env_overrides(),
        node_id=node_id,
        session_dir=session_dir,
        gcs_address=os.environ["RTPU_GCS_ADDRESS"],
        resources=json.loads(os.environ.get("RTPU_RESOURCES", "{}")),
        labels=json.loads(os.environ.get("RTPU_LABELS", "{}")),
        is_head=os.environ.get("RTPU_IS_HEAD") == "1",
        object_store_memory=int(os.environ["RTPU_OBJECT_STORE_BYTES"])
        if os.environ.get("RTPU_OBJECT_STORE_BYTES") else None,
    )
    await raylet.start()
    info = {"unix_address": raylet.unix_address,
            "tcp_address": raylet.address,
            "store_path": raylet.store_path,
            "node_id": node_id}
    tmp = os.path.join(session_dir, f".raylet_{node_id[:8]}.tmp")
    with open(tmp, "w") as f:
        json.dump(info, f)
    os.replace(tmp, os.path.join(session_dir, f"raylet_{node_id[:8]}.json"))
    # Graceful shutdown on SIGTERM/SIGINT: kill workers and unlink the shm
    # segment — otherwise every session leaks its plasmax file into /dev/shm
    # (a fixed-size tmpfs) until the host runs dry.
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    # SIGUSR2 = preemption notice (how a TPU spot/maintenance notice
    # reaches the host agent): graceful drain inside the grace window
    # instead of vanishing — see raylet._preempt_drain.
    loop.add_signal_handler(signal.SIGUSR2, raylet.preempt_from_signal)
    eng = chaos.engine()
    if eng is not None:
        # chaos faults land in the GCS event ring so fault→detect→
        # recover latency is measurable from one stream
        from ray_tpu._private import protocol

        def _ship_chaos_event(ev):
            def _go():
                try:
                    if raylet.gcs is not None:
                        protocol.spawn(raylet.gcs.notify("add_event", ev))
                except Exception:
                    pass
            loop.call_soon_threadsafe(_go)

        eng.set_notifier(_ship_chaos_event)
    await stop.wait()
    raylet.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
