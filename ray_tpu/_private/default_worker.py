"""Worker process entrypoint (reference:
python/ray/_private/workers/default_worker.py → RunTaskExecutionLoop)."""

import faulthandler
import logging
import os
import signal


def main():
    logging.basicConfig(level=os.environ.get("RTPU_LOG_LEVEL", "WARNING"))
    # SIGUSR1 dumps all thread stacks to stderr (worker .err log) — the
    # hung-worker debugging hook (reference: ray SIGTERM stack traces).
    faulthandler.register(signal.SIGUSR1, all_threads=True)
    if os.environ.get("RTPU_CPROFILE_DIR") and \
            "worker" in os.environ.get("RTPU_CPROFILE_PROCS", "worker"):
        import atexit
        import cProfile
        profiler = cProfile.Profile()
        profiler.enable()
        atexit.register(lambda: profiler.dump_stats(os.path.join(
            os.environ["RTPU_CPROFILE_DIR"],
            f"worker_{os.getpid()}.pstats")))
    from ray_tpu._private import chaos
    eng = chaos.init_from_env("worker")
    from ray_tpu._private.worker import Worker, MODE_WORKER

    w = Worker()
    w.connect(
        mode=MODE_WORKER,
        gcs_address=os.environ["RTPU_GCS_ADDRESS"],
        raylet_address=os.environ["RTPU_RAYLET_ADDRESS"],
        store_path=os.environ["RTPU_STORE_PATH"],
        node_id=os.environ["RTPU_NODE_ID"],
        session_dir=os.environ["RTPU_SESSION_DIR"],
    )
    reply = w.call_sync(w.raylet, "worker_register", {
        "worker_id": os.environ["RTPU_WORKER_ID"],
        "address": w.address,
        # 1.7: the native direct-call lane's socket (empty when the
        # pump is disabled/unbuildable); the raylet forwards it in
        # lease_worker replies so owners can skip the asyncio path
        "direct_address": w.direct_address,
        # 1.8: the lane's host:port twin (netx) for off-box owners
        "direct_tcp_address": w.direct_tcp_address,
    })
    from ray_tpu.common.config import SystemConfig, set_global_config
    w.config = SystemConfig.from_json(reply["config"])
    set_global_config(w.config)
    if eng is not None:
        eng.set_notifier(
            lambda ev: w.io.run_async(w.gcs.notify("add_event", ev)))
    w.task_execution_loop()


if __name__ == "__main__":
    main()
