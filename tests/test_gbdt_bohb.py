"""GBDT trainers + BOHB (reference: train/gbdt_trainer.py,
tune/search/bohb + schedulers/hb_bohb.py)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _toy_classification(n=400, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 5))
    y = ((X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2) > 0).astype(np.int64)
    return X, y


def test_sklearn_gbdt_train_and_checkpoint(cluster):
    from ray_tpu import data as rt_data
    from ray_tpu.train import GBDTTrainer, SklearnGBDTTrainer

    X, y = _toy_classification()
    items = [{"f0": r[0], "f1": r[1], "f2": r[2], "f3": r[3],
              "f4": r[4], "label": int(t)} for r, t in zip(X, y)]
    train_ds = rt_data.from_items(items[:300], parallelism=2)
    val_ds = rt_data.from_items(items[300:], parallelism=2)

    trainer = SklearnGBDTTrainer(
        label_column="label",
        params={"max_depth": 3, "learning_rate": 0.2},
        num_boost_round=40,
        datasets={"train": train_ds, "valid": val_ds})
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["train-score"] > 0.9
    assert result.metrics["valid-score"] > 0.8
    model = GBDTTrainer.get_model(result.checkpoint)
    acc = float((model.predict(X[300:]) == y[300:]).mean())
    assert acc > 0.8


def test_xgboost_lightgbm_gated(cluster):
    """Without the libraries the trainers fail with the pip hint, not a
    crash elsewhere (reference behavior for missing integrations)."""
    from ray_tpu.train import LightGBMTrainer, XGBoostTrainer
    X, y = _toy_classification(50)
    for cls, lib in ((XGBoostTrainer, "xgboost"),
                     (LightGBMTrainer, "lightgbm")):
        try:
            __import__(lib)
            pytest.skip(f"{lib} installed; gate not applicable")
        except ImportError:
            pass
        t = cls(label_column="y", datasets={"train": {"X": X, "y": y}})
        result = t.fit()
        assert result.error is not None and lib in result.error


def test_bohb_models_largest_budget():
    from ray_tpu.tune.search.bohb import BOHBSearcher
    from ray_tpu.tune import sample as s

    space = {"x": s.uniform(0, 1)}
    se = BOHBSearcher(space, metric="score", mode="max", seed=0,
                      n_startup_trials=4)
    # low-budget results say x~0.2 is good; high-budget says x~0.8
    for t in range(8):
        tid = f"lo{t}"
        cfg = se.suggest(tid)
        se.on_trial_result(tid, {"score": -abs(cfg["x"] - 0.2),
                                 "training_iteration": 1})
        se.on_trial_complete(tid, {"score": -abs(cfg["x"] - 0.2),
                                   "training_iteration": 1})
    for t in range(8):
        tid = f"hi{t}"
        cfg = se.suggest(tid)
        se.on_trial_result(tid, {"score": -abs(cfg["x"] - 0.8),
                                 "training_iteration": 9})
        se.on_trial_complete(tid, {"score": -abs(cfg["x"] - 0.8),
                                   "training_iteration": 9})
    # the model must now follow the HIGH-budget objective
    xs = [se.suggest(f"probe{i}")["x"] for i in range(12)]
    near_high = sum(1 for x in xs if abs(x - 0.8) < 0.25)
    near_low = sum(1 for x in xs if abs(x - 0.2) < 0.15)
    assert near_high > near_low, (xs,)


def test_bohb_beats_random_on_synthetic_landscape():
    """BOHB (searcher + HyperBandForBOHB) vs pure random under the same
    trial budget on a multi-fidelity landscape: score converges toward
    the true objective as iterations grow."""
    import random as pyrandom
    from ray_tpu.tune.search.bohb import BOHBSearcher
    from ray_tpu.tune import sample as s

    def true_obj(x, y):
        return -(x - 0.65) ** 2 - (y - 0.3) ** 2

    def observed(cfg, it, rng):
        noise = rng.gauss(0, 0.5 / it)  # fidelity improves with budget
        return true_obj(cfg["x"], cfg["y"]) + noise

    space = {"x": s.uniform(0, 1), "y": s.uniform(0, 1)}

    def run_search(searcher, seed, n_trials=40, iters=9):
        rng = pyrandom.Random(seed)
        best = -1e9
        for t in range(n_trials):
            tid = f"t{t}"
            cfg = searcher.suggest(tid) if searcher else \
                {"x": rng.random(), "y": rng.random()}
            score = None
            for it in (1, 3, iters):  # the hyperband rungs
                score = observed(cfg, it, rng)
                if searcher:
                    searcher.on_trial_result(
                        tid, {"score": score, "training_iteration": it})
            if searcher:
                searcher.on_trial_complete(
                    tid, {"score": score, "training_iteration": iters})
            best = max(best, true_obj(cfg["x"], cfg["y"]))
        return best

    bohb_wins = 0
    for seed in (0, 1, 2):
        b = run_search(BOHBSearcher(space, metric="score", mode="max",
                                    seed=seed, n_startup_trials=8),
                       seed)
        r = run_search(None, seed)
        if b >= r - 1e-9:
            bohb_wins += 1
    assert bohb_wins >= 2, f"BOHB won only {bohb_wins}/3 seeds"


def test_bohb_through_tune_run(cluster):
    from ray_tpu import tune
    from ray_tpu.air import session
    from ray_tpu.tune import sample as s
    from ray_tpu.tune.schedulers import HyperBandForBOHB
    from ray_tpu.tune.search.bohb import BOHBSearcher

    def train_fn(config):
        for it in range(9):
            session.report(
                {"score": -(config["x"] - 2.0) ** 2 - 0.5 / (it + 1)})

    analysis = tune.run(
        train_fn, config={"x": s.uniform(-10, 10)},
        search_alg=BOHBSearcher(num_samples=16, seed=0,
                                n_startup_trials=6),
        scheduler=HyperBandForBOHB(max_t=9, reduction_factor=3),
        metric="score", mode="max", max_concurrent_trials=4)
    assert len(analysis.trials) == 16
    assert analysis.best_result["score"] > -5.0
