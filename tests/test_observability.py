"""Structured events, log browsing, dashboard endpoints, cluster gauges.

Reference analogues: event framework tests, dashboard modules tests
(`ray list cluster-events`, `ray logs`).
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.experimental.state import api as state


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_node_added_event(cluster):
    events = state.list_cluster_events()
    labels = [e.get("label") for e in events]
    assert "NODE_ADDED" in labels
    ev = next(e for e in events if e.get("label") == "NODE_ADDED")
    assert ev["severity"] == "INFO"
    assert ev["source"] == "gcs"
    assert ev["fields"]["resources"].get("CPU") == 4


def test_worker_death_event(cluster):
    import os
    import signal

    @ray_tpu.remote
    def suicide():
        os.kill(os.getpid(), signal.SIGKILL)

    with pytest.raises(Exception):
        ray_tpu.get(suicide.options(max_retries=0).remote(), timeout=60)
    deadline = time.time() + 30
    while time.time() < deadline:
        labels = [e.get("label") for e in state.list_cluster_events()]
        if "WORKER_DIED" in labels:
            break
        time.sleep(0.5)
    assert "WORKER_DIED" in labels
    # severity filter works
    errors = state.list_cluster_events(severity="ERROR")
    assert all(e["severity"] == "ERROR" for e in errors)
    assert any(e["label"] == "WORKER_DIED" for e in errors)


def test_actor_dead_event(cluster):
    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.remote()
    ray_tpu.get(a.ping.remote(), timeout=30)
    ray_tpu.kill(a)
    deadline = time.time() + 30
    found = False
    while time.time() < deadline and not found:
        found = any(e.get("label") == "ACTOR_DEAD"
                    for e in state.list_cluster_events())
        time.sleep(0.5)
    assert found


def test_list_and_get_logs(cluster):
    logs = state.list_logs()
    assert any(name.startswith("gcs") for name in logs)
    assert any("events" in name for name in logs)
    gcs_log = next(n for n in logs if n.startswith("gcs"))
    content = state.get_log(gcs_log)
    assert "GCS listening" in content
    with pytest.raises(ValueError, match="escapes"):
        state.get_log("../../etc/passwd")


def test_dashboard_events_logs_metrics(cluster):
    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18265)

    def get(path):
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30).read()

    events = json.loads(get("/api/events"))["events"]
    assert any(e["label"] == "NODE_ADDED" for e in events)
    logs = json.loads(get("/api/logs"))["logs"]
    assert logs
    text = get(f"/api/logs/{logs[0]}").decode()
    assert isinstance(text, str)
    pgs = json.loads(get("/api/placement_groups"))
    assert "placement_groups" in pgs
    metrics = get("/metrics").decode()
    assert "ray_tpu_cluster_nodes_alive 1.0" in metrics
    assert 'ray_tpu_cluster_resource_total{resource="CPU"} 4.0' in metrics


def test_dashboard_frontend_page(cluster):
    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18265)  # reuses the module's instance
    html = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=30).read().decode()
    assert "<!doctype html>" in html
    assert "/api/cluster_status" in html
    assert "ray_tpu" in html
