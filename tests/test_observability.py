"""Structured events, log browsing, dashboard endpoints, cluster gauges.

Reference analogues: event framework tests, dashboard modules tests
(`ray list cluster-events`, `ray logs`).
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.experimental.state import api as state


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_node_added_event(cluster):
    events = state.list_cluster_events()
    labels = [e.get("label") for e in events]
    assert "NODE_ADDED" in labels
    ev = next(e for e in events if e.get("label") == "NODE_ADDED")
    assert ev["severity"] == "INFO"
    assert ev["source"] == "gcs"
    assert ev["fields"]["resources"].get("CPU") == 4


def test_worker_death_event(cluster):
    import os
    import signal

    @ray_tpu.remote
    def suicide():
        os.kill(os.getpid(), signal.SIGKILL)

    with pytest.raises(Exception):
        ray_tpu.get(suicide.options(max_retries=0).remote(), timeout=60)
    deadline = time.time() + 30
    while time.time() < deadline:
        labels = [e.get("label") for e in state.list_cluster_events()]
        if "WORKER_DIED" in labels:
            break
        time.sleep(0.5)
    assert "WORKER_DIED" in labels
    # severity filter works
    errors = state.list_cluster_events(severity="ERROR")
    assert all(e["severity"] == "ERROR" for e in errors)
    assert any(e["label"] == "WORKER_DIED" for e in errors)


def test_actor_dead_event(cluster):
    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.remote()
    ray_tpu.get(a.ping.remote(), timeout=30)
    ray_tpu.kill(a)
    deadline = time.time() + 30
    found = False
    while time.time() < deadline and not found:
        found = any(e.get("label") == "ACTOR_DEAD"
                    for e in state.list_cluster_events())
        time.sleep(0.5)
    assert found


def test_list_and_get_logs(cluster):
    logs = state.list_logs()
    assert any(name.startswith("gcs") for name in logs)
    assert any("events" in name for name in logs)
    gcs_log = next(n for n in logs if n.startswith("gcs"))
    content = state.get_log(gcs_log)
    assert "GCS listening" in content
    with pytest.raises(ValueError, match="escapes"):
        state.get_log("../../etc/passwd")


def test_dashboard_events_logs_metrics(cluster):
    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18265)

    def get(path):
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30).read()

    events = json.loads(get("/api/events"))["events"]
    assert any(e["label"] == "NODE_ADDED" for e in events)
    logs = json.loads(get("/api/logs"))["logs"]
    assert logs
    text = get(f"/api/logs/{logs[0]}").decode()
    assert isinstance(text, str)
    pgs = json.loads(get("/api/placement_groups"))
    assert "placement_groups" in pgs
    metrics = get("/metrics").decode()
    assert "ray_tpu_cluster_nodes_alive 1.0" in metrics
    assert 'ray_tpu_cluster_resource_total{resource="CPU"} 4.0' in metrics


def test_grafana_dashboards_generated(tmp_path):
    """Generated boards are valid Grafana JSON wired to the exported
    metric names (reference: grafana_dashboard_factory.py)."""
    import json
    import re

    from ray_tpu.dashboard.grafana import (generate_dashboards,
                                           write_dashboards)
    boards = generate_dashboards()
    assert {"ray_tpu_core", "ray_tpu_scheduler", "ray_tpu_object_store",
            "ray_tpu_nodes"} <= set(boards)
    metric_re = re.compile(r"ray_tpu_[a-z_]+")
    for doc in boards.values():
        assert doc["panels"], doc["title"]
        for p in doc["panels"]:
            assert p["targets"], p["title"]
            for t in p["targets"]:
                assert metric_re.search(t["expr"]), t["expr"]
        json.dumps(doc)  # serializable
    # every expr references a gauge family the /metrics endpoint exports
    exported_prefixes = (
        "ray_tpu_cluster_", "ray_tpu_node_")
    for doc in boards.values():
        for p in doc["panels"]:
            for t in p["targets"]:
                assert any(pref in t["expr"]
                           for pref in exported_prefixes), t["expr"]
    paths = write_dashboards(str(tmp_path))
    assert len(paths) == 4 and all(
        json.load(open(p)) for p in paths)


def test_dashboard_frontend_page(cluster):
    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18265)  # reuses the module's instance
    html = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=30).read().decode()
    assert "<!doctype html>" in html
    assert "/api/cluster_status" in html
    assert "ray_tpu" in html


def test_node_stats_agent(cluster):
    """Per-node agent snapshot (reference: dashboard/agent.py reporter
    + metric_defs.cc native gauges) carries physical + scheduler +
    object-store readings that move under load."""
    @ray_tpu.remote
    def burn(x):
        return bytes(2 * 1024 * 1024)  # forces plasma traffic

    refs = [burn.remote(i) for i in range(20)]
    ray_tpu.get(refs)
    state.node_stats()  # prime the cpu_percent delta sample
    time.sleep(0.5)     # the delta needs ticks between the two reads
    stats = state.node_stats()
    assert len(stats) == 1
    s = stats[0]
    assert s["physical"]["mem_total_bytes"] > 0
    assert s["physical"]["mem_available_bytes"] > 0
    assert "cpu_percent" in s["physical"]
    assert s["physical"]["disk_free_bytes"] > 0
    sched = s["scheduler"]
    assert sched["tasks_dispatched_total"] >= 20
    assert sched["workers_alive"] >= 1
    assert sched["resources_total"]["CPU"] == 4.0
    store = s["object_store"]
    assert store["capacity"] > 0
    assert store["num_created"] >= 20
    for key in ("used_bytes", "spilled_objects", "spill_count_total",
                "restored_bytes_total", "pull_inflight_bytes",
                "pushes_inflight", "pinned_objects"):
        assert key in store, key
    del refs


def test_node_stats_in_prometheus_and_api(cluster):
    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18265)

    def get(path):
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30).read()

    doc = json.loads(get("/api/nodes/stats"))
    assert doc["nodes"] and "scheduler" in doc["nodes"][0]
    metrics = get("/metrics").decode()
    for gauge in (
            "ray_tpu_node_mem_total_bytes",
            "ray_tpu_node_mem_available_bytes",
            "ray_tpu_node_disk_free_bytes",
            "ray_tpu_node_scheduler_tasks_pending",
            "ray_tpu_node_scheduler_tasks_running",
            "ray_tpu_node_scheduler_tasks_dispatched_total",
            "ray_tpu_node_scheduler_tasks_spilled_back_total",
            "ray_tpu_node_scheduler_workers_alive",
            "ray_tpu_node_scheduler_workers_idle",
            "ray_tpu_node_scheduler_actors_alive",
            "ray_tpu_node_resource_available",
            "ray_tpu_node_object_store_used_bytes",
            "ray_tpu_node_object_store_capacity",
            "ray_tpu_node_object_store_num_objects",
            "ray_tpu_node_object_store_num_created",
            "ray_tpu_node_object_store_num_evicted",
            "ray_tpu_node_object_store_spilled_objects",
            "ray_tpu_node_object_store_spill_count_total",
            "ray_tpu_node_object_store_pull_inflight_bytes",
            "ray_tpu_node_tpu_num_chips",
    ):
        assert gauge in metrics, gauge
    assert 'node="' in metrics
