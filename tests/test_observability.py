"""Structured events, log browsing, dashboard endpoints, cluster gauges,
and the state engine: task/object listing with cursor pagination +
server-side filters, bounded task-table memory, the task-event pipeline
(PENDING_SCHEDULING → ... → FINISHED/FAILED), timeline flush cursor.

Reference analogues: event framework tests, dashboard modules tests
(`ray list tasks/objects`, `ray list cluster-events`, `ray logs`).
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.experimental.state import api as state


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _list_tasks_until(predicate, timeout=20, **kw):
    """Poll list_tasks until ``predicate(result)`` (the pipeline is
    asynchronous: events batch-flush every ~0.5 s)."""
    deadline = time.time() + timeout
    while True:
        tasks = state.list_tasks(**kw)
        if predicate(tasks) or time.time() > deadline:
            return tasks
        time.sleep(0.3)


def test_node_added_event(cluster):
    events = state.list_cluster_events()
    labels = [e.get("label") for e in events]
    assert "NODE_ADDED" in labels
    ev = next(e for e in events if e.get("label") == "NODE_ADDED")
    assert ev["severity"] == "INFO"
    assert ev["source"] == "gcs"
    assert ev["fields"]["resources"].get("CPU") == 4


def test_worker_death_event(cluster):
    import os
    import signal

    @ray_tpu.remote
    def suicide():
        os.kill(os.getpid(), signal.SIGKILL)

    with pytest.raises(Exception):
        ray_tpu.get(suicide.options(max_retries=0).remote(), timeout=60)
    deadline = time.time() + 30
    while time.time() < deadline:
        labels = [e.get("label") for e in state.list_cluster_events()]
        if "WORKER_DIED" in labels:
            break
        time.sleep(0.5)
    assert "WORKER_DIED" in labels
    # severity filter works
    errors = state.list_cluster_events(severity="ERROR")
    assert all(e["severity"] == "ERROR" for e in errors)
    assert any(e["label"] == "WORKER_DIED" for e in errors)


def test_actor_dead_event(cluster):
    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.remote()
    ray_tpu.get(a.ping.remote(), timeout=30)
    ray_tpu.kill(a)
    deadline = time.time() + 30
    found = False
    while time.time() < deadline and not found:
        found = any(e.get("label") == "ACTOR_DEAD"
                    for e in state.list_cluster_events())
        time.sleep(0.5)
    assert found


def test_list_and_get_logs(cluster):
    logs = state.list_logs()
    assert any(name.startswith("gcs") for name in logs)
    assert any("events" in name for name in logs)
    gcs_log = next(n for n in logs if n.startswith("gcs"))
    content = state.get_log(gcs_log)
    assert "GCS listening" in content
    with pytest.raises(ValueError, match="escapes"):
        state.get_log("../../etc/passwd")


def test_dashboard_events_logs_metrics(cluster):
    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18265)

    def get(path):
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30).read()

    events = json.loads(get("/api/events"))["events"]
    assert any(e["label"] == "NODE_ADDED" for e in events)
    logs = json.loads(get("/api/logs"))["logs"]
    assert logs
    text = get(f"/api/logs/{logs[0]}").decode()
    assert isinstance(text, str)
    pgs = json.loads(get("/api/placement_groups"))
    assert "placement_groups" in pgs
    metrics = get("/metrics").decode()
    assert "ray_tpu_cluster_nodes_alive 1.0" in metrics
    assert 'ray_tpu_cluster_resource_total{resource="CPU"} 4.0' in metrics


def test_grafana_dashboards_generated(tmp_path):
    """Generated boards are valid Grafana JSON wired to the exported
    metric names (reference: grafana_dashboard_factory.py)."""
    import json
    import re

    from ray_tpu.dashboard.grafana import (generate_dashboards,
                                           write_dashboards)
    boards = generate_dashboards()
    assert {"ray_tpu_core", "ray_tpu_scheduler", "ray_tpu_object_store",
            "ray_tpu_nodes"} <= set(boards)
    metric_re = re.compile(r"ray_tpu_[a-z_]+")
    for doc in boards.values():
        assert doc["panels"], doc["title"]
        for p in doc["panels"]:
            assert p["targets"], p["title"]
            for t in p["targets"]:
                assert metric_re.search(t["expr"]), t["expr"]
        json.dumps(doc)  # serializable
    # every expr references a gauge family the /metrics endpoint exports
    exported_prefixes = (
        "ray_tpu_cluster_", "ray_tpu_node_")
    for doc in boards.values():
        for p in doc["panels"]:
            for t in p["targets"]:
                assert any(pref in t["expr"]
                           for pref in exported_prefixes), t["expr"]
    paths = write_dashboards(str(tmp_path))
    assert len(paths) == 4 and all(
        json.load(open(p)) for p in paths)


def test_dashboard_frontend_page(cluster):
    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18265)  # reuses the module's instance
    html = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=30).read().decode()
    assert "<!doctype html>" in html
    assert "/api/cluster_status" in html
    assert "ray_tpu" in html


def test_node_stats_agent(cluster):
    """Per-node agent snapshot (reference: dashboard/agent.py reporter
    + metric_defs.cc native gauges) carries physical + scheduler +
    object-store readings that move under load."""
    @ray_tpu.remote
    def burn(x):
        return bytes(2 * 1024 * 1024)  # forces plasma traffic

    refs = [burn.remote(i) for i in range(20)]
    ray_tpu.get(refs)
    state.node_stats()  # prime the cpu_percent delta sample
    time.sleep(0.5)     # the delta needs ticks between the two reads
    stats = state.node_stats()
    assert len(stats) == 1
    s = stats[0]
    assert s["physical"]["mem_total_bytes"] > 0
    assert s["physical"]["mem_available_bytes"] > 0
    assert "cpu_percent" in s["physical"]
    assert s["physical"]["disk_free_bytes"] > 0
    sched = s["scheduler"]
    assert sched["tasks_dispatched_total"] >= 20
    assert sched["workers_alive"] >= 1
    assert sched["resources_total"]["CPU"] == 4.0
    store = s["object_store"]
    assert store["capacity"] > 0
    assert store["num_created"] >= 20
    for key in ("used_bytes", "spilled_objects", "spill_count_total",
                "restored_bytes_total", "pull_inflight_bytes",
                "pushes_inflight", "pinned_objects"):
        assert key in store, key
    del refs


def test_list_tasks_lifecycle(cluster):
    """Tasks flow through the event pipeline into the GCS table with
    lifecycle state, node/pid attribution, duration, trace ids, and
    error detail for failures."""
    @ray_tpu.remote
    def obs_ok(i):
        return i + 1

    @ray_tpu.remote(max_retries=0)
    def obs_fail():
        raise RuntimeError("observed-boom")

    assert ray_tpu.get([obs_ok.remote(i) for i in range(6)],
                       timeout=60) == list(range(1, 7))
    with pytest.raises(Exception):
        ray_tpu.get(obs_fail.remote(), timeout=60)

    tasks = _list_tasks_until(
        lambda ts: sum(1 for t in ts if t.get("name") == "obs_ok"
                       and t["state"] == "FINISHED") >= 6
        and any(t.get("name") == "obs_fail" and t["state"] == "FAILED"
                for t in ts))
    done = [t for t in tasks if t.get("name") == "obs_ok"
            and t["state"] == "FINISHED"]
    assert len(done) >= 6
    rec = done[0]
    assert rec["node_id"] and rec["worker_pid"] > 0
    assert rec.get("duration_s") is not None
    assert rec.get("trace_ctx", {}).get("trace_id")
    failed = next(t for t in tasks if t.get("name") == "obs_fail")
    assert failed["state"] == "FAILED"
    assert "observed-boom" in (failed.get("error") or "")


def test_list_tasks_retry_attempt_visible(cluster):
    """A retried task's record carries the attempt number and ends
    FINISHED (the retry restarted the lifecycle)."""
    import tempfile
    marker = tempfile.mktemp(prefix="rtpu_obs_retry_")

    @ray_tpu.remote(max_retries=2, retry_exceptions=True)
    def flaky(path):
        import os as _os
        if not _os.path.exists(path):
            open(path, "w").close()
            raise ValueError("first attempt fails")
        return "ok"

    assert ray_tpu.get(flaky.remote(marker), timeout=60) == "ok"
    tasks = _list_tasks_until(
        lambda ts: any(t.get("name") == "flaky"
                       and t["state"] == "FINISHED"
                       and t.get("attempt", 0) >= 1 for t in ts),
        filters={"name": "flaky"})
    rec = next(t for t in tasks if t["state"] == "FINISHED")
    assert rec["attempt"] >= 1


def test_list_tasks_pagination_roundtrip(cluster):
    """Walk >=3 cursor pages; the union equals the full set with no
    duplicates (stable id-sorted cursor)."""
    @ray_tpu.remote
    def page_task(i):
        return i

    ray_tpu.get([page_task.remote(i) for i in range(9)], timeout=60)
    full = _list_tasks_until(
        lambda ts: sum(1 for t in ts
                       if t.get("name") == "page_task") >= 9)
    page_size = max(1, len(full) // 3)
    pages, token = [], None
    while True:
        page = state.list_tasks(page_size=page_size,
                                continuation_token=token)
        assert len(page) <= page_size
        pages.append(page)
        token = page.next_token
        if token is None:
            break
    assert len(pages) >= 3
    ids = [t["task_id"] for p in pages for t in p]
    assert len(ids) == len(set(ids)), "duplicate rows across pages"
    assert set(ids) == {t["task_id"] for t in full}


def test_list_tasks_filter_pushdown(cluster):
    """Filters evaluate server-side: the reply's total reflects the
    filtered count, and every row matches."""
    tasks = state.list_tasks(filters={"state": "FINISHED"})
    assert tasks and all(t["state"] == "FINISHED" for t in tasks)
    assert tasks.total == len(state.list_tasks(
        filters={"state": "FINISHED"}))
    by_name = _list_tasks_until(lambda ts: len(ts) >= 9,
                                filters={"name": "page_task"})
    assert len(by_name) >= 9
    assert all(t["name"] == "page_task" for t in by_name)
    none = state.list_tasks(filters={"name": "no-such-task"})
    assert list(none) == [] and none.total == 0


def test_task_table_bounded_memory_unit():
    """The GCS table never exceeds its cap: overflow evicts oldest
    TERMINAL records first and counts every eviction."""
    from ray_tpu._private.gcs import TaskEventTable
    t = TaskEventTable(cap=100)
    for i in range(250):
        t.apply({"task_id": f"t{i:04d}", "state": "PENDING_SCHEDULING",
                 "ts": float(i)})
        t.apply({"task_id": f"t{i:04d}", "state": "FINISHED",
                 "ts": float(i) + 0.5})
    assert len(t.records) == 100
    assert t.dropped == 150
    # the survivors are the NEWEST records (oldest-terminal evicted)
    assert "t0249" in t.records and "t0000" not in t.records
    s = t.summary()
    assert s["dropped"] == 150 and s["cap"] == 100
    assert s["by_state"]["FINISHED"] == 100
    # live (non-terminal) records out-survive older terminal ones
    t2 = TaskEventTable(cap=10)
    t2.apply({"task_id": "live", "state": "RUNNING", "ts": 0.0})
    for i in range(30):
        t2.apply({"task_id": f"d{i:03d}", "state": "FAILED",
                  "ts": float(i)})
    assert "live" in t2.records and len(t2.records) == 10


def test_task_table_cap_exceeded_drop_counter_exposed(cluster):
    """Shrinking the live table cap evicts immediately and the drop
    counter is visible through the listing API and the summary."""
    from ray_tpu._private import worker as wmod
    w = wmod._global_worker
    try:
        r = w.call_sync(w.gcs, "configure_state", {"task_table_max": 5})
        assert r["task_table_max"] == 5
        tasks = state.list_tasks()
        assert len(tasks) <= 5
        assert tasks.dropped > 0
        assert state.summarize_tasks()["dropped"] >= tasks.dropped
        assert state.summarize_cluster()["tasks"]["dropped"] >= \
            tasks.dropped
    finally:
        w.call_sync(w.gcs, "configure_state", {"task_table_max": 32768})


def test_list_objects_plasma_index(cluster):
    """Object listing aggregates per-raylet plasma indexes: a pinned
    primary shows up with its node, owner, and size."""
    import numpy as np
    blob = ray_tpu.put(np.zeros(1024 * 1024, dtype=np.uint8))
    deadline = time.time() + 15
    row = None
    while time.time() < deadline and row is None:
        for o in state.list_objects():
            if o["object_id"] == blob.hex():
                row = o
                break
        time.sleep(0.2)
    assert row is not None, "pinned primary never listed"
    assert row["pinned"] and row["size_bytes"] >= 1024 * 1024
    assert row["locations"] and row.get("owner")
    # filter pushdown on objects too
    mine = state.list_objects(filters={"object_id": blob.hex()})
    assert len(mine) == 1
    del blob


def test_paginated_actor_and_node_listing(cluster):
    """The pagination retrofit covers the pre-existing tables."""
    @ray_tpu.remote
    class PagedActor:
        def ping(self):
            return 1

    actors = [PagedActor.remote() for _ in range(4)]
    ray_tpu.get([a.ping.remote() for a in actors], timeout=60)
    page = state.list_actors(page_size=2)
    assert len(page) == 2 and page.next_token
    rest = state.list_actors(page_size=100,
                             continuation_token=page.next_token)
    ids = [a["actor_id"] for a in page + rest]
    assert len(ids) == len(set(ids)) == len(state.list_actors())
    alive = state.list_actors(filters={"state": "ALIVE"})
    assert all(a["state"] == "ALIVE" for a in alive)
    nodes = state.list_nodes(filters={"alive": True})
    assert len(nodes) == 1
    for a in actors:
        ray_tpu.kill(a)


def test_summarize_cluster_single_rpc(cluster):
    """summarize_cluster is one GCS rpc now: counts + resource totals
    + the task-table summary, no full-table fetches client-side."""
    s = state.summarize_cluster()
    assert s["nodes_alive"] >= 1 and s["nodes_total"] >= 1
    assert "actors_by_state" in s and "jobs_total" in s
    assert s["cluster_resources"].get("CPU") == 4
    t = s["tasks"]
    assert t["total"] >= 1 and "by_state" in t and "dropped" in t


def test_timeline_flush_only_advances_cursor_on_success(cluster):
    """Satellite regression: a failed kv_put must NOT advance
    _last_pushed_total — the events retry on the next flush instead of
    silently vanishing."""
    from ray_tpu._private import worker as wmod
    from ray_tpu.util import timeline
    w = wmod._global_worker
    orig = w.call_sync
    fails = {"n": 0}

    def failing(conn, method, payload, timeout=None):
        if method == "kv_put" and \
                str(payload.get("key", "")).startswith("@timeline/"):
            fails["n"] += 1
            raise RuntimeError("injected kv_put failure")
        return orig(conn, method, payload, timeout=timeout)

    w.call_sync = failing
    try:
        timeline.record("flush-probe", "X", ts=time.time() * 1e6,
                        dur=5.0, pid=os.getpid())
        with timeline._lock:
            cursor_before = timeline._last_pushed_total
            assert timeline._total_recorded > cursor_before
        timeline.flush()
        assert fails["n"] >= 1
        with timeline._lock:
            assert timeline._last_pushed_total == cursor_before, \
                "cursor advanced past a FAILED push"
    finally:
        w.call_sync = orig
    timeline.flush()  # now succeeds and advances
    with timeline._lock:
        assert timeline._last_pushed_total == timeline._total_recorded
    assert any(e.get("name") == "flush-probe"
               for e in timeline.timeline_dump())


def test_metrics_preaggregated_flush(cluster):
    """Satellite: a hot loop recording a Counter folds into the local
    buffer (one batch per flush tick), not one actor call per point —
    and the totals still converge exactly."""
    from ray_tpu.util import metrics
    assert os.environ.get("RTPU_METRICS_SYNC") != "1"
    c = metrics.Counter("preagg_total", tag_keys=("k",))
    for _ in range(5000):
        c.inc(1.0, tags={"k": "hot"})
    with metrics._pending_lock:
        buffered = sum(e["value"] for e in metrics._pending.values()
                       if e["name"] == "preagg_total")
    assert buffered > 0, "hot-loop points must buffer locally"
    h = metrics.Histogram("preagg_lat", boundaries=[0.1, 1.0])
    for v in (0.05, 0.5, 5.0, 0.6):
        h.observe(v)
    deadline = time.time() + 15
    while time.time() < deadline:
        dump = {m["name"]: m for m in metrics.dump_metrics()}
        if dump.get("preagg_total", {}).get("value") == 5000.0 and \
                dump.get("preagg_lat", {}).get("count") == 4:
            break
        time.sleep(0.2)
    assert dump["preagg_total"]["value"] == 5000.0
    assert dump["preagg_lat"]["count"] == 4
    assert dump["preagg_lat"]["buckets"] == [1, 2, 1]


def test_dashboard_state_routes(cluster):
    """/api/tasks (paged + filtered), /api/objects, /api/summary/tasks,
    /api/timeline, /api/serve/metrics, and the task/serve gauges on
    /metrics."""
    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18265)

    # self-sufficient workload (earlier tests shrink/restore the table)
    @ray_tpu.remote
    def dash_task(i):
        return i

    ray_tpu.get([dash_task.remote(i) for i in range(8)], timeout=60)
    _list_tasks_until(
        lambda ts: sum(1 for t in ts if t.get("name") == "dash_task"
                       and t["state"] == "FINISHED") >= 8)

    def get(path):
        return json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30).read())

    doc = get("/api/tasks?limit=3")
    assert len(doc["tasks"]) == 3 and doc["next_token"]
    assert doc["total"] >= 3 and "dropped" in doc
    fin = get("/api/tasks?state=FINISHED&limit=5")
    assert fin["tasks"] and all(t["state"] == "FINISHED"
                                for t in fin["tasks"])
    page2 = get(f"/api/tasks?limit=3&token={doc['next_token']}")
    ids1 = {t["task_id"] for t in doc["tasks"]}
    ids2 = {t["task_id"] for t in page2["tasks"]}
    assert not ids1 & ids2
    assert "objects" in get("/api/objects")
    summ = get("/api/summary/tasks")
    assert summ["summary"] and "by_state" in summ
    tl = get("/api/timeline")["events"]
    assert any(e.get("ph") == "X" for e in tl)
    assert get("/api/serve/metrics") == {"deployments": {}}
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=30).read().decode()
    assert 'ray_tpu_cluster_tasks{state="FINISHED"}' in text
    assert "ray_tpu_cluster_task_table_dropped" in text
    html = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=30).read().decode()
    for marker in ("/api/tasks", "/api/serve/metrics", "Task timeline",
                   "loadTimeline"):
        assert marker in html


def test_dashboard_profile_flamegraph_endpoint(cluster):
    """The timed-sampling flamegraph endpoint (VERDICT: shipped
    untested): folded-stack output in the collapsed format
    flamegraph.pl / speedscope import — 'frame;frame;frame count'."""
    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18265)

    # keep a worker busy so the sampler has a stack to fold
    @ray_tpu.remote
    def spin(sec):
        t0 = time.time()
        n = 0
        while time.time() - t0 < sec:
            n += 1
        return n

    ref = spin.remote(3.0)
    doc = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/api/profile/flamegraph?duration_s=1.0",
        timeout=60).read())
    ray_tpu.get(ref, timeout=60)
    workers = [w for n in doc.get("nodes", [])
               for w in n.get("workers", []) if not w.get("error")]
    assert workers, doc
    profiled = [w for w in workers if w.get("folded")]
    assert profiled, workers
    for w in profiled:
        assert w.get("samples", 0) >= 1
        line = w["folded"].strip().splitlines()[0]
        stack, _, count = line.rpartition(" ")
        assert stack and count.isdigit(), line
    # the spinning worker shows a multi-frame folded stack
    assert any(";" in w["folded"] for w in profiled), profiled

    stacks = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/api/profile/stacks",
        timeout=60).read())
    assert stacks.get("nodes"), stacks


def test_dashboard_gameday_panel_and_slo_gauges(cluster):
    """The game-day surface: /api/gameday serves the last published
    report, /metrics exports the ray_tpu_slo_* gauges from it, and the
    frontend carries the panel."""
    from ray_tpu.dashboard.dashboard import start_dashboard
    from ray_tpu.gameday import store
    port = start_dashboard(port=18265)

    report = {
        "scenario": "unit", "seed": 7, "duration_s": 1.0,
        "phases": {"peak": {"total": 100, "admitted": 99, "shed": 1,
                            "failed": 0, "p50_ms": 4.0, "p99_ms": 20.0,
                            "p999_ms": 35.0, "max_ms": 40.0,
                            "mean_ms": 5.0}},
        "overall": {"total": 100, "admitted": 99, "shed": 1,
                    "failed": 0, "p50_ms": 4.0, "p99_ms": 20.0,
                    "p999_ms": 35.0, "max_ms": 40.0, "mean_ms": 5.0},
        "slo": {"availability_target": 0.999, "availability_burn": 0.0,
                "latency_target_ms": 250.0, "latency_burn": 0.2},
        "reconciliation": {"ok": True, "checks": []},
        "passed": True,
    }
    assert store.publish_report(report)

    doc = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/api/gameday", timeout=30).read())
    assert doc["report"]["scenario"] == "unit"
    assert doc["report"]["overall"]["admitted"] == 99

    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=30).read().decode()
    assert ('ray_tpu_slo_requests{scenario="unit",phase="peak",'
            'outcome="admitted"} 99.0') in text
    assert ('ray_tpu_slo_latency_p99_seconds{scenario="unit",'
            'phase="peak"} 0.02') in text
    assert ('ray_tpu_slo_error_budget_burn{scenario="unit",'
            'slo="latency"} 0.2') in text
    assert 'ray_tpu_slo_reconcile_ok{scenario="unit"} 1.0' in text
    assert 'ray_tpu_slo_passed{scenario="unit"} 1.0' in text

    html = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/", timeout=30).read().decode()
    for marker in ("Game day", "/api/gameday", "gd-tiles"):
        assert marker in html


_CHAOS_LISTING_SCRIPT = r"""
import json, time
import ray_tpu
from ray_tpu.experimental.state import api as state

ray_tpu.init(num_cpus=1, object_store_memory=128 * 1024 * 1024)

@ray_tpu.remote(max_retries=0)
def victim(i):
    return i

errors = 0
for i in range(4):
    try:
        # SPREAD routes through the raylet dispatch path (the lease
        # fast lane transparently resubmits on worker death, which
        # would mask the failure this test asserts on)
        ray_tpu.get(victim.options(
            scheduling_strategy="SPREAD").remote(i), timeout=120)
    except Exception:
        errors += 1
assert errors >= 1, "chaos kill never surfaced"
deadline = time.time() + 30
failed = []
while time.time() < deadline:
    failed = list(state.list_tasks(filters={"state": "FAILED",
                                            "name": "victim"}))
    if failed:
        break
    time.sleep(0.5)
assert failed, "FAILED task never listed"
rec = failed[0]
assert "WORKER_DIED" in (rec.get("error") or ""), rec
assert rec.get("node_id"), rec
print("CHAOS_LISTING_OK", json.dumps(rec.get("error")))
ray_tpu.shutdown()
"""


def test_chaos_killed_task_listed_failed_with_error(tmp_path):
    """Chaos-seeded run (worker SIGKILL at its 2nd execution, no
    retries): the killed task appears in list_tasks as FAILED with the
    WORKER_DIED error detail — reported by the raylet, since the dead
    worker can't report itself. Runs in a subprocess so the chaos env
    doesn't leak into the shared cluster."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               RTPU_CHAOS=json.dumps({"seed": 5, "schedule": [
                   {"site": "worker.execute", "op": "kill", "at": 2,
                    "proc": "worker"}]}))
    env.pop("RTPU_ADDRESS", None)
    r = subprocess.run([sys.executable, "-c", _CHAOS_LISTING_SCRIPT],
                       env=env, capture_output=True, text=True,
                       timeout=240)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "CHAOS_LISTING_OK" in r.stdout


def test_node_stats_in_prometheus_and_api(cluster):
    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18265)

    def get(path):
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30).read()

    doc = json.loads(get("/api/nodes/stats"))
    assert doc["nodes"] and "scheduler" in doc["nodes"][0]
    metrics = get("/metrics").decode()
    for gauge in (
            "ray_tpu_node_mem_total_bytes",
            "ray_tpu_node_mem_available_bytes",
            "ray_tpu_node_disk_free_bytes",
            "ray_tpu_node_scheduler_tasks_pending",
            "ray_tpu_node_scheduler_tasks_running",
            "ray_tpu_node_scheduler_tasks_dispatched_total",
            "ray_tpu_node_scheduler_tasks_spilled_back_total",
            "ray_tpu_node_scheduler_workers_alive",
            "ray_tpu_node_scheduler_workers_idle",
            "ray_tpu_node_scheduler_actors_alive",
            "ray_tpu_node_resource_available",
            "ray_tpu_node_object_store_used_bytes",
            "ray_tpu_node_object_store_capacity",
            "ray_tpu_node_object_store_num_objects",
            "ray_tpu_node_object_store_num_created",
            "ray_tpu_node_object_store_num_evicted",
            "ray_tpu_node_object_store_spilled_objects",
            "ray_tpu_node_object_store_spill_count_total",
            "ray_tpu_node_object_store_pull_inflight_bytes",
            "ray_tpu_node_tpu_num_chips",
    ):
        assert gauge in metrics, gauge
    assert 'node="' in metrics
