"""AIR preprocessors, BatchPredictor, TorchTrainer tests."""

import numpy as np
import pytest

import ray_tpu


def test_standard_scaler(ray_start_shared):
    import ray_tpu.data as rdata
    from ray_tpu.air import StandardScaler
    ds = rdata.from_items([{"a": float(i), "b": float(i * 2)}
                           for i in range(100)])
    sc = StandardScaler(columns=["a"]).fit(ds)
    out = sc.transform(ds)
    vals = np.concatenate([np.atleast_1d(b["a"])
                           for b in out.iter_batches()])
    assert abs(float(vals.mean())) < 1e-5
    assert abs(float(vals.std()) - 1.0) < 1e-2


def test_minmax_and_label_encoder(ray_start_shared):
    import ray_tpu.data as rdata
    from ray_tpu.air import Chain, LabelEncoder, MinMaxScaler
    ds = rdata.from_items([{"x": float(i), "y": ["cat", "dog"][i % 2]}
                           for i in range(10)])
    pre = Chain(MinMaxScaler(columns=["x"]),
                LabelEncoder(label_column="y"))
    out = pre.fit_transform(ds)
    rows = out.take_all()
    xs = [r["x"] for r in rows]
    assert min(xs) == 0.0 and max(xs) == 1.0
    assert set(r["y"] for r in rows) == {0, 1}


def test_batch_mapper(ray_start_shared):
    import ray_tpu.data as rdata
    from ray_tpu.air import BatchMapper
    ds = rdata.from_items([{"v": i} for i in range(10)])
    bm = BatchMapper(lambda b: {"v": np.asarray(b["v"]) * 10})
    out = bm.transform(ds)
    assert sorted(r["v"] for r in out.take_all()) == \
        [i * 10 for i in range(10)]


def test_imputer_encoders_scalers(ray_start_shared):
    import ray_tpu.data as rdata
    from ray_tpu.air import (MaxAbsScaler, OneHotEncoder, OrdinalEncoder,
                             RobustScaler, SimpleImputer)
    items = [{"a": float(i) if i % 3 else float("nan"),
              "b": float(i - 5),
              "c": ["x", "y", "z"][i % 3]} for i in range(30)]
    ds = rdata.from_items(items)

    imp = SimpleImputer(columns=["a"], strategy="mean").fit(ds)
    vals = np.concatenate([np.atleast_1d(b["a"]) for b in
                           imp.transform(ds).iter_batches()])
    assert not np.isnan(vals).any()

    enc = OrdinalEncoder(columns=["c"]).fit(ds)
    rows = enc.transform(ds).take_all()
    assert set(r["c"] for r in rows) == {0, 1, 2}

    oh = OneHotEncoder(columns=["c"]).fit(ds)
    rows = oh.transform(ds).take_all()
    assert "c" not in rows[0] and rows[0]["c_onehot"].shape == (3,)
    assert all(np.asarray(r["c_onehot"]).sum() == 1.0 for r in rows)

    cat_items = [{"c": None if i % 5 == 0 else ["a", "b"][i % 2]}
                 for i in range(20)]
    cat_ds = rdata.from_items(cat_items)
    cat_imp = SimpleImputer(columns=["c"],
                            strategy="most_frequent").fit(cat_ds)
    rows = cat_imp.transform(cat_ds).take_all()
    assert all(r["c"] in ("a", "b") for r in rows)  # strings imputed

    rs = RobustScaler(columns=["b"]).fit(ds)
    vals = np.concatenate([np.atleast_1d(b["b"]) for b in
                           rs.transform(ds).iter_batches()])
    assert abs(float(np.median(vals))) < 1e-6

    ma = MaxAbsScaler(columns=["b"]).fit(ds)
    vals = np.concatenate([np.atleast_1d(b["b"]) for b in
                           ma.transform(ds).iter_batches()])
    assert float(np.abs(vals).max()) == pytest.approx(1.0)


def test_normalizer_and_concatenator(ray_start_shared):
    import ray_tpu.data as rdata
    from ray_tpu.air import Chain, Concatenator, Normalizer
    ds = rdata.from_items([{"f1": 3.0 * (i + 1), "f2": 4.0 * (i + 1)}
                           for i in range(5)])
    pre = Chain(Normalizer(columns=["f1", "f2"]),
                Concatenator(columns=["f1", "f2"], output_column="x"))
    rows = pre.fit_transform(ds).take_all()
    for r in rows:
        assert "f1" not in r and r["x"].shape == (2,)
        assert float(np.linalg.norm(r["x"])) == pytest.approx(1.0,
                                                              abs=1e-5)


def test_fit_train_predict_e2e(ray_start_shared):
    """fit -> train -> checkpoint(with preprocessor) -> BatchPredictor
    over a Dataset actor pool — the full AIR loop the reference ships
    (reference: air/examples batch prediction + preprocessor docs)."""
    import ray_tpu.data as rdata
    from ray_tpu.air import (BatchPredictor, Chain, Checkpoint,
                             Concatenator, JaxPredictor, StandardScaler)

    rng = np.random.default_rng(0)
    raw = [{"f1": float(v), "f2": float(v) * 3.0 + 1.0}
           for v in rng.normal(5.0, 2.0, 64)]
    ds = rdata.from_items(raw)
    pre = Chain(StandardScaler(columns=["f1", "f2"]),
                Concatenator(columns=["f1", "f2"], output_column="x"))
    train_ds = pre.fit_transform(ds)

    # "train" a 1-layer model on the preprocessed features: x @ w
    xs = np.stack([r["x"] for r in train_ds.take_all()])
    w, *_ = np.linalg.lstsq(xs, xs[:, :1], rcond=None)
    ckpt = Checkpoint.from_dict({"params": {"w": w.astype(np.float32)}}
                                ).with_preprocessor(pre)
    assert ckpt.get_preprocessor() is not None

    def apply_fn(p, x):
        return x @ p["w"]

    bp = BatchPredictor.from_checkpoint(ckpt, JaxPredictor,
                                        apply_fn=apply_fn,
                                        input_column="x")
    # RAW features in; the checkpoint's preprocessor normalizes inside
    # the actor-pool workers
    out = bp.predict(ds, batch_size=16, num_workers=2)
    preds = [float(np.asarray(r["predictions"]).ravel()[0])
             for r in out.take_all()]
    assert len(preds) == 64 and all(np.isfinite(p) for p in preds)


def test_jax_batch_predictor(ray_start_shared):
    import ray_tpu.data as rdata
    from ray_tpu.air import BatchPredictor, Checkpoint, JaxPredictor

    params = {"w": np.array([[2.0]], np.float32)}

    def apply_fn(p, x):
        return x @ p["w"]

    ckpt = Checkpoint.from_dict({"params": params})
    bp = BatchPredictor.from_checkpoint(ckpt, JaxPredictor,
                                        apply_fn=apply_fn,
                                        input_column="x")
    ds = rdata.from_items([{"x": [float(i)]} for i in range(8)])
    out = bp.predict(ds, batch_size=4)
    preds = sorted(float(np.asarray(r["predictions"]).ravel()[0])
                   for r in out.take_all())
    assert preds == [2.0 * i for i in range(8)]


@pytest.mark.slow
def test_torch_trainer_ddp(ray_start_shared):
    """2-worker gloo DDP on CPU: grads all-reduce so both workers hold
    identical weights after a step."""
    from ray_tpu.air import ScalingConfig
    from ray_tpu.train import TorchTrainer, prepare_model, report

    def train_fn(config):
        import torch
        import torch.nn as nn
        torch.manual_seed(0)
        model = prepare_model(nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        import torch.distributed as dist
        rank = dist.get_rank() if dist.is_initialized() else 0
        torch.manual_seed(rank)  # different data per worker
        x = torch.randn(16, 4)
        y = x.sum(dim=1, keepdim=True)
        for _ in range(3):
            opt.zero_grad()
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
        w = [p.detach().numpy().copy()
             for p in model.parameters()]
        report({"loss": float(loss), "rank": rank,
                "w0": float(w[0].ravel()[0])})

    trainer = TorchTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.metrics["loss"] < 10.0
