"""AIR preprocessors, BatchPredictor, TorchTrainer tests."""

import numpy as np
import pytest

import ray_tpu


def test_standard_scaler(ray_start_shared):
    import ray_tpu.data as rdata
    from ray_tpu.air import StandardScaler
    ds = rdata.from_items([{"a": float(i), "b": float(i * 2)}
                           for i in range(100)])
    sc = StandardScaler(columns=["a"]).fit(ds)
    out = sc.transform(ds)
    vals = np.concatenate([np.atleast_1d(b["a"])
                           for b in out.iter_batches()])
    assert abs(float(vals.mean())) < 1e-5
    assert abs(float(vals.std()) - 1.0) < 1e-2


def test_minmax_and_label_encoder(ray_start_shared):
    import ray_tpu.data as rdata
    from ray_tpu.air import Chain, LabelEncoder, MinMaxScaler
    ds = rdata.from_items([{"x": float(i), "y": ["cat", "dog"][i % 2]}
                           for i in range(10)])
    pre = Chain(MinMaxScaler(columns=["x"]),
                LabelEncoder(label_column="y"))
    out = pre.fit_transform(ds)
    rows = out.take_all()
    xs = [r["x"] for r in rows]
    assert min(xs) == 0.0 and max(xs) == 1.0
    assert set(r["y"] for r in rows) == {0, 1}


def test_batch_mapper(ray_start_shared):
    import ray_tpu.data as rdata
    from ray_tpu.air import BatchMapper
    ds = rdata.from_items([{"v": i} for i in range(10)])
    bm = BatchMapper(lambda b: {"v": np.asarray(b["v"]) * 10})
    out = bm.transform(ds)
    assert sorted(r["v"] for r in out.take_all()) == \
        [i * 10 for i in range(10)]


def test_jax_batch_predictor(ray_start_shared):
    import ray_tpu.data as rdata
    from ray_tpu.air import BatchPredictor, Checkpoint, JaxPredictor

    params = {"w": np.array([[2.0]], np.float32)}

    def apply_fn(p, x):
        return x @ p["w"]

    ckpt = Checkpoint.from_dict({"params": params})
    bp = BatchPredictor.from_checkpoint(ckpt, JaxPredictor,
                                        apply_fn=apply_fn,
                                        input_column="x")
    ds = rdata.from_items([{"x": [float(i)]} for i in range(8)])
    out = bp.predict(ds, batch_size=4)
    preds = sorted(float(np.asarray(r["predictions"]).ravel()[0])
                   for r in out.take_all())
    assert preds == [2.0 * i for i in range(8)]


@pytest.mark.slow
def test_torch_trainer_ddp(ray_start_shared):
    """2-worker gloo DDP on CPU: grads all-reduce so both workers hold
    identical weights after a step."""
    from ray_tpu.air import ScalingConfig
    from ray_tpu.train import TorchTrainer, prepare_model, report

    def train_fn(config):
        import torch
        import torch.nn as nn
        torch.manual_seed(0)
        model = prepare_model(nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        import torch.distributed as dist
        rank = dist.get_rank() if dist.is_initialized() else 0
        torch.manual_seed(rank)  # different data per worker
        x = torch.randn(16, 4)
        y = x.sum(dim=1, keepdim=True)
        for _ in range(3):
            opt.zero_grad()
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
        w = [p.detach().numpy().copy()
             for p in model.parameters()]
        report({"loss": float(loss), "rank": rank,
                "w0": float(w[0].ravel()[0])})

    trainer = TorchTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.metrics["loss"] < 10.0
