"""GCS fault tolerance: kill + restart the GCS, cluster state survives.

Reference analogue: python/ray/tests/test_gcs_fault_tolerance.py over
gcs/store_client (redis_store_client.cc) + gcs_init_data.cc rebuild. Here
the store is the file-backed WAL under the session dir; raylets and drivers
reconnect via ReconnectingConnection and re-register.
"""

import time

import pytest

import ray_tpu
from ray_tpu._private import node as node_mod
from ray_tpu.util.placement_group import placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


def _restart_gcs():
    procs = ray_tpu._node_processes
    assert procs is not None
    port = int(procs.gcs_address.rsplit(":", 1)[1])
    procs.gcs_proc.kill()
    procs.gcs_proc.wait(timeout=10)
    time.sleep(0.2)
    procs.gcs_proc = node_mod.start_gcs(
        procs.session_dir, ray_tpu.global_config(), port=port)
    # wait until the new GCS answers
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            ray_tpu.nodes()
            return
        except Exception:
            time.sleep(0.2)
    raise TimeoutError("GCS did not come back")


def test_gcs_restart_preserves_actors_pgs_and_functions():
    ray_tpu.init(num_cpus=4, num_tpus=2, ignore_reinit_error=True,
                 object_store_memory=64 * 1024 * 1024)
    try:
        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        a = Counter.options(name="survivor", lifetime="detached").remote()
        assert ray_tpu.get(a.incr.remote(), timeout=60) == 1

        pg = placement_group([{"CPU": 1, "TPU": 2}])
        assert pg.ready(timeout=30)

        _restart_gcs()

        # node table rebuilt by re-registration
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            if alive:
                break
            time.sleep(0.2)
        assert alive, "raylet did not re-register after GCS restart"

        # detached actor survives: name lookup + live worker still serving
        b = ray_tpu.get_actor("survivor")
        assert ray_tpu.get(b.incr.remote(), timeout=60) == 2

        # placement group survives: bundles still usable for new work
        @ray_tpu.remote(num_cpus=0.5, num_tpus=2,
                        scheduling_strategy=PlacementGroupSchedulingStrategy(
                            pg, placement_group_bundle_index=0))
        def chips():
            return ray_tpu.get_tpu_ids()

        got = ray_tpu.get(chips.remote(), timeout=60)
        assert len(got) == 2

        # exported functions survive (KV is persisted): a brand-new remote
        # function defined *after* the restart also works
        @ray_tpu.remote
        def after(x):
            return x * 2

        assert ray_tpu.get(after.remote(21), timeout=60) == 42
    finally:
        ray_tpu.shutdown()
