"""Scale-envelope tests (reference: release/benchmarks/README.md:9-31 —
250+ nodes / 10k+ tasks / 1k+ PGs / 1 GiB broadcast; scaled to this
box's single core for CI, with the full envelope runnable via
RTPU_SCALE_FULL=1 — measured numbers live in SCALE.md).

What each test proves:
  - 50+ simulated raylets register, schedule, and execute work
    (cluster_utils multi-raylet sim, reference: cluster_utils.Cluster).
  - A 10k-task backlog drains through the per-class dispatch queues +
    class-drain spillback without starving or deadlocking.
  - Batched submission (`remote_batch` -> submit_task_batch RPC) clears
    >=10k tasks/s from one driver.
  - Hundreds of placement groups 2-phase-commit and tear down cleanly.
  - A ~1 GiB object broadcasts to many nodes through chunked pulls.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import cluster_utils

FULL = bool(os.environ.get("RTPU_SCALE_FULL"))

N_NODES = 100 if FULL else 20
# each simulated raylet advertises CPUS_PER_NODE logical CPUs: resource
# accounting is what the PG envelope exercises (committed bundles
# holding capacity), and the reference bar of 1k+ SIMULTANEOUSLY
# RUNNING placement groups needs >=1k CPUs of logical capacity — its
# own numbers come from 64x64-core hosts
CPUS_PER_NODE = 12 if FULL else 1
N_TASKS = 10_000 if FULL else 3_000
N_PGS = 1_200 if FULL else 120
BCAST_MB = 1024 if FULL else 128
BCAST_NODES = 20 if FULL else 8
SUBMIT_N = 30_000 if FULL else 20_000


@pytest.fixture(scope="module")
def scale_cluster():
    # stores are sparse mmaps — only written pages take RAM, so the
    # FULL broadcast (1 GiB on ~20 nodes) fits /dev/shm comfortably
    head_store = (2048 if FULL else 256) * 1024 * 1024
    node_store = (1536 if FULL else 192) * 1024 * 1024
    c = cluster_utils.Cluster(head_node_args={
        "num_cpus": 4, "object_store_memory": head_store})
    c.add_nodes(N_NODES, num_cpus=CPUS_PER_NODE,
                object_store_memory=node_store)
    c.connect()
    c.wait_for_nodes(timeout=180)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_nodes_register_and_execute(scale_cluster):
    alive = [n for n in ray_tpu.nodes() if n["alive"]]
    assert len(alive) == N_NODES + 1

    @ray_tpu.remote
    def whoami():
        return ray_tpu.get_runtime_context().get_node_id()

    # a SPREAD wave must actually land on many distinct raylets
    refs = [whoami.options(scheduling_strategy="SPREAD").remote()
            for _ in range(2 * (N_NODES + 1))]
    nodes_hit = set(ray_tpu.get(refs, timeout=300))
    assert len(nodes_hit) >= N_NODES * 0.8, \
        f"SPREAD hit only {len(nodes_hit)} of {N_NODES + 1} nodes"


def test_batched_submission_rate(scale_cluster):
    @ray_tpu.remote
    def noop(i):
        return i

    ray_tpu.get(noop.remote_batch([(i,) for i in range(100)]), timeout=120)
    args = [(i,) for i in range(SUBMIT_N)]
    t0 = time.perf_counter()
    refs = noop.remote_batch(args)
    dt = time.perf_counter() - t0
    rate = SUBMIT_N / dt
    print(f"\nbatched submission: {rate:.0f} tasks/s")
    # envelope bar (>=10k/s, measured 27.9k) asserted on dedicated FULL
    # runs; the in-suite bar is laxer because this 1-core box runs the
    # whole suite concurrently
    bar = 10_000 if FULL else 5_000
    assert rate >= bar, f"batched submission {rate:.0f} tasks/s < {bar}"
    out = ray_tpu.get(refs, timeout=600)
    assert out[-1] == SUBMIT_N - 1 and len(out) == SUBMIT_N


def test_10k_task_backlog_drains(scale_cluster):
    @ray_tpu.remote
    def bump(i):
        return i + 1

    t0 = time.perf_counter()
    refs = bump.remote_batch([(i,) for i in range(N_TASKS)])
    out = ray_tpu.get(refs, timeout=900)
    dt = time.perf_counter() - t0
    assert out == list(range(1, N_TASKS + 1))
    # record-keeping only; the bar is completion without deadlock
    print(f"\ndrained {N_TASKS} tasks in {dt:.1f}s "
          f"= {N_TASKS / dt:.0f} tasks/s end-to-end")

    # observability gate: the drained backlog must be LISTABLE —
    # cursor pages stay bounded (no full-table RPC), their union
    # covers every drained task, no duplicates, and the head's table
    # stayed within its cap (drop counter visible, not silent loss)
    from ray_tpu.experimental.state import api as state
    page_cap = 1000
    deadline = time.time() + 180
    seen = set()
    pages = 0
    while time.time() < deadline:
        seen.clear()
        pages = 0
        token = None
        dropped = 0
        while True:
            page = state.list_tasks(
                filters={"name": "bump", "state": "FINISHED"},
                page_size=page_cap, continuation_token=token)
            assert len(page) <= page_cap
            seen.update(t["task_id"] for t in page)
            pages += 1
            dropped = page.dropped
            token = page.next_token
            if token is None:
                break
        if len(seen) + dropped >= N_TASKS:
            break
        time.sleep(1.0)
    assert len(seen) + dropped >= N_TASKS, \
        f"listed {len(seen)} of {N_TASKS} drained tasks " \
        f"(+{dropped} evicted)"
    assert pages >= max(1, min(N_TASKS, len(seen)) // page_cap), \
        "listing did not actually paginate"
    summary = state.summarize_tasks()
    bump_row = next(a for a in summary["summary"]
                    if a["name"] == "bump")
    assert bump_row["by_state"].get("FINISHED", 0) + dropped >= N_TASKS


def test_many_placement_groups(scale_cluster):
    from ray_tpu.util.placement_group import (
        placement_group, remove_placement_group)
    created = []
    # total cluster CPUs; ready PGs plateau here (FULL: 1,204 -> the
    # reference's "1k+ simultaneously running placement groups" bar)
    capacity = N_NODES * CPUS_PER_NODE + 4
    ready = 0
    try:
        t0 = time.perf_counter()
        for i in range(N_PGS):
            pg = placement_group([{"CPU": 1}], strategy="PACK")
            created.append(pg)
        # the cluster can only host `capacity` CPU:1 bundles at once; the
        # bar is that creating N_PGS at full blast neither wedges the GCS
        # nor loses PGs: the ready count must reach the plateau and the
        # rest must sit PENDING (not errored)
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            ready = sum(1 for pg in created if pg.ready(timeout=0.01))
            if ready >= min(N_PGS, int(capacity * 0.9)):
                break
            time.sleep(0.5)
        t_create = time.perf_counter() - t0
        assert ready >= min(N_PGS, int(capacity * 0.9)), \
            f"only {ready}/{N_PGS} PGs ready (capacity {capacity})"
    finally:
        t0 = time.perf_counter()
        for pg in created:
            remove_placement_group(pg)
        t_remove = time.perf_counter() - t0
    print(f"\n{N_PGS} PGs: created ({ready} ready at CPU capacity "
          f"{capacity}) in {t_create:.1f}s, removed in {t_remove:.1f}s "
          f"({N_PGS / max(t_remove, 1e-9):.0f} removals/s)")

    # resources must come all the way back: a full-width SPREAD wave runs
    @ray_tpu.remote
    def ok():
        return 1

    assert sum(ray_tpu.get(
        [ok.options(scheduling_strategy="SPREAD").remote()
         for _ in range(N_NODES)], timeout=300)) == N_NODES


def test_gib_broadcast(scale_cluster):
    """One large object read by tasks pinned across the cluster
    (reference envelope: 1 GiB broadcast to 50+ nodes)."""
    mb = BCAST_MB
    blob = np.frombuffer(os.urandom(1024 * 1024), np.uint8)
    big = np.tile(blob, mb)  # mb MiB, incompressible
    ref = ray_tpu.put(big)

    @ray_tpu.remote
    def readback(x):
        return int(x[::1024 * 1024].sum()), len(x)

    t0 = time.perf_counter()
    refs = [readback.options(scheduling_strategy="SPREAD").remote(ref)
            for _ in range(BCAST_NODES)]
    results = ray_tpu.get(refs, timeout=900)
    dt = time.perf_counter() - t0
    want = (int(big[::1024 * 1024].sum()), len(big))
    assert all(r == list(want) or tuple(r) == want for r in results)
    print(f"\nbroadcast {mb} MiB x {BCAST_NODES} readers in {dt:.1f}s "
          f"({mb * BCAST_NODES / dt:.0f} MiB/s aggregate)")
