"""Examples stay importable (full runs live in the component suites)."""

import ast
import os

import pytest

EX = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


@pytest.mark.parametrize("fname", sorted(os.listdir(EX)))
def test_example_parses(fname):
    if not fname.endswith(".py"):
        pytest.skip("not python")
    with open(os.path.join(EX, fname)) as f:
        tree = ast.parse(f.read(), filename=fname)
    # every example is a main()-guarded script
    names = {n.name for n in ast.walk(tree)
             if isinstance(n, ast.FunctionDef)}
    assert "main" in names
