"""Per-stage DatasetStats + size-based block splitting (reference:
data/_internal/stats.py DatasetStats; block splitting on
target_max_block_size in the reference's map tasks)."""

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rdata


@pytest.fixture(autouse=True, params=["streaming", "bulk"])
def _executor_mode(request, monkeypatch):
    """Stats must hold under both executor modes in one invocation."""
    monkeypatch.setenv("RTPU_DATA_STREAMING",
                       "1" if request.param == "streaming" else "0")


def test_stats_report_wall_cpu_rows(ray_start_shared):
    ds = rdata.from_items([{"v": float(i)} for i in range(100)],
                          parallelism=4)
    out = ds.map_batches(lambda b: {"v": np.asarray(b["v"]) * 2}) \
            .filter(lambda r: r["v"] >= 10.0)
    out.take_all()
    rows = out._plan.stats.to_dict()
    map_rows = [r for r in rows if "map_batches" in r["stage"]]
    assert map_rows, rows
    st = map_rows[0]
    # per-task wall/cpu/rows aggregated across blocks
    assert st["tasks"] == 4
    assert st["rows_in"] == 100
    assert st["rows_out"] == 95  # filter fused into the same stage
    assert st["wall_s"] >= 0 and st["cpu_s"] >= 0
    assert st["workers"] >= 1
    s = out.stats()
    assert "rows" in s and "wall" in s and "cpu" in s


def test_stats_all_to_all_stage_recorded(ray_start_shared):
    ds = rdata.from_items([{"v": i} for i in range(50)], parallelism=5)
    out = ds.repartition(2)
    out.take_all()
    names = [r["stage"] for r in out._plan.stats.to_dict()]
    assert "repartition" in names


def test_repartition_by_size_splits_oversized_blocks(ray_start_shared):
    # 2 blocks x ~4 MB each; target 1 MB -> every output block under it
    ds = rdata.from_items(
        [{"x": np.zeros(512 * 1024, np.uint8)} for _ in range(16)],
        parallelism=2)
    out = ds.repartition_by_size(1024 * 1024)
    metas = out._meta()
    assert len(metas) > 2
    assert all(m.size_bytes <= 1100 * 1024 for m in metas)
    assert sum(m.num_rows for m in metas) == 16
    # rows survive intact
    rows = out.take_all()
    assert len(rows) == 16 and all(r["x"].nbytes == 512 * 1024
                                   for r in rows)


def test_repartition_by_size_keeps_small_blocks(ray_start_shared):
    ds = rdata.from_items([{"v": i} for i in range(10)], parallelism=2)
    out = ds.repartition_by_size(64 * 1024 * 1024)
    assert len(out._blocks()) == 2  # untouched
    assert sorted(r["v"] for r in out.take_all()) == list(range(10))
