"""Unit tests for the scheduling ledger — the C++ schedcore
(src/schedcore/schedcore.cc) and the pure-Python fallback, driven
through the same interface and asserted to behave identically
(reference analogue: cluster_task_manager_test.cc + the fixed-point
resource tests under src/ray/raylet/scheduling/)."""

import pytest

from ray_tpu._private.sched import (
    NativeLedger, PendingTask, PyLedger, _lib, make_ledger)


def _pt(demand, pg=None, spilled=False):
    spec = {"resources": demand, "task_id": "t"}
    if pg:
        spec["placement_group"] = pg
    if spilled:
        spec["spilled_from"] = "other"
    return PendingTask(spec, None)


LEDGERS = [PyLedger]
if _lib() is not None:
    LEDGERS.append(NativeLedger)


@pytest.fixture(params=LEDGERS, ids=lambda c: c.__name__)
def led(request):
    return request.param({"CPU": 4.0, "TPU": 4.0, "memory": 1e9},
                         [0, 1, 2, 3])


def test_native_lib_builds():
    # the C++ core must actually be present in this environment
    assert _lib() is not None
    assert make_ledger({"CPU": 1.0}, []).native


def test_acquire_release_roundtrip(led):
    pt = _pt({"CPU": 2.0, "TPU": 2})
    assert led.feasible(pt)
    chips = led.acquire(pt)
    assert chips == (0, 1)
    assert led.avail_get("CPU") == pytest.approx(2.0)
    assert led.avail_get("TPU") == pytest.approx(2.0)
    # second acquire takes the remaining chips
    pt2 = _pt({"CPU": 2.0, "TPU": 2})
    assert led.acquire(pt2) == (2, 3)
    assert not led.feasible(_pt({"CPU": 0.5}))  # CPU exhausted... no: 0 left
    led.release(pt, chips)
    assert led.avail_get("CPU") == pytest.approx(2.0)
    assert led.feasible(_pt({"TPU": 2}))
    led.release(pt2, (2, 3))
    assert led.avail_get("CPU") == pytest.approx(4.0)
    assert led.node_chips_count() == 4


def test_fractional_cpu_no_epsilon_drift(led):
    # 40 x 0.1 CPU must exactly exhaust 4.0 CPU (fixed-point in native)
    tasks = [_pt({"CPU": 0.1}) for _ in range(40)]
    for t in tasks:
        assert led.acquire(t) == ()
    assert not led.feasible(_pt({"CPU": 0.1}))
    for t in tasks:
        led.release(t, ())
    assert led.avail_get("CPU") == pytest.approx(4.0)


def test_queue_poll_dispatches_in_fifo(led):
    pts = [_pt({"CPU": 1.0}) for _ in range(6)]
    for p in pts:
        led.append(p)
    assert led.pending_count() == 6
    dispatches, blocked, more = led.poll()
    got = [p for p, _ in dispatches]
    assert got == pts[:4]              # capacity for 4 CPUs
    assert blocked and blocked[0] is pts[4]
    assert led.pending_count() == 2
    led.release(pts[0], ())
    dispatches, _, _ = led.poll()
    assert [p for p, _ in dispatches] == [pts[4]]


def test_poll_blocked_class_does_not_starve_other_class(led):
    big = _pt({"CPU": 64.0})
    small = _pt({"CPU": 1.0})
    led.append(big)
    led.append(small)
    dispatches, blocked, _ = led.poll()
    assert [p for p, _ in dispatches] == [small]
    assert blocked == [big]


def test_remove_and_requeue(led):
    a, b = _pt({"CPU": 1.0}), _pt({"CPU": 1.0})
    led.append(a)
    led.append(b)
    assert led.remove(a)
    assert not led.remove(a)
    head = led.pop_head(b.sched_class)
    assert head is b
    led.requeue_front(b)
    assert led.head(b.sched_class) is b
    assert led.pending_count() == 1
    assert led.pending_tasks() == [b]


def test_bundle_lifecycle(led):
    key = ("pg1", 0)
    assert led.prepare_bundle(key, {"CPU": 2.0, "TPU": 2})
    assert led.has_bundle(key)
    assert led.avail_get("CPU") == pytest.approx(2.0)
    assert led.node_chips_count() == 2
    # idempotent prepare
    assert led.prepare_bundle(key, {"CPU": 2.0, "TPU": 2})
    assert led.avail_get("CPU") == pytest.approx(2.0)
    assert led.commit_bundle(key)
    assert led.commit_bundle(key)  # idempotent
    # PG task draws from the bundle pool, not the node
    pt = _pt({"CPU": 1.0, "TPU": 1},
             pg={"pg_id": "pg1", "bundle_index": 0})
    chips = led.acquire(pt)
    assert chips == (0,)
    assert led.avail_get("CPU") == pytest.approx(2.0)  # node untouched
    # return while the task still runs: only the free chip rejoins
    assert led.return_bundle(key)
    assert led.node_chips_count() == 3
    assert led.avail_get("TPU") == pytest.approx(3.0)
    assert led.avail_get("CPU") == pytest.approx(4.0)  # non-TPU in full
    # late release: chip + TPU count come home, nothing else
    led.release(pt, chips)
    assert led.node_chips_count() == 4
    assert led.avail_get("TPU") == pytest.approx(4.0)
    assert led.avail_get("CPU") == pytest.approx(4.0)


def test_bundle_cancel_restores(led):
    key = ("pg2", 0)
    assert led.prepare_bundle(key, {"CPU": 1.0, "TPU": 4})
    assert led.cancel_bundle(key)
    assert not led.has_bundle(key)
    assert led.avail_get("CPU") == pytest.approx(4.0)
    assert led.node_chips_count() == 4
    assert not led.cancel_bundle(key)


def test_bundle_task_infeasible_until_commit(led):
    pt = _pt({"CPU": 1.0}, pg={"pg_id": "pg3", "bundle_index": 0})
    assert not led.feasible(pt)
    led.append(pt)
    _, blocked, _ = led.poll()
    assert blocked == [pt]
    assert led.prepare_bundle(("pg3", 0), {"CPU": 1.0})
    assert not led.feasible(pt)          # still only prepared
    assert led.commit_bundle(("pg3", 0))
    dispatches, _, _ = led.poll()
    assert [p for p, _ in dispatches] == [pt]


def test_release_after_bundle_gone_credits_node_chips_only(led):
    key = ("pg4", 0)
    led.prepare_bundle(key, {"CPU": 2.0, "TPU": 2})
    led.commit_bundle(key)
    pt = _pt({"CPU": 2.0, "TPU": 2}, pg={"pg_id": "pg4", "bundle_index": 0})
    chips = led.acquire(pt)
    assert chips == (0, 1)
    led.return_bundle(key)
    cpu_before = led.avail_get("CPU")
    led.release(pt, chips)
    # CPU unchanged (was credited at return); chips + TPU count restored
    assert led.avail_get("CPU") == pytest.approx(cpu_before)
    assert led.avail_get("TPU") == pytest.approx(4.0)
    assert led.node_chips_count() == 4


def test_spilled_tasks_get_own_class(led):
    plain = _pt({"CPU": 1.0})
    spilled = _pt({"CPU": 1.0}, spilled=True)
    assert plain.sched_class != spilled.sched_class


def test_snapshot_reports_totals_keys(led):
    snap = led.snapshot()
    assert snap["CPU"] == pytest.approx(4.0)
    assert snap["TPU"] == pytest.approx(4.0)
    assert snap["memory"] == pytest.approx(1e9)


def test_drain_bundle_pops_tasks_and_frees_classes(led):
    key = ("pgd", 0)
    led.prepare_bundle(key, {"CPU": 1.0})
    led.commit_bundle(key)
    pts = [_pt({"CPU": 1.0}, pg={"pg_id": "pgd", "bundle_index": 0})
           for _ in range(3)]
    for p in pts:
        led.append(p)
    led.return_bundle(key)
    drained = led.drain_bundle(key)
    assert set(id(p) for p in drained) == set(id(p) for p in pts)
    assert led.pending_count() == 0
    # the same (pg, bundle) key re-interns cleanly afterwards
    pt2 = _pt({"CPU": 1.0}, pg={"pg_id": "pgd", "bundle_index": 0})
    assert not led.feasible(pt2)  # no pool anymore
    led.append(pt2)
    _, blocked, _ = led.poll()
    assert blocked == [pt2]
    assert led.drain_bundle(("never", 9)) == []


def test_release_after_drain_credits_node(led):
    # a task still running when its bundle is returned AND drained:
    # release must land in the node pool (chips + TPU count only)
    key = ("pgr", 0)
    led.prepare_bundle(key, {"CPU": 1.0, "TPU": 1})
    led.commit_bundle(key)
    pt = _pt({"CPU": 1.0, "TPU": 1},
             pg={"pg_id": "pgr", "bundle_index": 0})
    chips = led.acquire(pt)
    assert chips == (0,)
    led.return_bundle(key)
    led.drain_bundle(key)
    led.release(pt, chips)
    assert led.node_chips_count() == 4
    assert led.avail_get("TPU") == pytest.approx(4.0)
    assert led.avail_get("CPU") == pytest.approx(4.0)


def test_blocked_reporting_rotates_over_many_classes():
    # >POLL_MAXBLOCKED blocked classes: every class must surface in the
    # blocked report within a bounded number of polls (spillback must
    # eventually see each stuck class)
    from ray_tpu._private import sched as sched_mod
    if _lib() is None:
        pytest.skip("native lib unavailable")
    led = NativeLedger({"CPU": 0.0}, [])
    n = sched_mod.POLL_MAXBLOCKED + 40
    pts = [_pt({"CPU": 1.0, f"u{i}": 0.0}) for i in range(n)]
    for p in pts:
        led.append(p)
    seen = set()
    for _ in range(6):
        _, blocked, _ = led.poll()
        seen.update(id(p) for p in blocked)
    assert len(seen) == n


def test_drain_pg_covers_unhosted_sibling_bundles(led):
    # task queued for bundle 1 of a PG whose bundle 0 lives here: PG
    # removal must doom it even though return_bundle((pg,1)) never
    # arrives on this node
    led.prepare_bundle(("pgs", 0), {"CPU": 1.0})
    led.commit_bundle(("pgs", 0))
    sibling = _pt({"CPU": 1.0}, pg={"pg_id": "pgs", "bundle_index": 1})
    led.append(sibling)
    led.return_bundle(("pgs", 0))
    drained = led.drain_pg("pgs")
    assert sibling in drained
    assert led.pending_count() == 0


def test_tiny_fractional_demand_blocks_when_resource_absent(led):
    # sub-granularity demands must not round to "free" (native ledger
    # rounds demands UP at 1/10000 fixed-point)
    pt = _pt({"CPU": 1.0, "nonexistent": 4e-05})
    assert not led.feasible(pt)
    assert led.acquire(pt) is None


def test_class_interning_bounded_under_demand_churn():
    if _lib() is None:
        pytest.skip("native lib unavailable")
    led = NativeLedger({"CPU": 64.0}, [])
    for i in range(3000):
        p = _pt({"CPU": 0.0001 * (i + 1)})
        led.append(p)
        dispatches, _, _ = led.poll()
        for q, chips in dispatches:
            led.release(q, chips)
    # far fewer live interning entries than distinct demands seen
    assert len(led._cls_ids) <= 2 * led._GC_THRESHOLD
    assert led.avail_get("CPU") == pytest.approx(64.0)


def test_sibling_bundle_return_after_drain_pg_restores_node(led):
    # two committed bundles of one PG on this node; PG removal sends
    # return_bundle per bundle, and the FIRST one's handler runs a
    # pg-wide drain — the second bundle's return must still find its
    # pool and restore the node in full (the drain-orphaned-pool leak)
    led.prepare_bundle(("pg2b", 0), {"CPU": 1.0, "TPU": 2})
    led.commit_bundle(("pg2b", 0))
    led.prepare_bundle(("pg2b", 1), {"CPU": 1.0, "TPU": 2})
    led.commit_bundle(("pg2b", 1))
    led.return_bundle(("pg2b", 0))
    led.drain_pg("pg2b")
    assert led.return_bundle(("pg2b", 1))
    led.drain_pg("pg2b")
    assert led.avail_get("CPU") == pytest.approx(4.0)
    assert led.avail_get("TPU") == pytest.approx(4.0)
    assert led.node_chips_count() == 4


def test_one_third_cpu_packs_three_per_core(led):
    # fixed-point rounding must keep float-ledger parity for
    # non-representable fractions: 3 x 1/3 fit on 1.0 CPU
    third = 1.0 / 3.0
    taken = []
    for _ in range(12):
        p = _pt({"CPU": third})
        if led.acquire(p) is not None:
            taken.append(p)
    assert len(taken) == 12  # 4 CPUs x 3 per core
    assert led.acquire(_pt({"CPU": third})) is None
    for p in taken:
        led.release(p, ())
    assert led.avail_get("CPU") == pytest.approx(4.0, abs=1e-3)


def test_oversize_tpu_demand_reports_blocked_not_spin():
    from ray_tpu._private import sched as sched_mod
    if _lib() is None:
        pytest.skip("native lib unavailable")
    led = NativeLedger({"CPU": 1.0, "TPU": 8000.0},
                       list(range(8000)))
    pt = _pt({"TPU": 6000})  # exceeds POLL_MAXCHIPS
    led.append(pt)
    for _ in range(3):
        dispatches, blocked, more = led.poll()
        assert not dispatches
        assert blocked == [pt]   # visible to spillback policy
        assert not more          # must not busy-spin the loop


def test_poll_many_classes_many_tasks(led):
    # drain 300 tasks across 3 classes through repeated poll/release
    all_pts = []
    for i in range(100):
        for d in ({"CPU": 1.0}, {"CPU": 0.5}, {"CPU": 2.0}):
            p = _pt(dict(d))
            all_pts.append(p)
            led.append(p)
    done = []
    for _ in range(1000):
        dispatches, blocked, more = led.poll()
        if not dispatches and not more:
            if led.pending_count() == 0:
                break
        for p, chips in dispatches:
            done.append(p)
            led.release(p, chips)
    assert len(done) == 300
    assert led.avail_get("CPU") == pytest.approx(4.0)
