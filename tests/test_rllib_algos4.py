"""Fourth-wave RLlib algorithms: SimpleQ, A3C, DDPPO, Ape-X DDPG,
CQL, CRR, ES, ARS, LinUCB/LinTS bandits.

Reference analogues: rllib/algorithms/{simple_q,a3c,ddppo,apex_ddpg,
cql,crr,es,ars,bandit}/tests/.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def pendulum_dataset(tmp_path_factory):
    """Offline Pendulum data from a noisy PD controller (mean return
    ≈ -950 vs random ≈ -1270) — good enough for CQL/CRR to beat
    random by imitating-and-improving."""
    from ray_tpu.rllib.env import PendulumEnv
    from ray_tpu.rllib.offline import JsonWriter
    from ray_tpu.rllib.sample_batch import SampleBatch
    d = str(tmp_path_factory.mktemp("pendulum_offline"))
    rng = np.random.default_rng(0)
    env = PendulumEnv({"seed": 0})
    obs_l, act_l, rew_l, done_l, nobs_l = [], [], [], [], []
    for ep in range(30):
        obs, _ = env.reset(seed=ep)
        for _ in range(200):
            cos_th, sin_th, thdot = obs
            th = np.arctan2(sin_th, cos_th)
            a = np.clip(-8.0 * th - 2.0 * thdot
                        + rng.normal(0, 0.4), -2, 2)
            nobs, r, term, trunc, _ = env.step(
                np.array([a], np.float32))
            obs_l.append(obs); act_l.append([a]); rew_l.append(r)
            done_l.append(term or trunc); nobs_l.append(nobs)
            obs = nobs
            if term or trunc:
                break
    from ray_tpu.rllib.sample_batch import SampleBatch as SB
    w = JsonWriter(d)
    w.write(SB({
        SB.OBS: np.asarray(obs_l, np.float32),
        SB.ACTIONS: np.asarray(act_l, np.float32),
        SB.REWARDS: np.asarray(rew_l, np.float32),
        SB.DONES: np.asarray(done_l, bool),
        SB.NEXT_OBS: np.asarray(nobs_l, np.float32),
    }))
    w.close()
    return d


def test_simple_q_has_no_extras_and_learns_smoke():
    from ray_tpu.rllib.algorithms.simple_q import SimpleQConfig
    cfg = SimpleQConfig()
    assert cfg["double_q"] is False and not cfg["prioritized_replay"]
    algo = (SimpleQConfig().environment("CartPole-v1")
            .rollouts(rollout_fragment_length=32)
            .training(train_batch_size=32, learning_starts=64,
                      num_steps_sampled_before_learning=64)
            .debugging(seed=0).build())
    for _ in range(4):
        r = algo.step()
    assert "learner/mean_q" in r
    algo.cleanup()


def test_a3c_async_grads(cluster):
    from ray_tpu.rllib.algorithms.a3c import A3CConfig
    algo = (A3CConfig().environment("CartPole-v1")
            .rollouts(num_workers=2, rollout_fragment_length=50)
            .debugging(seed=0).build())
    total_grads = 0
    for _ in range(4):
        r = algo.step()
        total_grads += r["num_grads_applied"]
    assert total_grads >= 4
    assert "learner/policy_loss" in r
    assert r["num_env_steps_sampled_this_iter"] > 0
    algo.cleanup()


def test_a3c_requires_workers():
    from ray_tpu.rllib.algorithms.a3c import A3CConfig
    with pytest.raises(ValueError, match="num_workers"):
        (A3CConfig().environment("CartPole-v1")
         .rollouts(num_workers=0).build())


def test_ddppo_decentralized_learning(cluster):
    from ray_tpu.rllib.algorithms.ddppo import DDPPOConfig
    algo = (DDPPOConfig().environment("CartPole-v1")
            .rollouts(num_workers=2, rollout_fragment_length=100)
            .training(num_sgd_iter=3, sgd_minibatch_size=64)
            .debugging(seed=0).build())
    r = algo.step()
    assert r["num_ddppo_workers"] == 2
    assert r["num_env_steps_sampled_this_iter"] >= 200
    # driver policy got the averaged weights (it never learned itself)
    lw_w = algo.workers.local_worker.policy.get_weights()
    rw_w = ray_tpu.get(
        algo.workers.remote_workers[0].get_weights.remote())
    flat_l = np.concatenate([np.ravel(x) for x in
                             _tree_leaves(lw_w)])
    flat_r = np.concatenate([np.ravel(x) for x in
                             _tree_leaves(rw_w)])
    np.testing.assert_allclose(flat_l, flat_r, rtol=1e-5)
    algo.cleanup()


def _tree_leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)


def test_apex_ddpg_noise_ladder_and_learning(cluster):
    from ray_tpu.rllib.algorithms.apex_ddpg import ApexDDPGConfig
    algo = (ApexDDPGConfig().environment("Pendulum-v1")
            .rollouts(num_workers=2, rollout_fragment_length=16)
            .training(train_batch_size=64, learning_starts=128)
            .debugging(seed=0).build())
    for _ in range(6):
        r = algo.step()
    assert r["replay_size"] >= 128
    assert r["num_learner_steps"] > 0
    assert "learner/critic_loss" in r
    # per-worker noise ladder: EARLIER workers explore more
    # (base^1 > base^8 for base < 1)
    noises = ray_tpu.get([
        w.apply.remote(lambda w: w.policy.exploration_noise)
        for w in algo.workers.remote_workers])
    assert noises[0] > noises[1]
    assert algo.workers.local_worker.policy.exploration_noise == 0.0
    algo.cleanup()


def test_cql_offline_learns(pendulum_dataset):
    from ray_tpu.rllib.algorithms.cql import CQLConfig
    algo = (CQLConfig().environment("Pendulum-v1")
            .offline_data(input_path=pendulum_dataset)
            .training(train_batch_size=128, num_iters_per_step=30,
                      bc_iters=150, cql_alpha=0.5, lr=1e-3)
            .debugging(seed=0).build())
    ev0 = algo.evaluate(num_episodes=5)["evaluation"][
        "episode_reward_mean"]
    for _ in range(25):
        r = algo.step()
    assert "learner/cql_penalty" in r
    assert np.isfinite(r["learner/critic_loss"])
    # offline training improves on the untrained policy (fully seeded:
    # measured -1367 → -1264 after 750 learn steps; margin for drift)
    ev1 = algo.evaluate(num_episodes=5)["evaluation"][
        "episode_reward_mean"]
    assert ev1 > ev0 + 30, (ev0, ev1)
    algo.cleanup()


def test_crr_binary_and_exp_weights(pendulum_dataset):
    from ray_tpu.rllib.algorithms.crr import CRRConfig
    algo = (CRRConfig().environment("Pendulum-v1")
            .offline_data(input_path=pendulum_dataset)
            .training(train_batch_size=128, num_iters_per_step=10,
                      weight_type="binary")
            .debugging(seed=0).build())
    r = algo.step()
    assert 0.0 <= r["learner/mean_weight"] <= 1.0
    algo.cleanup()
    algo = (CRRConfig().environment("Pendulum-v1")
            .offline_data(input_path=pendulum_dataset)
            .training(train_batch_size=128, num_iters_per_step=10,
                      weight_type="exp", temperature=1.0)
            .debugging(seed=0).build())
    r = algo.step()
    assert r["learner/mean_weight"] > 0.0
    assert np.isfinite(r["learner/actor_loss"])
    algo.cleanup()


def test_es_learns_cartpole(cluster):
    """ES improves CartPole reward well above random (~20)."""
    from ray_tpu.rllib.algorithms.es import ESConfig
    algo = (ESConfig().environment("CartPole-v1")
            .rollouts(num_workers=2)
            .training(rollouts_per_worker=10, sigma=0.1, stepsize=0.05,
                      episode_horizon=200, noise_table_size=500_000)
            .debugging(seed=0).build())
    best = 0.0
    for i in range(15):
        r = algo.step()
        best = max(best, r["perturbation_reward_mean"])
        if best > 80:
            break
    algo.cleanup()
    assert best > 60, f"ES stuck at {best}"


def test_ars_top_directions(cluster):
    from ray_tpu.rllib.algorithms.es import ARSConfig
    algo = (ARSConfig().environment("CartPole-v1")
            .rollouts(num_workers=2)
            .training(rollouts_per_worker=6, num_top_directions=4,
                      sigma=0.1, stepsize=0.05, episode_horizon=100,
                      noise_table_size=500_000)
            .debugging(seed=0).build())
    r1 = algo.step()
    r2 = algo.step()
    assert np.isfinite(r2["update_gnorm"]) and r2["update_gnorm"] > 0
    assert r2["episodes_this_iter"] == 24  # 2 workers * 6 pairs * 2
    algo.cleanup()


def test_bandit_linucb_low_regret():
    from ray_tpu.rllib.algorithms.bandit import (
        BanditLinUCBConfig, LinearDiscreteBanditEnv)
    algo = (BanditLinUCBConfig()
            .environment(LinearDiscreteBanditEnv,
                         env_config={"feature_dim": 4, "num_arms": 3,
                                     "payoff_seed": 7})
            .debugging(seed=0).build())
    rewards = [algo.step()["learner/mean_reward"] for _ in range(15)]
    # converged per-step reward should be clearly positive (optimal arm
    # mean ≈ 1.0 for this payoff seed; uniform-random ≈ 0)
    assert np.mean(rewards[-5:]) > 0.5, rewards
    algo.cleanup()


def test_bandit_lints_converges():
    from ray_tpu.rllib.algorithms.bandit import (
        BanditLinTSConfig, LinearDiscreteBanditEnv)
    algo = (BanditLinTSConfig()
            .environment(LinearDiscreteBanditEnv,
                         env_config={"feature_dim": 4, "num_arms": 3,
                                     "payoff_seed": 7})
            .debugging(seed=0).build())
    rewards = [algo.step()["learner/mean_reward"] for _ in range(15)]
    assert np.mean(rewards[-5:]) > 0.5, rewards
    # checkpoint roundtrip keeps the sufficient statistics
    state = algo.save_checkpoint()
    A_before = algo.get_policy().A.copy()
    algo.load_checkpoint(state)
    np.testing.assert_allclose(algo.get_policy().A, A_before)
    algo.cleanup()


def test_algorithms_registry_exports():
    """All 26 algorithm classes import from the package root."""
    from ray_tpu.rllib import algorithms as A
    for name in ["PPO", "DDPPO", "APPO", "IMPALA", "DQN", "SimpleQ",
                 "ApexDQN", "ApexDDPG", "R2D2", "PG", "A2C", "A3C",
                 "SAC", "DDPG", "TD3", "BC", "MARWIL", "CQL", "CRR",
                 "DT", "ES", "ARS", "QMix", "MADDPG", "BanditLinUCB",
                 "BanditLinTS"]:
        assert hasattr(A, name), name
        assert hasattr(A, name + "Config"), name
