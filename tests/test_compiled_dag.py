"""Compiled actor DAGs (tier-1): build/compile/execute round-trips,
result equivalence vs dynamic ``.execute()``, plasmax ring-buffer reuse,
version-gated negotiation, and chaos-seeded stage-kill fallback
(docs/COMPILED_DAGS.md; reference strategy: the reference's
python/ray/dag compiled-graph tests)."""

import json
import os
import time

import pytest

import ray_tpu
from ray_tpu._private import chaos
from ray_tpu._private import worker as wmod
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.dag.compiled_dag import CompileError, CompiledDAG


@pytest.fixture(scope="module")
def dag_cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


@ray_tpu.remote
class AddK:
    def __init__(self, k):
        self.k = k

    def add(self, x):
        return x + self.k

    def boom(self, x):
        raise ValueError(f"boom on {x}")


def _pipeline():
    with InputNode() as inp:
        a, b, c = AddK.bind(1), AddK.bind(10), AddK.bind(100)
        dag = c.add.bind(b.add.bind(a.add.bind(inp)))
    return dag, (a, b, c)


def test_compile_execute_roundtrip_equivalence(dag_cluster):
    dag, _ = _pipeline()
    dynamic = [ray_tpu.get(dag.execute(i)) for i in range(3)]
    cdag = dag.compile()
    try:
        assert cdag._compiled and not cdag._fallback_only
        compiled = [cdag.execute(i) for i in range(3)]
        # equivalence: the compiled graph computes exactly what the
        # dynamic path computes on the same graph
        assert compiled == dynamic == [111 + i for i in range(3)]
        # repeated invocations keep working (pre-wired channels reused)
        assert [cdag.execute(i) for i in range(20)] == \
            [111 + i for i in range(20)]
    finally:
        cdag.teardown()


def test_compiled_pipelined_async(dag_cluster):
    dag, _ = _pipeline()
    cdag = dag.compile()
    try:
        assert cdag._compiled
        futs = [cdag.execute_async(i) for i in range(50)]
        assert [f.result(30) for f in futs] == \
            [111 + i for i in range(50)]
    finally:
        cdag.teardown()


def test_app_error_propagates_without_teardown(dag_cluster):
    with InputNode() as inp:
        a, b = AddK.bind(1), AddK.bind(10)
        dag = b.add.bind(a.boom.bind(inp))
    cdag = dag.compile()
    try:
        assert cdag._compiled
        with pytest.raises(ray_tpu.exceptions.RayTpuError,
                           match="boom on 7"):
            cdag.execute(7)
        # an APPLICATION error is a result, not a channel failure: the
        # graph stays compiled and keeps serving
        assert cdag._compiled
        with pytest.raises(ray_tpu.exceptions.RayTpuError):
            cdag.execute(8)
    finally:
        cdag.teardown()


def test_multi_output_node_dynamic_and_compiled(dag_cluster):
    with InputNode() as inp:
        src = AddK.bind(1)
        mid = src.add.bind(inp)
        dag = MultiOutputNode(
            [AddK.bind(10).add.bind(mid), AddK.bind(100).add.bind(mid)])
    refs = dag.execute(5)
    assert isinstance(refs, list) and len(refs) == 2
    assert ray_tpu.get(refs) == [16, 106]
    cdag = dag.compile()
    try:
        assert cdag._compiled
        assert cdag.execute(5) == [16, 106]
        assert cdag.execute(0) == [11, 101]
    finally:
        cdag.teardown()


def test_class_node_caches_actor_across_executions(dag_cluster):
    """Regression (dag/dag_node.py ClassNode): the actor is created ONCE
    per DAG instance — a 3-execute run must not leak 3 actors."""
    from ray_tpu.experimental.state import api as state_api

    @ray_tpu.remote
    class ChurnProbe:
        def ping(self, x):
            return x

    def alive_probes():
        return [a for a in state_api.list_actors()
                if a.get("class_name") == "ChurnProbe"
                and a.get("state") not in ("DEAD",)]

    before = len(alive_probes())
    with InputNode() as inp:
        dag = ChurnProbe.bind().ping.bind(inp)
    for i in range(3):
        assert ray_tpu.get(dag.execute(i)) == i
    assert len(alive_probes()) == before + 1


def test_uncompilable_graph_degrades_to_dynamic(dag_cluster):
    @ray_tpu.remote
    def double(x):
        return x * 2

    with InputNode() as inp:
        dag = double.bind(inp)  # function stage: no process to pre-wire
    cdag = dag.compile()
    assert cdag._fallback_only and not cdag._compiled
    assert cdag.execute(21) == 42  # transparently dynamic


def test_ring_buffer_reuse_stays_flat(dag_cluster):
    """Acceptance gate: plasmax segment usage flat across 100 compiled
    triggers carrying >inline payloads (seal/unseal ring cycling — no
    create-per-object)."""
    np = pytest.importorskip("numpy")
    with InputNode() as inp:
        a, b, c = AddK.bind(1.0), AddK.bind(1.0), AddK.bind(1.0)
        dag = c.add.bind(b.add.bind(a.add.bind(inp)))
    cdag = dag.compile()
    try:
        assert cdag._compiled
        arr = np.zeros(32 * 1024, dtype=np.float64)  # 256 KB > inline
        for _ in range(4):  # >= ring depth: lazy slots exist before t0
            cdag.execute(arr)
        w = wmod._global_worker
        s0 = w.plasma.stats()
        for _ in range(100):
            out = cdag.execute(arr)
        s1 = w.plasma.stats()
        assert float(out[0]) == 3.0
        assert s1["used_bytes"] == s0["used_bytes"]
        assert s1["num_created"] == s0["num_created"]
    finally:
        cdag.teardown()


def test_version_gate_refuses_legacy_peer(dag_cluster):
    """1.5 negotiation (the PR-4 pattern): a stage worker that declared
    wire schema 1.4 cannot host compiled channels — _negotiate raises
    and the graph degrades to dynamic instead of failing mid-graph."""
    import asyncio

    from ray_tpu._private import protocol

    class Legacy14Server(protocol.Server):
        async def _handle(self, method, payload, conn):
            if method == "__hello__":
                return {"protocol_version": [1, 4],
                        "schema_hash": "0" * 16}
            raise protocol.RpcError(f"no such method: {method}")

    w = wmod._global_worker
    server = Legacy14Server({})
    path = os.path.join(w.session_dir, "legacy14.sock")
    w.io.run(server.start_unix(path))
    try:
        conn = w.io.run(w._peer(f"unix:{path}"))
        with pytest.raises(CompileError, match="1.4 < 1.5"):
            CompiledDAG._negotiate(w, conn, f"unix:{path}")
        # the negotiated version is remembered on the connection
        assert conn.meta["peer_protocol_version"] == (1, 4)
    finally:
        server.close()

    # same-version peers pass: compiling against the live cluster works
    dag, _ = _pipeline()
    cdag = dag.compile()
    try:
        assert cdag._compiled
    finally:
        cdag.teardown()


def test_compile_failure_degrades_then_recompiles(dag_cluster,
                                                  monkeypatch):
    """A transient compile failure (e.g. channel refused) runs dynamic
    and re-compiles after the backoff — transparently."""
    dag, _ = _pipeline()
    monkeypatch.setattr(
        CompiledDAG, "_open_channels_broken", True, raising=False)
    real = CompiledDAG._compile

    def flaky(self):
        if getattr(CompiledDAG, "_open_channels_broken", False):
            raise CompileError("injected: channel refused")
        return real(self)

    monkeypatch.setattr(CompiledDAG, "_compile", flaky)
    cdag = dag.compile()
    try:
        assert not cdag._compiled and not cdag._fallback_only
        assert cdag.execute(1) == 112  # dynamic fallback
        monkeypatch.setattr(
            CompiledDAG, "_open_channels_broken", False, raising=False)
        time.sleep(CompiledDAG._COMPILE_RETRY_S + 0.1)
        assert cdag.execute(2) == 113
        assert cdag._compiled  # re-compiled past the backoff
    finally:
        cdag.teardown()


def test_dag_bench_smoke(dag_cluster):
    """The _BENCH_DAG pipeline shapes stay runnable (full gate numbers
    live in bench.py / PERF.md)."""
    dag, _ = _pipeline()
    cdag = dag.compile()
    try:
        assert cdag._compiled
        t0 = time.perf_counter()
        n = 50
        for i in range(n):
            assert cdag.execute(i) == 111 + i
        compiled_s = (time.perf_counter() - t0) / n
        # sanity bound, not the perf gate: compiled round trips must be
        # far under the ~2 ms dynamic hop cost even on a loaded CI box
        assert compiled_s < 0.05
    finally:
        cdag.teardown()


# --------------------------------------------------------- chaos coverage
#
# These manage their OWN cluster (PR-4 machinery: RTPU_CHAOS reaches
# workers via the spawn environment, and the shared cluster's idle
# workers — spawned chaos-free — would be reused for the stage actors).
# They run after every dag_cluster test in this module.


def _chaos_env(cfg, log_path):
    ray_tpu.shutdown()  # the module-shared cluster predates the env
    os.environ["RTPU_CHAOS"] = json.dumps(cfg)
    os.environ["RTPU_CHAOS_LOG"] = str(log_path)
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                 object_store_memory=256 * 1024 * 1024)


def _clear_chaos_env():
    ray_tpu.shutdown()
    os.environ.pop("RTPU_CHAOS", None)
    os.environ.pop("RTPU_CHAOS_LOG", None)
    chaos.clear()


def test_chaos_stage_kill_falls_back_exactly_once(tmp_path):
    """Acceptance gate: SIGKILL the middle stage's worker mid-graph (the
    seeded ``dag.stage`` op). The compiled graph degrades to dynamic
    dispatch with no lost or duplicated invocation — the surviving sink
    observes every input exactly once — and the chaos log records the
    replayable fault."""
    log = tmp_path / "dag_chaos.jsonl"
    # stage ids are topo order: 0=a (entry), 1=b (middle), 2=c (sink);
    # kill the worker hosting stage 1 at its 3rd compiled execution
    _chaos_env({"seed": 7, "schedule": [
        {"site": "dag.stage", "op": "kill", "at": 3, "method": "1",
         "proc": "worker"}]}, log)
    try:
        @ray_tpu.remote
        class CountingStage:
            def __init__(self, k):
                self.k = k
                self.seen = {}

            def f(self, x):
                self.seen[x] = self.seen.get(x, 0) + 1
                return x + self.k

            def seen_counts(self):
                return dict(self.seen)

        with InputNode() as inp:
            a, b, c = (CountingStage.bind(1), CountingStage.bind(10),
                       CountingStage.bind(100))
            dag = c.f.bind(b.f.bind(a.f.bind(inp)))
        cdag = dag.compile(execute_timeout_s=15.0)
        try:
            assert cdag._compiled
            out = [cdag.execute(i) for i in range(6)]
            # no lost and no duplicated invocation: every input yields
            # exactly one correct result...
            assert out == [111 + i for i in range(6)]
            # ...and the SINK (downstream of the kill) executed each
            # invocation exactly once — the in-flight one arrived via
            # the dynamic fallback, not twice. (The sink sees each
            # input shifted by the two upstream stages: i + 11.)
            counts = ray_tpu.get(
                c._cached_actor.seen_counts.remote())
            assert sorted(counts) == [11 + i for i in range(6)]
            assert all(n == 1 for n in counts.values()), counts
        finally:
            cdag.teardown()
        fired = [(r["site"], r["op"], r["n"])
                 for r in chaos.read_log(str(log))]
        assert ("dag.stage", "kill", 3) in fired, fired
    finally:
        _clear_chaos_env()


def test_chaos_channel_reset_recovers(tmp_path):
    """Seeded ``dag.channel`` reset severs a peer channel mid-stream;
    the affected invocation re-runs dynamically and later calls
    re-compile — no lost results."""
    log = tmp_path / "dag_reset.jsonl"
    _chaos_env({"seed": 8, "schedule": [
        {"site": "dag.channel", "op": "reset", "at": 4,
         "method": "dag_exec", "proc": "worker"}]}, log)
    try:
        @ray_tpu.remote
        class Plus:
            def __init__(self, k):
                self.k = k

            def f(self, x):
                return x + self.k

        with InputNode() as inp:
            a, b = Plus.bind(1), Plus.bind(10)
            dag = b.f.bind(a.f.bind(inp))
        cdag = dag.compile(execute_timeout_s=15.0)
        try:
            assert cdag._compiled
            out = [cdag.execute(i) for i in range(8)]
            assert out == [11 + i for i in range(8)]
        finally:
            cdag.teardown()
        assert any(r["op"] == "reset"
                   for r in chaos.read_log(str(log)))
    finally:
        _clear_chaos_env()
