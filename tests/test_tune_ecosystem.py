"""Tune ecosystem: loggers, syncer, resumable experiments, model-based
searchers, PB2.

Reference analogues: tune/tests/test_logger.py, test_syncer.py,
test_tuner_restore.py, test_searchers.py, test_trial_scheduler_pbt.py.
"""

import json
import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import session
from ray_tpu.air.config import CheckpointConfig, RunConfig
from ray_tpu.tune import sample as s
from ray_tpu.tune.search import (BayesOptSearch, HyperOptSearch,
                                 TPESearcher)


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


# ------------------------------------------------------- searcher-level


def test_tpe_converges_mixed_space():
    import math

    space = {"lr": s.loguniform(1e-5, 1e-1),
             "act": s.choice(["relu", "tanh", "gelu"]),
             "n": s.randint(1, 8)}

    def obj(cfg):
        return (-(math.log10(cfg["lr"]) + 3) ** 2
                - (0 if cfg["act"] == "gelu" else 1)
                - abs(cfg["n"] - 4) * 0.1)

    se = TPESearcher(space, metric="obj", mode="max", seed=0)
    best = None
    for t in range(50):
        tid = f"t{t}"
        cfg = se.suggest(tid)
        v = obj(cfg)
        se.on_trial_complete(tid, {"obj": v})
        best = v if best is None else max(best, v)
    # optimum is 0; random search lands around -0.5 at this budget
    assert best > -0.1


def test_bayesopt_converges_quadratic():
    space = {"x": s.uniform(-5, 5), "y": s.uniform(-5, 5)}

    def obj(cfg):
        return -(cfg["x"] - 0.3) ** 2 - (cfg["y"] + 2) ** 2

    se = BayesOptSearch(space, metric="obj", mode="max", seed=0)
    best = None
    for t in range(40):
        tid = f"t{t}"
        cfg = se.suggest(tid)
        v = obj(cfg)
        se.on_trial_complete(tid, {"obj": v})
        best = v if best is None else max(best, v)
    assert best > -0.05  # random search: ~-0.5 at this budget


def test_searcher_num_samples_exhaustion():
    se = TPESearcher({"x": s.uniform(0, 1)}, metric="m", mode="max",
                     num_samples=3, seed=0)
    out = [se.suggest(f"t{i}") for i in range(4)]
    assert all(c is not None for c in out[:3]) and out[3] is None


def test_external_searchers_gated():
    # needs hyperopt ABSENT (the point is the gate message)
    try:
        import hyperopt  # noqa: F401
        pytest.skip("hyperopt installed; gate not exercised")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="TPESearcher"):
        HyperOptSearch({"x": s.uniform(0, 1)})


def test_tpe_through_tune_run(cluster):
    def train_fn(config):
        session.report({"score": -(config["x"] - 2.0) ** 2})

    analysis = tune.run(
        train_fn, config={"x": s.uniform(-10, 10)},
        search_alg=TPESearcher(num_samples=20, seed=0),
        metric="score", mode="max", max_concurrent_trials=4)
    assert len(analysis.trials) == 20
    assert analysis.best_result["score"] > -4.0


# ------------------------------------------------------------- loggers


def test_loggers_write_files(cluster, tmp_path):
    def train_fn(config):
        for i in range(3):
            session.report({"score": config["a"] + i, "iter_f": float(i)})

    analysis = tune.run(
        train_fn, config={"a": tune.grid_search([1, 2])},
        metric="score", mode="max", name="log_exp",
        local_dir=str(tmp_path))
    exp_dir = tmp_path / "log_exp"
    assert exp_dir.is_dir()
    trial_dirs = [d for d in exp_dir.iterdir() if d.is_dir()]
    assert len(trial_dirs) == 2
    for td in trial_dirs:
        assert (td / "params.json").exists()
        results = [json.loads(line)
                   for line in (td / "result.json").read_text().splitlines()]
        assert len(results) == 3
        assert "score" in results[0]
        csv_lines = (td / "progress.csv").read_text().splitlines()
        assert len(csv_lines) == 4  # header + 3 rows
        assert "score" in csv_lines[0]
        tb_events = [f for f in os.listdir(td)
                     if f.startswith("events.out.tfevents")]
        assert tb_events, "TensorBoard event file missing"
    # experiment-level state summaries
    assert (exp_dir / "experiment_state.json").exists()
    assert (exp_dir / "experiment_state.pkl").exists()
    state = json.loads((exp_dir / "experiment_state.json").read_text())
    assert len(state) == 2
    assert all(t["status"] == "TERMINATED" for t in state)
    assert analysis.best_result["score"] == 4


def test_syncer_uploads_experiment_dir(cluster, tmp_path):
    from ray_tpu.tune.syncer import SyncConfig

    def train_fn(config):
        session.report({"score": 1.0})

    upload = tmp_path / "upload"
    tune.run(train_fn, config={}, metric="score", mode="max",
             name="sync_exp", local_dir=str(tmp_path / "local"),
             sync_config=SyncConfig(upload_dir=f"file://{upload}"))
    synced = upload / "sync_exp"
    assert synced.is_dir()
    assert (synced / "experiment_state.json").exists()
    trial_dirs = [d for d in synced.iterdir() if d.is_dir()]
    assert trial_dirs and (trial_dirs[0] / "result.json").exists()


# ------------------------------------------------------------- resume


def test_experiment_resume_from_snapshot(cluster, tmp_path):
    class Count(tune.Trainable):
        def setup(self, config):
            self.x = 0

        def step(self):
            self.x += 1
            return {"x": self.x, "done": self.x >= 6}

        def save_checkpoint(self):
            return {"x": self.x}

        def load_checkpoint(self, state):
            self.x = state["x"]

    from ray_tpu.tune.search import BasicVariantGenerator
    from ray_tpu.tune.tune import TrialRunner

    space = {"a": tune.grid_search([1, 2])}
    runner = TrialRunner(
        Count,
        BasicVariantGenerator(space, metric="x", mode="max"),
        experiment_name="resume_exp", metric="x", mode="max",
        checkpoint_freq=1, local_dir=str(tmp_path))
    # run part of the experiment, snapshotting as run_all would (forced:
    # snapshots are time-throttled and this test is faster than the period)
    for _ in range(5):
        runner.step()
        runner._snapshot(force=True)
    partial = sum(len(t.results) for t in runner.trials)
    assert 0 < partial, "no progress before interruption"
    assert not runner.is_finished()
    # simulate a driver crash: kill trial actors, drop the runner
    for t in runner.trials:
        if t.actor is not None:
            ray_tpu.kill(t.actor)

    runner2 = TrialRunner(
        Count,
        BasicVariantGenerator(space, metric="x", mode="max"),
        experiment_name="resume_exp", metric="x", mode="max",
        checkpoint_freq=1, local_dir=str(tmp_path))
    runner2.restore_from_dir(runner2.experiment_dir)
    assert len(runner2.trials) == 2  # trials carried over, not re-created
    runner2.run_all()
    assert all(t.status == "TERMINATED" for t in runner2.trials)
    for t in runner2.trials:
        # resumed from checkpoint: counting continued (6 total results),
        # never restarted from zero
        assert t.metric_history("x")[-1] == 6
        assert len(t.results) == 6


def test_tuner_restore_api(cluster, tmp_path):
    def train_fn(config):
        for i in range(2):
            session.report({"score": i})

    tuner = tune.Tuner(
        train_fn, param_space={},
        run_config=RunConfig(name="tr_exp", storage_path=str(tmp_path)))
    tuner.fit()
    # restoring a finished experiment is a no-op completion
    restored = tune.Tuner.restore(str(tmp_path / "tr_exp"), train_fn)
    grid = restored.fit()
    assert len(grid) == 1
    assert grid[0].metrics["score"] == 1


# ------------------------------------------------------------------ PB2


def test_pb2_smoke(cluster):
    def train_fn(config):
        x = 0.0
        for i in range(10):
            # reward gradient points toward lr=0.5
            x += 1.0 - (config["lr"] - 0.5) ** 2
            session.report({"score": x})

    from ray_tpu.tune.schedulers import PB2
    sched = PB2(metric="score", mode="max", perturbation_interval=3,
                hyperparam_bounds={"lr": [0.001, 1.0]}, seed=0)
    analysis = tune.run(
        train_fn, config={"lr": s.uniform(0.001, 1.0)},
        num_samples=4, metric="score", mode="max",
        scheduler=sched, checkpoint_freq=1, max_concurrent_trials=4)
    assert len(analysis.trials) == 4
    assert all(t.status == "TERMINATED" for t in analysis.trials)
    assert analysis.best_result["score"] > 0


# ------------------------------------------- HyperBand / resource changing


def test_hyperband_brackets_cull(cluster):
    """Weak trials stop early; at least one strong trial reaches max_t."""
    def train_fn(config):
        for i in range(27):
            session.report({"score": config["q"] * (i + 1),
                            "training_iteration": i + 1})

    from ray_tpu.tune.schedulers import HyperBandScheduler
    sched = HyperBandScheduler(metric="score", mode="max", max_t=27,
                               reduction_factor=3)
    analysis = tune.run(
        train_fn, config={"q": s.grid_search([0.1, 0.4, 0.7, 1.0])},
        metric="score", mode="max", scheduler=sched,
        max_concurrent_trials=4)
    iters = {t.config["q"]: (t.last_result or {}).get(
        "training_iteration", 0) for t in analysis.trials}
    assert max(iters.values()) == 27          # a survivor went the distance
    assert analysis.best_result["score"] >= 27 * 0.7


def test_resource_changing_scheduler(cluster):
    """The allocation hook reallocates CPU mid-run; the trial restarts
    from checkpoint with the new resources and still finishes."""
    seen = []

    def train_fn(config):
        import ray_tpu as rt
        for i in range(6):
            session.report({"score": i + 1,
                            "training_iteration": i + 1})

    def alloc(runner, trial, result, scheduler):
        if result.get("training_iteration") == 2:
            return {"CPU": 2.0}
        return None

    from ray_tpu.tune.schedulers import ResourceChangingScheduler
    sched = ResourceChangingScheduler(
        resources_allocation_function=alloc)
    analysis = tune.run(
        train_fn, config={}, num_samples=1, metric="score", mode="max",
        scheduler=sched, checkpoint_freq=1)
    t = analysis.trials[0]
    assert t.status == "TERMINATED"
    assert t.resources == {"CPU": 2.0}
    assert (t.last_result or {}).get("score") == 6
