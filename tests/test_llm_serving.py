"""LLM inference engine on serve (docs/LLM_SERVING.md; ROADMAP item 1):
continuous batching vs static batching equivalence, paged-attention
kernel numerics vs the whole-kv reference, incremental model decode vs
full forward, cost-aware admission, KV-aware graceful drain through a
rolling update, token streaming end to end (handle iterator + HTTP
SSE, first token BEFORE generation completes), chaos mid-stream
replica kill (clean failure or retry, never silent truncation), LLM
autoscaler signals, trace phase spans, and the llm-chat game day with
per-token reconciliation. Tier-1, CPU-only.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.exceptions import (ReplicaOverloadedError,
                                      StreamBrokenError)
from ray_tpu.serve.llm import (EngineConfig, LLMEngine, LLMServer,
                               PagedKVCache, SamplingParams, ToyAdapter)
from ray_tpu.serve.llm.kv_cache import OutOfKVBlocksError

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------ kernel numerics


def test_paged_attention_matches_whole_kv_reference():
    """The Pallas paged-decode kernel (interpret mode on CPU), the
    paged gather reference, and the contiguous whole-kv decode path
    must agree bit-for-bit-ish on the same cache contents."""
    import jax.numpy as jnp

    from ray_tpu.ops import attention as A
    rng = np.random.RandomState(0)
    B, H, Hkv, D, bs, NB = 3, 8, 2, 16, 8, 4
    P = 1 + B * NB
    lengths = jnp.asarray([5, 17, 30], jnp.int32)
    k_pages = jnp.asarray(rng.randn(P, bs, Hkv, D), jnp.float32)
    v_pages = jnp.asarray(rng.randn(P, bs, Hkv, D), jnp.float32)
    bt = jnp.asarray(np.arange(1, 1 + B * NB).reshape(B, NB), jnp.int32)
    q = jnp.asarray(rng.randn(B, H, D), jnp.float32)

    ref = A.paged_attention_reference(q, k_pages, v_pages, bt, lengths)
    kernel = A.paged_attention_decode(q, k_pages, v_pages, bt, lengths,
                                      interpret=True)
    np.testing.assert_allclose(np.asarray(kernel), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    # contiguous whole-kv path over the SAME logical cache
    k_cont = A.paged_gather(k_pages, bt)
    v_cont = A.paged_gather(v_pages, bt)
    whole = A.decode_attention(q[:, :, None, :], k_cont, v_cont,
                               lengths)[:, :, 0, :]
    np.testing.assert_allclose(np.asarray(kernel), np.asarray(whole),
                               rtol=1e-5, atol=1e-5)


def test_paged_kv_allocator_exact_admission():
    c = PagedKVCache(num_blocks=8, block_size=4)   # 7 usable pages
    assert c.blocks_for(9) == 3
    t1 = c.allocate("a", 9)             # 3 pages
    assert 0 not in t1                  # page 0 reserved (null page)
    assert c.can_allocate(16)           # 4 pages left
    assert not c.can_allocate(17)       # 5 needed, 4 free
    with pytest.raises(OutOfKVBlocksError):
        c.allocate("b", 17)
    assert abs(c.occupancy() - 3 / 7) < 1e-9
    assert c.free("a") == 3
    assert c.occupancy() == 0.0
    assert c.free("a") == 0             # double free is a no-op


# --------------------------------------------------- incremental decode


def test_gpt2_incremental_decode_matches_full_forward():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2
    cfg = gpt2.GPT2Config.tiny()
    m = gpt2.GPT2(cfg)
    ids = jnp.asarray(
        np.random.RandomState(1).randint(0, cfg.vocab_size, (2, 10)))
    params = m.init(jax.random.PRNGKey(0), ids)
    full = m.apply(params, ids)

    cache = gpt2.init_kv_cache(cfg, 2, 32)
    L = jnp.zeros((2,), jnp.int32)
    lg, cache = m.apply(params, ids[:, :6], kv_cache=cache,
                        seq_lengths=L)
    outs, L = [lg], L + 6
    for t in range(6, 10):
        lg, cache = m.apply(params, ids[:, t:t + 1], kv_cache=cache,
                            seq_lengths=L)
        outs.append(lg)
        L = L + 1
    inc = jnp.concatenate(outs, 1)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full),
                               rtol=1e-4, atol=1e-4)


def test_llama_incremental_decode_matches_full_forward():
    """GQA + rotary offsets: the decode path must rotate each new
    token by its TRUE absolute position."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama
    cfg = llama.LlamaConfig.tiny()     # n_kv_heads < n_heads
    m = llama.LlamaModel(cfg)
    ids = jnp.asarray(
        np.random.RandomState(2).randint(0, cfg.vocab_size, (2, 9)))
    params = m.init(jax.random.PRNGKey(0), ids)
    full = m.apply(params, ids)

    cache = llama.init_kv_cache(cfg, 2, 32)
    L = jnp.zeros((2,), jnp.int32)
    lg, cache = m.apply(params, ids[:, :5], kv_cache=cache,
                        seq_lengths=L)
    outs, L = [lg], L + 5
    for t in range(5, 9):
        lg, cache = m.apply(params, ids[:, t:t + 1], kv_cache=cache,
                            seq_lengths=L)
        outs.append(lg)
        L = L + 1
    inc = jnp.concatenate(outs, 1)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full),
                               rtol=1e-4, atol=1e-4)


# --------------------------------------------------------------- engine


def _drain_stream(eng, sid, timeout=30.0):
    toks, cur = [], 0
    deadline = time.time() + timeout
    while time.time() < deadline:
        ch = eng.poll(sid, cur, max_wait_s=5.0)
        toks += ch["tokens"]
        cur = ch["cursor"]
        if ch["done"]:
            return toks, ch
    raise TimeoutError("stream did not finish")


def test_continuous_vs_static_batching_same_tokens():
    """The headline correctness property: continuous batching changes
    WHEN sequences run, never WHAT they produce. The toy model reads
    its prefix back through the block tables, so a paging bug breaks
    this too."""
    rng = np.random.RandomState(0)
    reqs = [(list(rng.randint(0, 256, rng.randint(3, 12))),
             int(rng.randint(2, 10))) for _ in range(9)]

    def run(policy):
        eng = LLMEngine(ToyAdapter(seed=3), EngineConfig(
            max_running=4, num_blocks=64, block_size=8,
            max_seq_len=128, policy=policy))
        sids = [eng.add_request(p, SamplingParams(max_new_tokens=n))
                for p, n in reqs]
        outs = [_drain_stream(eng, sid)[0] for sid in sids]
        eng.stop()
        return outs

    assert run("continuous") == run("static")


def test_cost_aware_admission_long_prefill_goes_alone():
    """A prompt over the per-step prefill budget is admitted ALONE
    (and others never behind it in the same step) — and everything
    still completes."""
    eng = LLMEngine(ToyAdapter(), EngineConfig(
        max_running=8, max_prefill_tokens=8, num_blocks=64,
        block_size=8, max_seq_len=256))
    short = eng.add_request([1] * 6, SamplingParams(max_new_tokens=3))
    long = eng.add_request([2] * 40, SamplingParams(max_new_tokens=3))
    t_short, _ = _drain_stream(eng, short)
    t_long, _ = _drain_stream(eng, long)
    assert len(t_short) == 3 and len(t_long) == 3
    m = eng.metrics()
    assert m["finished_total"] == 2
    assert m["kv_occupancy"] == 0.0    # all pages returned
    eng.stop()


def test_kv_exhaustion_queues_instead_of_oom():
    """A sequence that doesn't fit the pool WAITS for pages (freed by
    finishing sequences) instead of failing mid-decode."""
    # 15 usable pages * 4 tokens = 60 tokens capacity; each request
    # needs 8+24=32 tokens -> 8 pages; two can't run at once
    eng = LLMEngine(ToyAdapter(), EngineConfig(
        max_running=8, num_blocks=16, block_size=4, max_seq_len=64))
    a = eng.add_request([1] * 8, SamplingParams(max_new_tokens=24))
    b = eng.add_request([2] * 8, SamplingParams(max_new_tokens=24))
    ta, ca = _drain_stream(eng, a)
    tb, cb = _drain_stream(eng, b)
    assert len(ta) == 24 and len(tb) == 24
    assert ca["finish_reason"] == "length"
    assert cb["finish_reason"] == "length"
    eng.stop()


def test_engine_sheds_when_waiting_room_full():
    eng = LLMEngine(ToyAdapter(per_seq_delay_s=0.01),
                    EngineConfig(max_running=1, max_waiting=1,
                                 num_blocks=64, block_size=8,
                                 max_seq_len=128))
    sids = []
    with pytest.raises(ReplicaOverloadedError):
        for _ in range(12):  # 1 running + 1 waiting, the rest shed
            sids.append(eng.add_request(
                [1, 2, 3], SamplingParams(max_new_tokens=20)))
    assert eng.metrics()["shed_total"] >= 1
    for sid in sids:
        _drain_stream(eng, sid)
    eng.stop()


def test_engine_drain_finishes_in_flight_sheds_new():
    eng = LLMEngine(ToyAdapter(per_seq_delay_s=0.005),
                    EngineConfig(max_running=4, num_blocks=64,
                                 block_size=8, max_seq_len=128))
    sid = eng.add_request([1] * 4, SamplingParams(max_new_tokens=30))
    eng.prepare_drain()
    with pytest.raises(ReplicaOverloadedError):
        eng.add_request([2] * 4, SamplingParams(max_new_tokens=2))
    toks, ch = _drain_stream(eng, sid)
    assert len(toks) == 30 and ch["finish_reason"] == "length"
    assert eng.in_flight() == 0
    eng.stop()


def test_temperature_sampling_is_seeded_deterministic():
    def gen(seed):
        eng = LLMEngine(ToyAdapter(), EngineConfig(
            num_blocks=32, block_size=8, max_seq_len=128))
        # temperature high enough to actually spread the toy model's
        # peaked logits — 1.0 still collapses to the argmax token
        sid = eng.add_request(
            [5, 6, 7], SamplingParams(max_new_tokens=12,
                                      temperature=3.0, seed=seed),
            request_id="r1")
        toks, _ = _drain_stream(eng, sid)
        eng.stop()
        return toks

    assert gen(7) == gen(7)
    assert gen(7) != gen(8)


# ---------------------------------------------------- autoscaler signals


def test_autoscaler_scales_on_llm_signals():
    from ray_tpu.serve._private.autoscaling import (AutoscalingConfig,
                                                    AutoscalingPolicy)
    cfg = AutoscalingConfig(min_replicas=1, max_replicas=8,
                            target_num_ongoing_requests_per_replica=100,
                            target_tokens_per_s_per_replica=50.0,
                            target_kv_occupancy=0.8,
                            upscale_delay_s=1.0, downscale_delay_s=1.0)
    p = AutoscalingPolicy(cfg)
    # queue is quiet but throughput demands 4 replicas
    assert p.get_decision(2, 0.0, now=0.0,
                          signals={"tokens_per_s": 200.0,
                                   "kv_occupancy": 0.1}) == 2  # delay
    assert p.get_decision(2, 0.0, now=2.0,
                          signals={"tokens_per_s": 200.0,
                                   "kv_occupancy": 0.1}) == 4
    # KV pressure alone scales out: 2 replicas at 100% occupancy
    # against a 0.8 target want ceil(2 * 1.0/0.8) = 3
    p2 = AutoscalingPolicy(cfg)
    p2.get_decision(2, 0.0, now=0.0, signals={"kv_occupancy": 1.0})
    assert p2.get_decision(2, 0.0, now=2.0,
                           signals={"kv_occupancy": 1.0}) == 3
    # no signals -> pure queue behavior unchanged
    p3 = AutoscalingPolicy(cfg)
    assert p3.get_decision(2, 0.0, now=0.0) == 2


# ------------------------------------------------------- cluster tests


@pytest.fixture(scope="module")
def llm_cluster():
    ctx = ray_tpu.init(num_cpus=8, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    deps = []

    def deploy(name, http_port=None, route=None, **kw):
        llm_kw = {"model": "toy",
                  "model_config": kw.pop("model_config", {}),
                  "engine_config": kw.pop("engine_config",
                                          {"num_blocks": 128,
                                           "block_size": 8,
                                           "max_seq_len": 256})}
        dep = serve.deployment(name=name, **kw)(LLMServer)
        h = serve.run(dep.bind(llm_kw["model"],
                               llm_kw["model_config"],
                               llm_kw["engine_config"]),
                      name=name, route_prefix=route or f"/{name}",
                      http_port=http_port)
        deps.append(name)
        return h

    yield deploy
    serve.shutdown()
    ray_tpu.shutdown()


def test_streaming_handle_end_to_end(llm_cluster):
    """Handle streaming delivers tokens incrementally: multiple
    chunks, the first long before the stream is done, and the final
    token list equals the unary result (acceptance criterion)."""
    h = llm_cluster("llmh", num_replicas=1, max_concurrent_queries=16,
                    model_config={"per_seq_delay_s": 0.02})
    payload = {"prompt": "the quick brown fox", "max_new_tokens": 10}
    unary = ray_tpu.get(h.remote(payload), timeout=60.0)
    assert unary["n_tokens"] == 10

    chunks, stamps = [], []
    for ch in h.stream(payload):
        chunks.append(ch)
        stamps.append(time.time())
    toks = [t for c in chunks for t in c["tokens"]]
    assert toks == unary["tokens"]
    assert chunks[-1]["done"] and chunks[-1]["finish_reason"] == "length"
    assert len(chunks) >= 3, "tokens must stream, not arrive in bulk"
    # first chunk lands well before the stream completes
    assert stamps[0] < stamps[-1] - 0.05


def test_streaming_http_sse_first_token_early(llm_cluster):
    """SSE through the proxy: events arrive incrementally on the
    socket (first data event before [DONE] by a real margin),
    X-Request-Id echoes, token payloads match the unary path."""
    import http.client
    llm_cluster("llmsse", http_port=8917, num_replicas=1,
                max_concurrent_queries=16,
                model_config={"per_seq_delay_s": 0.02})
    proxy = ray_tpu.get_actor("SERVE_PROXY")
    port = ray_tpu.get(proxy.get_port.remote())

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    body = json.dumps({"prompt": "stream me", "max_new_tokens": 10,
                       "stream": True})
    conn.request("POST", "/llmsse", body,
                 {"Content-Type": "application/json",
                  "X-Request-Id": "sse-e2e-1"})
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type") == "text/event-stream"
    assert resp.getheader("X-Request-Id") == "sse-e2e-1"
    events, stamps = [], []
    while True:
        line = resp.fp.readline()
        if not line:
            break
        line = line.strip()
        if not line.startswith(b"data: "):
            continue
        if line[6:] == b"[DONE]":
            stamps.append(("done", time.time()))
            break
        events.append(json.loads(line[6:]))
        stamps.append(("data", time.time()))
    conn.close()
    toks = [t for e in events for t in e.get("tokens", [])]
    assert len(toks) == 10
    assert events[-1].get("done") and not events[-1].get("error")
    data_times = [t for kind, t in stamps if kind == "data"]
    done_time = dict(stamps[-1:])  # ("done", t)
    assert len(events) >= 3, "SSE must deliver multiple events"
    # the FIRST token event beat the end of generation by a margin
    assert data_times[0] < done_time["done"] - 0.05

    # unary through the same route still works (no stream flag)
    import urllib.request
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/llmsse",
        json.dumps({"prompt": "stream me",
                    "max_new_tokens": 10}).encode(),
        {"Content-Type": "application/json"})
    u = json.loads(urllib.request.urlopen(req, timeout=60).read())
    assert u["tokens"] == toks


def test_rolling_update_drains_kv_zero_dropped_streams(llm_cluster):
    """KV-aware graceful drain (satellite): streams in flight when a
    rolling update lands must finish on the draining replicas — full
    token counts, zero broken streams — while the new version takes
    over fresh traffic."""
    name = "llmroll"
    h = llm_cluster(name, num_replicas=2, max_concurrent_queries=32,
                    model_config={"per_seq_delay_s": 0.03},
                    user_config={"v": 1},
                    graceful_shutdown_timeout_s=60.0)
    n_tok = 60   # ~2s+ of decoding: the update lands mid-stream
    streams = [h.stream({"tokens": [i + 1, i + 2, i + 3],
                         "max_new_tokens": n_tok},
                        request_id=f"roll-{i}") for i in range(4)]
    results: dict = {}
    errors: list = []

    def consume(i, st):
        toks = []
        try:
            for ch in st:
                toks += ch["tokens"]
            results[i] = (toks, st.finish_reason)
        except Exception as e:  # noqa: BLE001 — the assertion target
            errors.append((i, repr(e)))

    threads = [threading.Thread(target=consume, args=(i, st))
               for i, st in enumerate(streams)]
    for t in threads:
        t.start()
    time.sleep(0.3)  # streams decoding; now redeploy a new version
    dep = serve.deployment(name=name, num_replicas=2,
                           max_concurrent_queries=32,
                           user_config={"v": 2},
                           graceful_shutdown_timeout_s=60.0)(LLMServer)
    serve.run(dep.bind("toy", {"per_seq_delay_s": 0.03},
                       {"num_blocks": 128, "block_size": 8,
                        "max_seq_len": 256}),
              name=name, route_prefix=f"/{name}", http_port=None,
              _blocking_timeout=120.0)
    for t in threads:
        t.join(timeout=120.0)
    assert not errors, errors
    assert len(results) == 4
    for i, (toks, reason) in results.items():
        assert len(toks) == n_tok, \
            f"stream {i} truncated: {len(toks)}/{n_tok}"
        assert reason == "length"
    # and the new version serves fresh requests
    out = ray_tpu.get(h.remote({"tokens": [9, 9], "max_new_tokens": 2}),
                      timeout=60.0)
    assert out["n_tokens"] == 2


def test_serve_metrics_and_prometheus_llm_gauges(llm_cluster):
    """Autoscaler-signal satellite: the controller aggregates engine
    telemetry per deployment and /metrics exports the
    ``ray_tpu_serve_llm_*`` gauges."""
    import urllib.request

    from ray_tpu.dashboard.dashboard import start_dashboard
    h = llm_cluster("llmmet", num_replicas=1, max_concurrent_queries=8)
    for i in range(3):
        ray_tpu.get(h.remote({"tokens": [1, 2, 3, 4],
                              "max_new_tokens": 6}), timeout=60.0)

    def llm_agg():
        m = serve.metrics().get("llmmet") or {}
        return m.get("llm")

    deadline = time.time() + 15.0
    agg = None
    while time.time() < deadline:
        agg = llm_agg()
        if agg and agg.get("generated_tokens_total", 0) >= 18:
            break
        time.sleep(0.5)
    assert agg, "controller never aggregated llm telemetry"
    assert agg["generated_tokens_total"] >= 18
    assert agg["kv_blocks_total"] > 0
    assert "tokens_per_s" in agg and "kv_occupancy" in agg

    port = start_dashboard(port=18475)
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=15).read().decode()
    for gauge in ("ray_tpu_serve_llm_tokens_per_s",
                  "ray_tpu_serve_llm_kv_occupancy",
                  "ray_tpu_serve_llm_running_sequences",
                  "ray_tpu_serve_llm_waiting_sequences",
                  "ray_tpu_serve_llm_generated_tokens_total"):
        assert f'{gauge}{{deployment="llmmet"}}' in text, gauge


def test_trace_spans_cover_prefill_decode_kv(llm_cluster):
    """Tracing satellite: a sampled request's trace decomposes into
    the engine's phase spans (prefill + decode at minimum; kv_alloc
    and queue appear when they take measurable time), all parented
    into the request's span tree."""
    from ray_tpu._private import tracing
    from ray_tpu.experimental.state import api as state_api
    h = llm_cluster("llmtr", num_replicas=1, max_concurrent_queries=8,
                    model_config={"per_seq_delay_s": 0.005})
    rid = "trace-llm-1"
    st = h.stream({"tokens": [3, 1, 4, 1, 5], "max_new_tokens": 8},
                  request_id=rid)
    toks = [t for ch in st for t in ch["tokens"]]
    assert len(toks) == 8

    spans = None
    deadline = time.time() + 10.0
    while time.time() < deadline:
        doc = state_api.get_trace(rid)
        spans = doc.get("spans") or []
        names = {s["name"].split(":")[0] for s in spans}
        if {"llm.prefill", "llm.decode"} <= names:
            break
        time.sleep(0.5)
    names = {s["name"].split(":")[0] for s in spans}
    assert {"llm.prefill", "llm.decode"} <= names, sorted(names)
    ok, detail = tracing.tree_complete(spans)
    assert ok, detail
    decode = next(s for s in spans
                  if s["name"].startswith("llm.decode"))
    assert decode["attrs"]["tokens"] == 8
    assert decode["phase"] == "execute"


# -------------------------------------------- subprocess isolation tests


def _run_script(script, extra_env=None, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RTPU_PRESTART_WORKERS="0")
    env.update(extra_env or {})
    return subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True,
                          timeout=timeout, cwd=REPO_ROOT)


def test_mid_stream_replica_kill_is_clean_never_truncated():
    """Chaos satellite: a replica SIGKILLed mid-stream (seeded chaos,
    serve.replica.request op=kill) must surface as StreamBrokenError
    (or a retried-whole, full-length stream) — never a silently short
    token list presented as success."""
    script = r"""
import json, sys, time
import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.exceptions import StreamBrokenError
from ray_tpu.serve.llm import LLMServer

ray_tpu.init(num_cpus=4, object_store_memory=128*1024*1024,
             _system_config={"prestart_workers": False})
dep = serve.deployment(name="llmkill", num_replicas=1,
                       max_concurrent_queries=16)(LLMServer)
h = serve.run(dep.bind("toy", {"per_seq_delay_s": 0.03},
                       {"num_blocks": 128, "block_size": 8,
                        "max_seq_len": 256}),
              http_port=None, _blocking_timeout=120.0)
n_tok = 50
verdict = None
try:
    st = h.stream({"tokens": [1, 2, 3], "max_new_tokens": n_tok},
                  request_id="kill-1")
    toks = []
    for ch in st:   # the poll that trips the chaos counter kills the
        toks += ch["tokens"]  # replica under us
    # stream completed: only acceptable at FULL length
    verdict = {"outcome": "complete", "n": len(toks), "want": n_tok}
except StreamBrokenError as e:
    verdict = {"outcome": "broken", "tokens_so_far": e.tokens_so_far}
except Exception as e:
    verdict = {"outcome": "other", "error": repr(e)}
print("VERDICT=" + json.dumps(verdict))
serve.shutdown(); ray_tpu.shutdown()
"""
    # the replica dies at its 8th accepted request: the open + a few
    # polls land first, then a poll hits the counter mid-generation
    chaos = {"seed": 11, "schedule": [
        {"site": "serve.replica.request", "op": "kill", "at": 8,
         "method": "llmkill", "proc": "worker"}]}
    r = _run_script(script, {"RTPU_CHAOS": json.dumps(chaos)})
    assert r.returncode == 0, r.stdout + r.stderr
    line = [ln for ln in r.stdout.splitlines()
            if ln.startswith("VERDICT=")]
    assert line, r.stdout + r.stderr
    v = json.loads(line[0][len("VERDICT="):])
    if v["outcome"] == "complete":
        assert v["n"] == v["want"], f"silent truncation: {v}"
    else:
        assert v["outcome"] == "broken", v


def test_llm_chat_gameday_reconciles_per_token():
    """The llm-chat game day (satellite): heavy-tail streaming load +
    a rolling update, graded outside-in — zero failed requests and an
    exact per-token client/engine reconciliation."""
    script = r"""
import json
from ray_tpu.gameday.runner import run_scenario
from ray_tpu.gameday.scenario import load_scenario
res = run_scenario(load_scenario("llm-chat"), scale=0.4,
                   dashboard_port=18476)
out = {
    "passed": res.passed,
    "failed": res.report["overall"]["failed"],
    "admitted": res.report["overall"]["admitted"],
    "llm": res.report.get("llm"),
    "checks": {c["name"]: c["ok"]
               for c in res.reconciliation.get("checks", [])},
    "details": [c for c in res.reconciliation.get("checks", [])
                if not c["ok"]],
}
print("GAMEDAY=" + json.dumps(out))
"""
    r = _run_script(script, timeout=300)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    line = [ln for ln in r.stdout.splitlines()
            if ln.startswith("GAMEDAY=")]
    assert line, r.stdout + r.stderr
    out = json.loads(line[0][len("GAMEDAY="):])
    assert out["failed"] == 0, out
    assert out["admitted"] > 30, out
    assert out["checks"].get("llm-tokens") is True, out["details"]
    assert out["passed"], out["details"]
    assert out["llm"]["tokens_total"] > 100, out["llm"]


def test_bench_llm_smoke():
    """The `_BENCH_LLM=1` harness runs end to end in smoke mode and
    emits the gate numbers PERF.md records."""
    env = dict(os.environ, _BENCH_LLM="1", LLM_BENCH_SMOKE="1",
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "bench.py"], env=env,
                       capture_output=True, text=True, timeout=240,
                       cwd=REPO_ROOT)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    assert "continuous_tokens_per_s" in r.stdout, r.stdout[-2000:]
    assert "paged_kernel_max_err" in r.stdout, r.stdout[-2000:]
