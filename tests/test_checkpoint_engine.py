"""Checkpoint engine (ray_tpu.checkpoint): atomic commit, retention,
async sharded saves, failure paths, and gang-restart integration.

The failure-path coverage mirrors the preemptible-pod story: a save
killed mid-write must never become restorable, and the gang must restart
from the newest *intact* step."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ray_tpu.air.checkpoint import Checkpoint, ShardedCheckpoint
from ray_tpu.checkpoint import (AsyncCheckpointer, CheckpointManager,
                                PendingCheckpoint)
from ray_tpu.checkpoint import async_checkpointer as ac_mod
from ray_tpu.checkpoint.manager import COMMIT_MARKER, MANIFEST_NAME


def _state(seed=0, n=256):
    rng = np.random.default_rng(seed)
    return {"params": {"w": rng.standard_normal(n).astype(np.float32),
                       "b": rng.standard_normal(4).astype(np.float32)},
            "step": np.asarray(seed, np.int32)}


# ------------------------------------------------------------- manager core


def test_atomic_commit_layout_and_load(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "root"))
    for step in range(3):
        mgr.stage(step, Checkpoint.from_dict({"step": step}))
        mgr.commit_step(step)
    assert mgr.committed_steps() == [0, 1, 2]
    assert mgr.latest_committed() == 2
    sdir = mgr.step_dir(2)
    assert os.path.exists(os.path.join(sdir, COMMIT_MARKER))
    with open(os.path.join(sdir, MANIFEST_NAME)) as f:
        manifest = json.load(f)
    assert "checkpoint.pkl" in manifest["files"]
    assert manifest["files"]["checkpoint.pkl"]["bytes"] > 0
    assert mgr.load().to_dict() == {"step": 2}
    assert mgr.load(1).to_dict() == {"step": 1}


def test_latest_committed_skips_partial_and_uncommitted(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "root"))
    mgr.stage(1, Checkpoint.from_dict({"step": 1}))
    mgr.commit_step(1)
    # a save that died mid-write: staged files, never committed
    tmp2 = mgr.begin_step(2)
    (tmp_path / "root").joinpath(os.path.basename(tmp2))  # exists
    with open(os.path.join(tmp2, "half_written.npy"), "wb") as f:
        f.write(b"\x00" * 128)
    # a save that died between rename and COMMIT: step dir, no marker
    os.makedirs(mgr.step_dir(3))
    with open(os.path.join(mgr.step_dir(3), "checkpoint.pkl"), "wb") as f:
        f.write(b"torn")
    assert mgr.latest_committed() == 1
    assert mgr.load().to_dict() == {"step": 1}
    with pytest.raises(FileNotFoundError):
        mgr.load(3)


def test_checksum_mismatch_detection(tmp_path, monkeypatch):
    mgr = CheckpointManager(str(tmp_path / "root"))
    for step in (1, 2):
        mgr.stage(step, Checkpoint.from_dict({"step": step}))
        mgr.commit_step(step)
    # flip bytes in step 2's payload without changing its size
    victim = os.path.join(mgr.step_dir(2), "checkpoint.pkl")
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xff\xff\xff")
    assert mgr.verify_step(1)
    assert not mgr.verify_step(2)
    # without verification the corrupt step still resolves…
    assert mgr.latest_committed() == 2
    # …with RTPU_CKPT_VERIFY=1 it is skipped and refuses to load
    monkeypatch.setenv("RTPU_CKPT_VERIFY", "1")
    assert mgr.latest_committed() == 1
    with pytest.raises(FileNotFoundError):
        mgr.load(2)


def test_retention_num_to_keep_and_keep_every_k(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "root"), num_to_keep=2,
                            keep_every_k=3)
    for step in range(7):
        mgr.stage(step, Checkpoint.from_dict({"step": step}))
        mgr.commit_step(step)
    # newest 2 = {5, 6}; every-3rd milestones = {0, 3, 6}
    assert mgr.committed_steps() == [0, 3, 5, 6]


def test_retention_from_checkpoint_config(tmp_path):
    from ray_tpu.air.config import CheckpointConfig
    cfg = CheckpointConfig(num_to_keep=1, keep_every_k=0)
    mgr = CheckpointManager(str(tmp_path / "root"), checkpoint_config=cfg)
    for step in range(3):
        mgr.stage(step, Checkpoint.from_dict({"step": step}))
        mgr.commit_step(step)
    assert mgr.committed_steps() == [2]


# ------------------------------------------------------- async checkpointer


def test_async_save_commit_restore_and_stats(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "root"), num_to_keep=2)
    ck = AsyncCheckpointer(mgr)  # single process: self-committing
    for step in range(3):
        pending = ck.save(step, _state(step))
        assert isinstance(pending, PendingCheckpoint)
        assert pending.step == step
    ck.finalize()
    assert mgr.latest_committed() == 2
    assert mgr.committed_steps() == [1, 2]  # retention applied
    restored = mgr.restore_state(_state(99))
    np.testing.assert_array_equal(restored["params"]["w"],
                                  _state(2)["params"]["w"])
    assert int(restored["step"]) == 2
    stats = ck.stats
    assert len(stats) == 3
    for st in stats:
        assert st.error is None and st.committed
        assert st.bytes > 0 and st.files > 0
        assert st.snapshot_ms >= 0 and st.write_ms > 0
        # async: the train thread never pays for write/commit
        assert st.blocked_ms <= st.snapshot_ms + st.backpressure_ms + 50


def test_kill_mid_write_previous_step_survives(tmp_path, monkeypatch):
    """A save that dies mid-write leaves latest_committed() on the
    previous intact step, and the engine recovers on the next save."""
    mgr = CheckpointManager(str(tmp_path / "root"))
    ck = AsyncCheckpointer(mgr)
    ck.save(0, _state(0))
    ck.wait()
    assert mgr.latest_committed() == 0

    real_write = ac_mod.write_host_snapshot

    def dying_write(pdir, entries):
        # emulate SIGKILL mid-file: half the entries land, then death
        real_write(pdir, entries[: len(entries) // 2])
        raise OSError("killed mid-write")

    monkeypatch.setattr(ac_mod, "write_host_snapshot", dying_write)
    ck.save(1, _state(1))
    with pytest.raises(RuntimeError, match="killed mid-write"):
        ck.wait()
    assert mgr.latest_committed() == 0  # torn step never visible
    assert os.path.isdir(mgr.tmp_dir(1))  # debris, not a checkpoint
    np.testing.assert_array_equal(
        mgr.restore_state(_state(9))["params"]["w"],
        _state(0)["params"]["w"])

    monkeypatch.setattr(ac_mod, "write_host_snapshot", real_write)
    ck.save(2, _state(2))
    ck.finalize()
    assert mgr.latest_committed() == 2
    assert not os.path.isdir(mgr.tmp_dir(1))  # debris reaped by retention


def test_backpressure_single_inflight(tmp_path, monkeypatch):
    """A second save blocks until the first write lands (bounded host
    memory), and the wait is accounted as backpressure."""
    import threading
    import time as _time

    mgr = CheckpointManager(str(tmp_path / "root"))
    ck = AsyncCheckpointer(mgr)
    real_write = ac_mod.write_host_snapshot
    gate = threading.Event()

    def slow_write(pdir, entries):
        gate.wait(5.0)
        return real_write(pdir, entries)

    monkeypatch.setattr(ac_mod, "write_host_snapshot", slow_write)
    ck.save(0, _state(0))
    t0 = _time.perf_counter()
    releaser = threading.Timer(0.3, gate.set)
    releaser.start()
    ck.save(1, _state(1))  # must wait for save 0 to clear
    waited = _time.perf_counter() - t0
    ck.finalize()
    releaser.cancel()
    assert waited >= 0.25
    assert ck.stats[1].backpressure_ms >= 200
    assert mgr.latest_committed() == 1


def test_restore_onto_different_process_count(tmp_path):
    """State written by a 2-process gang (each process owning half the
    rows) restores in a single process: shards are keyed by global index
    slices, not ranks."""
    full = np.arange(32, dtype=np.float32).reshape(8, 4)
    mgr = CheckpointManager(str(tmp_path / "root"))
    tmp = mgr.begin_step(0)
    for pidx, sl in ((0, slice(0, 4)), (1, slice(4, 8))):
        entries = [{"key": "params/w",
                    "data": full[sl],
                    "index": [[sl.start, sl.stop, None],
                              [None, None, None]],
                    "shape": list(full.shape), "dtype": "float32"}]
        if pidx == 0:  # host-replicated leaf: owner writes once
            entries.append({"key": "step", "data": np.asarray(7, np.int32),
                            "index": None, "shape": [],
                            "dtype": "int32"})
        ac_mod.write_host_snapshot(
            os.path.join(tmp, f"process_{pidx}"), entries)
    mgr.commit_step(0)
    target = {"params": {"w": np.zeros_like(full)},
              "step": np.asarray(0, np.int32)}
    restored = mgr.restore_state(target)
    np.testing.assert_array_equal(restored["params"]["w"], full)
    assert int(restored["step"]) == 7


def test_sharded_save_dedups_replicated_leaves(tmp_path):
    """On a mesh, fully-replicated leaves produce exactly one shard file
    (replica_id==0), not one per device."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices("cpu")[:4]
    mesh = Mesh(np.array(devs), ("dp",))
    sharded = jax.device_put(np.arange(8, dtype=np.float32),
                             NamedSharding(mesh, P("dp")))
    replicated = jax.device_put(np.ones(3, np.float32),
                                NamedSharding(mesh, P()))
    state = {"w": sharded, "scale": replicated}
    root = str(tmp_path / "sharded")
    ShardedCheckpoint(root).save(state, process_index=0)
    names = sorted(os.listdir(os.path.join(root, "process_0")))
    assert names == ["manifest.json", "scale__shard0.npy",
                     "w__shard0.npy", "w__shard1.npy",
                     "w__shard2.npy", "w__shard3.npy"]
    # restore reassembles onto a *different* layout (plain host arrays)
    out = ShardedCheckpoint(root).restore(
        {"w": np.zeros(8, np.float32), "scale": np.zeros(3, np.float32)})
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.arange(8, dtype=np.float32))
    np.testing.assert_array_equal(np.asarray(out["scale"]),
                                  np.ones(3, np.float32))


# -------------------------------------------------------- satellite fixes


def test_to_dict_on_sharded_directory(tmp_path):
    """to_dict() on a directory with process_<i>/ subdirs flattens to
    relative-path keys instead of raising IsADirectoryError."""
    root = tmp_path / "ckpt"
    (root / "process_0").mkdir(parents=True)
    (root / "process_0" / "manifest.json").write_bytes(b"[]")
    (root / "meta.txt").write_bytes(b"hello")
    out = Checkpoint.from_directory(str(root)).to_dict()
    assert out == {"meta.txt": b"hello",
                   os.path.join("process_0", "manifest.json"): b"[]"}


def test_to_directory_crash_safe(tmp_path, monkeypatch):
    dst = str(tmp_path / "out")
    import pickle as _pickle

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(_pickle, "dump", boom)
    with pytest.raises(OSError):
        Checkpoint.from_dict({"x": 1}).to_directory(dst)
    monkeypatch.undo()
    # a failed materialization leaves nothing at the target, and no
    # staging debris in the parent
    assert not os.path.exists(dst)
    assert [n for n in os.listdir(tmp_path) if n.startswith(".out")] == []
    # success path: atomic swap, including over an existing directory
    assert Checkpoint.from_dict({"x": 1}).to_directory(dst) == dst
    Checkpoint.from_dict({"x": 2}).to_directory(dst)
    assert Checkpoint.from_directory(dst).to_dict() == {"x": 2}


def test_deterministic_shard_filenames(tmp_path):
    state = {"layer/0": {"w": np.ones((2, 2), np.float32)},
             "b": np.zeros(3, np.float32)}
    r1, r2 = str(tmp_path / "a"), str(tmp_path / "b")
    ShardedCheckpoint(r1).save(state, process_index=0)
    ShardedCheckpoint(r2).save(state, process_index=0)
    n1 = sorted(os.listdir(os.path.join(r1, "process_0")))
    n2 = sorted(os.listdir(os.path.join(r2, "process_0")))
    assert n1 == n2  # no per-process hash salt
    assert "b__full.npy" in n1 and "layer_0_w__full.npy" in n1


# --------------------------------------------------- trainer integration


@pytest.fixture(scope="module")
def ckpt_cluster():
    import ray_tpu
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_gang_restart_resumes_from_latest_committed(ckpt_cluster, tmp_path):
    """End-to-end acceptance: checkpoints flow through session.report →
    manager staging → driver commit; a worker that stages a *partial*
    step and dies mid-save restarts the gang from the previous committed
    step, and numbering continues past it."""
    from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train import DataParallelTrainer

    def train_fn(config):
        from ray_tpu.air import session
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["i"] + 1 if ckpt else 0
        for i in range(start, 5):
            session.report({"i": i},
                           checkpoint=Checkpoint.from_dict({"i": i}))
            if i == 2 and start == 0:
                # die mid-save: the *next* step is half-staged (no commit
                # can ever happen for it), then the worker crashes
                mgr = session.get_checkpoint_manager()
                tmp = mgr.begin_step(session.next_checkpoint_step())
                with open(os.path.join(tmp, "half.npy"), "wb") as f:
                    f.write(b"\x00" * 64)
                raise RuntimeError("preempted mid-save")

    run_config = RunConfig(
        name="gang_restart_ckpt", storage_path=str(tmp_path),
        failure_config=FailureConfig(max_failures=1))
    trainer = DataParallelTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1),
        run_config=run_config)
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["i"] == 4
    # the committed root: steps 0..2 from attempt 1 (partial step 3
    # reaped), then the resumed attempt continues the numbering
    root = os.path.join(str(tmp_path), "gang_restart_ckpt", "checkpoints")
    mgr = CheckpointManager(root)
    latest = mgr.latest_committed()
    assert latest is not None
    assert mgr.load(latest).to_dict() == {"i": 4}
    # the final checkpoint handed back is directory-backed + committed
    assert result.checkpoint is not None
    assert result.checkpoint.to_dict() == {"i": 4}

    # a fresh trainer with the same run identity auto-resumes — and the
    # train_fn (which stops at 5) has nothing left to do
    trainer2 = DataParallelTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="gang_restart_ckpt",
                             storage_path=str(tmp_path)))
    r2 = trainer2.fit()
    assert r2.error is None
    assert r2.checkpoint.to_dict() == {"i": 4}


def test_async_checkpointer_through_session(ckpt_cluster, tmp_path):
    """train_fn drives an AsyncCheckpointer for sharded state; the driver
    commits the step after the round barrier and the result resolves to
    the committed directory."""
    from ray_tpu.air.config import RunConfig, ScalingConfig
    from ray_tpu.train import DataParallelTrainer

    def train_fn(config):
        import numpy as _np
        from ray_tpu.air import session
        ckpter = session.get_async_checkpointer()
        assert ckpter is not None
        for i in range(3):
            state = {"w": _np.full(64, float(i), _np.float32)}
            pending = ckpter.save(session.next_checkpoint_step(), state)
            session.report({"i": i}, checkpoint=pending)
        ckpter.finalize()

    trainer = DataParallelTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="async_session_ckpt",
                             storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    root = os.path.join(str(tmp_path), "async_session_ckpt", "checkpoints")
    mgr = CheckpointManager(root)
    assert mgr.latest_committed() == 2
    restored = mgr.restore_state({"w": np.zeros(64, np.float32)})
    np.testing.assert_array_equal(restored["w"],
                                  np.full(64, 2.0, np.float32))
    # result checkpoint points at the committed step dir
    assert result.checkpoint is not None
    assert os.path.basename(result.checkpoint._dir).endswith("00000002")


# ----------------------------------------------------------- bench smoke


def test_bench_ckpt_smoke():
    """Tier-1 acceptance gate: async save blocks the train loop for
    < 25% of the sync save wall time on the _BENCH_CKPT workload."""
    env = dict(os.environ, _BENCH_CKPT="1", JAX_PLATFORMS="cpu",
               BENCH_CKPT_MB="16", BENCH_CKPT_SAVES="3",
               BENCH_CKPT_STEP_MS="200")
    bench = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    proc = subprocess.run([sys.executable, bench], stdout=subprocess.PIPE,
                          text=True, timeout=120, env=env)
    row = None
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.strip().startswith("{"):
            row = json.loads(line)
            break
    assert row is not None, proc.stdout
    assert row.get("metric") == "checkpoint", row
    assert row["blocked_frac_vs_sync"] < 0.25, row
    assert row["async_blocked_ms_per_save"] < row["sync_blocked_ms_per_save"]
