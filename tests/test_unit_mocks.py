"""Unit tests over the interface-mock layer — no clusters, no sockets.

Reference analogue: the C++ unit suites under ``src/ray/*/test`` built
on ``src/mock/ray/**`` gmock doubles (SURVEY §4: components test in
isolation against mock interfaces). These cover logic that the
integration suite can only reach statistically: actor-call ordering,
pull admission, wire-schema validation, version negotiation.
"""

import asyncio

import pytest

from ray_tpu._private import schema
from ray_tpu._private.testing import MockConnection, MockStore, make_bare
from ray_tpu.common.ids import ObjectID


# ------------------------------------------------------------- wire schema

def test_schema_validate_good_and_bad():
    assert schema.validate("resource_report", {
        "node_id": "n1", "available": {"CPU": 1.0}}) == []
    errs = schema.validate("resource_report", {"available": "nope"})
    assert any("node_id" in e and "missing" in e for e in errs)
    assert any("available" in e and "expected" in e for e in errs)
    # unknown fields pass (proto3 forward-compat rule)
    assert schema.validate("kv_get", {"key": "k", "future_field": 1}) == []
    # unknown methods pass through
    assert schema.validate("not_a_method", {"x": 1}) == []


def test_schema_hello_negotiation():
    assert schema.check_hello(schema.hello_payload()) is None
    bad = {"protocol_version": [schema.PROTOCOL_VERSION[0] + 1, 0]}
    assert "incompatible" in schema.check_hello(bad)
    # minor skew is compatible
    minor = {"protocol_version": [schema.PROTOCOL_VERSION[0], 99]}
    assert schema.check_hello(minor) is None
    assert len(schema.schema_hash()) == 16


def test_server_rejects_invalid_payload_when_enabled(monkeypatch):
    from ray_tpu._private import protocol
    monkeypatch.setenv("RTPU_VALIDATE_WIRE", "1")
    async def kv_get(payload, conn):
        return {"value": None}

    server = protocol.Server({"kv_get": kv_get})

    async def drive():
        with pytest.raises(protocol.RpcError, match="wire schema"):
            await server._handle("kv_get", {"wrong": 1}, None)
        # __hello__ negotiates without a registered handler
        reply = await server._handle(
            "__hello__", schema.hello_payload(), None)
        assert reply["schema_hash"] == schema.schema_hash()

    asyncio.run(drive())


# ----------------------------------------------------- actor-call ordering

def _bare_receiver():
    from ray_tpu._private.worker import Worker
    return make_bare(Worker, _actor_seq={}, _actor_waiting={})


def test_ordering_parks_until_predecessor():
    w = _bare_receiver()
    order = []

    async def handler(seq, upto=0):
        await w._order_actor_call("c", seq, upto)
        order.append(seq)
        w._release_actor_call("c", seq)

    async def drive():
        # seq 3 and 2 arrive before 1: both park; 1 unlocks the chain
        t3 = asyncio.create_task(handler(3))
        t2 = asyncio.create_task(handler(2))
        await asyncio.sleep(0.05)
        assert order == []
        await handler(1)
        await asyncio.gather(t2, t3)

    asyncio.run(drive())
    assert order == [1, 2, 3]


def test_ordering_fast_forwards_on_processed_up_to():
    w = _bare_receiver()
    done = []

    async def drive():
        # fresh receiver (actor restart): first arrival has seq 42 but
        # advertises 41 already processed — dispatch immediately
        await asyncio.wait_for(
            w._order_actor_call("c", 42, processed_up_to=41), timeout=1)
        done.append(42)
        assert w._actor_seq["c"] == 42

    asyncio.run(drive())
    assert done == [42]


def test_ordering_duplicate_dispatches_immediately():
    w = _bare_receiver()

    async def drive():
        await w._order_actor_call("c", 1, 0)
        w._release_actor_call("c", 1)
        # a retry of seq 1 must not park behind itself
        await asyncio.wait_for(w._order_actor_call("c", 1, 0), timeout=1)

    asyncio.run(drive())


# --------------------------------------------------------- pull admission

def test_pull_admission_caps_inflight_bytes():
    from ray_tpu._private.raylet import Raylet
    from ray_tpu.common.config import SystemConfig

    MB = 1024 * 1024
    store = MockStore(capacity=100 * MB)
    r = make_bare(Raylet, store=store, _pull_inflight_bytes=0,
                  _pull_waiters=None,
                  config=SystemConfig(pull_admission_fraction=0.5))
    acquired = []

    async def drive():
        a = await r._admit_pull(30 * MB)   # budget = 50 MB
        acquired.append(a)
        b_task = asyncio.create_task(r._admit_pull(30 * MB))  # exceeds
        await asyncio.sleep(0.05)
        assert not b_task.done()      # blocked on the budget
        await r._release_pull(a)
        acquired.append(await asyncio.wait_for(b_task, timeout=1))
        await r._release_pull(acquired[-1])
        # one object larger than the whole budget still admits (clamped)
        c = await asyncio.wait_for(r._admit_pull(10_000 * MB), timeout=1)
        assert c <= 50 * MB
        await r._release_pull(c)

    asyncio.run(drive())
    assert acquired == [30 * MB, 30 * MB]
    assert r._pull_inflight_bytes == 0


# ------------------------------------------------------------------ mocks

def test_mock_connection_records_and_scripts():
    conn = MockConnection({"ping": "pong",
                           "echo": lambda p: {"got": p}})

    async def drive():
        assert await conn.call("ping") == "pong"
        assert await conn.call("echo", {"x": 1}) == {"got": {"x": 1}}
        await conn.notify("fire", {"y": 2})

    asyncio.run(drive())
    assert conn.calls_to("ping") == [None]
    assert conn.notifications == [("fire", {"y": 2})]


def test_mock_store_plasma_surface():
    from ray_tpu.exceptions import ObjectStoreFullError
    store = MockStore(capacity=10)
    oid = ObjectID.from_random()
    store.put_bytes(oid, b"12345")
    assert store.contains(oid)
    buf = store.get_buffer(oid)
    assert bytes(buf) == b"12345"
    store.release(oid)
    with pytest.raises(ObjectStoreFullError):
        store.create(ObjectID.from_random(), 6)
    assert store.delete(oid)
    assert not store.contains(oid)


# ------------------------------------------------------------ usage stats

def test_usage_stats_opt_in(tmp_path, monkeypatch):
    from ray_tpu._private import usage
    monkeypatch.delenv("RTPU_USAGE_STATS_ENABLED", raising=False)
    assert usage.write_report(str(tmp_path)) is None  # opt-in: off

    monkeypatch.setenv("RTPU_USAGE_STATS_ENABLED", "1")
    usage.record_library_usage("tune")
    path = usage.write_report(str(tmp_path), {"node_id": "n1"})
    import json
    doc = json.load(open(path))
    assert doc["schema_version"] == 1
    assert "tune" in doc["libraries_used"]
    assert doc["node_id"] == "n1"
    assert doc["python_version"]
