"""SPMD train-step + driver entry tests (8-device virtual CPU mesh)."""

import jax
import numpy as np
import pytest


def test_causal_lm_trainer_multiaxis(cpu_mesh8):
    from ray_tpu.models.gpt2 import GPT2Config
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train.spmd import make_causal_lm_trainer, put_batch

    spec = MeshSpec(dp=2, sp=2, tp=2)
    mesh = spec.build(jax.devices("cpu")[:8])
    import dataclasses
    cfg = dataclasses.replace(GPT2Config.tiny(), n_embd=128, n_head=4,
                              attention_backend="reference")
    tr = make_causal_lm_trainer(cfg, mesh=mesh, spec=spec)
    state = tr.init(jax.random.PRNGKey(0))
    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (4, 32), dtype=np.int32)
    batch = put_batch(tr, {"input_ids": tokens, "labels": tokens})
    losses = []
    for _ in range(3):
        state, m = tr.step(state, batch)
        losses.append(float(jax.device_get(m["loss"])))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], f"loss did not fall: {losses}"
    # params actually sharded: at least one leaf is not fully replicated
    shardings = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda x: x.sharding, state["params"]))
    assert any(not s.is_fully_replicated for s in shardings)


def test_image_trainer_dp(cpu_mesh8):
    from ray_tpu.models.resnet import create_resnet
    from ray_tpu.parallel.mesh import MeshSpec
    from ray_tpu.train.spmd import make_image_classifier_trainer, put_batch

    spec = MeshSpec(dp=8)
    mesh = spec.build(jax.devices("cpu")[:8])
    import jax.numpy as jnp
    model = create_resnet("resnet18", num_classes=10, small_images=True,
                          dtype=jnp.float32)
    tr = make_image_classifier_trainer(model, mesh=mesh, spec=spec,
                                       input_shape=(1, 32, 32, 3))
    state = tr.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    batch = put_batch(tr, {
        "image": rng.standard_normal((16, 32, 32, 3)).astype(np.float32),
        "label": rng.integers(0, 10, (16,), dtype=np.int32)})
    state, m = tr.step(state, batch)
    assert np.isfinite(float(jax.device_get(m["loss"])))


def test_graft_entry_shapes():
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
    import __graft_entry__ as g
    fn, args = g.entry()
    out = jax.eval_shape(fn, *args)
    assert out.shape == (4, 512, 50257)


def test_graft_dryrun_8():
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
    import __graft_entry__ as g
    g.dryrun_multichip(8)
