"""Wire-format conformance vectors (docs/WIRE_PROTOCOL.md).

Pins the byte-exact framing a second-language client implements — the
JVM-less stand-in for a Java worker conformance suite (the C++ client
in src/cpp_client implements the same bytes; reference analogue: the
protobuf golden files a .proto change would break).

Every golden vector runs against BOTH framer implementations
(WIRE_PROTOCOL.md "Implementations"): the Python asyncio one
(protocol.pack_frame) and the native pump (src/rpccore/ via
ray_tpu/_private/rpccore.py) — the native check pushes the raw vector
bytes through a real pump socket in both directions and asserts the
on-wire bytes are identical.
"""

import os
import socket
import struct
import tempfile

import msgpack
import pytest

from ray_tpu._private import protocol, rpccore, schema


def _native_listener(pump, transport: str):
    """Bind the pump on the requested transport; returns a connected
    raw client socket and the unix path to unlink (or None)."""
    if transport == "tcp":
        port = pump.listen_tcp("127.0.0.1", 0)
        raw = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        raw.connect(("127.0.0.1", port))
        return raw, None
    path = tempfile.mktemp(suffix=".sock")
    pump.listen(path)
    raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    raw.connect(path)
    return raw, path


def _native_roundtrip(frame: bytes, transport: str = "unix") -> None:
    """Assert the native pump (a) delivers exactly the vector's body
    when the vector's bytes arrive on the wire and (b) produces exactly
    the vector's bytes when asked to send that body — over the unix
    listener or the 1.8 TCP listener (identical framing either way)."""
    if rpccore._lib() is None:
        pytest.skip("native rpc library unavailable on this host")
    pump = rpccore.Pump()
    raw, path = _native_listener(pump, transport)
    try:
        raw.settimeout(10)
        # wire -> pump: the pump must deframe to exactly the body
        raw.sendall(frame)
        evs = []
        for _ in range(100):
            evs = pump.next_batch(timeout_ms=200)
            if evs:
                break
        assert evs and evs[0][1] == rpccore.KIND_FRAME
        cid, _, body = evs[0]
        assert body == frame[4:]
        # pump -> wire: sending the body must produce the exact frame
        assert pump.send(cid, body)
        got = b""
        while len(got) < len(frame):
            got += raw.recv(len(frame) - len(got))
        assert got == frame
    finally:
        raw.close()
        pump.shutdown()
        pump.destroy()
        if path is not None and os.path.exists(path):
            os.unlink(path)


_IMPLS = ["python", "native", "native-tcp"]


def _check_vector(impl: str, body_list, hex_frame: str) -> None:
    frame = protocol.pack_frame(body_list)
    assert frame.hex() == hex_frame
    if impl == "native":
        _native_roundtrip(frame)
    elif impl == "native-tcp":
        _native_roundtrip(frame, transport="tcp")


@pytest.mark.parametrize("impl", _IMPLS)
def test_frame_layout_golden_vectors(impl):
    # NOTIFY task_done
    _check_vector(impl,
                  [protocol.NOTIFY, None, "task_done", {"task_id": "ab"}],
                  "19000000"  # uint32-le length 25
                  "9403c0a97461736b5f646f6e6581a77461736b5f6964a26162")
    # REQUEST seq=1 ping {}
    _check_vector(impl, [protocol.REQUEST, 1, "ping", {}],
                  "09000000940001a470696e6780")
    # REPLY seq=1 {"ok": true}
    _check_vector(impl, [protocol.REPLY, 1, "ping", {"ok": True}],
                  "0d000000940101a470696e6781a26f6bc3")


@pytest.mark.parametrize("impl", _IMPLS)
def test_dag_channel_frame_golden_vectors(impl):
    """Compiled-DAG channel frames (1.5; docs/WIRE_PROTOCOL.md §1.5 +
    docs/COMPILED_DAGS.md). They ride dedicated channel sockets but use
    the same framing, so a second-language stage implements these exact
    bytes."""
    from ray_tpu.dag.channel import pack_dag_frame
    frame = pack_dag_frame("dag_exec",
                           {"d": "ab.g1", "t": 0, "s": 1, "b": b"\x01"})
    assert frame.hex() == (
        "20000000"
        "9403c0a8"
        "6461675f6578656384a164a561622e6731a17400a17301a162c40101")
    if impl == "native":
        _native_roundtrip(frame)
    elif impl == "native-tcp":
        _native_roundtrip(frame, transport="tcp")
    frame = pack_dag_frame("dag_result", {"d": "ab.g1", "s": 1, "i": 0,
                                          "ae": False, "b": b"\x02"})
    assert frame.hex() == (
        "26000000"
        "9403c0aa6461675f726573756c7485a164a561622e6731"
        "a17301a16900a26165c2a162c40102")
    if impl == "native":
        _native_roundtrip(frame)
    elif impl == "native-tcp":
        _native_roundtrip(frame, transport="tcp")
    for method in ("dag_channel_open", "dag_channel_close",
                   "dag_register", "dag_unregister", "dag_stage_error",
                   "dag_peer_down", "dag_exec", "dag_result"):
        assert method in schema.SCHEMAS, method
    assert schema.PROTOCOL_VERSION >= (1, 5)


@pytest.mark.parametrize("impl", _IMPLS)
def test_leased_task_frame_both_framers(impl):
    """The direct-execution lane's hot frame (1.7): a leased_task
    REQUEST must be byte-identical from either implementation — the
    native pump frames the same msgpack body the asyncio path packs."""
    body = [protocol.REQUEST, 7, "leased_task",
            {"spec": {"task_id": "ab", "fn_key": "k"}}]
    frame = protocol.pack_frame(body)
    (n,) = struct.unpack("<I", frame[:4])
    assert n == len(frame) - 4
    assert msgpack.unpackb(frame[4:], raw=False) == [
        0, 7, "leased_task", {"spec": {"task_id": "ab", "fn_key": "k"}}]
    if impl == "native":
        _native_roundtrip(frame)
    elif impl == "native-tcp":
        _native_roundtrip(frame, transport="tcp")


@pytest.mark.parametrize("transport", ["unix", "tcp"])
def test_native_framer_rejects_oversized_frames(transport):
    """A length prefix above _MAX_FRAME is a protocol error in BOTH
    implementations and over BOTH listeners: read_frame raises, the
    native pump drops the connection."""
    if rpccore._lib() is None:
        pytest.skip("native rpc library unavailable on this host")
    pump = rpccore.Pump()
    raw, path = _native_listener(pump, transport)
    try:
        raw.sendall(struct.pack("<I", protocol._MAX_FRAME + 1) + b"x")
        evs = []
        for _ in range(100):
            evs = pump.next_batch(timeout_ms=200)
            if evs:
                break
        # the pump closes the peer instead of allocating 256MB+
        assert evs and evs[0][1] == rpccore.KIND_CLOSED
    finally:
        raw.close()
        pump.shutdown()
        pump.destroy()
        if path is not None and os.path.exists(path):
            os.unlink(path)


@pytest.mark.parametrize("transport", ["unix", "tcp"])
def test_native_framer_mid_frame_reset(transport):
    """A peer dying mid-frame (length prefix + partial body, then a
    hard close) must surface as exactly one KIND_CLOSED — never a
    truncated KIND_FRAME."""
    if rpccore._lib() is None:
        pytest.skip("native rpc library unavailable on this host")
    body = msgpack.packb([protocol.REQUEST, 1, "ping", {}],
                         use_bin_type=True)
    frame = struct.pack("<I", len(body)) + body
    pump = rpccore.Pump()
    raw, path = _native_listener(pump, transport)
    try:
        raw.sendall(frame[:len(frame) - 3])  # stop 3 bytes short
        if transport == "tcp":
            # RST instead of FIN: SO_LINGER 0 makes close() abortive
            raw.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                           struct.pack("ii", 1, 0))
        raw.close()
        evs = []
        for _ in range(100):
            evs = pump.next_batch(timeout_ms=200)
            if evs:
                break
        assert evs and evs[0][1] == rpccore.KIND_CLOSED
        assert all(kind != rpccore.KIND_FRAME for _, kind, _b in evs)
    finally:
        pump.shutdown()
        pump.destroy()
        if path is not None and os.path.exists(path):
            os.unlink(path)


def test_frame_roundtrip_and_length_prefix():
    body = [protocol.REQUEST, 7, "kv_get", {"key": b"\x00\x01"}]
    frame = protocol.pack_frame(body)
    (n,) = struct.unpack("<I", frame[:4])
    assert n == len(frame) - 4
    decoded = msgpack.unpackb(frame[4:], raw=False)
    assert decoded == [0, 7, "kv_get", {"key": b"\x00\x01"}]


def test_msg_type_constants_are_pinned():
    # a renumbering would break every deployed second-language client
    assert (protocol.REQUEST, protocol.REPLY, protocol.ERROR,
            protocol.NOTIFY) == (0, 1, 2, 3)
    assert protocol._MAX_FRAME == 256 * 1024 * 1024


def test_hello_negotiation_contract():
    hello = schema.hello_payload()
    assert hello["protocol_version"] == list(schema.PROTOCOL_VERSION)
    assert len(hello["schema_hash"]) == 16
    # same major, newer minor: compatible
    assert schema.check_hello(
        {"protocol_version": [schema.PROTOCOL_VERSION[0], 99],
         "schema_hash": "ffff"}) is None
    # different major: rejected
    assert schema.check_hello(
        {"protocol_version": [schema.PROTOCOL_VERSION[0] + 1, 0]})
    assert schema.check_hello({"protocol_version": "bogus"})


def test_schema_table_covers_worker_protocol_surface():
    """The methods docs/WIRE_PROTOCOL.md tells a second-language worker
    to implement must stay declared in the schema registry."""
    for method in ("submit_task", "submit_task_batch", "leased_task",
                   "task_done", "cancel_task", "actor_call",
                   "pull_object", "receive_push", "kv_put", "kv_get",
                   "lease_worker", "release_lease", "revoke_lease",
                   "profile_worker",
                   # worker lifecycle (WIRE_PROTOCOL.md "Worker
                   # protocol" section)
                   "worker_register", "push_task", "task_result",
                   "ping", "exit_worker"):
        assert method in schema.SCHEMAS, method
