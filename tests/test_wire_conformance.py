"""Wire-format conformance vectors (docs/WIRE_PROTOCOL.md).

Pins the byte-exact framing a second-language client implements — the
JVM-less stand-in for a Java worker conformance suite (the C++ client
in src/cpp_client implements the same bytes; reference analogue: the
protobuf golden files a .proto change would break).
"""

import struct

import msgpack

from ray_tpu._private import protocol, schema


def test_frame_layout_golden_vectors():
    # NOTIFY task_done
    frame = protocol.pack_frame(
        [protocol.NOTIFY, None, "task_done", {"task_id": "ab"}])
    assert frame.hex() == (
        "19000000"  # uint32-le length 25
        "9403c0a97461736b5f646f6e6581a77461736b5f6964a26162")
    # REQUEST seq=1 ping {}
    frame = protocol.pack_frame([protocol.REQUEST, 1, "ping", {}])
    assert frame.hex() == "09000000940001a470696e6780"
    # REPLY seq=1 {"ok": true}
    frame = protocol.pack_frame(
        [protocol.REPLY, 1, "ping", {"ok": True}])
    assert frame.hex() == "0d000000940101a470696e6781a26f6bc3"


def test_dag_channel_frame_golden_vectors():
    """Compiled-DAG channel frames (1.5; docs/WIRE_PROTOCOL.md §1.5 +
    docs/COMPILED_DAGS.md). They ride dedicated channel sockets but use
    the same framing, so a second-language stage implements these exact
    bytes."""
    from ray_tpu.dag.channel import pack_dag_frame
    frame = pack_dag_frame("dag_exec",
                           {"d": "ab.g1", "t": 0, "s": 1, "b": b"\x01"})
    assert frame.hex() == (
        "20000000"
        "9403c0a8"
        "6461675f6578656384a164a561622e6731a17400a17301a162c40101")
    frame = pack_dag_frame("dag_result", {"d": "ab.g1", "s": 1, "i": 0,
                                          "ae": False, "b": b"\x02"})
    assert frame.hex() == (
        "26000000"
        "9403c0aa6461675f726573756c7485a164a561622e6731"
        "a17301a16900a26165c2a162c40102")
    for method in ("dag_channel_open", "dag_channel_close",
                   "dag_register", "dag_unregister", "dag_stage_error",
                   "dag_peer_down", "dag_exec", "dag_result"):
        assert method in schema.SCHEMAS, method
    assert schema.PROTOCOL_VERSION >= (1, 5)


def test_frame_roundtrip_and_length_prefix():
    body = [protocol.REQUEST, 7, "kv_get", {"key": b"\x00\x01"}]
    frame = protocol.pack_frame(body)
    (n,) = struct.unpack("<I", frame[:4])
    assert n == len(frame) - 4
    decoded = msgpack.unpackb(frame[4:], raw=False)
    assert decoded == [0, 7, "kv_get", {"key": b"\x00\x01"}]


def test_msg_type_constants_are_pinned():
    # a renumbering would break every deployed second-language client
    assert (protocol.REQUEST, protocol.REPLY, protocol.ERROR,
            protocol.NOTIFY) == (0, 1, 2, 3)
    assert protocol._MAX_FRAME == 256 * 1024 * 1024


def test_hello_negotiation_contract():
    hello = schema.hello_payload()
    assert hello["protocol_version"] == list(schema.PROTOCOL_VERSION)
    assert len(hello["schema_hash"]) == 16
    # same major, newer minor: compatible
    assert schema.check_hello(
        {"protocol_version": [schema.PROTOCOL_VERSION[0], 99],
         "schema_hash": "ffff"}) is None
    # different major: rejected
    assert schema.check_hello(
        {"protocol_version": [schema.PROTOCOL_VERSION[0] + 1, 0]})
    assert schema.check_hello({"protocol_version": "bogus"})


def test_schema_table_covers_worker_protocol_surface():
    """The methods docs/WIRE_PROTOCOL.md tells a second-language worker
    to implement must stay declared in the schema registry."""
    for method in ("submit_task", "submit_task_batch", "leased_task",
                   "task_done", "cancel_task", "actor_call",
                   "pull_object", "receive_push", "kv_put", "kv_get",
                   "lease_worker", "release_lease", "revoke_lease",
                   "profile_worker",
                   # worker lifecycle (WIRE_PROTOCOL.md "Worker
                   # protocol" section)
                   "worker_register", "push_task", "task_result",
                   "ping", "exit_worker"):
        assert method in schema.SCHEMAS, method
