"""netx: the cross-node transport plane (tier-1).

Covers the 1.8 acceptance surface (docs/WIRE_PROTOCOL.md §1.8):

* endpoint registry unit behaviour — ``node_ip``/``host_of``/``pick``
  dial-side choice, the ``RTPU_NET_FORCE_TCP`` harness override;
* the ``px_*`` pull protocol against a miniature scripted pump server —
  full-object streaming, crc rejection, and stall-resume from the
  contiguous high-water mark (a dropped chunk is never papered over);
* a simulated two-"host" cluster (distinct ``RTPU_NODE_IP`` per raylet
  + ``RTPU_NET_FORCE_TCP``) where object pulls, direct-lane actor
  calls and compiled-DAG hops all cross the raylet boundary over TCP
  only;
* the ``net.partition`` chaos site — an asymmetric severance drops
  frames BEFORE the wire, so retries fall back and heal with no lost
  or duplicated invocation, and a ``px_chunk`` frame drop at the TCP
  boundary resumes instead of sealing a hole into plasma.
"""

import json
import os
import subprocess
import sys
import threading
import time
import zlib

import msgpack
import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import chaos, netx, protocol, rpccore
from ray_tpu._private.cluster_utils import Cluster
from ray_tpu._private.netx import endpoints
from ray_tpu.dag import InputNode


@pytest.fixture(autouse=True)
def _netx_hygiene():
    """Chaos config and cached node identity must not leak between
    tests (both ride env vars that every process spawn inherits)."""
    yield
    os.environ.pop("RTPU_CHAOS", None)
    os.environ.pop("RTPU_CHAOS_LOG", None)
    chaos.clear()
    netx.reset_client_for_tests()
    endpoints._reset_for_tests()


def _require_native():
    if rpccore._lib() is None:
        pytest.skip("native rpc library unavailable on this host")


# ------------------------------------------------------- endpoint registry


def test_host_of_and_endpoint_pick(monkeypatch):
    monkeypatch.delenv("RTPU_NET_FORCE_TCP", raising=False)
    monkeypatch.setenv("RTPU_NODE_IP", "10.0.0.7")
    endpoints._reset_for_tests()
    assert endpoints.node_ip() == "10.0.0.7"
    assert endpoints.host_of("/tmp/w.sock") == ""
    assert endpoints.host_of("unix:/tmp/w.sock") == ""
    assert endpoints.host_of("10.0.0.8:7001") == "10.0.0.8"
    assert endpoints.host_of("tcp:10.0.0.8:7001") == "10.0.0.8"
    # on-box peer (loopback or our own advertised IP): unix wins
    assert endpoints.pick("/tmp/w.sock", "127.0.0.1:7001") == "/tmp/w.sock"
    assert endpoints.pick("/tmp/w.sock", "10.0.0.7:7001") == "/tmp/w.sock"
    # off-box peer: the TCP endpoint
    assert endpoints.pick("/tmp/w.sock", "10.0.0.8:7001") == "10.0.0.8:7001"
    assert endpoints.pick("", "10.0.0.8:7001") == "10.0.0.8:7001"
    # degraded advertisements
    assert endpoints.pick("/tmp/w.sock", "") == "/tmp/w.sock"
    assert endpoints.pick(None, None) == ""
    # harness override: every peer is off-box, the TCP lane is exercised
    monkeypatch.setenv("RTPU_NET_FORCE_TCP", "1")
    assert endpoints.pick("/tmp/w.sock", "127.0.0.1:7001") == \
        "127.0.0.1:7001"


def test_node_ip_is_cached_per_process(monkeypatch):
    monkeypatch.setenv("RTPU_NODE_IP", "10.1.1.1")
    endpoints._reset_for_tests()
    assert endpoints.node_ip() == "10.1.1.1"
    # identity is read ONCE, like the rest of the node's identity
    monkeypatch.setenv("RTPU_NODE_IP", "10.2.2.2")
    assert endpoints.node_ip() == "10.1.1.1"
    endpoints._reset_for_tests()
    assert endpoints.node_ip() == "10.2.2.2"


def test_partition_spec_is_directional_and_heals():
    """The sustained-partition spec shape: fires on EVERY matching hit
    (at=1, every=1, max_fires=0) for one direction of one host pair,
    then ``until_s`` heals it."""
    e = chaos.ChaosEngine(seed=0, schedule=[
        {"site": "net.partition", "op": "partition", "at": 1, "every": 1,
         "max_fires": 0, "method": "a>b", "until_s": 0.3}])
    assert all(e.hit("net.partition", "a>b") for _ in range(5))
    assert e.hit("net.partition", "b>a") is None  # reverse stays up
    assert e.hit("net.partition", "a>c") is None  # other peers stay up
    time.sleep(0.35)
    assert e.hit("net.partition", "a>b") is None  # healed


def test_partitioned_gate(monkeypatch):
    monkeypatch.setenv("RTPU_NODE_IP", "127.0.0.1")
    endpoints._reset_for_tests()
    assert not endpoints.partitioned("127.0.0.2")  # no engine: no faults
    chaos.configure(seed=0, schedule=[
        {"site": "net.partition", "op": "partition", "at": 1, "every": 1,
         "max_fires": 0, "method": "127.0.0.1>127.0.0.2", "until_s": 30.0}])
    assert endpoints.partitioned("127.0.0.2")
    assert not endpoints.partitioned("127.0.0.3")
    assert not endpoints.partitioned("")  # unix peers have no host
    chaos.clear()
    assert not endpoints.partitioned("127.0.0.2")


# ----------------------------------------------------- px_* pull protocol


_CHUNK = 64 * 1024


class _MiniPxServer:
    """A raylet-shaped ``px_*`` peer on a native pump — small enough to
    script transfer faults the real server never emits (mid-stream
    silence, corrupted crc)."""

    def __init__(self, data, chunk=_CHUNK, serve_limits=None,
                 corrupt_crc_at=None):
        self.data = data
        self.chunk = chunk
        self.serve_limits = list(serve_limits or [])  # per-pull chunk cap
        self.corrupt_crc_at = corrupt_crc_at  # (pull_index, chunk_index)
        self.pulls = []
        self.pump = rpccore.Pump()
        port = self.pump.listen_tcp("127.0.0.1", 0)
        self.address = f"127.0.0.1:{port}"
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name="mini-px")
        self.thread.start()

    def close(self):
        self.pump.shutdown()
        self.thread.join(timeout=5)
        self.pump.destroy()

    def _reply(self, cid, seq, method, payload):
        self.pump.send(cid, msgpack.packb(
            [protocol.REPLY, seq, method, payload], use_bin_type=True))

    def _run(self):
        while True:
            try:
                evs = self.pump.next_batch(100)
            except Exception:
                return
            if evs is None:
                return
            for cid, kind, body in evs:
                if kind != rpccore.KIND_FRAME:
                    continue
                mtype, seq, method, payload = msgpack.unpackb(
                    body, raw=False)
                if mtype != protocol.REQUEST:
                    continue  # px_ack/ping notifies: the script ignores
                if method == "ping":
                    self._reply(cid, seq, "ping", {})
                elif method == "px_get":
                    self._reply(cid, seq, "px_get",
                                {"found": True, "busy": False,
                                 "total_size": len(self.data)})
                elif method == "px_pull":
                    self._serve_pull(cid, seq, payload)

    def _serve_pull(self, cid, seq, payload):
        idx = len(self.pulls)
        self.pulls.append(dict(payload))
        self._reply(cid, seq, "px_pull",
                    {"found": True, "total_size": len(self.data)})
        off = int(payload["offset"])
        limit = (self.serve_limits[idx]
                 if idx < len(self.serve_limits) else None)
        sent = 0
        while off < len(self.data):
            if limit is not None and sent >= limit:
                return  # the link "goes quiet" mid-stream
            d = self.data[off:off + self.chunk]
            crc = zlib.crc32(d) & 0xFFFFFFFF
            if self.corrupt_crc_at == (idx, sent):
                crc ^= 1
            self.pump.send(cid, msgpack.packb(
                [protocol.NOTIFY, None, "px_chunk",
                 {"stream": payload["stream"], "offset": off, "data": d,
                  "crc": crc, "total_size": len(self.data),
                  "last": off + len(d) >= len(self.data)}],
                use_bin_type=True))
            off += len(d)
            sent += 1


def _pattern_bytes(n):
    return bytes(bytearray((i * 7 + 3) % 256 for i in range(n)))


def test_px_pull_streams_full_object():
    _require_native()
    data = _pattern_bytes(6 * _CHUNK + 13)
    srv = _MiniPxServer(data)
    client = netx.NetxClient()
    try:
        hdr = client.get_header(srv.address, "ab" * 8)
        assert hdr["found"] and hdr["total_size"] == len(data)
        buf = bytearray(len(data))
        n = client.pull_into(srv.address, "ab" * 8, buf, len(data))
        assert n == len(data) and bytes(buf) == data
        assert client.stats["chunks_in"] == 7
        assert client.stats["bytes_in"] == len(data)
        assert len(srv.pulls) == 1 and srv.pulls[0]["offset"] == 0
    finally:
        client.close()
        srv.close()


def test_px_pull_stall_resumes_from_high_water_mark():
    _require_native()
    data = _pattern_bytes(5 * _CHUNK)
    srv = _MiniPxServer(data, serve_limits=[2])  # pull 1 dies at 2 chunks
    client = netx.NetxClient()
    try:
        buf = bytearray(len(data))
        n = client.pull_into(srv.address, "cd" * 8, buf, len(data),
                             stall_timeout=0.6)
        assert n == len(data) and bytes(buf) == data
        # resume re-requested from the high-water mark, never byte 0
        assert [p["offset"] for p in srv.pulls] == [0, 2 * _CHUNK]
    finally:
        client.close()
        srv.close()


def test_px_pull_crc_mismatch_is_a_data_error():
    """crc failures are replica failures, not transport flaps: they
    raise immediately instead of burning resume attempts."""
    _require_native()
    data = _pattern_bytes(4 * _CHUNK)
    srv = _MiniPxServer(data, corrupt_crc_at=(0, 1))
    client = netx.NetxClient()
    try:
        buf = bytearray(len(data))
        with pytest.raises(IOError, match="crc"):
            client.pull_into(srv.address, "ef" * 8, buf, len(data),
                             stall_timeout=0.6)
        assert len(srv.pulls) == 1  # no retry against known-bad data
    finally:
        client.close()
        srv.close()


# ------------------------------------------- simulated two-"host" cluster


@ray_tpu.remote
class _AddK:
    def __init__(self, k):
        self.k = k

    def add(self, x):
        return x + self.k


def _two_host_cluster(monkeypatch):
    """Two raylets on one machine that can only reach each other over
    TCP: each advertises a distinct loopback alias as its node IP and
    ``RTPU_NET_FORCE_TCP`` makes every dial treat the peer as off-box."""
    monkeypatch.setenv("RTPU_NODE_IP", "127.0.0.1")
    monkeypatch.setenv("RTPU_NET_FORCE_TCP", "1")
    endpoints._reset_for_tests()
    netx.reset_client_for_tests()
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2,
                                      "resources": {"hosta": 4}})
    cluster.add_node(num_cpus=2, resources={"hostb": 4},
                     env_overrides={"RTPU_NODE_IP": "127.0.0.2",
                                    "RTPU_NET_FORCE_TCP": "1"})
    cluster.connect()
    cluster.wait_for_nodes()
    return cluster


def test_two_host_cluster_runs_all_lanes_over_tcp(monkeypatch):
    """Object pulls, direct-lane actor calls and compiled-DAG hops all
    cross the raylet boundary with TCP as the only shared transport."""
    _require_native()
    cluster = _two_host_cluster(monkeypatch)
    try:
        hosts = {netx.host_of(n.get("netx_address") or "")
                 for n in ray_tpu.nodes() if n["alive"]}
        assert {"127.0.0.1", "127.0.0.2"} <= hosts

        # bulk object created on "host" B, pulled across the TCP plane
        @ray_tpu.remote(resources={"hostb": 1})
        def make(n):
            return (np.arange(n) % 251).astype(np.uint8)

        n = 6_000_000
        arr = ray_tpu.get(make.remote(n), timeout=120)
        assert arr.shape == (n,)
        assert int(arr[0]) == 0 and int(arr[1_000_000]) == \
            1_000_000 % 251 and int(arr[-1]) == (n - 1) % 251

        # direct-lane actor calls ride the netx TCP fast path
        @ray_tpu.remote(resources={"hostb": 1})
        class Counter:
            def __init__(self):
                self.v = 0

            def add(self, k):
                self.v += k
                return self.v

            def where(self):
                import os as _os
                return _os.environ.get("RTPU_NODE_IP", "")

        c = Counter.remote()
        assert ray_tpu.get(c.where.remote(), timeout=60) == "127.0.0.2"
        vals = ray_tpu.get([c.add.remote(1) for _ in range(25)],
                           timeout=90)
        assert vals == list(range(1, 26))
        nx = netx.get_client()
        assert nx is not None and nx.stats["requests"] >= 25

        # compiled-DAG hop: host A stage feeds host B stage over the
        # TCP channel listener
        with InputNode() as inp:
            a = _AddK.options(resources={"hosta": 1}).bind(1)
            b = _AddK.options(resources={"hostb": 1}).bind(10)
            dag = b.add.bind(a.add.bind(inp))
        cdag = dag.compile()
        try:
            assert cdag._compiled and not cdag._fallback_only
            assert [cdag.execute(i) for i in range(5)] == \
                [11 + i for i in range(5)]
        finally:
            cdag.teardown()
    finally:
        cluster.shutdown()


def test_net_partition_heals_with_no_lost_or_duplicated_calls(monkeypatch):
    """Sever the driver→hostB request direction mid-stream of actor
    calls. The partition drops frames BEFORE the wire, so fallback
    retries re-send an invocation that never arrived — each call
    executes exactly once, in order, and the lane heals at
    ``until_s``."""
    _require_native()
    cluster = _two_host_cluster(monkeypatch)
    try:
        @ray_tpu.remote(resources={"hostb": 1}, max_task_retries=-1)
        class Counter:
            def __init__(self):
                self.v = 0

            def add(self, k):
                self.v += k
                return self.v

        c = Counter.remote()
        assert ray_tpu.get(c.add.remote(1), timeout=60) == 1  # lane warm
        chaos.configure(seed=1, schedule=[
            {"site": "net.partition", "op": "partition", "at": 1,
             "every": 1, "max_fires": 0,
             "method": "127.0.0.1>127.0.0.2", "until_s": 1.0}])
        refs = [c.add.remote(1) for _ in range(10)]
        vals = ray_tpu.get(refs, timeout=90)
        assert vals == list(range(2, 12))
        time.sleep(1.1)  # past until_s: the direction is restored
        assert ray_tpu.get(c.add.remote(1), timeout=60) == 12
    finally:
        cluster.shutdown()


def test_px_chunk_drop_at_tcp_boundary_resumes(monkeypatch, tmp_path):
    """A px_chunk frame lost at the TCP boundary (chaos drop in the
    pulling raylet) leaves a gap the later chunks must not paper over:
    the stream stalls at the contiguous high-water mark and the pull
    resumes — the sealed object is bit-exact."""
    _require_native()
    log = tmp_path / "chaos.jsonl"
    monkeypatch.setenv("RTPU_NET_STALL_S", "1.5")
    os.environ["RTPU_CHAOS"] = json.dumps({
        "seed": 3,
        "schedule": [{"site": "protocol.recv", "op": "drop",
                      "method": "px_chunk", "at": 1,
                      "proc": "raylet", "head": True}]})
    os.environ["RTPU_CHAOS_LOG"] = str(log)
    cluster = _two_host_cluster(monkeypatch)
    try:
        @ray_tpu.remote(resources={"hostb": 1})
        def make(n):
            return np.full(n, 7, dtype=np.uint8)

        n = 10 * 1024 * 1024
        arr = ray_tpu.get(make.remote(n), timeout=120)
        assert arr.shape == (n,)
        assert int(arr.min()) == 7 and int(arr.max()) == 7
        # the fault actually fired where intended (the head raylet's
        # netx receive path)
        fired = [e for e in chaos.read_log(str(log))
                 if e.get("method") == "px_chunk"]
        assert fired, "chaos drop on px_chunk never fired"
    finally:
        cluster.shutdown()


# ------------------------------------------------------------ bench smoke


def test_bench_net_smoke():
    """`_BENCH_NET=1 python bench.py` runs end to end in smoke mode and
    the netx pull beats the 63 MiB/s SCALE.md baseline (full-size gate
    numbers recorded in PERF.md)."""
    _require_native()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, _BENCH_NET="1", NET_BENCH_SMOKE="1",
               JAX_PLATFORMS="cpu")
    env.pop("RTPU_CHAOS", None)
    r = subprocess.run([sys.executable, "bench.py"], env=env,
                       capture_output=True, text=True, timeout=300,
                       cwd=repo)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines()
            if l.startswith("{") and '"metric": "net"' in l]
    assert line, r.stdout[-2000:] + r.stderr[-2000:]
    out = json.loads(line[-1])
    assert out["netx_pull_mib_s"] > 0 and out["asyncio_pull_mib_s"] > 0
    assert out["actor_call_rtt_us"] > 0
    assert out["dag_cross_host_exec_us"] > 0
    assert out["gate_pull_63mibs"] is True, out
