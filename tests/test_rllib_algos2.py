"""Second-wave RLlib algorithms: PG/A2C/SAC/BC/MARWIL + offline IO."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.sample_batch import SampleBatch


def test_pg_learns_cartpole():
    from ray_tpu.rllib.algorithms.pg import PGConfig
    algo = (PGConfig().environment("CartPole-v1")
            .rollouts(num_envs_per_worker=8, rollout_fragment_length=64)
            .training(train_batch_size=512, lr=4e-3)
            .debugging(seed=2).build())
    best = 0.0
    for _ in range(25):
        r = algo.step()
        if not np.isnan(r["episode_reward_mean"]):
            best = max(best, r["episode_reward_mean"])
        if best > 60:
            break
    algo.cleanup()
    assert best > 60, f"PG stuck at {best}"


def test_a2c_smoke():
    from ray_tpu.rllib.algorithms.pg import A2CConfig
    algo = (A2CConfig().environment("CartPole-v1")
            .rollouts(num_envs_per_worker=4, rollout_fragment_length=32)
            .training(train_batch_size=128)
            .debugging(seed=0).build())
    r = algo.step()
    assert "learner/vf_loss" in r
    assert r["num_env_steps_sampled_this_iter"] == 128
    algo.cleanup()


def test_sac_pendulum_smoke():
    from ray_tpu.rllib.algorithms.sac import SACConfig
    algo = (SACConfig().environment("Pendulum-v1")
            .rollouts(num_envs_per_worker=1,
                      rollout_fragment_length=32)
            .training(train_batch_size=64, learning_starts=64)
            .debugging(seed=0).build())
    for _ in range(4):
        r = algo.step()
    assert r["replay_size"] >= 128
    assert "learner/critic_loss" in r
    # actions respect the Box bounds
    a = algo.compute_single_action(np.zeros(3, np.float32))
    assert (-2.0 <= a).all() and (a <= 2.0).all()
    algo.cleanup()


def test_offline_json_roundtrip(tmp_path):
    from ray_tpu.rllib.offline import JsonReader, JsonWriter
    w = JsonWriter(str(tmp_path / "data"))
    b1 = SampleBatch({
        SampleBatch.OBS: np.random.randn(5, 4).astype(np.float32),
        SampleBatch.ACTIONS: np.array([0, 1, 0, 1, 1]),
        SampleBatch.REWARDS: np.ones(5, np.float32),
        SampleBatch.DONES: np.array([0, 0, 0, 0, 1], bool),
    })
    w.write(b1)
    w.write(b1)
    w.close()
    r = JsonReader(str(tmp_path / "data")).read_all()
    assert r.count == 10
    np.testing.assert_allclose(r[SampleBatch.OBS][:5],
                               b1[SampleBatch.OBS], rtol=1e-6)


def test_bc_imitates_expert(tmp_path):
    """BC on synthetic expert data: action = argmax over obs dims."""
    from ray_tpu.rllib.algorithms.bc import BCConfig
    from ray_tpu.rllib.offline import JsonWriter
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(2000, 4)).astype(np.float32)
    acts = (obs[:, 0] > 0).astype(np.int64)  # expert rule
    w = JsonWriter(str(tmp_path / "expert"))
    w.write(SampleBatch({
        SampleBatch.OBS: obs, SampleBatch.ACTIONS: acts,
        SampleBatch.REWARDS: np.ones(2000, np.float32),
        SampleBatch.DONES: np.zeros(2000, bool),
        SampleBatch.NEXT_OBS: obs,
    }))
    w.close()
    algo = (BCConfig().environment("CartPole-v1")
            .offline_data(input_path=str(tmp_path / "expert"))
            .training(lr=5e-3, train_batch_size=256)
            .debugging(seed=0).build())
    for _ in range(8):
        algo.step()
    test_obs = rng.normal(size=(200, 4)).astype(np.float32)
    pred, _ = algo.get_policy().compute_actions(test_obs,
                                                explore=False)
    acc = np.mean(pred == (test_obs[:, 0] > 0))
    algo.cleanup()
    assert acc > 0.9, f"BC accuracy {acc}"


def test_marwil_runs(tmp_path):
    from ray_tpu.rllib.algorithms.bc import MARWILConfig
    from ray_tpu.rllib.offline import JsonWriter
    rng = np.random.default_rng(0)
    n = 500
    w = JsonWriter(str(tmp_path / "d"))
    w.write(SampleBatch({
        SampleBatch.OBS: rng.normal(size=(n, 4)).astype(np.float32),
        SampleBatch.ACTIONS: rng.integers(2, size=n),
        SampleBatch.REWARDS: rng.normal(size=n).astype(np.float32),
        SampleBatch.DONES: (rng.random(n) < 0.05),
        SampleBatch.NEXT_OBS: rng.normal(size=(n, 4)).astype(
            np.float32),
    }))
    w.close()
    algo = (MARWILConfig().environment("CartPole-v1")
            .offline_data(input_path=str(tmp_path / "d"))
            .debugging(seed=0).build())
    r = algo.step()
    assert "learner/imitation_loss" in r
    assert "learner/mean_weight" in r
    algo.cleanup()
