"""ParallelIterator (util/iter.py) and Dask-on-ray_tpu scheduler
(util/dask.py).

Reference analogues: python/ray/util/iter.py tests,
python/ray/util/dask/scheduler.py (ray_dask_get).
"""

import operator

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_parallel_iterator_for_each_gather_sync(cluster):
    from ray_tpu.util.iter import from_range
    it = from_range(10, num_shards=3).for_each(lambda x: x * 2)
    got = sorted(it.gather_sync())
    assert got == [0, 2, 4, 6, 8, 10, 12, 14, 16, 18]
    it.stop()


def test_parallel_iterator_filter_batch_flatten(cluster):
    from ray_tpu.util.iter import from_items
    it = (from_items(list(range(20)), num_shards=2)
          .filter(lambda x: x % 2 == 0)
          .batch(3)
          .flatten())
    assert sorted(it.gather_sync()) == list(range(0, 20, 2))
    it.stop()


def test_parallel_iterator_gather_async_and_take(cluster):
    from ray_tpu.util.iter import from_range
    it = from_range(100, num_shards=4).for_each(lambda x: x + 1)
    got = sorted(it.gather_async(fetch=8))
    assert got == list(range(1, 101))
    assert len(it.take(5)) == 5
    assert it.count() == 100
    it.stop()


def test_ray_dask_get_executes_graph(cluster):
    from ray_tpu.util.dask import ray_dask_get
    # diamond: d depends on b and c, both depend on a
    dsk = {
        "a": 10,
        "b": (operator.add, "a", 1),
        "c": (operator.mul, "a", 2),
        "d": (operator.add, "b", "c"),
    }
    assert ray_dask_get(dsk, "d") == 31
    assert ray_dask_get(dsk, ["b", "c"]) == [11, 20]
    assert ray_dask_get(dsk, [["a", "d"]]) == [[10, 31]]


def test_ray_dask_get_nested_tasks_and_lists(cluster):
    from ray_tpu.util.dask import ray_dask_get
    dsk = {
        "x": 4,
        # nested task inside a task + list-of-keys argument
        "y": (sum, [(operator.mul, "x", 2), "x", 1]),
    }
    assert ray_dask_get(dsk, "y") == 13


def test_ray_dask_get_cycle_detection(cluster):
    from ray_tpu.util.dask import ray_dask_get
    with pytest.raises(ValueError, match="cycle"):
        ray_dask_get({"a": (operator.neg, "b"),
                      "b": (operator.neg, "a")}, "a")
