"""Pipeline-parallel schedule tests on the virtual 8-device CPU mesh.

Parity oracle: sequential_apply (pp=1 semantics). The pipelined program
must match it in forward outputs AND parameter gradients — the backward
pass is pure autodiff through scan+ppermute, so this exercises the whole
1F1B-equivalent schedule.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.pipeline import (pipeline_apply, sequential_apply,
                                       stack_stage_params)

D = 16


def stage_fn(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return x + h @ params["w2"]


def init_stage(rng):
    k1, k2 = jax.random.split(rng)
    return {"w1": jax.random.normal(k1, (D, D)) * 0.3,
            "b1": jnp.zeros((D,)),
            "w2": jax.random.normal(k2, (D, D)) * 0.3}


@pytest.fixture
def pp4_mesh():
    devs = jax.devices("cpu")[:4]
    return Mesh(np.array(devs).reshape(4), ("pp",))


def _setup(n_stages, n_micro, mb=4):
    rngs = jax.random.split(jax.random.PRNGKey(0), n_stages)
    params = stack_stage_params(init_stage, rngs)
    x = jax.random.normal(jax.random.PRNGKey(1), (n_micro, mb, D))
    return params, x


def test_forward_parity(pp4_mesh):
    params, x = _setup(4, 8)
    piped = pipeline_apply(stage_fn, pp4_mesh)
    want = sequential_apply(stage_fn, params, x)
    got = piped(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_gradient_parity(pp4_mesh):
    params, x = _setup(4, 8)
    piped = pipeline_apply(stage_fn, pp4_mesh)

    def loss_piped(p):
        return jnp.mean(piped(p, x) ** 2)

    def loss_seq(p):
        return jnp.mean(sequential_apply(stage_fn, p, x) ** 2)

    g_piped = jax.grad(loss_piped)(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in g_seq:
        np.testing.assert_allclose(np.asarray(g_piped[k]),
                                   np.asarray(g_seq[k]),
                                   rtol=2e-4, atol=2e-5), k


def test_mixed_mesh_pp_dp():
    """pp manual + dp auto in one program (partial-manual shard_map)."""
    devs = jax.devices("cpu")[:8]
    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "pp"))
    params, x = _setup(4, 4, mb=8)
    piped = pipeline_apply(stage_fn, mesh)

    p_sh = jax.tree_util.tree_map(
        lambda a: jax.device_put(a, NamedSharding(
            mesh, P("pp", *([None] * (a.ndim - 1))))), params)
    # microbatch dim replicated; per-microbatch batch dim over dp
    x_sh = jax.device_put(x, NamedSharding(mesh, P(None, "dp")))

    got = jax.jit(piped)(p_sh, x_sh)
    want = sequential_apply(stage_fn, params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_uneven_microbatches(pp4_mesh):
    params, x = _setup(4, 5)  # M not divisible by S
    piped = pipeline_apply(stage_fn, pp4_mesh)
    want = sequential_apply(stage_fn, params, x)
    np.testing.assert_allclose(np.asarray(piped(params, x)),
                               np.asarray(want), rtol=2e-5, atol=2e-5)
