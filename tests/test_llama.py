"""Llama-family model tests: shape/loss sanity, GQA, long-context ring
path compatibility, and NUMERIC parity against a locally-initialized HF
LlamaForCausalLM through the checkpoint importer (no downloads — the HF
model is randomly initialized in-process, exported, imported)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_tiny_llama_forward_and_loss():
    from ray_tpu.models.llama import (LlamaConfig, LlamaModel,
                                      causal_lm_loss)
    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 32)))
    params = model.init(jax.random.PRNGKey(0), ids)
    logits = jax.jit(model.apply)(params, ids)
    assert logits.shape == (2, 32, cfg.vocab_size)
    loss = causal_lm_loss(logits, ids)
    # random init ≈ uniform: loss ~ log(vocab)
    assert abs(float(loss) - np.log(cfg.vocab_size)) < 1.0


def test_gqa_heads_shared():
    from ray_tpu.models.llama import LlamaConfig
    cfg = LlamaConfig.tiny()
    assert cfg.n_kv_heads < cfg.n_heads  # tiny config exercises GQA


def test_llama_train_step_reduces_loss():
    import optax

    from ray_tpu.models.llama import (LlamaConfig, LlamaModel,
                                      causal_lm_loss)
    cfg = LlamaConfig.tiny(vocab_size=64)
    model = LlamaModel(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 16, (4, 32)))  # low-entropy data
    params = model.init(jax.random.PRNGKey(0), ids)
    opt = optax.adam(1e-3)
    state = opt.init(params)

    @jax.jit
    def step(params, state, ids):
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(model.apply(p, ids), ids))(params)
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state, loss

    first = None
    for i in range(30):
        params, state, loss = step(params, state, ids)
        if first is None:
            first = float(loss)
    assert float(loss) < first - 0.5


def test_hf_llama_import_numeric_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from ray_tpu.models.llama import LlamaConfig, LlamaModel, import_hf_llama

    cfg = LlamaConfig(vocab_size=128, max_seq_len=64, dim=64, n_layers=2,
                      n_heads=4, n_kv_heads=2, ffn_hidden=128,
                      dtype=jnp.float32, attention_backend="reference")
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-5,
        attention_bias=False, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg)  # random init, no download
    hf.eval()

    import tempfile
    with tempfile.TemporaryDirectory() as d:
        hf.save_pretrained(d, safe_serialization=False)
        variables = import_hf_llama(d, cfg)

    ids_np = np.random.default_rng(0).integers(0, 128, (2, 16))
    with torch.no_grad():
        ref = hf(torch.tensor(ids_np)).logits.numpy()
    got = np.asarray(LlamaModel(cfg).apply(variables, jnp.asarray(ids_np)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
