"""Workflow crash recovery, management actor, events, cancel.

Reference strategy: workflow/tests/test_recovery.py (kill the driver
mid-step, resume, assert exactly-once step effects) +
test_events.py + workflow_access tests.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu


@pytest.fixture
def wf_storage(tmp_path, monkeypatch):
    root = str(tmp_path / "wf")
    monkeypatch.setenv("RTPU_WORKFLOW_STORAGE", root)
    from ray_tpu import workflow
    workflow.set_storage(root)
    yield root


def test_kill9_mid_step_resume_exactly_once(wf_storage, tmp_path):
    """Kill -9 the driver while step2 executes; resume in a NEW process
    context and prove step1 did NOT re-run (exactly-once per committed
    step) while the workflow still completes correctly."""
    effects = str(tmp_path / "effects")
    os.makedirs(effects)
    script = f"""
import os, sys, time
sys.path.insert(0, {os.getcwd()!r})
import ray_tpu
from ray_tpu import workflow
workflow.set_storage({wf_storage!r})
ray_tpu.init(num_cpus=2, object_store_memory=128*1024*1024)

@ray_tpu.remote
def step1(x):
    with open(os.path.join({effects!r}, "step1"), "a") as f:
        f.write("ran\\n")
    return x + 1

@ray_tpu.remote
def step2(x):
    eff = os.path.join({effects!r}, "step2")
    first = not os.path.exists(eff)
    with open(eff, "a") as f:
        f.write("started\\n")
    print("STEP2_STARTED", flush=True)
    if first:
        time.sleep(60)  # killed here; the resumed attempt skips the nap
    return x * 10

@ray_tpu.remote
def step3(x):
    return x + 5

dag = step3.bind(step2.bind(step1.bind(1)))
workflow.run(dag, workflow_id="chaos")
"""
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, text=True,
                            start_new_session=True)
    deadline = time.time() + 120
    started = False
    while time.time() < deadline:
        line = proc.stdout.readline()
        if "STEP2_STARTED" in line:
            started = True
            break
        if proc.poll() is not None:
            break
    assert started, "driver never reached step2"
    time.sleep(0.5)  # let step1's checkpoint land
    os.killpg(proc.pid, signal.SIGKILL)
    proc.wait(timeout=10)
    # reap the dead driver's cluster
    subprocess.run([sys.executable, "-c", (
        "import os,signal\n"
        "for p in os.listdir('/proc'):\n"
        "  if not p.isdigit(): continue\n"
        "  try: cmd=open(f'/proc/{p}/cmdline','rb').read()\n"
        "  except OSError: continue\n"
        "  if b'ray_tpu._private' in cmd:\n"
        "    os.kill(int(p), signal.SIGKILL)\n")])
    time.sleep(1)

    with open(os.path.join(effects, "step1")) as f:
        assert f.read() == "ran\n"  # committed exactly once pre-crash

    from ray_tpu import workflow
    assert workflow.get_status("chaos") == "RUNNING"  # crashed mid-run

    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    try:
        # make the resumed step2 fast: monkey-see, the DAG was persisted
        # with the sleeping body — instead resume must SKIP step1 (its
        # checkpoint exists) and re-run step2/step3. Patch time.sleep in
        # the resumed workers via the persisted body's 60s? No: resume
        # re-executes step2's real body; cap the wait by asserting the
        # step1 effect count instead of waiting for completion is not
        # enough — so run resume in a thread with a generous timeout.
        import threading
        result = {}

        def _resume():
            result["value"] = workflow.resume("chaos")

        t = threading.Thread(target=_resume, daemon=True)
        t.start()
        t.join(timeout=120)
        assert "value" in result, "resume did not complete"
        assert result["value"] == (1 + 1) * 10 + 5
        # step1 never re-ran (exactly-once); step2 ran at-least-once
        with open(os.path.join(effects, "step1")) as f:
            assert f.read() == "ran\n"
        with open(os.path.join(effects, "step2")) as f:
            starts = f.read().count("started")
        assert starts >= 2  # pre-crash attempt + resumed attempt
        assert workflow.get_status("chaos") == "SUCCESSFUL"
    finally:
        ray_tpu.shutdown()


@pytest.fixture
def wf_cluster(wf_storage):
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_management_actor_submit_status_list(wf_cluster):
    from ray_tpu import workflow
    from ray_tpu.workflow.workflow_access import get_management_actor
    import cloudpickle

    @ray_tpu.remote
    def add(a, b):
        return a + b

    actor = get_management_actor()
    assert ray_tpu.get(actor.ping.remote()) == "ok"
    blob = cloudpickle.dumps((add.bind(2, 3), None))
    wid = ray_tpu.get(actor.submit.remote(blob, "mgmt-wf"))
    assert wid == "mgmt-wf"
    deadline = time.time() + 60
    while time.time() < deadline and \
            ray_tpu.get(actor.get_status.remote("mgmt-wf")) != "SUCCESSFUL":
        time.sleep(0.2)
    assert ray_tpu.get(actor.get_status.remote("mgmt-wf")) == "SUCCESSFUL"
    assert workflow.get_output("mgmt-wf") == 5
    rows = ray_tpu.get(actor.list_all.remote("SUCCESSFUL"))
    assert any(r["workflow_id"] == "mgmt-wf" for r in rows)


def test_resume_all_skips_live_and_revives_crashed(wf_cluster):
    from ray_tpu import workflow
    from ray_tpu.workflow.storage import WorkflowStorage
    import cloudpickle

    @ray_tpu.remote
    def add_one(x):
        return x + 1

    blob = cloudpickle.dumps((add_one.bind(41), None))
    # "crashed": RUNNING status, stale (absent) claim
    crashed = WorkflowStorage("crashed-wf")
    crashed.save_status("RUNNING")
    crashed.save_dag(blob)
    # "live": RUNNING status with a fresh claim
    live = WorkflowStorage("live-wf")
    live.save_status("RUNNING")
    live.save_dag(blob)
    live.touch_claim()

    resumed = workflow.resume_all()
    assert "crashed-wf" in resumed
    assert "live-wf" not in resumed
    deadline = time.time() + 60
    while time.time() < deadline and \
            workflow.get_status("crashed-wf") != "SUCCESSFUL":
        time.sleep(0.2)
    assert workflow.get_output("crashed-wf") == 42


def test_resume_refuses_cancelled_workflow(wf_cluster):
    from ray_tpu import workflow

    @ray_tpu.remote
    def f(x):
        return x

    workflow.run(f.bind(0), workflow_id="torefuse")
    # force a cancelled, incomplete workflow state
    from ray_tpu.workflow.storage import WorkflowStorage
    st = WorkflowStorage("canc-wf")
    st.save_status("CANCELED")
    st.save_dag(b"irrelevant")
    with pytest.raises(workflow.WorkflowCancelledError):
        workflow.resume("canc-wf")


def test_cancel_stops_between_steps(wf_cluster, tmp_path):
    from ray_tpu import workflow
    marker = str(tmp_path / "s2ran")

    @ray_tpu.remote
    def slow_step(x):
        time.sleep(3)
        return x

    @ray_tpu.remote
    def never_step(x, m):
        open(m, "w").write("ran")
        return x

    dag = never_step.bind(slow_step.bind(1), marker)
    ref = workflow.run_async(dag, workflow_id="cancel-wf")
    time.sleep(0.8)  # inside slow_step
    assert workflow.cancel("cancel-wf")
    with pytest.raises(Exception):
        ray_tpu.get(ref, timeout=60)
    assert workflow.get_status("cancel-wf") == "CANCELED"
    assert not os.path.exists(marker)  # the next step never launched
    assert "cancel-wf" in [r["workflow_id"]
                           for r in workflow.list_all("CANCELED")]


def test_event_listener_checkpointed(wf_cluster, tmp_path):
    from ray_tpu import workflow

    @ray_tpu.remote
    def after_event(ts):
        return ("fired", ts)

    fire_at = time.time() + 1.0
    dag = after_event.bind(
        workflow.wait_for_event(workflow.TimerListener, fire_at))
    out = workflow.run(dag, workflow_id="event-wf")
    assert out[0] == "fired" and abs(out[1] - fire_at) < 1e-6
    # resume does not wait again: the event payload was checkpointed
    t0 = time.time()
    assert workflow.resume("event-wf") == out
    assert time.time() - t0 < 1.0
