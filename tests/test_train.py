"""Train tests: session plumbing, gang scheduling, the 2-worker SPMD island
(jax.distributed over CPU workers), checkpoint/restore."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.air import Checkpoint
from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train import DataParallelTrainer


@pytest.fixture(scope="module")
def train_cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_single_worker_report(train_cluster):
    def train_fn(config):
        from ray_tpu.air import session
        for i in range(3):
            session.report({"step": i, "loss": 1.0 / (i + 1)})

    trainer = DataParallelTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3


def test_train_loop_config_and_ranks(train_cluster):
    def train_fn(config):
        from ray_tpu.air import session
        session.report({
            "rank": session.get_world_rank(),
            "world": session.get_world_size(),
            "mult": config["x"] * 2,
        })

    trainer = DataParallelTrainer(
        train_fn, train_loop_config={"x": 21},
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["world"] == 2
    assert result.metrics["mult"] == 42


def test_two_worker_spmd_island_psum(train_cluster):
    """The north-star mechanic: 2 worker processes form one jax.distributed
    island; a psum over the combined device set sees both workers' data
    (this is the TPU-pod data-parallel loop in miniature)."""

    def train_fn(config):
        import numpy as np
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from ray_tpu.air import session

        world = session.get_world_size()
        rank = session.get_world_rank()
        assert jax.process_count() == world, \
            f"island has {jax.process_count()} processes, want {world}"
        devices = jax.devices()  # global: local devices × processes
        n_local = len(jax.local_devices())
        mesh = Mesh(np.array(devices), ("dp",))
        sharding = NamedSharding(mesh, P("dp"))

        # each process contributes rows filled with its rank+1; the global
        # mean over the sharded array must see every process's data
        local = np.full((n_local, 4), rank + 1, np.float32)
        arr = jax.make_array_from_process_local_data(
            sharding, local, (n_local * world, 4))

        mean = float(jax.jit(lambda x: x.mean())(arr))
        expect = sum(r + 1 for r in range(world)) / world
        session.report({"psum_ok": bool(np.isclose(mean, expect)),
                        "num_devices": len(devices)})

    trainer = DataParallelTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["psum_ok"] is True


def test_checkpoint_resume(train_cluster):
    def train_fn(config):
        from ray_tpu.air import session
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["step"] + 1 if ckpt else 0
        for i in range(start, start + 2):
            session.report({"step": i},
                           checkpoint=Checkpoint.from_dict({"step": i}))

    trainer = DataParallelTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1))
    r1 = trainer.fit()
    assert r1.metrics["step"] == 1
    trainer2 = DataParallelTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1),
        resume_from_checkpoint=r1.checkpoint)
    r2 = trainer2.fit()
    assert r2.metrics["step"] == 3


def test_failure_restarts_from_checkpoint(train_cluster):
    def train_fn(config):
        from ray_tpu.air import session
        ckpt = session.get_checkpoint()
        start = ckpt.to_dict()["step"] + 1 if ckpt else 0
        for i in range(start, 4):
            session.report({"step": i},
                           checkpoint=Checkpoint.from_dict({"step": i}))
            if i == 1 and ckpt is None:
                raise RuntimeError("injected failure at step 1")

    trainer = DataParallelTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(failure_config=FailureConfig(max_failures=1)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 3


def test_jax_training_loop_converges(train_cluster):
    """Linear regression under jit inside a train worker: the minimum viable
    'model trains through the framework' check."""

    def train_fn(config):
        import jax
        import jax.numpy as jnp
        import optax
        from ray_tpu.air import session

        key = jax.random.PRNGKey(0)
        w_true = jnp.array([2.0, -1.0])
        x = jax.random.normal(key, (256, 2))
        y = x @ w_true + 0.5

        params = {"w": jnp.zeros(2), "b": jnp.zeros(())}
        opt = optax.sgd(0.1)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                pred = x @ p["w"] + p["b"]
                return jnp.mean((pred - y) ** 2)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        for i in range(100):
            params, opt_state, loss = step(params, opt_state)
        session.report({"loss": float(loss)})

    trainer = DataParallelTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["loss"] < 1e-3
