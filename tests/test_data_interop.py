"""Data interop: tf conversion, image reading, mongo gating, ingress
with a real-FastAPI-shaped app.

Reference analogues: data/read_api.py read_images/read_mongo,
Dataset.to_tf/iter_tf_batches, serve fastapi integration.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rt_data


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                       object_store_memory=64 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_to_tf_and_iter_tf_batches(cluster):
    import tensorflow as tf
    ds = rt_data.from_items([{"x": np.float32(i), "y": np.int64(i % 2)}
                             for i in range(32)])
    tfds = ds.to_tf(feature_columns="x", label_columns="y",
                    batch_size=8)
    batches = list(tfds)
    assert len(batches) == 4
    feats, labels = batches[0]
    assert feats.dtype == tf.float32 and int(tf.size(feats)) == 8
    assert labels.dtype == tf.int64
    total = sum(float(tf.reduce_sum(f)) for f, _ in batches)
    assert total == float(sum(range(32)))

    # multi-column -> dict elements
    tfds2 = ds.to_tf(feature_columns=["x", "y"], batch_size=16)
    el = next(iter(tfds2))
    assert set(el.keys()) == {"x", "y"}

    got = list(ds.iter_tf_batches(batch_size=16))
    assert len(got) == 2 and set(got[0].keys()) == {"x", "y"}
    assert got[0]["x"].dtype == tf.float32


def test_read_images(cluster, tmp_path):
    from PIL import Image
    for i in range(4):
        arr = np.full((12 + i, 10, 3), i * 10, np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img_{i}.png")
    ds = rt_data.read_images(str(tmp_path), size=(8, 8),
                             include_paths=True)
    rows = ds.take_all()
    assert len(rows) == 4
    assert all(r["image"].shape == (8, 8, 3) for r in rows)
    assert all(r["image"].dtype == np.uint8 for r in rows)
    assert sorted(int(r["image"][0, 0, 0]) for r in rows) == \
        [0, 10, 20, 30]
    assert all(r["path"].endswith(".png") for r in rows)


def test_read_mongo_gated(cluster):
    try:
        import pymongo  # noqa: F401
        pytest.skip("pymongo installed; gating not testable")
    except ImportError:
        pass
    with pytest.raises(RuntimeError, match="pymongo"):
        rt_data.read_mongo("mongodb://x", "db", "coll")


def test_ingress_accepts_fastapi_shaped_app():
    """serve.ingress duck-types real FastAPI apps via app.routes
    (path/methods/endpoint) — proven with an object of that shape."""
    from ray_tpu import serve
    from ray_tpu.serve.ingress import _dispatch

    class FakeRoute:  # fastapi.routing.APIRoute surface
        def __init__(self, path, methods, endpoint):
            self.path = path
            self.methods = methods
            self.endpoint = endpoint

    class FakeFastAPI:
        def __init__(self):
            self.routes = []

    app = FakeFastAPI()

    def hello(self, who: str):
        return {"msg": f"hi {who} from {self.tag}"}

    app.routes.append(FakeRoute("/hello/{who}", {"GET"}, hello))

    @serve.ingress(app)
    class Svc:
        tag = "svc1"

    s = Svc()
    out = s(None, __serve_path__="/hello/ray", __serve_method__="GET")
    assert out == {"msg": "hi ray from svc1"}
    miss = s(None, __serve_path__="/nope", __serve_method__="GET")
    assert miss["__serve_http_status__"] == 404
    assert _dispatch  # imported symbol used by the unit surface
