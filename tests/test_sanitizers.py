"""Race detection + event-loop stall detection (SURVEY §5.2).

Reference analogues: the reference runs its C++ components under
TSAN/ASAN in CI (bazel --config=tsan over plasma/object_manager tests)
and instruments its asio event loops (common/asio/event_stats.cc).
Here: plasmax is rebuilt with -fsanitize=thread and hammered from
concurrent threads (halt_on_error makes any data race fail the
subprocess), and EventLoopThread's stall watchdog is driven past its
threshold.
"""

import os
import subprocess
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tsan_stress_bin(tmp_path_factory):
    """store.cc + the native stress harness, built under TSAN (a TSAN
    shared lib cannot be dlopened into a non-TSAN python process, so
    the stress is a standalone binary)."""
    import shutil
    if shutil.which("g++") is None:
        pytest.skip("g++ not available")
    out = str(tmp_path_factory.mktemp("tsan") / "px_stress")
    p = subprocess.run(
        ["g++", "-O1", "-g", "-fsanitize=thread", "-o", out,
         os.path.join(REPO, "src", "plasmax", "store.cc"),
         os.path.join(REPO, "src", "plasmax", "stress_main.cc"),
         "-lpthread"], capture_output=True, text=True, timeout=300)
    if p.returncode != 0:  # e.g. libtsan not installed
        pytest.skip(f"TSAN build unavailable: {p.stderr[-300:]}")
    return out


def test_plasmax_concurrent_ops_race_free(tsan_stress_bin):
    """8 threads hammer create/seal/get/pin/release/delete on one
    segment under ThreadSanitizer: any data race in the store's mutex
    discipline aborts the binary (halt_on_error=1)."""
    env = dict(os.environ, TSAN_OPTIONS="halt_on_error=1 exitcode=66")
    p = subprocess.run([tsan_stress_bin], capture_output=True,
                       text=True, env=env, timeout=300)
    assert p.returncode == 0, (p.stdout + p.stderr)[-3000:]
    assert "STRESS-OK" in p.stdout
    assert "WARNING: ThreadSanitizer" not in p.stderr


def test_event_loop_stall_detector():
    """A blocking call parked on the IO loop trips the watchdog with
    the loop thread's stack (reference: asio stats / loop-lag
    monitors)."""
    os.environ["RTPU_LOOP_STALL_S"] = "0.4"
    try:
        from ray_tpu._private.protocol import EventLoopThread
        io = EventLoopThread("stall-test")

        async def blocker():
            time.sleep(1.2)  # blocking sleep ON the loop — the bug class

        io.run(blocker(), timeout=10)
        deadline = time.time() + 5
        while io.stalls_detected == 0 and time.time() < deadline:
            time.sleep(0.1)
        assert io.stalls_detected >= 1
        # a healthy loop afterwards does not keep accumulating stalls
        n = io.stalls_detected
        time.sleep(1.0)
        assert io.stalls_detected == n
        io.stop()
    finally:
        os.environ.pop("RTPU_LOOP_STALL_S", None)
