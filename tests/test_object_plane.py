"""Object-plane maturity: external-storage spilling backends, pull
admission, proactive pushes, and plasmax crash recovery (reference:
object_manager/{push,pull}_manager.cc, _private/external_storage.py,
plasma/store.cc disconnect cleanup)."""

import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from ray_tpu._private.external_storage import (FileSystemStorage,
                                               MemoryStorage,
                                               RayStorageImpl,
                                               SmartOpenStorage,
                                               storage_from_config)


def test_external_storage_backends(tmp_path):
    for store in (FileSystemStorage(str(tmp_path / "fs")),
                  MemoryStorage(),
                  RayStorageImpl(str(tmp_path / "root"), "node01")):
        uri = store.spill("abc123", b"payload-bytes")
        assert store.restore(uri) == b"payload-bytes"
        store.delete(uri)
        if isinstance(store, MemoryStorage):
            with pytest.raises(KeyError):
                store.restore(uri)


def test_storage_from_config(tmp_path):
    s = storage_from_config("", str(tmp_path))
    assert isinstance(s, FileSystemStorage)
    s = storage_from_config({"type": "memory"}, str(tmp_path))
    assert isinstance(s, MemoryStorage)
    s = storage_from_config(
        '{"type": "filesystem", "params": {"directory_path": "%s"}}'
        % tmp_path, "/unused")
    assert s.dir == str(tmp_path)
    s = storage_from_config({"type": "ray_storage",
                             "params": {"root": str(tmp_path)}},
                            str(tmp_path), "n1")
    assert isinstance(s, RayStorageImpl)
    with pytest.raises(ValueError):
        storage_from_config({"type": "nope"}, str(tmp_path))
    # smart_open backend is gated on the library
    try:
        import smart_open  # noqa: F401
        has = True
    except ImportError:
        has = False
    if not has:
        with pytest.raises(ImportError):
            SmartOpenStorage("s3://bucket/spill")


def test_spilling_through_memory_backend():
    """End-to-end spill/restore through a NON-filesystem backend proves
    the raylet really goes through the ExternalStorage seam."""
    import ray_tpu
    ray_tpu.init(num_cpus=1,
                 object_store_memory=32 * 1024 * 1024,
                 _system_config={
                     "object_spilling_config":
                         '{"type": "memory"}',
                     "object_spilling_threshold": 0.5,
                 })
    try:
        refs = [ray_tpu.put(np.full(4 * 1024 * 1024, i, np.uint8))
                for i in range(6)]  # 24 MB >> 50% of 32MB store
        time.sleep(1.0)
        for i, r in enumerate(refs):  # every value restores correctly
            arr = ray_tpu.get(r, timeout=60)
            assert arr[0] == i and len(arr) == 4 * 1024 * 1024
    finally:
        ray_tpu.shutdown()


def _px_script(body: str) -> str:
    return textwrap.dedent("""
        import os, sys
        import numpy as np
        sys.path.insert(0, %r)
        from ray_tpu._private.object_store import PlasmaxStore
        from ray_tpu.common.ids import ObjectID
        store = PlasmaxStore(sys.argv[1])
    """) % os.path.dirname(os.path.dirname(os.path.abspath(__file__))) \
        + textwrap.dedent(body)


@pytest.fixture
def px_store(tmp_path):
    from ray_tpu._private.object_store import PlasmaxStore
    path = str(tmp_path / "seg")
    store = PlasmaxStore(path, capacity=8 * 1024 * 1024, create=True)
    yield path, store
    store.close()


def test_plasmax_survives_writer_killed_mid_create(px_store):
    """A client SIGKILLed between create() and seal() must not corrupt
    the segment: the unsealed entry is invisible to readers, abortable,
    and the store keeps allocating (reference: plasma store.cc client-
    disconnect cleanup)."""
    from ray_tpu.common.ids import ObjectID
    path, store = px_store
    script = _px_script("""
        oid = bytes.fromhex(sys.argv[2])
        from ray_tpu.common.ids import ObjectID as OID
        buf = store.create(OID(oid), 1024 * 1024)
        buf[:5] = b"hello"
        print("created", flush=True)
        import time
        time.sleep(30)   # killed here, object never sealed
    """)
    oid = ObjectID.from_random()
    proc = subprocess.Popen(
        [sys.executable, "-c", script, path, oid.hex()],
        stdout=subprocess.PIPE, text=True)
    assert proc.stdout.readline().strip() == "created"
    proc.kill()
    proc.wait()
    # unsealed object: not readable
    assert store.get_buffer(oid) is None
    # the store still works for new objects
    oid2 = ObjectID.from_random()
    store.put_bytes(oid2, b"x" * 1024)
    got = store.get_buffer(oid2)
    assert bytes(got[:1]) == b"x"
    got.release()
    store.release(oid2)
    # the orphaned allocation is reclaimable
    store.abort(oid)
    assert not store.contains(oid)


def test_plasmax_robust_mutex_recovers_from_dead_holder(px_store):
    """A process killed while HOLDING the segment mutex must not
    deadlock every other client: the robust mutex hands EOWNERDEAD to
    the next locker, which marks it consistent (store.cc Locker)."""
    from ray_tpu.common.ids import ObjectID
    path, store = px_store
    script = _px_script("""
        rc = store._lib.px_debug_lock(store._base)
        print("locked", rc, flush=True)
        import time
        time.sleep(30)   # killed while holding the mutex
    """)
    proc = subprocess.Popen([sys.executable, "-c", script, path],
                            stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline().strip()
    assert line.startswith("locked")
    proc.kill()
    proc.wait()
    # any subsequent op must acquire the orphaned mutex and recover
    oid = ObjectID.from_random()
    t0 = time.monotonic()
    store.put_bytes(oid, b"recovered")
    assert time.monotonic() - t0 < 5.0, "robust mutex did not recover"
    buf = store.get_buffer(oid)
    assert bytes(buf[:9]) == b"recovered"
    buf.release()
    store.release(oid)


def test_pull_admission_caps_inflight_bytes():
    """Concurrent fetches beyond the byte budget queue instead of
    overcommitting the store (reference: pull_manager.cc)."""
    import asyncio
    from ray_tpu._private import cluster_utils

    c = cluster_utils.Cluster(head_node_args={
        "num_cpus": 2, "object_store_memory": 64 * 1024 * 1024})
    c.add_node(num_cpus=1, object_store_memory=48 * 1024 * 1024)
    c.connect()
    c.wait_for_nodes(timeout=60)
    import ray_tpu
    try:
        # several 8 MB objects on the head; a SPREAD task on the worker
        # node gets them all at once — with a 48 MB store and a 50%
        # admission budget the pulls must serialize, not fail
        refs = [ray_tpu.put(np.full(8 * 1024 * 1024, i, np.uint8))
                for i in range(5)]

        @ray_tpu.remote
        def read_all(*arrs):
            return [int(a[0]) for a in arrs]

        out = ray_tpu.get(
            read_all.options(scheduling_strategy="SPREAD")
            .remote(*refs), timeout=300)
        assert out == [0, 1, 2, 3, 4]
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_proactive_push_on_spillback():
    """A task spilled to a peer gets its big arg PUSHED; the task runs
    and sees the data (correctness of the push path end-to-end)."""
    from ray_tpu._private import cluster_utils

    c = cluster_utils.Cluster(head_node_args={
        "num_cpus": 1, "object_store_memory": 64 * 1024 * 1024})
    c.add_node(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    c.connect()
    c.wait_for_nodes(timeout=60)
    import ray_tpu
    try:
        blob = ray_tpu.put(np.full(4 * 1024 * 1024, 7, np.uint8))

        @ray_tpu.remote
        def hold():
            import time as _t
            _t.sleep(3.0)
            return 1

        @ray_tpu.remote
        def read(a):
            return int(a[0]) + int(len(a))

        # saturate the head's single CPU so `read` spills to the worker
        h = hold.remote()
        reads = [read.remote(blob) for _ in range(4)]
        out = ray_tpu.get(reads, timeout=120)
        assert out == [7 + 4 * 1024 * 1024] * 4
        assert ray_tpu.get(h, timeout=60) == 1
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_fallback_disk_allocation(tmp_path):
    """When shm cannot hold an allocation, create() overflows into the
    disk-backed fallback segment (reference: plasma fallback allocation,
    create_request_queue.cc + plasma_allocator.cc mmap under /tmp);
    attachers discover the overflow segment via the sidecar."""
    from ray_tpu._private.object_store import PlasmaxStore
    from ray_tpu.common.ids import ObjectID

    path = str(tmp_path / "seg")
    store = PlasmaxStore(path, capacity=4 * 1024 * 1024, create=True,
                         fallback_path=str(tmp_path / "seg.fb"),
                         fallback_capacity=16 * 1024 * 1024)
    # pin primary-resident objects so eviction can't make room
    pinned = []
    for i in range(3):
        oid = ObjectID.from_random()
        store.put_bytes(oid, b"x" * (1024 * 1024))
        assert store.pin(oid)
        pinned.append(oid)
    big = ObjectID.from_random()
    # without the opt-in, a full store still refuses (spill-first
    # ordering: callers only fall back once spilling failed)
    with pytest.raises(Exception):
        store.put_bytes(big, b"y" * (3 * 1024 * 1024))
    store.put_bytes(big, b"y" * (3 * 1024 * 1024), allow_fallback=True)
    st = store.stats()
    assert st["fallback_used_bytes"] >= 3 * 1024 * 1024
    assert store.contains(big)
    buf = store.get_buffer(big)
    assert bytes(buf[:3]) == b"yyy"
    buf.release()
    store.release(big)

    # a second process attaches by the PRIMARY path alone and still
    # reads the overflowed object (sidecar discovery)
    attacher = PlasmaxStore(path)
    assert attacher.contains(big)
    b2 = attacher.get_buffer(big)
    assert len(b2) == 3 * 1024 * 1024
    b2.release()
    attacher.release(big)
    attacher.close()

    # delete reaches into the fallback segment too
    assert store.delete(big)
    assert not store.contains(big)
    store.close()


def test_object_channel_long_poll():
    """Long-poll object channels (reference: GCS pubsub object-location
    channels): a borrower blocked on a not-yet-created object wakes via
    the obj:<id> notification — a worker-side get on a ref that another
    task creates LATER completes well inside the poll-free window."""
    import ray_tpu
    ray_tpu.init(num_cpus=3, object_store_memory=64 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def slow_producer():
            time.sleep(1.5)
            return np.arange(200_000)  # plasma-sized

        @ray_tpu.remote
        def borrower(ref_list):
            t0 = time.time()
            val = ray_tpu.get(ref_list[0], timeout=30)
            return float(val.sum()), time.time() - t0

        ref = slow_producer.remote()
        # pass inside a list so the borrower resolves it itself (the
        # borrower-without-owner wait path that long-polls the channel)
        total, waited = ray_tpu.get(borrower.remote([ref]), timeout=60)
        assert total == float(np.arange(200_000).sum())
        assert waited < 20  # woke, didn't exhaust the timeout
    finally:
        ray_tpu.shutdown()
