"""ray_tpu.data tests (reference analogue: python/ray/data/tests/)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


pytestmark = pytest.mark.usefixtures("ray_start_shared")


@pytest.fixture(autouse=True, params=["streaming", "bulk"])
def _executor_mode(request, monkeypatch):
    """The whole data suite runs under BOTH executor modes in one pytest
    invocation: the streaming data-plane (default) and the bulk fallback
    (RTPU_DATA_STREAMING=0)."""
    monkeypatch.setenv("RTPU_DATA_STREAMING",
                       "1" if request.param == "streaming" else "0")


def test_range_count_take():
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.num_blocks() == 4
    assert ds.take(5) == [0, 1, 2, 3, 4]
    assert ds.sum() == 4950


def test_from_items_map_filter():
    ds = rd.from_items(list(range(20)), parallelism=3)
    out = ds.map(lambda x: x * 2).filter(lambda x: x % 4 == 0)
    assert sorted(out.take_all()) == sorted(
        [x * 2 for x in range(20) if (x * 2) % 4 == 0])


def test_map_batches_numpy_format():
    ds = rd.range(32, parallelism=2)
    out = ds.map_batches(lambda b: b + 1, batch_format="numpy")
    assert out.take(3) == [1, 2, 3]


def test_map_batches_dict_and_add_column():
    ds = rd.from_numpy({"x": np.arange(10), "y": np.ones(10)})
    ds2 = ds.add_column("z", lambda cols: cols["x"] + cols["y"])
    rows = ds2.take_all()
    assert rows[3]["z"] == 4.0
    ds3 = ds2.select_columns(["z"])
    assert set(ds3.take(1)[0].keys()) == {"z"}


def test_flat_map():
    ds = rd.from_items([1, 2, 3])
    out = ds.flat_map(lambda x: [x, x * 10])
    assert sorted(out.take_all()) == [1, 2, 3, 10, 20, 30]


def test_random_shuffle_preserves_multiset():
    ds = rd.range(50, parallelism=4)
    out = ds.random_shuffle(seed=7)
    vals = sorted(out.take_all())
    assert vals == list(range(50))
    # deterministic under the same seed
    vals2 = rd.range(50, parallelism=4).random_shuffle(seed=7).take_all()
    assert vals2 == ds.random_shuffle(seed=7).take_all()


def test_sort():
    ds = rd.from_items([5, 3, 8, 1, 9, 2, 7], parallelism=3)
    assert ds.sort().take_all() == [1, 2, 3, 5, 7, 8, 9]
    assert ds.sort(descending=True).take_all() == [9, 8, 7, 5, 3, 2, 1]


def test_sort_by_key():
    ds = rd.from_items([{"a": i % 3, "b": i} for i in range(9)],
                       parallelism=2)
    out = ds.sort(key="a").take_all()
    assert [r["a"] for r in out] == sorted(i % 3 for i in range(9))


def test_repartition():
    ds = rd.range(30, parallelism=3).repartition(5)
    assert ds.num_blocks() == 5
    assert sorted(ds.take_all()) == list(range(30))


def test_split_equal():
    ds = rd.range(40, parallelism=3)
    shards = ds.split(4)
    assert len(shards) == 4
    counts = [s.count() for s in shards]
    assert counts == [10, 10, 10, 10]
    all_vals = sorted(v for s in shards for v in s.take_all())
    assert all_vals == list(range(40))


def test_split_at_indices():
    ds = rd.range(20, parallelism=2)
    a, b, c = ds.split_at_indices([5, 12])
    assert a.take_all() == list(range(5))
    assert b.take_all() == list(range(5, 12))
    assert c.take_all() == list(range(12, 20))


def test_iter_batches_fixed_shapes():
    ds = rd.range(25, parallelism=3)
    batches = list(ds.iter_batches(batch_size=8, batch_format="numpy"))
    sizes = [len(b) for b in batches]
    assert sizes == [8, 8, 8, 1]
    padded = list(ds.iter_batches(batch_size=8, batch_format="numpy",
                                  pad_to_batch=True))
    assert [len(b) for b in padded] == [8, 8, 8, 8]
    dropped = list(ds.iter_batches(batch_size=8, drop_last=True,
                                   batch_format="numpy"))
    assert [len(b) for b in dropped] == [8, 8, 8]


def test_iter_batches_local_shuffle():
    ds = rd.range(64, parallelism=4)
    vals = []
    for b in ds.iter_batches(batch_size=16, batch_format="numpy",
                             local_shuffle_buffer_size=16,
                             local_shuffle_seed=3):
        vals.extend(b.tolist())
    assert sorted(vals) == list(range(64))
    assert vals != list(range(64))


def test_iter_device_batches():
    import jax
    ds = rd.from_numpy({"x": np.arange(32, dtype=np.float32),
                        "y": np.arange(32, dtype=np.int32)})
    seen = 0
    for b in ds.iter_device_batches(batch_size=8):
        assert isinstance(b["x"], jax.Array)
        assert b["x"].shape == (8,)
        seen += 1
    assert seen == 4


def test_aggregates():
    ds = rd.from_numpy({"v": np.arange(10, dtype=np.float64)})
    assert ds.sum("v") == 45
    assert ds.min("v") == 0
    assert ds.max("v") == 9
    assert ds.mean("v") == 4.5
    assert abs(ds.std("v") - np.std(np.arange(10), ddof=1)) < 1e-9


def test_groupby():
    ds = rd.from_items([{"k": i % 3, "v": i} for i in range(12)],
                       parallelism=3)
    out = ds.groupby("k").count().take_all()
    assert sorted((r["k"], r["count()"]) for r in out) == [
        (0, 4), (1, 4), (2, 4)]
    sums = ds.groupby("k").sum("v").take_all()
    assert sorted((r["k"], r["sum(v)"]) for r in sums) == [
        (0, 0 + 3 + 6 + 9), (1, 1 + 4 + 7 + 10), (2, 2 + 5 + 8 + 11)]


def test_zip_union_limit():
    a = rd.range(10, parallelism=2)
    b = rd.from_numpy({"w": np.arange(10) * 2})
    z = a.zip(b)
    rows = z.take_all()
    assert rows[4]["w"] == 8
    u = a.union(rd.range(5))
    assert u.count() == 15
    assert rd.range(100, parallelism=4).limit(7).count() == 7


def test_file_roundtrip(tmp_path):
    ds = rd.from_items([{"a": i, "b": float(i) * 0.5} for i in range(20)],
                       parallelism=2)
    p = str(tmp_path / "pq")
    ds.write_parquet(p)
    back = rd.read_parquet(p)
    assert back.count() == 20
    assert sorted(r["a"] for r in back.take_all()) == list(range(20))

    c = str(tmp_path / "csv")
    ds.write_csv(c)
    assert rd.read_csv(c).count() == 20


def test_read_text_and_numpy(tmp_path):
    f = tmp_path / "t.txt"
    f.write_text("alpha\nbeta\ngamma\n")
    assert rd.read_text(str(f)).take_all() == ["alpha", "beta", "gamma"]

    npf = tmp_path / "a.npy"
    np.save(npf, np.arange(6))
    ds = rd.read_numpy(str(npf))
    assert ds.count() == 6


def test_train_test_split():
    tr, te = rd.range(100, parallelism=4).train_test_split(0.2)
    assert tr.count() == 80 and te.count() == 20


def test_pipeline_repeat_and_windows():
    ds = rd.range(12, parallelism=4)
    pipe = ds.repeat(2)
    assert pipe.count() == 24
    w = ds.window(blocks_per_window=2)
    total = sum(len(b) for b in w.iter_batches(batch_size=4,
                                               batch_format="numpy"))
    assert total == 12


def test_custom_datasource():
    class Src(rd.Datasource):
        def get_read_tasks(self, parallelism):
            return [lambda i=i: {"x": np.full(4, i)} for i in range(3)]
    ds = rd.read_datasource(Src())
    assert ds.count() == 12


def test_stage_fusion_stats():
    ds = rd.range(16, parallelism=2).map(lambda x: x + 1).map(
        lambda x: x * 2)
    ds.materialize()
    s = ds.stats()
    assert "map+map" in s


def test_arrow_filter_to_empty_block(tmp_path):
    # regression: empty take() on arrow blocks (null-typed index array)
    import pyarrow as pa
    ds = rd.from_arrow(pa.table({"a": list(range(8))}))
    out = ds.filter(lambda r: r["a"] > 100)
    assert out.take_all() == []
    # shuffle/sort of arrow datasets exercise empty partitions too
    assert sorted(r["a"] for r in
                  rd.from_arrow(pa.table({"a": [3, 1, 2]})).sort("a")
                  .take_all()) == [1, 2, 3]


def test_tensor_shape_survives_arrow_roundtrip(tmp_path):
    ds = rd.range_tensor(8, shape=(2, 4), parallelism=2)
    p = str(tmp_path / "t")
    ds.write_parquet(p)
    back = rd.read_parquet(p).to_numpy()["value"]
    assert back.shape == (8, 2, 4)


def test_pad_to_batch_wins_over_drop_last():
    ds = rd.range(35, parallelism=2)
    batches = list(ds.iter_batches(batch_size=8, batch_format="numpy",
                                   pad_to_batch=True, drop_last=True))
    assert [len(b) for b in batches] == [8, 8, 8, 8, 8]
    # padded rows repeat real rows; multiset of first 35 values intact
    flat = [v for b in batches for v in b.tolist()]
    assert sorted(set(flat)) == list(range(35))


def test_prefetch_iter_batches():
    ds = rd.range(64, parallelism=8)
    vals = []
    for b in ds.iter_batches(batch_size=16, batch_format="numpy",
                             prefetch_blocks=3):
        vals.extend(b.tolist())
    assert sorted(vals) == list(range(64))
