"""Atari-scale image-RL path: pixel env + frame-stack/resize/grayscale
connectors + AtariCNN end-to-end through PPO and IMPALA (reference:
rllib/env/wrappers/atari_wrappers.py + release/rllib_tests image
learning; ALE itself is not installable in this image, so the pixel env
is procedurally generated — see rllib/env.py PixelCatcher)."""

import time

import numpy as np
import pytest

from ray_tpu.rllib.connectors import (ConnectorPipeline,
                                      FrameStackConnector,
                                      GrayscaleObsConnector,
                                      ResizeObsConnector)
from ray_tpu.rllib.env import Box, PixelCatcher


def test_pixel_connectors_shapes_and_state():
    env = PixelCatcher({"seed": 0})
    obs, _ = env.reset(seed=0)
    assert obs.shape == (84, 84, 1) and obs.dtype == np.uint8
    pipe = ConnectorPipeline([ResizeObsConnector(21, 21),
                              FrameStackConnector(4)])
    space = pipe.observation_space(env.observation_space)
    assert space.shape == (21, 21, 4)
    batch = np.stack([obs, obs])
    out = pipe(batch)
    assert out.shape == (2, 21, 21, 4)
    # stacking advances: a new frame occupies the LAST channel slot
    obs2 = env.step(2)[0]
    out2 = pipe(np.stack([obs2, obs2]))
    assert not np.array_equal(out2[..., 3], out2[..., 0]) or \
        np.array_equal(obs, obs2)
    # transform() peeks without advancing
    peek = pipe.transform(np.stack([obs, obs]))
    again = pipe.transform(np.stack([obs, obs]))
    assert np.array_equal(peek, again)
    # done rows restart their stack from the fresh obs
    out3 = pipe(np.stack([obs, obs2]), dones=np.array([True, False]))
    resized_first = out3[0, ..., 0]
    assert np.array_equal(out3[0, ..., 3], resized_first)


def test_grayscale_connector():
    rgb = Box(0, 255, (8, 8, 3), np.uint8)
    g = GrayscaleObsConnector()
    assert g.output_space(rgb).shape == (8, 8, 1)
    x = np.random.default_rng(0).integers(
        0, 255, (2, 8, 8, 3)).astype(np.uint8)
    out = g(x)
    assert out.shape == (2, 8, 8, 1)
    assert np.allclose(out[..., 0], x.mean(-1).astype(np.uint8), atol=1)


def test_ppo_learns_pixel_catcher():
    """The Atari-path learning bar: CNN policy from 84x84 pixels
    through resize+framestack connectors must learn to catch."""
    from ray_tpu.rllib.algorithms.ppo import PPOConfig
    algo = (PPOConfig().environment("PixelCatcher-v0")
            .rollouts(num_envs_per_worker=8, rollout_fragment_length=64,
                      connectors={"obs": [ResizeObsConnector(21, 21),
                                          FrameStackConnector(2)]})
            .training(train_batch_size=512, sgd_minibatch_size=128,
                      num_sgd_iter=6, lr=1e-3, entropy_coeff=0.01)
            .debugging(seed=0).build())
    best = -4.0
    t0 = time.perf_counter()
    steps = 0
    for i in range(44):
        r = algo.step()
        steps = r["timesteps_total"]
        if not np.isnan(r["episode_reward_mean"]):
            best = max(best, r["episode_reward_mean"])
        if best >= 2.0:
            break
    sps = steps / (time.perf_counter() - t0)
    # random play scores about -2.8 of a max +4; >=1.5 means the CNN
    # actually tracks the ball (observed 3.0 at iter 40)
    assert best >= 1.5, f"pixel PPO stuck at {best}"
    print(f"\npixel PPO: best={best:.2f} SPS={sps:.0f}")


@pytest.fixture(scope="module")
def local_cluster():
    import ray_tpu
    ctx = ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_impala_runs_pixel_catcher(local_cluster):
    """IMPALA's async learner thread on the image path: liveness +
    measured SPS (the PERF.md row)."""
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig
    algo = (IMPALAConfig().environment("PixelCatcher-v0")
            .rollouts(num_envs_per_worker=4, rollout_fragment_length=32,
                      connectors={"obs": [ResizeObsConnector(21, 21),
                                          FrameStackConnector(2)]})
            .training(lr=8e-4)
            .debugging(seed=0).build())
    t0 = time.perf_counter()
    steps = 0
    updates = 0
    for _ in range(10):
        r = algo.step()
        steps = r["timesteps_total"]
        updates = r.get("learner/num_updates", updates) or updates
    sps = steps / (time.perf_counter() - t0)
    algo.cleanup()
    assert steps > 0
    assert np.isfinite(r.get("learner/loss", np.nan)) or updates >= 0
    print(f"\npixel IMPALA: {steps} steps, SPS={sps:.0f}")
