"""MoE / expert-parallel tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel.moe import MoE

S, D, FF, E = 32, 16, 32, 4


@pytest.fixture
def moe_and_params():
    layer = MoE(num_experts=E, d_ff=FF, capacity_factor=2.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, S // 2, D))
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    return layer, params, x


def _oracle(params, x):
    """Per-token dense evaluation of the same routing decisions."""
    xf = np.asarray(x, np.float32).reshape(-1, x.shape[-1])
    rk = np.asarray(params["router"]["kernel"], np.float32)
    rb = np.asarray(params["router"]["bias"], np.float32)
    logits = xf @ rk + rb
    gates = jax.nn.softmax(jnp.asarray(logits), -1)
    idx = np.argmax(np.asarray(gates), -1)
    w1 = np.asarray(params["experts_w1"], np.float32)
    w2 = np.asarray(params["experts_w2"], np.float32)
    out = np.zeros_like(xf)
    counts = {e: 0 for e in range(E)}
    cap = int(2.0 * xf.shape[0] / E)
    for i, e in enumerate(idx):
        if counts[e] >= cap:
            continue  # dropped token -> zero output
        counts[e] += 1
        h = np.asarray(jax.nn.gelu(jnp.asarray(xf[i] @ w1[e])))
        out[i] = (h @ w2[e]) * float(gates[i, e])
    return out.reshape(x.shape)


def test_moe_matches_per_token_oracle(moe_and_params):
    layer, params, x = moe_and_params
    y, aux = layer.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(y), _oracle(params, x),
                               rtol=1e-4, atol=1e-5)
    assert float(aux) > 0.0


def test_moe_sharded_matches_unsharded(moe_and_params):
    layer, params, x = moe_and_params
    want, _ = layer.apply({"params": params}, x)

    devs = jax.devices("cpu")[:8]
    mesh = Mesh(np.array(devs).reshape(2, 4), ("dp", "ep"))
    shard = {
        "router": jax.tree_util.tree_map(
            lambda a: jax.device_put(a, NamedSharding(mesh, P())),
            params["router"]),
        "experts_w1": jax.device_put(
            params["experts_w1"], NamedSharding(mesh, P("ep"))),
        "experts_w2": jax.device_put(
            params["experts_w2"], NamedSharding(mesh, P("ep"))),
    }
    x_sh = jax.device_put(x, NamedSharding(mesh, P("dp")))
    got, _ = jax.jit(
        lambda p, xx: layer.apply({"params": p}, xx))(shard, x_sh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_moe_gradients_flow(moe_and_params):
    layer, params, x = moe_and_params

    def loss(p):
        y, aux = layer.apply({"params": p}, x)
        return jnp.mean(y ** 2) + 0.01 * aux

    g = jax.grad(loss)(params)
    gnorm = float(jax.tree_util.tree_reduce(
        lambda a, b: a + jnp.sum(b * b), g, 0.0))
    assert np.isfinite(gnorm) and gnorm > 0.0