"""Push-based shuffle, actor-pool compute, dataset stats.

Reference analogues: data/tests/test_push_based_shuffle.py,
test_actor_pool.py (compute strategy), test_stats.py.
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_push_based_shuffle_preserves_rows(cluster, monkeypatch):
    monkeypatch.setenv("RTPU_PUSH_BASED_SHUFFLE", "1")
    ds = rdata.range(1000).repartition(10)
    out = ds.random_shuffle(seed=7)
    rows = sorted(out.take_all())
    assert rows == list(range(1000))
    # pipelined substages actually ran and are visible in stats
    stats = out.stats()
    assert "merge_tasks" in stats and "rounds" in stats, stats
    # deterministic under the same seed
    again = sorted(rdata.range(1000).repartition(10)
                   .random_shuffle(seed=7).take_all())
    assert again == rows


def test_push_and_pull_shuffle_same_multiset(cluster, monkeypatch):
    """Tier-1 variant: small enough to hold its timeout even when the
    fully loaded suite has this 1-core box oversubscribed (the original
    300-row/6-partition shape passed in ~1s standalone but timed out
    only under full-suite contention); the slow-marked test below keeps
    the original shape for nightly runs."""
    monkeypatch.setenv("RTPU_PUSH_BASED_SHUFFLE", "0")
    pull = sorted(rdata.range(120).repartition(4)
                  .random_shuffle(seed=3).take_all())
    monkeypatch.setenv("RTPU_PUSH_BASED_SHUFFLE", "1")
    push = sorted(rdata.range(120).repartition(4)
                  .random_shuffle(seed=3).take_all())
    assert pull == push == list(range(120))


@pytest.mark.slow
def test_push_and_pull_shuffle_same_multiset_full(cluster, monkeypatch):
    monkeypatch.setenv("RTPU_PUSH_BASED_SHUFFLE", "0")
    pull = sorted(rdata.range(300).repartition(6)
                  .random_shuffle(seed=3).take_all())
    monkeypatch.setenv("RTPU_PUSH_BASED_SHUFFLE", "1")
    push = sorted(rdata.range(300).repartition(6)
                  .random_shuffle(seed=3).take_all())
    assert pull == push == list(range(300))


def test_push_shuffle_actually_shuffles(cluster, monkeypatch):
    monkeypatch.setenv("RTPU_PUSH_BASED_SHUFFLE", "1")
    out = rdata.range(1000).repartition(8).random_shuffle(seed=1)
    assert out.take_all() != list(range(1000))


def test_actor_pool_map_batches(cluster):
    def fn(cols):
        # records the executing process so the test can prove pool reuse
        return {"value": cols["x"] * 2,
                "pid": np.full(len(cols["x"]), os.getpid(), np.int64)}

    ds = (rdata.from_numpy({"x": np.arange(200)}).repartition(8)
          .map_batches(fn, compute=rdata.ActorPoolStrategy(size=2)))
    rows = ds.take_all()
    values = sorted(r["value"] for r in rows)
    assert values == [2 * i for i in range(200)]
    pids = {r["pid"] for r in rows}
    # 8 blocks ran on a pool of exactly 2 worker processes, none of them
    # the driver
    assert len(pids) <= 2
    assert os.getpid() not in pids


def test_actor_pool_amortizes_setup(cluster):
    class Expensive:
        """Stateful callable pattern: setup once per pool worker."""
        _model = None

        def __call__(self, batch):
            if Expensive._model is None:
                Expensive._model = {"offset": 100}  # expensive init
            return batch + Expensive._model["offset"]

    ds = (rdata.range(100).repartition(4)
          .map_batches(Expensive(), batch_format="numpy",
                       compute="actors"))
    assert sorted(ds.take_all()) == [100 + i for i in range(100)]


def test_stats_records_stages(cluster):
    ds = (rdata.range(100).repartition(4)
          .map(lambda x: x + 1)
          .random_shuffle(seed=0))
    ds.materialize()
    s = ds.stats()
    assert "map" in s and "random_shuffle" in s
    assert "blocks" in s


def test_iter_torch_batches(cluster):
    import torch

    ds = rdata.from_numpy({"x": np.arange(20, dtype=np.float64),
                           "y": np.ones(20, np.int32)})
    batches = list(ds.iter_torch_batches(
        batch_size=8, dtypes={"x": torch.float32}))
    assert all(isinstance(b["x"], torch.Tensor) for b in batches)
    assert batches[0]["x"].dtype == torch.float32
    assert batches[0]["y"].dtype == torch.int32
    assert sum(len(b["x"]) for b in batches) == 20


def test_serve_rest_on_dashboard(cluster):
    import json as _json
    import urllib.request

    from ray_tpu.dashboard.dashboard import start_dashboard
    port = start_dashboard(port=18266)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/serve/applications",
        method="PUT",
        data=_json.dumps({
            "http_options": {"port": 8127},
            "applications": [{
                "name": "rest_app",
                "import_path": "tests.serve_test_app:app",
                "route_prefix": "/rest",
            }],
        }).encode(),
        headers={"Content-Type": "application/json"})
    out = _json.loads(urllib.request.urlopen(req, timeout=60).read())
    assert out["deployed"] == ["rest_app"]
    status = _json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/api/serve/applications",
        timeout=30).read())
    assert "rest_app" in status["applications"]
    body = _json.dumps({"k": 5}).encode()
    resp = _json.loads(urllib.request.urlopen(urllib.request.Request(
        "http://127.0.0.1:8127/rest", data=body,
        headers={"Content-Type": "application/json"}),
        timeout=30).read())
    assert resp == {"cfg_echo": {"k": 5}}
    from ray_tpu import serve
    serve.shutdown()
