"""Serve control-plane HA tests (reference strategy:
serve/tests/test_controller_recovery.py + test_deploy_* rollout suites).

The scenarios mirror docs/SERVE_HA.md's failure matrix: a controller
killed mid-load (journal recovery + replica re-adoption, traffic from
cached route tables), health-gated start-before-stop rolling updates
with zero failed requests, graceful drain on downscale/delete, and the
chaos-seeded kills (`serve.controller.tick` / `serve.replica.request`)
that the `_BENCH_SERVE_HA` bench measures.
"""

import json
import os
import pickle
import signal
import subprocess
import sys
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu._private import chaos

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _chaos_env_hygiene():
    yield
    os.environ.pop("RTPU_CHAOS", None)
    os.environ.pop("RTPU_CHAOS_LOG", None)
    chaos.clear()


@pytest.fixture(scope="module")
def ha_cluster():
    ctx = ray_tpu.init(num_cpus=8, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


def _controller_info(timeout=60.0):
    """get_controller_info from whatever controller incarnation is
    live, retrying across a restart window."""
    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            return ctrl, ray_tpu.get(
                ctrl.get_controller_info.remote(), timeout=5.0)
        except Exception as e:
            last = e
            time.sleep(0.5)
    raise AssertionError(f"controller unreachable: {last}")


def _wait_status(name, pred, timeout=45.0):
    deadline = time.time() + timeout
    st = {}
    while time.time() < deadline:
        st = serve.status()
        if pred(st.get(name, {})):
            return st[name]
        time.sleep(0.3)
    raise AssertionError(f"status never converged for {name}: {st}")


# --------------------------------------------- controller restart + HA


def test_controller_restart_recovers_and_readopts(ha_cluster):
    """SIGKILL the controller mid-load: traffic keeps flowing from the
    cached route table, the restarted controller rebuilds state from
    the GCS journal and re-adopts the SAME replica actors (no replica
    restarts), statuses converge HEALTHY, and a handle pickled before
    the crash still routes after it."""

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            return x + 1

    h = serve.run(Echo.bind(), http_port=None)
    assert ray_tpu.get(h.remote(1), timeout=30.0) == 2
    pickled_handle = pickle.dumps(h)

    ctrl, info = _controller_info()
    _, table0 = ray_tpu.get(ctrl.get_route_table.remote(), timeout=10.0)
    ids_before = sorted(table0["Echo"]["replicas"])
    assert len(ids_before) == 2

    os.kill(info["pid"], signal.SIGKILL)

    # data plane keeps serving through the outage (handle path never
    # touches the controller once the route table is cached)
    for i in range(15):
        assert ray_tpu.get(h.remote(i), timeout=15.0) == i + 1
        time.sleep(0.05)

    ctrl, info2 = _controller_info()
    assert info2["pid"] != info["pid"]
    assert info2["recovered"], info2
    assert info2["adopted_replicas"] >= 2, info2

    _wait_status("Echo", lambda s: s.get("status") == "HEALTHY")
    _, table1 = ray_tpu.get(ctrl.get_route_table.remote(), timeout=10.0)
    assert sorted(table1["Echo"]["replicas"]) == ids_before, \
        "replicas were restarted instead of re-adopted"

    # a handle deserialized across the restart still routes
    h2 = pickle.loads(pickled_handle)
    assert ray_tpu.get(h2.remote(41), timeout=30.0) == 42

    # the reconnected long-poll applies post-restart updates (version
    # counters regressed to the new incarnation's)
    @serve.deployment(num_replicas=2, user_config={"off": 10})
    class Echo:  # noqa: F811
        def __init__(self):
            self.off = 1

        def reconfigure(self, cfg):
            self.off = cfg["off"]

        def __call__(self, x):
            return x + self.off

    h3 = serve.run(Echo.bind(), http_port=None, _blocking_timeout=90.0)
    deadline = time.time() + 30
    while time.time() < deadline:
        if ray_tpu.get(h3.remote(1), timeout=15.0) == 11:
            break
        time.sleep(0.2)
    assert ray_tpu.get(h3.remote(1), timeout=15.0) == 11
    serve.delete("Echo")


# ------------------------------------------------- rolling update + drain


def _versioned(name, v, extra=None):
    cfg = dict(num_replicas=2, user_config={"v": v}, name=name,
               graceful_shutdown_timeout_s=8.0)
    cfg.update(extra or {})

    @serve.deployment(**cfg)
    class Versioned:
        def __init__(self):
            self.v = None

        def reconfigure(self, c):
            self.v = c["v"]

        def __call__(self, x):
            time.sleep(0.03)
            return self.v

    return Versioned


def test_rolling_update_zero_failed_requests(ha_cluster):
    """Health-gated start-before-stop: a redeploy under sustained load
    completes with ZERO failed requests (the old stop-then-start order
    dropped every request routed to a replica killed before its
    replacement existed)."""
    h = serve.run(_versioned("Roll", 1).bind(), http_port=None)
    assert ray_tpu.get(h.remote(0), timeout=30.0) == 1

    errors, results = [], []
    stop = threading.Event()

    def load():
        while not stop.is_set():
            try:
                results.append(ray_tpu.get(h.remote(0), timeout=20.0))
            except Exception as e:  # noqa: BLE001 — every failure counts
                errors.append(repr(e))

    threads = [threading.Thread(target=load) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.8)
    serve.run(_versioned("Roll", 2).bind(), http_port=None,
              _blocking_timeout=90.0)
    time.sleep(0.8)
    stop.set()
    for t in threads:
        t.join()

    assert not errors, f"{len(errors)} dropped during rollout: {errors[:5]}"
    assert results, "load loop never completed a request"
    assert ray_tpu.get(h.remote(0), timeout=15.0) == 2
    st = _wait_status("Roll", lambda s: s.get("status") == "HEALTHY")
    assert st["live_replicas"] == 2
    # old AND new versions served during the window — the update really
    # overlapped instead of stopping the world
    assert 1 in results and 2 in results
    serve.delete("Roll")


def test_health_gate_keeps_old_version_serving(ha_cluster):
    """A new version whose replicas never pass health checks must NOT
    take down the old version: the gate drains old replicas only
    one-for-one against READY new ones."""
    h = serve.run(_versioned("Gate", 1).bind(), http_port=None)
    assert ray_tpu.get(h.remote(0), timeout=30.0) == 1

    @serve.deployment(num_replicas=2, name="Gate")
    class Broken:
        def __init__(self):
            raise RuntimeError("bad build: constructor always fails")

        def __call__(self, x):
            return -1

    # deploy_application returns immediately; reconciliation tries (and
    # fails) to bring the broken version up in surge waves
    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    import cloudpickle
    cfg = dict(Broken.config)
    cfg["name"] = "Gate"
    cfg["app_name"] = "default"
    cfg["serialized_callable"] = cloudpickle.dumps(Broken.func_or_class)
    cfg["init_args"] = ()
    cfg["init_kwargs"] = {}
    assert ray_tpu.get(ctrl.deploy_application.remote([cfg]),
                       timeout=30.0) == "ok"

    # the old version keeps serving the whole time
    deadline = time.time() + 6.0
    while time.time() < deadline:
        assert ray_tpu.get(h.remote(0), timeout=20.0) == 1
        time.sleep(0.25)
    st = serve.status()["Gate"]
    assert st["status"] == "UPDATING", st
    assert st["stale_replicas"] >= 2, st  # old replicas still in the table

    # a fixed build rolls out normally
    serve.run(_versioned("Gate", 3).bind(), http_port=None,
              _blocking_timeout=120.0)
    deadline = time.time() + 30
    while time.time() < deadline:
        if ray_tpu.get(h.remote(0), timeout=20.0) == 3:
            break
        time.sleep(0.2)
    assert ray_tpu.get(h.remote(0), timeout=20.0) == 3
    serve.delete("Gate")


def test_flaky_health_probe_does_not_kill_replica(ha_cluster, tmp_path):
    """Fewer than RTPU_SERVE_HEALTH_FAILURES consecutive probe failures
    must NOT remove a replica — one flaky probe used to kill a healthy
    replica on the spot."""
    marker = str(tmp_path / "flaky_fails")

    @serve.deployment(num_replicas=1, name="Flaky")
    class Flaky:
        def __init__(self, path):
            self.path = path

        def check_health(self):
            # fail exactly two probes (threshold is 3), then recover
            n = 0
            if os.path.exists(self.path):
                n = int(open(self.path).read() or 0)
            if n < 2:
                with open(self.path, "w") as f:
                    f.write(str(n + 1))
                raise RuntimeError(f"flaky probe {n + 1}")

        def __call__(self, x):
            return x * 3

    h = serve.run(Flaky.bind(marker), http_port=None,
                  _blocking_timeout=90.0)
    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    _, table0 = ray_tpu.get(ctrl.get_route_table.remote(), timeout=10.0)
    rid = table0["Flaky"]["replicas"]
    # ride through > threshold health-check rounds
    deadline = time.time() + 6.0
    while time.time() < deadline:
        assert ray_tpu.get(h.remote(2), timeout=20.0) == 6
        time.sleep(0.4)
    _, table1 = ray_tpu.get(ctrl.get_route_table.remote(), timeout=10.0)
    assert table1["Flaky"]["replicas"] == rid, \
        "flaky (sub-threshold) probes killed a healthy replica"
    assert int(open(marker).read()) == 2  # the probes really did fail
    serve.delete("Flaky")


def test_graceful_drain_completes_inflight_on_downscale(ha_cluster):
    """Downscale routes through the drain path: the victim leaves the
    route table first, finishes its in-flight requests, and only then
    dies — no dropped work."""

    @serve.deployment(num_replicas=2, name="Slow",
                      graceful_shutdown_timeout_s=10.0)
    def slow(x):
        time.sleep(1.2)
        return x * 2

    hs = serve.run(slow.options(name="Slow").bind(), name="slowapp",
                   http_port=None)
    refs = [hs.remote(i) for i in range(4)]
    time.sleep(0.1)
    # same version hash (num_replicas is excluded) → pure downscale
    serve.run(slow.options(name="Slow", num_replicas=1).bind(),
              name="slowapp", http_port=None, _blocking_timeout=60.0)
    assert sorted(ray_tpu.get(refs, timeout=60.0)) == [0, 2, 4, 6]
    st = _wait_status("Slow", lambda s: s.get("live_replicas") == 1
                      and s.get("draining_replicas", 1) == 0)
    assert st["status"] == "HEALTHY"
    serve.delete_application("slowapp")


# ------------------------------------------------------------- unit-level


def _bare_controller():
    """A ServeController shell for pure-logic tests: no cluster, no
    control loop, no journal."""
    from ray_tpu.serve.controller import ServeController
    import threading as _t
    c = ServeController.__new__(ServeController)
    c._lock = _t.RLock()
    c._deployments = {}
    c._last_errors = {}
    c._last_error = None
    c._last_load_table = {}
    c._replica_nodes = {}
    c._draining_nodes = {}
    return c


class _FakeHandle:
    def __init__(self, hex_id):
        self._id_hex = hex_id


def test_downscale_victim_is_least_loaded():
    """The autoscaler/downscale eviction order is ascending reported
    queue depth — not dict-iteration order (which routinely picked the
    busiest replica)."""
    c = _bare_controller()
    a, b, d = _FakeHandle("aa"), _FakeHandle("bb"), _FakeHandle("dd")
    c._last_load_table = {"Dep": {
        "aa": {"queue_len": 7.0}, "bb": {"queue_len": 0.0},
        "dd": {"queue_len": 3.0}}}
    order = c._least_loaded("Dep", [a, b, d])
    assert [h._id_hex for h in order] == ["bb", "dd", "aa"]
    # replicas without a report sort as idle (safe victims)
    e = _FakeHandle("ee")
    order = c._least_loaded("Dep", [a, e])
    assert [h._id_hex for h in order] == ["ee", "aa"]


def test_controller_error_scoped_to_failing_deployment():
    """last_controller_error lands ONLY on the deployment whose
    reconcile/health pass failed, not on every deployment."""
    from ray_tpu.serve.controller import _DeploymentInfo
    c = _bare_controller()
    for name in ("Good", "Bad"):
        info = _DeploymentInfo({"name": name, "num_replicas": 0})
        c._deployments[name] = info
    c._last_errors["Bad"] = "Traceback: boom"
    statuses = c.get_deployment_statuses()
    assert "last_controller_error" not in statuses["Good"]
    assert statuses["Bad"]["last_controller_error"] == "Traceback: boom"


def test_longpoll_host_handles_version_regression():
    """A client cursor AHEAD of the host (previous controller
    incarnation) returns immediately instead of parking for the full
    listen timeout."""
    from ray_tpu.serve._private.long_poll import LongPollHost
    host = LongPollHost()
    host.notify_changed("route_table", {"a": 1})
    t0 = time.monotonic()
    version, snap = host.listen("route_table", last_version=99,
                                timeout=5.0)
    assert time.monotonic() - t0 < 1.0
    assert version == 1 and snap == {"a": 1}


def test_wait_healthy_reports_controller_death(ha_cluster):
    """api._wait_healthy raises a clear controller-death error, not a
    bare deployment timeout, when the controller actor is gone for
    good (killed with no_restart)."""
    from ray_tpu.serve import api as serve_api

    @serve.deployment(name="Doomed")
    def doomed(x):
        return x

    serve.run(doomed.options(name="Doomed").bind(), http_port=None)
    ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
    ray_tpu.kill(ctrl)  # no_restart=True: max_restarts is zeroed
    time.sleep(0.5)
    with pytest.raises(RuntimeError, match="controller has died"):
        serve_api._wait_healthy(ctrl, ["Doomed"], timeout=15.0)
    # leave the module cluster usable: a fresh start() builds a new
    # controller (the old name is freed by DEAD state)
    serve.shutdown()


# ------------------------------------------------------ chaos-seeded e2e


def test_chaos_replica_kill_traffic_survives(tmp_path):
    """RTPU_CHAOS kills a replica at its 5th accepted request; the
    proxy retries onto surviving replicas and the controller replaces
    the dead one — HTTP GETs keep succeeding end to end."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()  # chaos must ride a FRESH cluster's env
    log = str(tmp_path / "chaos.jsonl")
    os.environ["RTPU_CHAOS"] = json.dumps({
        "seed": 21,
        "schedule": [{"site": "serve.replica.request", "op": "kill",
                      "at": 5, "method": "ChaosEcho", "proc": "worker"}]})
    os.environ["RTPU_CHAOS_LOG"] = log
    ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    try:
        @serve.deployment(num_replicas=2, name="ChaosEcho")
        def echo(x=None):
            return {"ok": True}

        serve.run(echo.options(name="ChaosEcho").bind(),
                  route_prefix="/chaos", http_port=8321)
        proxy = ray_tpu.get_actor("SERVE_PROXY")
        port = ray_tpu.get(proxy.get_port.remote(), timeout=10.0)

        import urllib.request
        ok = 0
        for _ in range(25):
            resp = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/chaos", timeout=30).read())
            assert resp == {"ok": True}
            ok += 1
            time.sleep(0.05)
        assert ok == 25

        fired = chaos.read_log(log)
        assert any(r["site"] == "serve.replica.request" for r in fired), \
            "chaos never fired — the site is not wired"
        _wait_status("ChaosEcho",
                     lambda s: s.get("status") == "HEALTHY"
                     and s.get("live_replicas") == 2)
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_chaos_controller_kill_zero_dropped_requests(tmp_path):
    """The acceptance scenario: RTPU_CHAOS SIGKILLs the controller at a
    fixed control-loop tick while handle traffic runs. Zero requests
    fail (the data plane routes from cached tables), and the restarted
    controller re-adopts the live replicas."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()  # chaos must ride a FRESH cluster's env
    os.environ["RTPU_CHAOS"] = json.dumps({
        "seed": 23,
        "schedule": [{"site": "serve.controller.tick", "op": "kill",
                      "at": 4, "proc": "worker"}]})
    ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    try:
        @serve.deployment(num_replicas=2, name="Steady")
        def steady(x=None):
            return x + 1 if isinstance(x, int) else {"ok": True}

        h = serve.run(steady.options(name="Steady").bind(),
                      route_prefix="/steady", http_port=8331)
        proxy = ray_tpu.get_actor("SERVE_PROXY")
        port = ray_tpu.get(proxy.get_port.remote(), timeout=10.0)
        pid0 = _controller_info()[1]["pid"]

        errors, results = [], []
        stop = threading.Event()

        def load():
            i = 0
            while not stop.is_set():
                try:
                    assert ray_tpu.get(h.remote(i), timeout=20.0) == i + 1
                    results.append(i)
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))
                i += 1

        def http_load():
            import urllib.request
            while not stop.is_set():
                try:
                    resp = json.loads(urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/steady",
                        timeout=30).read())
                    assert resp == {"ok": True}
                    results.append(-1)
                except Exception as e:  # noqa: BLE001
                    errors.append("http: " + repr(e))
                time.sleep(0.05)

        threads = [threading.Thread(target=load) for _ in range(2)] + \
            [threading.Thread(target=http_load)]
        for t in threads:
            t.start()
        # the chaos kill lands ~4 ticks (~4s) in; ride through it
        deadline = time.time() + 45
        info = None
        while time.time() < deadline:
            try:
                info = _controller_info(timeout=5.0)[1]
                if info["pid"] != pid0 and info["recovered"]:
                    break
            except AssertionError:
                pass
            time.sleep(0.5)
        stop.set()
        for t in threads:
            t.join()
        assert info and info["pid"] != pid0, "chaos kill never fired"
        assert info["recovered"] and info["adopted_replicas"] >= 2, info
        assert not errors, \
            f"{len(errors)} requests dropped during controller outage: " \
            f"{errors[:5]}"
        assert len(results) > 20
        _wait_status("Steady", lambda s: s.get("status") == "HEALTHY")
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


# -------------------------------------------------- node preemption drain


def test_node_preemption_replaces_replicas_before_drain(tmp_path):
    """A draining node's replicas get start-before-stop replacements on
    surviving nodes inside the grace window (the PR-4 preemption drain
    feeding the serve control plane)."""
    from ray_tpu._private.cluster_utils import Cluster
    from ray_tpu._private import worker as wmod
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    try:
        cluster.add_node(num_cpus=4)
        cluster.connect()
        cluster.wait_for_nodes()
        w = wmod._global_worker

        @serve.deployment(num_replicas=2, name="Spread",
                          graceful_shutdown_timeout_s=10.0,
                          ray_actor_options={"scheduling_strategy":
                                             "SPREAD"})
        def spread(x):
            return x + 1

        h = serve.run(spread.options(name="Spread").bind(),
                      http_port=None)
        assert ray_tpu.get(h.remote(1), timeout=30.0) == 2

        # find a node hosting a replica but NOT the controller
        actors = w.call_sync(w.gcs, "list_actors", {})
        ctrl_node = next(a["node_id"] for a in actors
                         if a.get("class_name") == "ServeController"
                         and a.get("state") == "ALIVE")
        replica_nodes = {a["node_id"] for a in actors
                         if a.get("class_name") == "ReplicaActor"
                         and a.get("state") == "ALIVE"}
        victims = replica_nodes - {ctrl_node}
        if not victims:
            pytest.skip("replicas co-located with the controller; "
                        "SPREAD did not separate them on this box")
        victim = next(iter(victims))

        w.call_sync(w.gcs, "preempt_node", {
            "node_id": victim, "grace_s": 10.0,
            "reason": "test spot notice"})

        # replacements start elsewhere; statuses converge with BOTH
        # replicas off the victim
        def replicas_ok(_st):
            acts = w.call_sync(w.gcs, "list_actors", {})
            live = [a for a in acts
                    if a.get("class_name") == "ReplicaActor"
                    and a.get("state") == "ALIVE"]
            return (_st.get("status") == "HEALTHY"
                    and _st.get("live_replicas") == 2
                    and all(a["node_id"] != victim for a in live))

        _wait_status("Spread", replicas_ok, timeout=60.0)
        # traffic still flows after failover
        assert ray_tpu.get(h.remote(2), timeout=30.0) == 3
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        cluster.shutdown()


# ---------------------------------------------------------- bench smoke


def test_bench_serve_ha_smoke():
    env = dict(os.environ, _BENCH_SERVE_HA="1", JAX_PLATFORMS="cpu",
               BENCH_SERVE_HA_DURATION="4", BENCH_SERVE_HA_CLIENTS="3")
    env.pop("LIBTPU_INIT_ARGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
        stdout=subprocess.PIPE, text=True, timeout=300, env=env,
        cwd=REPO_ROOT)
    row = None
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            row = json.loads(line)
            break
    assert row is not None, proc.stdout
    assert row.get("metric") == "serve_ha", row
    for key in ("rolling_total", "rolling_failed", "rolling_p99_ms",
                "ctrl_kill_total", "ctrl_kill_failed", "ctrl_kill_p99_ms",
                "ctrl_recovery_s"):
        assert key in row, (key, row)
    # the acceptance bar: zero dropped requests in both scenarios
    assert row["rolling_failed"] == 0, row
    assert row["ctrl_kill_failed"] == 0, row
