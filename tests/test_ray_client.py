"""ray:// client connectivity — a driver OUTSIDE the cluster host process.

Reference analogue: python/ray/tests/test_client.py. The server runs in a
subprocess holding a real cluster; this test process connects over TCP
with ray_tpu.init("ray://...") and uses the public API end to end.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

SERVER_SCRIPT = """
import os, sys, time
os.environ.setdefault("RTPU_PRESTART_WORKERS", "0")
os.environ["JAX_PLATFORMS"] = "cpu"
import ray_tpu
from ray_tpu.util.client.server import ClientServer
ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
srv = ClientServer(port=0, host="127.0.0.1")
print(f"PORT={srv.port}", flush=True)
# serve until the parent kills us
while True:
    time.sleep(1)
"""


@pytest.fixture(scope="module")
def client_server():
    env = dict(os.environ)
    env.pop("RTPU_ADDRESS", None)
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", SERVER_SCRIPT],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    port = None
    deadline = time.time() + 120
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("PORT="):
            port = int(line.strip().split("=", 1)[1])
            break
    if port is None:
        proc.kill()
        pytest.fail("client server did not start")
    yield port
    proc.kill()
    proc.wait(timeout=30)


@pytest.fixture()
def ray_client(client_server):
    import ray_tpu
    ray_tpu.init(address=f"ray://127.0.0.1:{client_server}")
    yield
    ray_tpu.shutdown()


def test_client_put_get_roundtrip(ray_client):
    import ray_tpu
    from ray_tpu.util.client import ClientObjectRef
    arr = np.arange(1000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    assert isinstance(ref, ClientObjectRef)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out, arr)


def test_client_remote_task(ray_client):
    import ray_tpu

    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(2, 40)) == 42
    # ref args resolve server-side to the real objects
    ref = ray_tpu.put(10)
    assert ray_tpu.get(add.remote(ref, 5)) == 15
    # options + multiple returns
    @ray_tpu.remote
    def pair(x):
        return x, x + 1

    r1, r2 = ray_tpu.get(pair.options(num_returns=2).remote(7))
    assert (r1, r2) == (7, 8)


def test_client_wait(ray_client):
    import ray_tpu

    @ray_tpu.remote
    def slow(t):
        import time as _t
        _t.sleep(t)
        return t

    fast = slow.remote(0.01)
    slow_ref = slow.remote(5.0)
    ready, not_ready = ray_tpu.wait([fast, slow_ref], num_returns=1,
                                    timeout=10.0)
    assert ready == [fast] and not_ready == [slow_ref]


def test_client_actor_lifecycle(ray_client):
    import ray_tpu
    from ray_tpu.util.client import ClientActorHandle

    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.x = start

        def incr(self, n=1):
            self.x += n
            return self.x

    c = Counter.remote(100)
    assert isinstance(c, ClientActorHandle)
    assert ray_tpu.get(c.incr.remote()) == 101
    assert ray_tpu.get(c.incr.remote(9)) == 110
    # actor handles pass through task args (rehydrated server-side)
    @ray_tpu.remote
    def poke(counter):
        return ray_tpu.get(counter.incr.remote(5))

    assert ray_tpu.get(poke.remote(c)) == 115
    ray_tpu.kill(c)


def test_client_named_actor(ray_client):
    import ray_tpu

    @ray_tpu.remote
    class KV:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v
            return True

        def get(self, k):
            return self.d.get(k)

    KV.options(name="kv_client_test").remote()
    h = ray_tpu.get_actor("kv_client_test")
    assert ray_tpu.get(h.set.remote("a", 1))
    assert ray_tpu.get(h.get.remote("a")) == 1


def test_client_cluster_info_and_errors(ray_client):
    import ray_tpu
    res = ray_tpu.cluster_resources()
    assert res.get("CPU", 0) >= 4
    assert ray_tpu.is_initialized()

    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(Exception, match="kaboom"):
        ray_tpu.get(boom.remote())
