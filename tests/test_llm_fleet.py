"""Fleet-efficient LLM serving (docs/LLM_SERVING.md): radix prefix KV
cache (refcounted page sharing, copy-on-extend, LRU eviction),
prefill/decode disaggregation (KV handoff between engines, the
``llm.kv_ship`` chaos site's fallback-to-re-prefill), and greedy
speculative decoding (token-for-token identical to sequential greedy
for the toy model, gpt2, and llama-with-a-gpt2-draft), plus the
role-aware router/autoscaler units and the llm-chat-disagg game day
with exact per-token + cache-hit reconciliation. Tier-1, CPU-only.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ray_tpu.serve.llm import (EngineConfig, KVShipper, LLMEngine,
                               PagedKVCache, RadixPrefixCache,
                               SamplingParams, ToyAdapter, greedy_verify)
from ray_tpu.serve.llm.kv_cache import OutOfKVBlocksError
from ray_tpu.serve.llm.model_runner import make_adapter
from ray_tpu.serve.llm.spec_decode import ToyDraft, make_draft

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _drain(eng, sid, timeout=60.0):
    toks, cur = [], 0
    deadline = time.time() + timeout
    while time.time() < deadline:
        ch = eng.poll(sid, cur, max_wait_s=5.0)
        toks += ch["tokens"]
        cur = ch["cursor"]
        if ch["done"]:
            return toks, ch
    raise TimeoutError(f"stream {sid} never finished")


# -------------------------------------------- refcounted page allocator


def test_kv_refcount_share_cow_eviction_interleave():
    """Satellite: refcounted page lifetimes survive an interleaving of
    prefix sharing, copy-on-write privatization, sequence completion
    and cache-branch release — pages return to the free list exactly
    when their LAST reference drops, and never twice."""
    c = PagedKVCache(num_blocks=9, block_size=4)     # 8 usable pages
    a = c.allocate("a", 16)                          # 4 pages, ref 1
    assert c.free_blocks() == 4
    shared = a[:2]
    # b maps a's first two pages read-only + 2 fresh
    b = c.allocate_with_prefix("b", 16, shared)
    assert b[:2] == shared and c.free_blocks() == 2
    assert all(c.ref_count(p) == 2 for p in shared)

    # the "prefix cache" takes its own reference on one shared page
    c.incref([shared[0]])
    assert c.ref_count(shared[0]) == 3

    # b writes into a shared page -> private copy, a's view unchanged
    old, new = c.copy_on_write("b", 1)
    assert old == shared[1] and new != old
    assert c.block_table("b")[1] == new
    assert c.block_table("a")[1] == old
    assert c.ref_count(old) == 1 and c.ref_count(new) == 1
    assert c.free_blocks() == 1

    # a already-private page is a no-op COW
    o2, n2 = c.copy_on_write("b", 1)
    assert (o2, n2) == (new, new)

    # a finishes: its private pages free; shared[0] lives on (b + cache)
    freed = c.free("a")
    assert freed == 3                                # a[1..3]; a[0] shared
    assert c.ref_count(shared[0]) == 2
    # b finishes: everything b held frees, shared[0] still cached
    c.free("b")
    assert c.ref_count(shared[0]) == 1
    assert c.free_blocks() == 7
    # the cache drops its branch: the final reference frees the page
    assert c.decref([shared[0]]) == 1
    assert c.free_blocks() == 8
    # double release is inert, not a corruption
    assert c.decref([shared[0]]) == 0
    assert c.free_blocks() == 8

    # dead pages are not shareable
    with pytest.raises(ValueError):
        c.incref([shared[0]])
    with pytest.raises(ValueError):
        c.allocate_with_prefix("c", 8, [shared[0]])


def test_kv_cow_exhaustion_and_exact_admission():
    c = PagedKVCache(num_blocks=4, block_size=4)     # 3 usable
    c.allocate("a", 8)                               # 2 pages
    c.allocate_with_prefix("b", 12, c.block_table("a"))  # +1 fresh
    assert c.free_blocks() == 0
    with pytest.raises(OutOfKVBlocksError):
        c.copy_on_write("b", 0)                      # shared, no free page
    with pytest.raises(OutOfKVBlocksError):
        c.allocate("c", 4)


# ------------------------------------------------------- radix prefix


def test_radix_prefix_lookup_insert_evict():
    """Tree semantics: whole-page matches only, first-writer-wins
    publication, LRU eviction skips pages live sequences still map."""
    c = PagedKVCache(num_blocks=12, block_size=4)
    pc = RadixPrefixCache(c)
    prompt = list(range(4 * 3 + 2))                  # 3 full pages + 2
    t = c.allocate("donor", len(prompt))
    assert pc.insert(prompt, t) == 3                 # partial page unshared
    assert len(pc) == 3
    # donor finishes; cache refs keep all 3 published pages resident
    c.free("donor")
    assert all(c.ref_count(p) == 1 for p in t[:3])

    # lookup: full-page prefix only, longest match wins
    m, pages = pc.lookup(prompt)
    assert m == 12 and pages == t[:3]
    m, pages = pc.lookup(prompt[:7])                 # 1 full page + 3
    assert m == 4 and pages == t[:1]
    m, pages = pc.lookup([999] * 8)
    assert m == 0 and pages == []

    # a consumer maps the prefix; eviction must not touch its pages
    c.allocate_with_prefix("user", len(prompt), t[:3])
    # burn the remaining pool so eviction has something to do
    filler = c.allocate("filler", 4 * c.free_blocks())
    assert c.free_blocks() == 0
    freed = pc.evict(1)
    assert freed == 0                                # everything referenced
    c.free("filler")
    c.free("user")
    # now the leaf branch (deepest first) is evictable, LRU order
    freed = pc.evict(c.free_blocks() + 2)
    assert freed >= 2
    st = pc.stats()
    assert st["prefix_evicted_pages"] == freed
    assert st["prefix_hit_tokens_total"] == 16

    # insert against freed pages must not publish dangling entries
    assert pc.insert(prompt, filler[:3]) == 0
    m2, pages2 = pc.lookup(prompt)
    for p in pages2:
        assert c.ref_count(p) >= 1


def _toy_engine(**cfg):
    defaults = dict(num_blocks=64, block_size=8, max_seq_len=256,
                    max_running=8)
    defaults.update(cfg)
    return LLMEngine(ToyAdapter(), EngineConfig(**defaults))


def _gen(eng, prompts, ntok=10, rid_prefix="r"):
    out = []
    for i, p in enumerate(prompts):
        sid = eng.add_request(
            list(p), SamplingParams(max_new_tokens=ntok),
            request_id=f"{rid_prefix}{i}")
        toks, ch = _drain(eng, sid)
        assert not ch.get("error"), ch
        out.append(toks)
    return out


def test_prefix_cache_engine_identity_and_hit_accounting():
    """Warm (prefix-cached) generation is token-identical to cold, the
    engine's cache-hit counter matches the tree's, and hits show up in
    the per-request ledger column reconcile C11 audits."""
    rng = np.random.RandomState(3)
    sys_prompt = [int(t) for t in rng.randint(0, 256, 24)]  # 3 pages
    prompts = [sys_prompt + [int(t) for t in rng.randint(0, 256, n)]
               for n in (5, 9, 13, 2, 17, 8)]

    cold = _gen(_toy_engine(), prompts)
    eng = _toy_engine(enable_prefix_cache=True)
    warm = _gen(eng, prompts)
    assert warm == cold

    m = eng.metrics()
    assert m["cache_hit_tokens_total"] > 0
    assert m["cache_hit_tokens_total"] == \
        eng.prefix_cache.stats()["prefix_hit_tokens_total"]
    # ledger rows carry (rid, n, reason, n_prompt, cached): the sum of
    # the cached column IS the counter (C11's replica-level join)
    ledger = eng.token_ledger()
    assert sum(r[4] for r in ledger) == m["cache_hit_tokens_total"]
    for i, r in enumerate(sorted(ledger, key=lambda r: r[0])):
        assert r[3] == len(prompts[i])
    # every request after the first shares >= 2 full pages (the third
    # page is sacrificed to copy-on-extend when the tail is partial)
    by_rid = {r[0]: r[4] for r in ledger}
    assert all(by_rid[f"r{i}"] >= 16 for i in range(1, len(prompts)))
    eng.stop()


def test_prefix_cache_copy_on_extend_does_not_corrupt_shared_pages():
    """A warm request whose cached prefix ends mid-page privatizes that
    page before writing (copy-on-extend); the shared original must
    still serve later requests byte-identically."""
    rng = np.random.RandomState(7)
    base = [int(t) for t in rng.randint(0, 256, 20)]  # 2.5 pages @ bs 8
    divergent = base + [int(t) for t in rng.randint(0, 256, 11)]
    eng = _toy_engine(enable_prefix_cache=True)
    cold_eng = _toy_engine()
    # publish base; extend it (COW on page 2); then replay base EXACTLY
    seq = [base, divergent, base, divergent]
    warm = _gen(eng, seq)
    cold = _gen(cold_eng, seq)
    assert warm == cold
    assert eng.metrics()["cache_hit_tokens_total"] > 0
    eng.stop()
    cold_eng.stop()


def test_prefix_cache_eviction_under_pool_pressure():
    """A pool too small for the working set still admits everything:
    the engine evicts cold branches instead of shedding, and outputs
    stay identical to an uncached engine."""
    rng = np.random.RandomState(11)
    prompts = [[int(t) for t in rng.randint(0, 256, 24 + (i % 3) * 8)]
               for i in range(10)]
    small = dict(num_blocks=24, block_size=8, max_running=2)
    warm_eng = _toy_engine(enable_prefix_cache=True, **small)
    warm = _gen(warm_eng, prompts, ntok=6)
    cold = _gen(_toy_engine(**small), prompts, ntok=6)
    assert warm == cold
    assert warm_eng.prefix_cache.stats()["prefix_evicted_pages"] > 0
    warm_eng.stop()


# --------------------------------------------------- speculative decode


def test_greedy_verify_accept_reject_bonus():
    # full acceptance -> every proposal + the bonus token
    assert greedy_verify([5, 1, 2, 3], [1, 2, 3, 9]) == [1, 2, 3, 9]
    # first mismatch replaced by the target's token, rest discarded
    assert greedy_verify([5, 1, 2, 3], [1, 7, 8, 9]) == [1, 7]
    assert greedy_verify([5, 1, 2, 3], [4, 7, 8, 9]) == [4]
    # window of 1 (no proposals) degenerates to plain greedy
    assert greedy_verify([5], [6]) == [6]


@pytest.mark.parametrize("draft_seed", [0, 7])
def test_spec_decode_toy_identity(draft_seed):
    """Speculative greedy == sequential greedy, token for token — with
    a perfect draft (same seed: ~100% acceptance) AND an adversarial
    one (different seed: constant rejection)."""
    rng = np.random.RandomState(13)
    prompts = [[int(t) for t in rng.randint(0, 256, n)]
               for n in (4, 11, 23, 7)]
    base = _gen(_toy_engine(), prompts, ntok=18)
    eng = _toy_engine(spec_k=3,
                      draft_model_config={"seed": draft_seed})
    spec = _gen(eng, prompts, ntok=18)
    assert spec == base
    m = eng.metrics()
    assert m["spec_draft_tokens_total"] > 0
    assert 0 <= m["spec_accepted_tokens_total"] \
        <= m["spec_draft_tokens_total"]
    if draft_seed == 0:   # draft IS the target's LM -> full acceptance
        assert m["spec_accepted_tokens_total"] == \
            m["spec_draft_tokens_total"]
    eng.stop()


def _flax_identity(target_kind, draft_model, prompts, ntok=6):
    cfgkw = dict(num_blocks=64, block_size=8, max_seq_len=128,
                 max_running=4)
    base_eng = LLMEngine(make_adapter(target_kind),
                         EngineConfig(**cfgkw))
    base = _gen(base_eng, prompts, ntok=ntok, rid_prefix="b")
    base_eng.stop()
    spec_eng = LLMEngine(
        make_adapter(target_kind),
        EngineConfig(spec_k=2, draft_model=draft_model, **cfgkw))
    spec = _gen(spec_eng, prompts, ntok=ntok, rid_prefix="s")
    m = spec_eng.metrics()
    spec_eng.stop()
    assert spec == base, (target_kind, draft_model)
    assert m["spec_draft_tokens_total"] > 0


def test_spec_decode_gpt2_batched_verify_identity():
    """Satellite numerics: gpt2's ONE batched multi-token verify step
    through the paged decode path commits exactly what sequential
    greedy commits, over seeded prompts."""
    rng = np.random.RandomState(17)
    prompts = [[int(t) for t in rng.randint(0, 512, n)]
               for n in (5, 12, 9)]
    _flax_identity("gpt2", "gpt2", prompts)


def test_spec_decode_llama_with_gpt2_draft_identity():
    """Satellite numerics: a gpt2 tiny draft legally drafts for a llama
    tiny target (both 512-token vocabs); verification stays
    token-identical no matter how bad the cross-model proposals are."""
    rng = np.random.RandomState(19)
    prompts = [[int(t) for t in rng.randint(0, 512, n)]
               for n in (6, 13)]
    _flax_identity("llama", "gpt2", prompts)


def test_spec_decode_composes_with_prefix_cache():
    rng = np.random.RandomState(23)
    sys_prompt = [int(t) for t in rng.randint(0, 256, 16)]
    prompts = [sys_prompt + [int(t) for t in rng.randint(0, 256, n)]
               for n in (3, 8, 5)]
    base = _gen(_toy_engine(), prompts, ntok=12)
    eng = _toy_engine(enable_prefix_cache=True, spec_k=3)
    both = _gen(eng, prompts, ntok=12)
    assert both == base
    m = eng.metrics()
    assert m["cache_hit_tokens_total"] > 0
    assert m["spec_draft_tokens_total"] > 0
    eng.stop()


# ------------------------------------------------ disaggregation (engine)


def test_disagg_engine_roundtrip_identity_no_leaked_pages():
    """Satellite: prefill_export -> KVShipper -> adopt_request across
    two engines is output-identical to a unified engine, the ledgers
    split into handoff + completion rows, and both pools drain to zero
    used pages when the streams finish."""
    rng = np.random.RandomState(29)
    prompts = [[int(t) for t in rng.randint(0, 256, n)]
               for n in (21, 9, 33)]
    unified = _gen(_toy_engine(), prompts, ntok=12)

    pe, de = _toy_engine(), _toy_engine()
    shipper = KVShipper("test")          # no plasma -> inline lane
    outs = []
    for i, p in enumerate(prompts):
        sampling = SamplingParams(max_new_tokens=12)
        sid = pe.prefill_export(list(p), sampling,
                                request_id=f"r{i}")
        toks, ch = _drain(pe, sid)
        export = pe.take_export(sid)
        assert export is not None and export["first_token"] == toks[0]
        desc = shipper.ship({"kv": export["kv"]})
        assert desc["lane"] == "inline"
        frame = shipper.receive(desc)
        did = de.adopt_request(list(p), export["first_token"],
                               frame["kv"], sampling,
                               request_id=f"r{i}")
        dtoks, dch = _drain(de, did)
        assert not dch.get("error"), dch
        outs.append(dtoks)
    assert outs == unified

    pl, dl = pe.token_ledger(), de.token_ledger()
    assert all(r[2] == "handoff" and r[1] == 1 for r in pl)
    assert all(r[2] == "length" and r[1] == 12 for r in dl)
    assert [r[3] for r in sorted(pl)] == [len(p) for p in prompts]
    # cached column on the decode side = whole adopted prompt (C11:
    # adopted tokens are cache-hit tokens — no prefill ran for them)
    assert [r[4] for r in sorted(dl)] == [len(p) for p in prompts]
    assert pe.metrics()["kv_blocks_used"] == 0
    assert de.metrics()["kv_blocks_used"] == 0
    pe.stop()
    de.stop()


def test_disagg_corrupt_frame_detected_by_crc():
    """A torn frame never reaches deserialization: flip one byte and
    receive() returns None (re-prefill signal), not garbage."""
    shipper = KVShipper("crc")
    desc = shipper.ship({"kv": {"kind": "toy", "n": 3,
                                "pages": np.ones((2, 8, 4))}})
    desc = dict(desc)
    body = bytearray(desc["b"])
    body[len(body) // 2] ^= 0x5A
    desc["b"] = bytes(body)
    assert shipper.receive(desc) is None


# ----------------------------------------- role-aware router/autoscaler


class _FakeReplica:
    def __init__(self, hex_id):
        self._id_hex = hex_id


def test_replica_set_tracks_roles():
    from ray_tpu.serve._private.router import ReplicaSet
    rs = ReplicaSet("d", 8)
    reps = [_FakeReplica(f"{i:02d}aa") for i in range(3)]
    rs.update_replicas(reps, replica_roles={
        "00aa": "prefill", "01aa": "decode", "02aa": "decode"})
    assert rs.disaggregated()
    assert rs.role_members("prefill") == {"00aa"}
    assert rs.role_members("decode") == {"01aa", "02aa"}
    # a role map referencing dead replicas is filtered on update
    rs.update_replicas(reps[:1], replica_roles={
        "00aa": "prefill", "01aa": "decode"})
    assert not rs.disaggregated()     # no live decode replica
    # no roles at all -> unified
    rs.update_replicas(reps)
    assert not rs.disaggregated() and rs.role_members("prefill") == set()


def test_controller_role_assignment_is_age_stable():
    from ray_tpu.serve.controller import ServeController
    info = type("I", (), {})()
    info.config = {"llm_roles": {"prefill": 1, "decode": 2}}
    info.replica_names = {"b" * 8: "rep#2", "a" * 8: "rep#1",
                          "c" * 8: "rep#3"}
    roles = ServeController._llm_roles_map(
        info, ["c" * 8, "a" * 8, "b" * 8])
    assert roles == {"a" * 8: "prefill", "b" * 8: "decode",
                     "c" * 8: "decode"}
    # oldest replica keeps prefill across membership churn
    roles2 = ServeController._llm_roles_map(info, ["b" * 8, "a" * 8])
    assert roles2 == {"a" * 8: "prefill", "b" * 8: "decode"}
    info.config = {}
    assert ServeController._llm_roles_map(info, ["a" * 8]) is None


def test_autoscaler_per_role_and_cache_hit_signals():
    from ray_tpu.serve._private.autoscaling import (AutoscalingConfig,
                                                    AutoscalingPolicy)
    cfg = AutoscalingConfig(min_replicas=1, max_replicas=10,
                            target_tokens_per_s_per_replica=100.0,
                            upscale_delay_s=0.0, downscale_delay_s=0.0)
    # cache-hit tokens/s count as served demand: 150 generated + 150
    # cache-skipped needs 3 replicas at a 100 tok/s target
    p = AutoscalingPolicy(cfg)
    assert p.get_decision(2, 0.0, now=0.0, signals={
        "tokens_per_s": 150.0,
        "cache_hit_tokens_per_s": 150.0}) == 3
    # per-role: a saturated decode tier can't hide behind an idle
    # prefill tier — ceil(10/100)=1 prefill + ceil(250/100)=3 decode
    p2 = AutoscalingPolicy(cfg)
    assert p2.get_decision(3, 0.0, now=0.0, signals={
        "tokens_per_s": 260.0,
        "per_role": {"prefill": {"tokens_per_s": 10.0},
                     "decode": {"tokens_per_s": 250.0}}}) == 4


# -------------------------------------------- subprocess isolation tests


def _run_script(script, extra_env=None, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RTPU_PRESTART_WORKERS="0")
    env.update(extra_env or {})
    return subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True,
                          timeout=timeout, cwd=REPO_ROOT)


_DISAGG_SERVE_SCRIPT = r"""
import json, random
import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.llm import LLMServer
from ray_tpu.actor import get_actor_by_id

ray_tpu.init(num_cpus=8, object_store_memory=128*1024*1024,
             _system_config={"prestart_workers": False})
eng = {"num_blocks": 128, "block_size": 8, "max_seq_len": 256}
dep = serve.deployment(name="d", num_replicas=3,
                       llm_roles={"prefill": 1, "decode": 2},
                       max_concurrent_queries=16)(LLMServer)
h = serve.run(dep.bind("toy", {"per_seq_delay_s": 0.001}, eng),
              name="d", route_prefix="/d")
dep_u = serve.deployment(name="u", num_replicas=1,
                         max_concurrent_queries=16)(LLMServer)
hu = serve.run(dep_u.bind("toy", {"per_seq_delay_s": 0.001}, eng),
               name="u", route_prefix="/u")

base = [random.Random("sys").randrange(256) for _ in range(24)]
streams = []
for i in range(6):
    rng = random.Random(i)
    p = base + [rng.randrange(256) for _ in range(rng.randrange(3, 20))]
    payload = {"tokens": p, "max_new_tokens": 12}
    got = [t for ch in h.stream(payload, request_id=f"r{i}")
           for t in ch.get("tokens") or ()]
    want = [t for ch in hu.stream(payload, request_id=f"u{i}")
            for t in ch.get("tokens") or ()]
    streams.append({"rid": f"r{i}", "n": len(got),
                    "identical": got == want})

ctrl = ray_tpu.get_actor("SERVE_CONTROLLER")
_, table = ray_tpu.get(ctrl.get_route_table.remote())
roles = table["d"].get("replica_roles") or {}
per_replica = {}
for hex_id, role in roles.items():
    rep = get_actor_by_id(hex_id)
    m = ray_tpu.get(rep.handle_request.remote("__llm_metrics__", (), {}),
                    timeout=10)
    per_replica[role + ":" + hex_id[:6]] = {
        "kv_used": m.get("kv_blocks_used"),
        "reasons": sorted({r[2] for r in (m.get("token_ledger") or [])}),
    }
print("VERDICT=" + json.dumps({
    "streams": streams,
    "roles": sorted(roles.values()),
    "per_replica": per_replica}))
serve.shutdown(); ray_tpu.shutdown()
"""


def test_disagg_serve_two_hop_end_to_end():
    """The full serve path: llm_roles in the deployment config, roles
    published in the route table, every admission routed
    prefill->decode with a KV handoff, streams identical to a unified
    deployment, zero pages leaked anywhere."""
    r = _run_script(_DISAGG_SERVE_SCRIPT)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    line = [ln for ln in r.stdout.splitlines()
            if ln.startswith("VERDICT=")]
    assert line, r.stdout + r.stderr
    v = json.loads(line[0][len("VERDICT="):])
    assert v["roles"] == ["decode", "decode", "prefill"], v
    assert all(s["identical"] and s["n"] == 12 for s in v["streams"]), v
    reasons = {k: d["reasons"] for k, d in v["per_replica"].items()}
    assert any("handoff" in rs for k, rs in reasons.items()
               if k.startswith("prefill")), reasons
    assert any("length" in rs for k, rs in reasons.items()
               if k.startswith("decode")), reasons
    assert all(d["kv_used"] == 0 for d in v["per_replica"].values()), v


def test_kv_ship_chaos_falls_back_to_reprefill():
    """Satellite: seeded chaos at ``llm.kv_ship`` (drop, corrupt, reset
    — each mid-handoff) degrades every faulted admission to a decode-
    side re-prefill: all streams complete with outputs identical to a
    unified deployment, nothing corrupted, no KV pages leaked."""
    chaos = {"seed": 31, "schedule": [
        {"site": "llm.kv_ship", "op": "drop", "at": 1},
        {"site": "llm.kv_ship", "op": "corrupt", "at": 2},
        {"site": "llm.kv_ship", "op": "reset", "at": 3},
    ]}
    r = _run_script(_DISAGG_SERVE_SCRIPT,
                    {"RTPU_CHAOS": json.dumps(chaos)})
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    line = [ln for ln in r.stdout.splitlines()
            if ln.startswith("VERDICT=")]
    assert line, r.stdout + r.stderr
    v = json.loads(line[0][len("VERDICT="):])
    # the first three admissions each ate a distinct mid-handoff fault
    # and still produced full-length, byte-identical output
    assert all(s["identical"] and s["n"] == 12 for s in v["streams"]), v
    # and no replica leaked a page over the fallback path
    assert all(d["kv_used"] == 0 for d in v["per_replica"].values()), v


def test_llm_chat_disagg_gameday_reconciles():
    """Acceptance: the disaggregated llm-chat game day — Zipf shared-
    prefix tenants, two-hop admissions, rolling update mid-run —
    grades fully reconciled: 0 failed streams, exact per-token AND
    cache-hit-token ledger joins (checks C10 + C11)."""
    script = r"""
import json
from ray_tpu.gameday.runner import run_scenario
from ray_tpu.gameday.scenario import load_scenario
res = run_scenario(load_scenario("llm-chat-disagg"), scale=0.4,
                   dashboard_port=18477)
out = {
    "passed": res.passed,
    "failed": res.report["overall"]["failed"],
    "admitted": res.report["overall"]["admitted"],
    "llm": res.report.get("llm"),
    "checks": {c["name"]: c["ok"]
               for c in res.reconciliation.get("checks", [])},
    "details": [c for c in res.reconciliation.get("checks", [])
                if not c["ok"]],
}
print("GAMEDAY=" + json.dumps(out))
"""
    r = _run_script(script, timeout=300)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
    line = [ln for ln in r.stdout.splitlines()
            if ln.startswith("GAMEDAY=")]
    assert line, r.stdout + r.stderr
    out = json.loads(line[0][len("GAMEDAY="):])
    assert out["failed"] == 0, out
    assert out["admitted"] > 30, out
    assert out["checks"].get("llm-tokens") is True, out["details"]
    assert out["checks"].get("llm-cache-hit") is True, out["details"]
    assert out["passed"], out["details"]
    assert out["llm"]["tokens_total"] > 100, out["llm"]
