"""TransformersTrainer: HF transformers on the worker gang.

Reference analogue: train/tests/test_huggingface_trainer.py, scaled to a
tiny CPU model.
"""

import numpy as np
import pytest

import ray_tpu

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_transformers_trainer_end_to_end(cluster, tmp_path):
    from ray_tpu.train import ScalingConfig, TransformersTrainer

    # locally-defined → cloudpickle ships them by value to the workers
    class TinyDataset(torch.utils.data.Dataset):
        def __init__(self, n=64, dim=8):
            g = torch.Generator().manual_seed(0)
            self.x = torch.randn(n, dim, generator=g)
            self.y = (self.x.sum(dim=1) > 0).long()

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return {"x": self.x[i], "labels": self.y[i]}

    class TinyModel(torch.nn.Module):
        def __init__(self, dim=8):
            super().__init__()
            self.lin = torch.nn.Linear(dim, 2)

        def forward(self, x=None, labels=None):
            logits = self.lin(x)
            loss = None
            if labels is not None:
                loss = torch.nn.functional.cross_entropy(logits, labels)
            return {"loss": loss, "logits": logits}

    def trainer_init(train_dataset=None, eval_dataset=None, **config):
        args = transformers.TrainingArguments(
            output_dir=str(tmp_path / "hf_out"),
            num_train_epochs=2,
            per_device_train_batch_size=8,
            logging_steps=4,
            report_to=[],
            save_strategy="no",
            use_cpu=True,
        )
        return transformers.Trainer(
            model=TinyModel(), args=args, train_dataset=train_dataset)

    trainer = TransformersTrainer(
        trainer_init,
        datasets={"train": TinyDataset()},
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    m = result.metrics
    assert m.get("done_training")
    assert "train_loss" in m and m["train_loss"] < 1.5


def test_transformers_trainer_checkpoints_and_ray_dataset(cluster,
                                                          tmp_path):
    import ray_tpu.data as rdata
    from ray_tpu.train import ScalingConfig, TransformersTrainer

    class TinyModel(torch.nn.Module):
        def __init__(self, dim=4):
            super().__init__()
            self.lin = torch.nn.Linear(dim, 2)

        def forward(self, x=None, labels=None):
            x = torch.as_tensor(np.asarray(x), dtype=torch.float32)
            logits = self.lin(x)
            loss = None
            if labels is not None:
                labels = torch.as_tensor(np.asarray(labels),
                                         dtype=torch.long)
                loss = torch.nn.functional.cross_entropy(logits, labels)
            return {"loss": loss, "logits": logits}

    def trainer_init(train_dataset=None, eval_dataset=None, **config):
        args = transformers.TrainingArguments(
            output_dir=str(tmp_path / "hf_out2"),
            num_train_epochs=1,
            per_device_train_batch_size=8,
            logging_steps=2,
            report_to=[],
            save_strategy="epoch",
            use_cpu=True,
        )
        return transformers.Trainer(
            model=TinyModel(), args=args, train_dataset=train_dataset)

    rng = np.random.default_rng(0)
    ds = rdata.from_numpy({
        "x": rng.standard_normal((48, 4)).astype(np.float32),
        "labels": (rng.standard_normal(48) > 0).astype(np.int64),
    })
    trainer = TransformersTrainer(
        trainer_init, datasets={"train": ds},
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    assert result.metrics.get("done_training")
    # rank 0 shipped a portable dict checkpoint with real HF files
    assert result.checkpoint is not None
    files = result.checkpoint.to_dict()
    assert any(n.startswith(("model", "pytorch_model"))
               for n in files), sorted(files)
