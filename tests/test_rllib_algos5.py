"""Fifth-wave RLlib algorithms: QMIX (cooperative multi-agent value
decomposition) and R2D2 (recurrent replay DQN).

Reference analogues: rllib/algorithms/qmix/tests/,
rllib/algorithms/r2d2/tests/test_r2d2.py.
"""

import numpy as np


def test_qmix_machinery_and_checkpoint():
    from ray_tpu.rllib.algorithms.qmix import QMixConfig
    algo = (QMixConfig().environment("CoopCartPole",
                                     env_config={"num_agents": 2})
            .training(learning_starts=200, rollout_fragment_length=64,
                      train_batch_size=32)
            .debugging(seed=0).build())
    for _ in range(6):
        r = algo.step()
    assert r["replay_size"] >= 300
    assert "learner/mean_qtot" in r
    assert np.isfinite(r["learner/loss"])
    st = algo.save_checkpoint()
    algo.load_checkpoint(st)
    acts = algo.compute_joint_actions(
        {a: np.zeros(4, np.float32) for a in algo.agent_ids})
    assert set(acts) == set(algo.agent_ids)
    algo.cleanup()


def test_qmix_mixer_is_monotonic():
    """∂Q_tot/∂Q_i ≥ 0 for every agent — the defining QMIX constraint."""
    import jax
    import jax.numpy as jnp
    from ray_tpu.rllib.algorithms.qmix import _QMixer
    mixer = _QMixer(n_agents=3, embed=16)
    rng = jax.random.PRNGKey(0)
    params = mixer.init(rng, jnp.zeros((1, 3)), jnp.zeros((1, 12)))[
        "params"]
    state = jax.random.normal(jax.random.PRNGKey(1), (8, 12))
    qs = jax.random.normal(jax.random.PRNGKey(2), (8, 3))
    grads = jax.vmap(
        jax.grad(lambda q, s: mixer.apply(
            {"params": params}, q[None], s[None])[0]))(qs, state)
    assert (np.asarray(grads) >= -1e-6).all(), grads.min()


def test_qmix_learns_coop_cartpole():
    """Team reward (episode ends when ANY pole falls) climbs well above
    the random baseline (~13)."""
    from ray_tpu.rllib.algorithms.qmix import QMixConfig
    algo = (QMixConfig().environment("CoopCartPole",
                                     env_config={"num_agents": 2})
            .training(learning_starts=300, rollout_fragment_length=64,
                      train_batch_size=64, epsilon_timesteps=4000,
                      training_intensity=4, lr=1e-3)
            .debugging(seed=0).build())
    best = 0.0
    for i in range(170):
        algo.step()
        if (i + 1) % 15 == 0:
            ev = algo.evaluate(num_episodes=3)["evaluation"]
            best = max(best, ev["episode_reward_mean"])
            if best > 40:
                break
    algo.cleanup()
    assert best > 30, f"QMIX stuck at {best}"


def test_r2d2_sequence_replay_padding():
    from ray_tpu.rllib.algorithms.r2d2 import _SequenceReplay
    rep = _SequenceReplay(capacity_episodes=10, seq_len=8, seed=0)
    rep.add_episode({
        "obs": np.ones((3, 4), np.float32),
        "next_obs": np.ones((3, 4), np.float32),
        "actions": np.zeros(3, np.int64),
        "rewards": np.ones(3, np.float32),
        "dones": np.array([False, False, True]),
    })
    out = rep.sample(4)
    assert out["obs"].shape == (4, 8, 4)
    assert out["mask"].shape == (4, 8)
    # 3-step episode inside an 8-step window: exactly 3 valid rows
    assert (out["mask"].sum(axis=1) == 3).all()
    # padded rows are zeroed
    assert (out["rewards"] * (1 - out["mask"]) == 0).all()


def test_r2d2_learns_cartpole():
    from ray_tpu.rllib.algorithms.r2d2 import R2D2Config
    algo = (R2D2Config().environment("CartPole-v1")
            .training(learning_starts=300, rollout_fragment_length=64,
                      train_batch_size=32, epsilon_timesteps=3000,
                      training_intensity=8, lr=1e-3, seq_len=10,
                      burn_in=2, target_network_update_freq=200)
            .debugging(seed=0).build())
    best = 0.0
    for i in range(100):
        algo.step()
        if (i + 1) % 20 == 0:
            ev = algo.evaluate(num_episodes=3)["evaluation"]
            best = max(best, ev["episode_reward_mean"])
            if best > 70:
                break
    # checkpoint roundtrip keeps recurrent-net params
    st = algo.save_checkpoint()
    algo.load_checkpoint(st)
    algo.cleanup()
    assert best > 60, f"R2D2 stuck at {best}"


def test_maddpg_centralized_critic_machinery():
    from ray_tpu.rllib.algorithms.maddpg import MADDPGConfig
    algo = (MADDPGConfig().environment("MultiAgentTarget1D",
                                       env_config={"num_agents": 2})
            .training(learning_starts=200, train_batch_size=64,
                      rollout_fragment_length=50)
            .debugging(seed=0).build())
    for _ in range(8):
        r = algo.step()
    assert r["replay_size"] >= 400
    assert np.isfinite(r["learner/critic_loss"])
    # per-agent params are stacked on a leading (n,) axis
    import jax
    leaves = jax.tree_util.tree_leaves(algo.params["actor"])
    assert all(l.shape[0] == 2 for l in leaves)
    st = algo.save_checkpoint()
    algo.load_checkpoint(st)
    algo.cleanup()


def test_maddpg_learns_rendezvous():
    """3 agents converge to the origin: eval climbs from ≈ -45
    (untrained) toward the ≈ -3 optimum."""
    from ray_tpu.rllib.algorithms.maddpg import MADDPGConfig
    algo = (MADDPGConfig().environment("MultiAgentTarget1D",
                                       env_config={"num_agents": 3})
            .training(learning_starts=500, train_batch_size=128,
                      training_intensity=4)
            .debugging(seed=0).build())
    best = -1e9
    for i in range(160):
        algo.step()
        if (i + 1) % 20 == 0:
            ev = algo.evaluate(num_episodes=4)["evaluation"]
            best = max(best, ev["episode_reward_mean"])
            if best > -10:
                break
    algo.cleanup()
    assert best > -15, f"MADDPG stuck at {best}"
