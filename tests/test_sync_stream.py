"""Versioned bidirectional sync stream (ray_syncer analogue).

Reference analogue: src/ray/common/ray_syncer — versioned snapshots up,
cluster-view deltas down on the same report exchange. Isolated from the
observability module: this test owns its own multi-node cluster.
"""

import time

from ray_tpu.experimental.state import api as state


def test_versioned_sync_stream():
    """ray_syncer analogue (reference: src/ray/common/ray_syncer):
    raylet reports carry (epoch, version); the GCS reply piggybacks
    cluster-view deltas, so every raylet's local view converges on
    membership changes — including a peer's death — without extra
    RPCs."""
    import ray_tpu as rt
    from ray_tpu._private.cluster_utils import Cluster
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    try:
        doomed = cluster.add_node(num_cpus=1, resources={"doomed": 1})
        cluster.connect()
        cluster.wait_for_nodes()

        def head_view():
            s = state.node_stats()
            entries = [n for n in s if "error" not in n]
            head = [n for n in entries
                    if n["scheduler"]["resources_total"].get("CPU")]
            return head[0]["scheduler"]

        deadline = time.time() + 30
        while time.time() < deadline:
            sched = head_view()
            if sched["cluster_view_nodes"] >= 2 and \
                    sched["known_view_version"] > 0:
                break
            time.sleep(0.5)
        assert sched["cluster_view_nodes"] >= 2
        assert sched["sync_version"] > 0

        v_before = sched["known_view_version"]
        cluster.remove_node(doomed)
        deadline = time.time() + 30
        while time.time() < deadline:
            sched = head_view()
            if sched["known_view_version"] > v_before:
                break
            time.sleep(0.5)
        # the death bumped the view version and the delta reached the
        # surviving raylet's local cache
        assert sched["known_view_version"] > v_before
    finally:
        cluster.shutdown()
