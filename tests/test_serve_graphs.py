"""Serve deployment graphs, DAGDriver, multi-app, config schema.

Reference analogues: serve/tests/test_deployment_graph*.py,
test_multi_application.py, test_schema.py, test_cli.py (scaled down).
"""

import json
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_cluster():
    ctx = ray_tpu.init(num_cpus=8, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


def _get(port, path, payload=None):
    url = f"http://127.0.0.1:{port}{path}"
    if payload is not None:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
    else:
        req = url
    return json.loads(urllib.request.urlopen(req, timeout=30).read())


def test_dag_driver_multiplexes_routes(serve_cluster):
    from ray_tpu.serve.drivers import DAGDriver

    @serve.deployment
    class Doubler:
        def __call__(self, x=0):
            return {"doubled": 2 * x}

    @serve.deployment
    class Negator:
        def __call__(self, x=0):
            return {"negated": -x}

    app = DAGDriver.bind({"/double": Doubler.bind(),
                          "/negate": Negator.bind()})
    serve.run(app, http_port=8124)
    proxy = ray_tpu.get_actor("SERVE_PROXY")
    port = ray_tpu.get(proxy.get_port.remote())
    assert _get(port, "/double", 21) == {"doubled": 42}
    assert _get(port, "/negate", 5) == {"negated": -5}
    # unknown sub-route → error surfaced (500 from the driver's KeyError)
    with pytest.raises(urllib.error.HTTPError):
        _get(port, "/nothing", 1)


def test_dag_driver_under_non_root_prefix(serve_cluster):
    from ray_tpu.serve.drivers import DAGDriver

    @serve.deployment
    class Upper:
        def __call__(self, x=""):
            return {"up": str(x).upper()}

    app = DAGDriver.options(name="ApiDriver").bind({"/up": Upper.bind()})
    serve.run(app, name="api_app", route_prefix="/api", http_port=8124)
    proxy = ray_tpu.get_actor("SERVE_PROXY")
    port = ray_tpu.get(proxy.get_port.remote())
    # the driver sees the path BELOW its route prefix
    assert _get(port, "/api/up", "hi") == {"up": "HI"}
    serve.delete_application("api_app")


def test_duplicate_deployment_name_across_apps_rejected(serve_cluster):
    @serve.deployment(name="SharedName")
    class One:
        def __call__(self, x=None):
            return 1

    @serve.deployment(name="SharedName")
    class Two:
        def __call__(self, x=None):
            return 2

    serve.run(One.bind(), name="first_app", route_prefix="/one",
              http_port=None)
    with pytest.raises(RuntimeError, match="unique across apps"):
        serve.run(Two.bind(), name="second_app", route_prefix="/two",
                  http_port=None)
    serve.delete_application("first_app")


def test_multi_app_coexistence(serve_cluster):
    @serve.deployment(name="AppA")
    class A:
        def __call__(self, x=None):
            return {"app": "a"}

    @serve.deployment(name="AppB")
    class B:
        def __call__(self, x=None):
            return {"app": "b"}

    serve.run(A.bind(), name="app_a", route_prefix="/a", http_port=8124)
    serve.run(B.bind(), name="app_b", route_prefix="/b", http_port=8124)
    apps = serve.list_applications()
    assert "app_a" in apps and "app_b" in apps
    proxy = ray_tpu.get_actor("SERVE_PROXY")
    port = ray_tpu.get(proxy.get_port.remote())
    # deploying app_b must NOT have torn down app_a
    assert _get(port, "/a") == {"app": "a"}
    assert _get(port, "/b") == {"app": "b"}
    # app-scoped deletion
    serve.delete_application("app_a")
    assert "app_a" not in serve.list_applications()
    assert _get(port, "/b") == {"app": "b"}


def test_schema_build_and_overrides():
    from ray_tpu.serve.schema import (ServeApplicationSchema, build_app)
    schema = ServeApplicationSchema(
        name="cfg_app",
        import_path="tests.serve_test_app:app",
        deployments=[{"name": "ConfigEcho", "num_replicas": 2,
                      "max_concurrent_queries": 7}])
    app = build_app(schema)
    nodes = app._collect()
    (node,) = [n for n in nodes if n.deployment.name == "ConfigEcho"]
    assert node.deployment.config["num_replicas"] == 2
    assert node.deployment.config["max_concurrent_queries"] == 7


def test_deploy_config_end_to_end(serve_cluster):
    from ray_tpu.serve.schema import deploy_config
    names = deploy_config({
        "http_options": {"port": 8124},
        "applications": [{
            "name": "cfg_app",
            "import_path": "tests.serve_test_app:app",
            "route_prefix": "/cfg",
        }],
    })
    assert names == ["cfg_app"]
    proxy = ray_tpu.get_actor("SERVE_PROXY")
    port = ray_tpu.get(proxy.get_port.remote())
    assert _get(port, "/cfg", {"k": 1}) == {"cfg_echo": {"k": 1}}
    st = serve.status()
    assert st["ConfigEcho"]["app"] == "cfg_app"


def test_builder_function_import_path():
    from ray_tpu.serve.schema import ServeApplicationSchema, build_app
    schema = ServeApplicationSchema(
        name="built", import_path="tests.serve_test_app:build_echo",
        args={"prefix": "yo"})
    app = build_app(schema)
    assert app.root.deployment.name == "ConfigEcho"


def test_http_adapters_unit():
    import numpy as np
    from ray_tpu.serve import http_adapters as ha
    a = ha.json_to_ndarray({"array": [[1, 2], [3, 4]]})
    assert a.shape == (2, 2) and a.dtype == np.float32
    assert ha.json_to_ndarray([1.0, 2.0]).tolist() == [1.0, 2.0]
    with pytest.raises(ValueError):
        ha.json_to_ndarray({"wrong": 1})
    multi = ha.json_to_multi_ndarray({"x": [1], "y": [2, 3]})
    assert set(multi) == {"x", "y"} and multi["y"].shape == (2,)
    assert ha.starlette_request({"a": 1}) == {"a": 1}
    df = ha.pandas_read_json([{"a": 1, "b": 2}, {"a": 3, "b": 4}])
    assert list(df.columns) == ["a", "b"] and len(df) == 2


def test_dag_driver_with_http_adapter(serve_cluster):
    from ray_tpu.serve.drivers import DAGDriver
    from ray_tpu.serve.http_adapters import json_to_ndarray

    @serve.deployment
    class SumModel:
        def __call__(self, arr):
            return {"sum": float(arr.sum())}

    app = DAGDriver.options(name="AdapterDriver").bind(
        {"/sum": SumModel.bind()}, http_adapter=json_to_ndarray)
    serve.run(app, http_port=8127)
    proxy = ray_tpu.get_actor("SERVE_PROXY")
    port = ray_tpu.get(proxy.get_port.remote())
    assert _get(port, "/sum", {"array": [1, 2, 3.5]}) == {"sum": 6.5}
