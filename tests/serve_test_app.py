"""Importable serve app used by the config-schema tests."""

from ray_tpu import serve


@serve.deployment(name="ConfigEcho")
class ConfigEcho:
    def __init__(self, prefix: str = "cfg_echo"):
        self.prefix = prefix

    def __call__(self, payload=None):
        return {self.prefix: payload}


app = ConfigEcho.bind()


def build_echo(prefix: str = "cfg_echo"):
    return ConfigEcho.bind(prefix)
