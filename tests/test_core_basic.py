"""Core API tests: tasks, objects, actors, options.

Mirrors the reference's python/ray/tests/test_basic.py coverage tier.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions


def test_put_get(ray_start_shared):
    ref = ray_tpu.put(42)
    assert ray_tpu.get(ref) == 42
    ref2 = ray_tpu.put({"a": [1, 2, 3], "b": "x"})
    assert ray_tpu.get(ref2) == {"a": [1, 2, 3], "b": "x"}


def test_put_get_numpy_zero_copy(ray_start_shared):
    arr = np.arange(500_000, dtype=np.float64)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref, timeout=10)
    np.testing.assert_array_equal(out, arr)


def test_simple_task(ray_start_shared):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1), timeout=60) == 2


def test_task_chaining(ray_start_shared):
    @ray_tpu.remote
    def f(x):
        return x * 2

    ref = f.remote(1)
    for _ in range(4):
        ref = f.remote(ref)
    assert ray_tpu.get(ref, timeout=60) == 32


def test_task_large_args_and_returns(ray_start_shared):
    @ray_tpu.remote
    def double(a):
        return a * 2

    arr = np.ones(300_000, dtype=np.float32)
    out = ray_tpu.get(double.remote(arr), timeout=60)
    assert out.shape == arr.shape
    assert out[0] == 2.0


def test_multiple_returns(ray_start_shared):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c], timeout=60) == [1, 2, 3]


def test_task_error_propagates(ray_start_shared):
    @ray_tpu.remote
    def boom():
        raise ValueError("boom!")

    with pytest.raises(exceptions.TaskError) as ei:
        ray_tpu.get(boom.remote(), timeout=60)
    assert "boom!" in str(ei.value)


def test_wait(ray_start_shared):
    @ray_tpu.remote
    def fast():
        return "fast"

    @ray_tpu.remote
    def slow():
        time.sleep(3)
        return "slow"

    f, s = fast.remote(), slow.remote()
    ready, pending = ray_tpu.wait([f, s], num_returns=1, timeout=30)
    assert ready and ray_tpu.get(ready[0]) == "fast"
    assert pending == [s] or not pending


def test_actor_basics(ray_start_shared):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, k=1):
            self.n += k
            return self.n

        def get(self):
            return self.n

    c = Counter.remote(10)
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 11
    assert ray_tpu.get(c.incr.remote(5), timeout=30) == 16
    assert ray_tpu.get(c.get.remote(), timeout=30) == 16


def test_actor_error(ray_start_shared):
    @ray_tpu.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor oops")

    b = Bad.remote()
    with pytest.raises(exceptions.ActorError) as ei:
        ray_tpu.get(b.fail.remote(), timeout=60)
    assert "actor oops" in str(ei.value)


def test_named_actor(ray_start_shared):
    @ray_tpu.remote
    class Store:
        def __init__(self):
            self.v = None

        def set(self, v):
            self.v = v
            return True

        def get(self):
            return self.v

    s = Store.options(name="kvstore").remote()
    ray_tpu.get(s.set.remote("hello"), timeout=60)
    s2 = ray_tpu.get_actor("kvstore")
    assert ray_tpu.get(s2.get.remote(), timeout=30) == "hello"


def test_actor_kill(ray_start_shared):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote(), timeout=60) == "pong"
    ray_tpu.kill(v)
    time.sleep(0.5)
    with pytest.raises((exceptions.ActorDiedError, exceptions.ActorError,
                        exceptions.ActorUnavailableError)):
        ray_tpu.get(v.ping.remote(), timeout=30)


def test_options_validation(ray_start_shared):
    with pytest.raises(ValueError):
        @ray_tpu.remote(num_cpus=-1)
        def f():
            pass

    with pytest.raises(ValueError):
        @ray_tpu.remote(bogus_option=1)
        def g():
            pass


def test_nested_tasks(ray_start_shared):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 10

    assert ray_tpu.get(outer.remote(1), timeout=90) == 12


def test_cluster_resources(ray_start_shared):
    res = ray_tpu.cluster_resources()
    assert res.get("CPU", 0) >= 4


def test_actor_call_with_temporary_put_ref(ray_start_shared):
    """A put() ref passed as an actor-call arg with no other Python
    reference must stay pinned until the call completes — the un-pinned
    path freed the object mid-flight and wedged the actor forever
    (regression: Ape-X/IMPALA weight broadcasts)."""
    import numpy as np

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.w = None

        def set_w(self, w):
            self.w = w
            return float(w.sum())

        def ping(self):
            return "ok"

    h = Holder.remote()
    big = np.ones((256, 256), np.float32)  # plasma-sized
    # temporary ref: dropped by the driver the moment .remote() returns
    h.set_w.remote(ray_tpu.put(big))
    # the queued ping only runs if set_w did not wedge the actor
    assert ray_tpu.get(h.ping.remote(), timeout=30) == "ok"
    assert ray_tpu.get(h.set_w.remote(ray_tpu.put(big * 2)),
                       timeout=30) == float(big.sum() * 2)


def test_gc_during_refcount_no_deadlock(ray_start_shared):
    """GC firing inside refcount critical sections must not deadlock:
    ObjectRef.__del__ only defers its decrement (regression for a
    GC-in-add_local self-deadlock caught in the full-suite run)."""
    import gc

    old = gc.get_threshold()
    gc.set_threshold(1, 1, 1)  # collect on almost every allocation
    try:
        for i in range(200):
            refs = [ray_tpu.put((i, j)) for j in range(5)]
            assert ray_tpu.get(refs, timeout=60) == [(i, j)
                                                    for j in range(5)]
            del refs
    finally:
        gc.set_threshold(*old)
    # deferred decrements actually APPLY: after draining, the dropped
    # put-ids are gone from the refcount table
    w = ray_tpu._worker_mod.global_worker()
    w.reference_counter.drain_deferred()
    assert not w.reference_counter._deferred
    import gc as _gc
    _gc.collect()
    w.reference_counter.drain_deferred()
    remaining = len(w.reference_counter.table)
    assert remaining < 50, f"refcount table leaked: {remaining} entries"


def test_pipelined_actor_calls_execute_in_order(ray_start_shared):
    """Per-caller actor ordering (reference: actor_scheduling_queue.cc):
    fire-and-forget calls must execute in submission order even though
    their async sends race — create-then-train style pipelining depends
    on it."""
    @ray_tpu.remote
    class Log:
        def __init__(self):
            self.seen = []

        def add(self, i):
            self.seen.append(i)
            return i

        def dump(self):
            return list(self.seen)

    for _ in range(5):  # the race was intermittent — several rounds
        log = Log.remote()
        for i in range(20):
            log.add.remote(i)  # no gets: sends race on the event loop
        assert ray_tpu.get(log.dump.remote(),
                           timeout=60) == list(range(20))
        ray_tpu.kill(log)
