"""Decision Transformer (rllib/algorithms/dt.py).

Reference analogue: rllib/algorithms/dt/tests/test_dt.py.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def cartpole_dataset(tmp_path_factory):
    """Mixed-quality CartPole data (noisy heuristic, episodes capped at
    120 steps) — mean return ≈ 90, best ≈ 120."""
    from ray_tpu.rllib.env import CartPoleEnv
    from ray_tpu.rllib.offline import JsonWriter
    from ray_tpu.rllib.sample_batch import SampleBatch as SB
    d = str(tmp_path_factory.mktemp("dt_cartpole"))
    rng = np.random.default_rng(0)
    env = CartPoleEnv({"seed": 0})
    cols = {k: [] for k in ("obs", "act", "rew", "done")}
    rets = []
    for ep in range(120):
        o, _ = env.reset(seed=ep)
        tot = 0.0
        noise = 0.6 if ep % 2 else 0.15  # half bad, half decent
        for t in range(120):
            a = int(o[2] + 0.4 * o[3] > 0)
            if rng.random() < noise:
                a = int(rng.integers(2))
            no, r, term, trunc, _ = env.step(a)
            ended = term or trunc or t == 119
            cols["obs"].append(o)
            cols["act"].append(a)
            cols["rew"].append(r)
            cols["done"].append(ended)
            o = no
            tot += r
            if ended:
                break
        rets.append(tot)
    w = JsonWriter(d)
    w.write(SB({SB.OBS: np.asarray(cols["obs"], np.float32),
                SB.ACTIONS: np.asarray(cols["act"], np.int64),
                SB.REWARDS: np.asarray(cols["rew"], np.float32),
                SB.DONES: np.asarray(cols["done"], bool)}))
    w.close()
    return d, float(np.mean(rets)), float(np.max(rets))


def test_dt_segmentation_rtg(cartpole_dataset):
    from ray_tpu.rllib.algorithms.dt import DTConfig
    path, _, best = cartpole_dataset
    algo = (DTConfig().environment("CartPole-v1")
            .offline_data(input_path=path)
            .training(context_length=4, num_iters_per_step=1)
            .debugging(seed=0).build())
    assert len(algo._ep_lengths) >= 100
    K = algo.K
    for i in range(5):
        # episode i's rows sit after K-1 pad rows in the flat store;
        # return-to-go starts at the episode length (reward 1.0/step)
        n = int(algo._ep_lengths[i])
        lo = int(algo._ep_bases[i]) + K - 1
        rtg = algo._flat["rtg"][lo:lo + n]
        assert rtg[0] == pytest.approx(n)
        assert rtg[-1] == pytest.approx(1.0)
        # the pad rows carry sentinel action -1
        assert (algo._flat["acts"][lo - (K - 1):lo] == -1).all()
    # default target = best dataset return
    assert algo.target_return == pytest.approx(best)
    algo.cleanup()


def test_dt_learns_return_conditioned_policy(cartpole_dataset):
    """DT trained on mediocre data, prompted with the best dataset
    return, performs at least near the dataset's BEST episodes (it
    typically exceeds them via trajectory stitching)."""
    from ray_tpu.rllib.algorithms.dt import DTConfig
    path, mean_ret, best = cartpole_dataset
    algo = (DTConfig().environment("CartPole-v1")
            .offline_data(input_path=path)
            .training(context_length=8, num_iters_per_step=40,
                      train_batch_size=64, lr=1e-3)
            .debugging(seed=0).build())
    for _ in range(8):
        r = algo.step()
    assert r["learner/action_acc"] > 0.7
    ev = algo.evaluate(num_episodes=5)["evaluation"]
    assert ev["episode_reward_mean"] > mean_ret + 10, (ev, mean_ret)
    # conditioning matters: the action logits must actually DEPEND on
    # the return-to-go tokens (a model ignoring rtg regresses here)
    import jax.numpy as jnp
    K = algo.K
    obs = jnp.zeros((1, K, algo.obs_dim))
    acts = jnp.zeros((1, K), jnp.int32)
    ts = jnp.arange(K, dtype=jnp.int32)[None]
    lo = algo._jit_logits(algo.params, jnp.zeros((1, K, 1)), obs,
                          acts, ts)
    hi = algo._jit_logits(algo.params,
                          jnp.full((1, K, 1), algo.target_return),
                          obs, acts, ts)
    assert float(jnp.max(jnp.abs(lo - hi))) > 1e-3
    algo.cleanup()
