"""Model-based / planning RLlib families: AlphaZero (MCTS self-play),
Dreamer (world model + imagination), MAML (meta-gradients), SlateQ
(slate Q-decomposition). Reference analogues:
rllib/algorithms/{alpha_zero,dreamer,maml,slateq}/.

Each gets a learning test with an explicit threshold plus the
machinery checks (checkpoint round-trip, decomposition invariants).
"""

import numpy as np
import pytest


def test_alpha_zero_learns_tictactoe():
    from ray_tpu.rllib.algorithms.alpha_zero import AlphaZeroConfig
    algo = (AlphaZeroConfig().environment("tictactoe")
            .training(games_per_iteration=24, num_sims=32, sgd_iters=8,
                      lr=2e-3)
            .debugging(seed=0).build())
    for _ in range(24):
        r = algo.step()
    assert np.isfinite(r["learner/total_loss"])
    # the RAW NET (no search) must beat a random opponent decisively —
    # that isolates what self-play taught the policy/value net
    net = algo.play_vs_random(30, use_search=False, seed=7)
    assert net["win_rate"] + net["draw_rate"] >= 0.85, net
    assert net["loss_rate"] <= 0.15, net
    # with search on top it should be at least as strong
    search = algo.play_vs_random(20, use_search=True, seed=11)
    assert search["win_rate"] + search["draw_rate"] >= 0.85, search
    st = algo.save_checkpoint()
    algo.load_checkpoint(st)
    assert algo.play_vs_random(10, seed=3)["loss_rate"] <= 0.3


def test_alpha_zero_connect4_machinery():
    """Self-play + update runs on the bigger game; terminal detection
    must see all four win directions."""
    from ray_tpu.rllib.algorithms.alpha_zero import (AlphaZeroConfig,
                                                     Connect4)
    g = Connect4()
    # vertical win: player 1 stacks column 0 (player -1 plays col 1)
    s = g.initial_state()
    for _ in range(3):
        s = g.next_state(s, 0)
        s = g.next_state(s, 1)
    s = g.next_state(s, 0)  # fourth in a row, mover flips to -1
    assert g.terminal_value(s) == -1.0  # the player to move lost
    algo = (AlphaZeroConfig().environment("connect4")
            .training(games_per_iteration=2, num_sims=8, sgd_iters=1)
            .debugging(seed=0).build())
    r = algo.step()
    assert r["num_env_steps_sampled_this_iter"] > 0
    assert np.isfinite(r["learner/total_loss"])


def test_mcts_prefers_winning_move():
    """Search alone (uniform net) must find an immediate win."""
    from ray_tpu.rllib.algorithms.alpha_zero import MCTS, TicTacToe
    g = TicTacToe()
    # X to move with two in a row: playing cell 2 wins
    board = np.zeros(9, np.int8)
    board[0] = board[1] = 1
    board[3] = board[4] = -1
    state = (board, 1)

    def uniform_eval(obs):
        return (np.zeros((obs.shape[0], 9), np.float32),
                np.zeros((obs.shape[0],), np.float32))

    counts = MCTS(g, uniform_eval,
                  rng=np.random.default_rng(0)).run(
        state, 200, add_noise=False)
    assert int(np.argmax(counts)) == 2, counts


def test_dreamer_learns_pendulum_balance():
    from ray_tpu.rllib.algorithms.dreamer import DreamerConfig
    algo = (DreamerConfig()
            .environment("Pendulum-v1", env_config={"balance_init": True})
            .training(prefill_steps=600).debugging(seed=0).build())
    untrained = algo.evaluate(4)["evaluation"]["episode_reward_mean"]
    first = None
    for i in range(25):
        r = algo.step()
        if first is None and "learner/recon_loss" in r:
            first = r
    # world model must actually fit: recon + reward losses shrink
    assert r["learner/recon_loss"] < first["learner/recon_loss"] * 0.7
    assert r["learner/reward_loss"] < first["learner/reward_loss"]
    trained = algo.evaluate(4)["evaluation"]["episode_reward_mean"]
    assert trained > untrained + 150, (untrained, trained)
    assert trained > -850, trained
    st = algo.save_checkpoint()
    algo.load_checkpoint(st)
    again = algo.evaluate(2)["evaluation"]["episode_reward_mean"]
    assert np.isfinite(again)


def test_maml_adaptation_gap():
    from ray_tpu.rllib.algorithms.maml import MAMLConfig
    algo = (MAMLConfig().training(inner_lr=0.3, lr=3e-3)
            .debugging(seed=0).build())
    before = algo.adaptation_eval(8)
    for _ in range(20):
        r = algo.step()
    assert np.isfinite(r["learner/meta_loss"])
    after = algo.adaptation_eval(8)
    # one inner step on a held-out task must pay off (the MAML claim)
    gap = after["post_adaptation_reward"] - after["pre_adaptation_reward"]
    assert gap > 2.0, after
    # and meta-training must have improved the post-adaptation policy
    assert after["post_adaptation_reward"] > \
        before["post_adaptation_reward"] + 2.0, (before, after)


def test_slateq_beats_random_slates():
    from ray_tpu.rllib.algorithms.slateq import SlateQConfig
    algo = SlateQConfig().debugging(seed=0).build()
    baseline = algo.random_baseline(30)
    for _ in range(30):
        r = algo.step()
    assert np.isfinite(r["learner/loss"])
    trained = algo.evaluate(20)["evaluation"]["episode_reward_mean"]
    assert trained > baseline + 1.5, (baseline, trained)
    st = algo.save_checkpoint()
    algo.load_checkpoint(st)


def test_slateq_decomposition_matches_choice_model():
    """Q(s, A) must decompose through the SAME MNL probabilities the
    simulator uses — pin the slate-building rule to the env's choice
    scores."""
    from ray_tpu.rllib.algorithms.slateq import (InterestEvolutionEnv,
                                                 SlateQConfig)
    env = InterestEvolutionEnv({"num_docs": 8, "slate_size": 2})
    obs, _ = env.reset(seed=0)
    v = env.choice_scores(obs)
    assert v.shape == (8,) and (v > 0).all()
    algo = SlateQConfig().environment(
        "interest_evolution",
        env_config={"num_docs": 8, "slate_size": 2}).debugging(
        seed=0).build()
    q = np.arange(8, dtype=np.float32)
    slate = algo._build_slate(q, obs)
    v_all = algo.env.choice_scores(obs)
    want = np.argsort(-(v_all * q))[:2]
    assert list(slate) == list(want)


def test_alpha_star_league_learns_and_cycles():
    """League self-play (reference: alpha_star league_builder +
    distributed training shape): the main agent must (a) beat a random
    player, (b) beat its own first snapshot (real progress, not noise),
    while the league accrues historical snapshots and a populated
    payoff matrix with exploiters applying pressure."""
    from ray_tpu.rllib.algorithms import AlphaStar, AlphaStarConfig
    from ray_tpu.rllib.algorithms.alpha_star import (
        HISTORICAL, MAIN, pfsp_weights)
    import numpy as np

    algo = AlphaStar(AlphaStarConfig().to_dict()
                     | {"seed": 0, "matches_per_iter": 48,
                        "snapshot_interval": 8})
    last = {}
    rates = []
    for _ in range(24):
        last = algo.step()
        rates.append(last["main_vs_random_win_rate"])
    assert max(rates[-6:]) >= 0.7, rates
    assert sum(rates[-6:]) / 6 >= 0.6, rates

    roles = {p.ptype for p in algo.league.values()}
    assert HISTORICAL in roles and "main_exploiter" in roles \
        and "league_exploiter" in roles
    assert last["num_historical"] >= 2
    # payoff matrix drives PFSP and shows main beating its oldest self
    # (EMA over every PFSP match against it — hundreds of samples)
    assert algo.payoff[MAIN]["historical_0"] > 0.5

    # pfsp weighting prefers hard opponents
    w = pfsp_weights(np.array([0.9, 0.5, 0.1]))
    assert w[2] > w[1] > w[0]

    # checkpoint round-trips the WHOLE league (roster, payoff,
    # snapshot counter), not just main's params
    ckpt = algo.save_checkpoint()
    fresh = AlphaStar(AlphaStarConfig().to_dict() | {"seed": 1})
    fresh.load_checkpoint(ckpt)
    assert set(fresh.league) == set(algo.league)
    assert fresh._snapshots == algo._snapshots
    assert fresh.payoff[MAIN].keys() == algo.payoff[MAIN].keys()
    assert fresh.eval_vs_random(MAIN, 10) >= 0.5  # restored, not fresh
