"""Actor concurrency groups, check_serialize, cluster storage root.

Reference analogues: test_concurrency_group.py,
util/check_serialize tests, _private/storage tests.
"""

import threading
import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    import os
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024,
                       storage="/tmp/rtpu_storage_test")
    yield ctx
    ray_tpu.shutdown()
    os.environ.pop("RTPU_STORAGE", None)


def test_concurrency_groups_isolate(cluster):
    @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
    class Worker:
        def __init__(self):
            self._evt = threading.Event()

        @ray_tpu.method(concurrency_group="compute")
        def block(self):
            self._evt.wait(30)
            return "done"

        @ray_tpu.method(concurrency_group="io")
        def quick(self):
            return "io-ok"

        def unblock(self):  # default group
            self._evt.set()
            return True

    w = Worker.remote()
    blocked = w.block.remote()
    time.sleep(0.3)
    # the compute group is saturated by block(); io + default groups
    # still serve — without groups this get would deadlock until 30s
    assert ray_tpu.get(w.quick.remote(), timeout=10) == "io-ok"
    assert ray_tpu.get(w.unblock.remote(), timeout=10)
    assert ray_tpu.get(blocked, timeout=30) == "done"


def test_concurrency_groups_list_form(cluster):
    @ray_tpu.remote(concurrency_groups=[
        {"name": "a", "max_concurrency": 2}])
    class W:
        @ray_tpu.method(concurrency_group="a")
        def f(self):
            return 1

    assert ray_tpu.get(W.remote().f.remote(), timeout=30) == 1


def test_inspect_serializability():
    from ray_tpu.util.check_serialize import inspect_serializability

    ok, failures = inspect_serializability(lambda: 42)
    assert ok and not failures

    lock = threading.Lock()

    def bad():
        return lock

    ok, failures = inspect_serializability(bad)
    assert not ok
    assert any(f.obj is lock for f in failures), failures


def test_storage_root(cluster):
    from ray_tpu._private.storage import get_storage_root, storage_path
    assert get_storage_root() == "/tmp/rtpu_storage_test"
    p = storage_path("sub", "file.txt")
    assert p.startswith("/tmp/rtpu_storage_test/")
    # workflows default under the cluster storage root (reset any
    # explicit set_storage() a previous test applied — an explicit
    # setting rightly takes precedence over the cluster root)
    import os
    os.environ.pop("RTPU_WORKFLOW_STORAGE", None)
    from ray_tpu.workflow import storage as ws
    old_root = ws._storage_root
    ws._storage_root = ws._DEFAULT_ROOT
    try:
        assert ws.get_storage() == "/tmp/rtpu_storage_test/workflows"
    finally:
        ws._storage_root = old_root


def test_unknown_concurrency_group_errors(cluster):
    @ray_tpu.remote(concurrency_groups={"io": 1})
    class W:
        @ray_tpu.method(concurrency_group="oops")
        def f(self):
            return 1

    w = W.remote()
    with pytest.raises(Exception, match="concurrency_group"):
        ray_tpu.get(w.f.remote(), timeout=30)


def test_joblib_backend_sklearn(cluster):
    """GridSearchCV fans out over cluster tasks via the joblib backend
    (reference: util/joblib ray backend)."""
    import joblib
    import numpy as np
    from sklearn.linear_model import LogisticRegression
    from sklearn.model_selection import GridSearchCV

    from ray_tpu.util.joblib import register_ray
    register_ray()

    rng = np.random.default_rng(0)
    X = rng.standard_normal((120, 5))
    y = (X.sum(axis=1) > 0).astype(int)
    with joblib.parallel_backend("ray_tpu", n_jobs=4):
        gs = GridSearchCV(LogisticRegression(max_iter=200),
                          {"C": [0.1, 1.0, 10.0]}, cv=3, n_jobs=4)
        gs.fit(X, y)
    assert gs.best_score_ > 0.8
    assert gs.best_params_["C"] in (0.1, 1.0, 10.0)
