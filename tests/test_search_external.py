"""External searcher adapters driven through interface mocks of their
backing libraries (reference: tune/search/{skopt,nevergrad,ax,flaml}
integrations + SURVEY §4's mock strategy — none of these packages ship
in this image, so the adapters are exercised against faked modules and
the gates against the real absence)."""

import sys
import types

import pytest

from ray_tpu.tune.search import (AxSearch, DragonflySearch, FLAMLSearch,
                                 HEBOSearch, NevergradSearch, SigOptSearch,
                                 SkOptSearch, ZOOptSearch)
from ray_tpu.tune import sample as s

SPACE = {"lr": s.loguniform(1e-4, 1e-1), "depth": s.randint(1, 5),
         "act": s.choice(["relu", "tanh"]), "fixed": 7}


@pytest.mark.parametrize("cls", [SkOptSearch, NevergradSearch, AxSearch,
                                 FLAMLSearch, ZOOptSearch, DragonflySearch,
                                 SigOptSearch, HEBOSearch])
def test_gates_raise_with_native_pointer(cls):
    with pytest.raises(ImportError, match="built-in|BayesOptSearch|"
                                          "TPESearcher"):
        cls(space=SPACE, metric="score", mode="max") if cls in (
            SkOptSearch, NevergradSearch, AxSearch, FLAMLSearch) else cls()


class _FakeModule(types.ModuleType):
    pass


@pytest.fixture
def fake_skopt(monkeypatch):
    mod = _FakeModule("skopt")
    space_mod = _FakeModule("skopt.space")

    class _Dim:
        def __init__(self, *a, **kw):
            self.args, self.kw = a, kw

    space_mod.Real = _Dim
    space_mod.Integer = _Dim
    space_mod.Categorical = _Dim

    class _Optimizer:
        def __init__(self, dims, random_state=None):
            self.dims = dims
            self.told = []
            self._n = 0

        def ask(self):
            self._n += 1
            # [lr, depth, act] in declaration order
            return [0.01 * self._n, 2, "relu"]

        def tell(self, x, loss):
            self.told.append((list(x), loss))

    mod.Optimizer = _Optimizer
    mod.space = space_mod
    monkeypatch.setitem(sys.modules, "skopt", mod)
    monkeypatch.setitem(sys.modules, "skopt.space", space_mod)
    return mod


def test_skopt_ask_tell_roundtrip(fake_skopt):
    searcher = SkOptSearch(space=SPACE, metric="score", mode="max", seed=0)
    cfg = searcher.suggest("t1")
    assert cfg["lr"] == pytest.approx(0.01)
    assert cfg["depth"] == 2
    assert cfg["act"] == "relu"
    assert cfg["fixed"] == 7
    searcher.on_trial_complete("t1", {"score": 0.9})
    impl = searcher._impl
    assert impl.told == [([0.01, 2, "relu"], -0.9)]  # max -> minimize flip
    # error completions are told a penalized objective (strictly worse
    # than everything observed) so the optimizer learns the region is bad
    searcher.suggest("t2")
    searcher.on_trial_complete("t2", error=True)
    assert len(impl.told) == 2
    assert impl.told[1][0] == [0.02, 2, "relu"]
    assert impl.told[1][1] > -0.9  # worse than the only real loss
    # categorical dims got the category list
    cats = [d for d in impl.dims if d.args and
            isinstance(d.args[0], list) and "relu" in d.args[0]]
    assert cats


def test_skopt_error_before_any_success_is_parked(fake_skopt):
    # an error with no prior success is parked (no loss scale yet), then
    # flushed after the first real completion with a penalty worse than it
    searcher = SkOptSearch(space=SPACE, metric="score", mode="max", seed=0)
    impl_told = lambda: searcher._impl.told
    searcher.suggest("t1")
    searcher.suggest("t2")
    searcher.on_trial_complete("t1", error=True)
    assert impl_told() == []  # parked, nothing told yet
    searcher.on_trial_complete("t2", {"score": 0.5})
    assert len(impl_told()) == 2  # real loss + flushed penalty
    assert impl_told()[0][1] == pytest.approx(-0.5)
    assert impl_told()[1][1] > -0.5


@pytest.fixture
def fake_nevergrad(monkeypatch):
    ng = _FakeModule("nevergrad")

    class _Param:
        def __init__(self, **kw):
            self.kw = kw

        def set_integer_casting(self):
            self.integer = True
            return self

    class _Dict:
        def __init__(self, **params):
            self.params = params

    class _Candidate:
        def __init__(self, value):
            self.value = value

    class _Opt:
        def __init__(self, parametrization=None, budget=None):
            self.parametrization = parametrization
            self.tells = []

        def ask(self):
            return _Candidate({"lr": 0.005, "depth": 3, "act": "tanh"})

        def tell(self, cand, loss):
            self.tells.append((cand.value, loss))

    ng.p = types.SimpleNamespace(Choice=lambda c: _Param(choices=c),
                                 Scalar=lambda **kw: _Param(**kw),
                                 Log=lambda **kw: _Param(**kw),
                                 Dict=_Dict)
    ng.optimizers = types.SimpleNamespace(registry={"NGOpt": _Opt})
    monkeypatch.setitem(sys.modules, "nevergrad", ng)
    return ng


def test_nevergrad_ask_tell_roundtrip(fake_nevergrad):
    searcher = NevergradSearch(space=SPACE, metric="loss", mode="min")
    cfg = searcher.suggest("t1")
    assert cfg["lr"] == pytest.approx(0.005)
    assert cfg["depth"] == 3
    assert cfg["act"] == "tanh"
    searcher.on_trial_complete("t1", {"loss": 1.25})
    assert searcher._impl.tells[0][1] == pytest.approx(1.25)  # min: no flip


@pytest.fixture
def fake_flaml(monkeypatch):
    flaml = _FakeModule("flaml")
    ftune = _FakeModule("flaml.tune")

    class _Dom:
        def __init__(self, kind, *args):
            self.kind, self.args = kind, args

    ftune.choice = lambda c: _Dom("choice", c)
    ftune.loguniform = lambda lo, hi: _Dom("loguniform", lo, hi)
    ftune.randint = lambda lo, hi: _Dom("randint", lo, hi)
    ftune.uniform = lambda lo, hi: _Dom("uniform", lo, hi)

    class _Blend:
        def __init__(self, metric=None, mode=None, space=None):
            self.space = space
            self.completed = []

        def suggest(self, tid):
            return {"lr": 0.02, "depth": 1, "act": "relu"}

        def on_trial_complete(self, tid, result=None, error=False):
            self.completed.append((tid, result, error))

    flaml.BlendSearch = _Blend
    flaml.tune = ftune
    monkeypatch.setitem(sys.modules, "flaml", flaml)
    monkeypatch.setitem(sys.modules, "flaml.tune", ftune)
    return flaml


def test_flaml_adapter(fake_flaml):
    searcher = FLAMLSearch(space=SPACE, metric="score", mode="max")
    cfg = searcher.suggest("t1")
    assert cfg["lr"] == pytest.approx(0.02)
    searcher.on_trial_complete("t1", {"score": 2.0})
    tid, result, error = searcher._impl.completed[0]
    assert result == {"score": -2.0} and not error
    # translated space used flaml.tune sample constructors
    assert searcher._impl.space["lr"].kind == "loguniform"
    assert searcher._impl.space["depth"].kind == "randint"
    assert searcher._impl.space["act"].kind == "choice"


def test_num_samples_exhausts(fake_skopt):
    searcher = SkOptSearch(space=SPACE, metric="m", mode="min",
                           num_samples=2)
    assert searcher.suggest("a") is not None
    assert searcher.suggest("b") is not None
    assert searcher.suggest("c") is None


def test_quniform_normal_func_and_dotted_keys(fake_skopt):
    # QUniform quantizes, Normal maps to a bounded range, sample_from
    # rides through to resolve(), dotted keys survive round-trip
    space = {"batch": s.quniform(32, 256, 32),
             "noise": s.randn(0.0, 1.0),
             "derived": s.sample_from(lambda: 11),
             "opt.lr": s.uniform(0.0, 1.0)}
    searcher = SkOptSearch(space=space, metric="m", mode="min")
    cfg = searcher.suggest("t1")
    assert cfg["batch"] % 32 == 0
    assert isinstance(cfg["noise"], (int, float))  # mock feeds ints
    assert cfg["derived"] == 11
    assert "opt.lr" in cfg          # dotted key NOT exploded into nests


def test_flaml_backoff_returns_none_without_consuming(fake_flaml):
    class _Backoff(fake_flaml.BlendSearch):
        def suggest(self, tid):
            return None

    fake_flaml.BlendSearch = _Backoff
    searcher = FLAMLSearch(space=SPACE, metric="m", mode="min",
                           num_samples=1)
    assert searcher.suggest("t1") is None
    assert searcher._suggested == 0  # budget not consumed on backoff


def test_nevergrad_error_completion_dropped(fake_nevergrad):
    searcher = NevergradSearch(space=SPACE, metric="m", mode="min")
    searcher.suggest("t1")
    searcher.on_trial_complete("t1", error=True)
    assert searcher._impl.tells == []  # inf loss never told
