"""Fault-tolerance tests: worker death, actor restart, lineage
reconstruction, node death, spillback.

Reference analogue: python/ray/tests/test_failure*.py,
test_gcs_fault_tolerance.py, and the NodeKillerActor pattern
(_private/test_utils.py) per SURVEY.md §4 fault injection. Every recovery
path in worker.py (_retry, _maybe_reconstruct) and the GCS actor RESTARTING
state machine gets at least one kill-based test here.
"""

import json
import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as exc

# Background chaos for the parametrized scenarios: a fixed seed of
# low-probability frame delays across every process. The failure paths
# under test must hold under protocol jitter exactly as they do on a
# quiet wire (the chaos engine replays the same jitter every run).
_CHAOS_BG = {"seed": 77, "delay_s": 0.02,
             "p": {"protocol.send.delay": 0.01,
                   "protocol.recv.delay": 0.01}}


@pytest.fixture(scope="function", params=["quiet", "chaos-seed-77"])
def ray_4cpu(request):
    if request.param != "quiet":
        os.environ["RTPU_CHAOS"] = json.dumps(_CHAOS_BG)
    try:
        ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                           object_store_memory=128 * 1024 * 1024)
        yield ctx
        ray_tpu.shutdown()
    finally:
        os.environ.pop("RTPU_CHAOS", None)


def test_task_retry_on_worker_death(ray_4cpu, tmp_path):
    marker = str(tmp_path / "died_once")

    @ray_tpu.remote(max_retries=3)
    def die_once():
        if not os.path.exists(marker):
            open(marker, "w").close()
            os.kill(os.getpid(), signal.SIGKILL)
        return "survived"

    assert ray_tpu.get(die_once.remote(), timeout=60) == "survived"


def test_task_fails_after_retries_exhausted(ray_4cpu):
    @ray_tpu.remote(max_retries=1)
    def always_dies():
        os.kill(os.getpid(), signal.SIGKILL)

    with pytest.raises(exc.WorkerCrashedError):
        ray_tpu.get(always_dies.remote(), timeout=60)


def test_actor_restart(ray_4cpu):
    @ray_tpu.remote(max_restarts=2)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def pid(self):
            return os.getpid()

    a = Counter.remote()
    assert ray_tpu.get(a.incr.remote(), timeout=60) == 1
    pid1 = ray_tpu.get(a.pid.remote(), timeout=30)
    os.kill(pid1, signal.SIGKILL)

    # the GCS restarts the actor (state lost: fresh __init__)
    deadline = time.monotonic() + 60
    val = None
    while time.monotonic() < deadline:
        try:
            val = ray_tpu.get(a.incr.remote(), timeout=10)
            break
        except Exception:
            time.sleep(0.5)
    assert val == 1, f"restarted actor should have fresh state, got {val}"
    pid2 = ray_tpu.get(a.pid.remote(), timeout=30)
    assert pid2 != pid1


def test_actor_dead_after_kill(ray_4cpu):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "pong"

    a = Victim.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    ray_tpu.kill(a)
    with pytest.raises(Exception):
        ray_tpu.get(a.ping.remote(), timeout=15)


def test_lineage_reconstruction_after_object_loss(ray_4cpu):
    @ray_tpu.remote
    def produce():
        return np.arange(512 * 1024, dtype=np.int64)  # 4 MB -> plasma

    ref = produce.remote()
    first = ray_tpu.get(ref, timeout=60)
    expect = int(first.sum())
    del first

    # simulate object loss: drop the primary copy from plasma + directory
    w = ray_tpu._private.worker.global_worker()
    w.call_sync(w.raylet, "free_objects", {"object_ids": [ref.id().hex()]})
    w.memory_store.delete(ref.id())
    assert not w.plasma.contains(ref.id())

    again = ray_tpu.get(ref, timeout=60)  # lineage resubmit
    assert int(again.sum()) == expect


def test_internode_object_pull():
    """Object produced on node B is pulled to the driver on node A
    (reference: test_object_manager.py transfer tests)."""
    from ray_tpu._private.cluster_utils import Cluster
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=2, resources={"nodeB": 1})
        cluster.connect()
        cluster.wait_for_nodes()

        @ray_tpu.remote(resources={"nodeB": 1})
        def produce_remote():
            return np.full(1024 * 1024, 7, dtype=np.uint8)  # 1 MB

        v = ray_tpu.get(produce_remote.remote(), timeout=90)
        assert v.nbytes == 1024 * 1024 and int(v[0]) == 7
    finally:
        cluster.shutdown()


def test_node_death_lineage_reconstruction():
    """Kill the node holding the only copy; the owner resubmits the creating
    task elsewhere (reference: object_recovery_manager + NodeKiller tests)."""
    from ray_tpu._private.cluster_utils import Cluster
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2})
    try:
        info = cluster.add_node(num_cpus=2, resources={"doomed": 1})
        cluster.connect()
        cluster.wait_for_nodes()

        @ray_tpu.remote(max_retries=3)
        def produce():
            return np.full(512 * 1024, 3, dtype=np.uint8)

        # force first execution onto the doomed node
        ref = produce.options(resources={"doomed": 0.5},
                              max_retries=3).remote()
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=60)
        assert ready

        cluster.remove_node(info)  # SIGKILL: object's only copy is gone
        time.sleep(1.0)
        # reconstruction reuses the lineage spec (same resource demand), so
        # bring up a replacement node carrying the same custom resource —
        # the pattern the reference's node-failure tests use
        cluster.add_node(num_cpus=2, resources={"doomed": 1})
        cluster.wait_for_nodes()

        v = ray_tpu.get(ref, timeout=90)
        assert int(v[0]) == 3
    finally:
        cluster.shutdown()


def test_spillback_to_free_node():
    """A task that does not fit on the head spills to a worker node
    (reference: spillback scheduling, local_task_manager)."""
    from ray_tpu._private.cluster_utils import Cluster
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    try:
        cluster.add_node(num_cpus=4)
        cluster.connect()
        cluster.wait_for_nodes()
        head_id = ray_tpu.get_runtime_context().get_node_id()

        @ray_tpu.remote(num_cpus=1)
        def hold(t):
            time.sleep(t)
            return ray_tpu.get_runtime_context().get_node_id()

        @ray_tpu.remote(num_cpus=3)
        def big_task():
            return ray_tpu.get_runtime_context().get_node_id()

        blocker = hold.remote(5.0)  # may land on either node
        node = ray_tpu.get(big_task.remote(), timeout=60)
        # 3 CPUs only exist on the worker node
        assert node != head_id
        ray_tpu.get(blocker, timeout=60)
    finally:
        cluster.shutdown()
