"""Serve layer tests (reference strategy: serve/tests/* against a local
cluster — controller reconcile, handles, HTTP, batching, autoscaling)."""

import json
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_cluster():
    ctx = ray_tpu.init(num_cpus=8, ignore_reinit_error=True,
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


def test_deploy_and_handle(serve_cluster):
    @serve.deployment(num_replicas=2)
    class Adder:
        def __init__(self, offset):
            self.offset = offset

        def __call__(self, x):
            return x + self.offset

        def mult(self, x):
            return x * self.offset

    h = serve.run(Adder.bind(10), http_port=None)
    assert ray_tpu.get(h.remote(5)) == 15
    # method routing
    assert ray_tpu.get(h.mult.remote(5)) == 50
    st = serve.status()
    assert st["Adder"]["status"] == "HEALTHY"
    assert st["Adder"]["live_replicas"] == 2


def test_function_deployment_and_composition(serve_cluster):
    @serve.deployment
    class Preprocessor:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Ingress:
        def __init__(self, pre):
            self.pre = pre

        def __call__(self, x):
            y = ray_tpu.get(self.pre.remote(x))
            return y + 1

    h = serve.run(Ingress.bind(Preprocessor.bind()), http_port=None)
    assert ray_tpu.get(h.remote(10)) == 21


def test_rolling_update_reconfigure(serve_cluster):
    @serve.deployment(num_replicas=1, user_config={"factor": 2})
    class Scaler:
        def __init__(self):
            self.factor = 1

        def reconfigure(self, cfg):
            self.factor = cfg["factor"]

        def __call__(self, x):
            return x * self.factor

    h = serve.run(Scaler.bind(), http_port=None)
    assert ray_tpu.get(h.remote(10)) == 20
    # redeploy with new user_config → new version → rolling replace
    h = serve.run(Scaler.options(user_config={"factor": 5}).bind(),
                  http_port=None)
    deadline = time.time() + 30
    while time.time() < deadline:
        if ray_tpu.get(h.remote(10)) == 50:
            break
        time.sleep(0.2)
    assert ray_tpu.get(h.remote(10)) == 50


def test_http_proxy(serve_cluster):
    @serve.deployment
    class Echo:
        def __call__(self, payload=None):
            return {"echo": payload}

    serve.run(Echo.bind(), route_prefix="/echo", http_port=8123)
    # the proxy may have bound a fallback port; ask the proxy actor
    proxy = ray_tpu.get_actor("SERVE_PROXY")
    port = ray_tpu.get(proxy.get_port.remote())
    body = json.dumps({"msg": "hi"}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/echo", data=body,
        headers={"Content-Type": "application/json"})
    resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
    assert resp == {"echo": {"msg": "hi"}}
    # GET with query params
    resp2 = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/echo?a=1", timeout=30).read())
    assert resp2 == {"echo": {"a": "1"}}
    # 404 for unknown route when no "/" route exists... "/echo" matches
    # everything under /echo only; /nope should 404.
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/nope", timeout=30)
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_batching_pads_to_bucket():
    calls = []

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05,
                 pad_to_bucket=True)
    def handler(items):
        calls.append(len(items))
        return [i * 2 for i in items]

    import threading
    results = {}

    def call(i):
        results[i] = handler(i)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == {0: 0, 1: 2, 2: 4}
    # 3 concurrent requests → padded to bucket of 4 (or served in
    # smaller flushes, each a power of two)
    assert all(c in (1, 2, 4, 8) for c in calls)


def test_batching_caps_at_max_batch_size():
    sizes = []

    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
    def handler(items):
        sizes.append(len(items))
        return list(items)

    import threading
    threads = [threading.Thread(target=handler, args=(i,))
               for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert max(sizes) <= 4
    assert sum(sizes) == 12


def test_batching_per_instance_isolation():
    class Scorer:
        def __init__(self, scale):
            self.scale = scale

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.02)
        def score(self, items):
            return [i * self.scale for i in items]

    a, b = Scorer(10), Scorer(100)
    import threading
    results = {}

    def call(obj, key, x):
        results[key] = obj.score(x)

    ts = [threading.Thread(target=call, args=(a, "a1", 1)),
          threading.Thread(target=call, args=(a, "a2", 2)),
          threading.Thread(target=call, args=(b, "b1", 1)),
          threading.Thread(target=call, args=(b, "b2", 2))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # items from instance b must never be scored with instance a's scale
    assert results == {"a1": 10, "a2": 20, "b1": 100, "b2": 200}


def test_autoscaling_policy_decisions():
    from ray_tpu.serve._private.autoscaling import (AutoscalingConfig,
                                                    AutoscalingPolicy)
    p = AutoscalingPolicy(AutoscalingConfig(
        min_replicas=1, max_replicas=4,
        target_num_ongoing_requests_per_replica=2,
        upscale_delay_s=0.0, downscale_delay_s=0.0))
    # 8 ongoing / target 2 → 4 replicas
    assert p.get_decision(1, 8.0, now=100.0) == 4
    # idle → scale back to min
    assert p.get_decision(4, 0.0, now=200.0) == 1
    # at target → hold
    assert p.get_decision(2, 4.0, now=300.0) == 2


def test_autoscaling_hysteresis():
    from ray_tpu.serve._private.autoscaling import (AutoscalingConfig,
                                                    AutoscalingPolicy)
    p = AutoscalingPolicy(AutoscalingConfig(
        min_replicas=1, max_replicas=4,
        target_num_ongoing_requests_per_replica=1,
        upscale_delay_s=5.0, downscale_delay_s=5.0))
    # spike shorter than upscale_delay → no change
    assert p.get_decision(1, 4.0, now=0.0) == 1
    assert p.get_decision(1, 4.0, now=2.0) == 1
    assert p.get_decision(1, 4.0, now=6.0) == 4


def test_function_deployment_and_delete(serve_cluster):
    @serve.deployment
    def stateless(x):
        return x + 100

    h = serve.run(stateless.options(name="ToDelete").bind(),
                  http_port=None)
    assert ray_tpu.get(h.remote(1)) == 101
    serve.delete("ToDelete")
    deadline = time.time() + 15
    while time.time() < deadline and "ToDelete" in serve.status():
        time.sleep(0.2)
    assert "ToDelete" not in serve.status()
