"""Liveness semantics: busy != dead, wedged == dead (the 1 GiB
broadcast regression chain; see SCALE.md 'What the full-size broadcast
re-run caught')."""

import asyncio
import time

import pytest

from ray_tpu._private.gcs import GcsServer
from ray_tpu.common.config import SystemConfig


class _Node:
    def __init__(self):
        self.alive = True
        self.last_seen = 0.0


@pytest.fixture
def gcs():
    g = GcsServer.__new__(GcsServer)
    g.config = SystemConfig()
    g.nodes = {"n1": _Node()}
    return g


def test_liveness_beat_refreshes_last_seen(gcs):
    gcs.nodes["n1"].last_seen = 0.0
    asyncio.run(gcs.node_liveness(
        {"node_id": "n1", "loop_lag_s": 2.0}, None))
    assert time.monotonic() - gcs.nodes["n1"].last_seen < 1.0


def test_wedged_loop_does_not_count_as_alive(gcs):
    """A beat carrying lag beyond loop_stall_death_s must NOT refresh
    last_seen: the process is up but its event loop is dead."""
    gcs.nodes["n1"].last_seen = 123.0
    asyncio.run(gcs.node_liveness(
        {"node_id": "n1",
         "loop_lag_s": gcs.config.loop_stall_death_s + 1}, None))
    assert gcs.nodes["n1"].last_seen == 123.0


def test_beat_for_dead_node_does_not_resurrect(gcs):
    """Death is sticky (reference: a dead raylet must restart, not
    sneak back): a late beat from a node already marked dead must not
    refresh its liveness."""
    gcs.nodes["n1"].alive = False
    gcs.nodes["n1"].last_seen = 123.0
    asyncio.run(gcs.node_liveness(
        {"node_id": "n1", "loop_lag_s": 0.1}, None))
    assert gcs.nodes["n1"].last_seen == 123.0


def test_beat_for_unknown_node_is_ignored(gcs):
    asyncio.run(gcs.node_liveness(
        {"node_id": "ghost", "loop_lag_s": 0.0}, None))  # no raise


def test_death_window_default_tolerates_starved_hosts():
    """The default death window must stay in the tens of seconds — the
    reference declares death only after a probe-failure STREAK, and a
    10s window killed 50 starved-but-healthy raylets in the full-size
    broadcast."""
    cfg = SystemConfig()
    assert cfg.health_check_timeout_s >= 30.0
    assert cfg.loop_stall_death_s > cfg.health_check_timeout_s
