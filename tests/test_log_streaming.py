"""Worker log streaming to the driver.

Reference analogue: python/ray/_private/log_monitor.py (tail worker out/err
→ GCS pubsub → driver stdout) and test_output.py. Here the raylet tails its
workers' log files and publishes to the 'worker_logs' channel; the driver
subscribes and mirrors matching lines.
"""

import time

import ray_tpu


def test_task_print_reaches_driver(capfd):
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=64 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def shout():
            print("HELLO-FROM-WORKER-42")
            return 1

        assert ray_tpu.get(shout.remote(), timeout=60) == 1
        # the tail->pubsub->driver path is asynchronous; poll the captured fd
        deadline = time.monotonic() + 15
        seen = ""
        while time.monotonic() < deadline:
            out, _ = capfd.readouterr()
            seen += out
            if "HELLO-FROM-WORKER-42" in seen:
                break
            time.sleep(0.25)
        assert "HELLO-FROM-WORKER-42" in seen
    finally:
        ray_tpu.shutdown()


def test_actor_stderr_reaches_driver(capfd):
    import sys

    ray_tpu.init(num_cpus=2, ignore_reinit_error=True,
                 object_store_memory=64 * 1024 * 1024)
    try:
        @ray_tpu.remote
        class Noisy:
            def speak(self):
                print("ACTOR-ERR-LINE-7", file=sys.stderr)
                return "ok"

        a = Noisy.remote()
        assert ray_tpu.get(a.speak.remote(), timeout=60) == "ok"
        deadline = time.monotonic() + 15
        seen = ""
        while time.monotonic() < deadline:
            _, err = capfd.readouterr()
            seen += err
            if "ACTOR-ERR-LINE-7" in seen:
                break
            time.sleep(0.25)
        assert "ACTOR-ERR-LINE-7" in seen
    finally:
        ray_tpu.shutdown()
