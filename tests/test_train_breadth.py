"""Trainer-breadth tests: SklearnTrainer (full fit + parallel CV),
LightningTrainer (gated import, reference-style soft dependency), and
RLTrainer (RLlib through the Train API).  Reference analogues:
train/sklearn/sklearn_trainer.py, ray_lightning shim,
train/rl/rl_trainer.py."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.air.config import ScalingConfig


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _blobs(n=120, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.int64)
    rows = [{"f0": float(a), "f1": float(b), "f2": float(c),
             "f3": float(d), "label": int(t)}
            for (a, b, c, d), t in zip(X, y)]
    return rows


def test_sklearn_trainer_fit_and_cv(cluster):
    from sklearn.linear_model import LogisticRegression

    from ray_tpu import data
    from ray_tpu.train.sklearn_trainer import SklearnTrainer

    rows = _blobs()
    train_ds = data.from_items(rows[:100])
    valid_ds = data.from_items(rows[100:])
    trainer = SklearnTrainer(
        estimator=LogisticRegression(max_iter=200),
        label_column="label", cv=3,
        scaling_config=ScalingConfig(num_workers=1),
        datasets={"train": train_ds, "valid": valid_ds})
    result = trainer.fit()
    m = result.metrics
    assert m["train-score"] > 0.8
    assert m["valid-score"] > 0.6
    assert len(m["cv_scores"]) == 3
    assert m["cv_score_mean"] > 0.6
    model = SklearnTrainer.get_model(result.checkpoint)
    assert model.predict(np.zeros((1, 4))).shape == (1,)


def test_lightning_trainer_gates_on_missing_dep():
    from ray_tpu.train.lightning_trainer import LightningTrainer
    try:
        import pytorch_lightning  # noqa: F401
        pytest.skip("lightning installed; gate not exercised")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="Lightning"):
        LightningTrainer(lightning_module_cls=object)


def test_rl_trainer_trains_and_restores(cluster):
    from ray_tpu.train.rl_trainer import RLTrainer

    trainer = RLTrainer(
        algorithm="PG",
        config={"env": "CartPole-v1", "num_workers": 0,
                "train_batch_size": 200, "lr": 1e-2},
        num_iterations=2,
        scaling_config=ScalingConfig(num_workers=1))
    result = trainer.fit()
    assert result.metrics["training_iteration"] == 2
    algo = RLTrainer.restore_algorithm(result.checkpoint)
    action = algo.compute_single_action(np.zeros(4, dtype=np.float32))
    assert action in (0, 1)
    algo.cleanup()
