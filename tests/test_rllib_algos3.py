"""Third-wave RLlib algorithms: DDPG/TD3, Ape-X DQN, async-IMPALA.

Reference analogues: rllib/algorithms/ddpg/tests/, td3, apex_dqn/tests/,
impala/tests/test_impala.py (learner-thread behavior).
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ctx = ray_tpu.init(num_cpus=4, ignore_reinit_error=True,
                       object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_ddpg_pendulum_smoke():
    from ray_tpu.rllib.algorithms.ddpg import DDPGConfig
    algo = (DDPGConfig().environment("Pendulum-v1")
            .rollouts(num_envs_per_worker=1, rollout_fragment_length=32)
            .training(train_batch_size=64, learning_starts=64)
            .debugging(seed=0).build())
    for _ in range(4):
        r = algo.step()
    assert r["replay_size"] >= 128
    assert "learner/critic_loss" in r
    assert "learner/actor_loss" in r  # policy_delay=1: every step
    a = algo.compute_single_action(np.zeros(3, np.float32))
    assert (-2.0 <= a).all() and (a <= 2.0).all()
    algo.cleanup()


def test_td3_twin_q_and_delay():
    from ray_tpu.rllib.algorithms.ddpg import TD3Config
    cfg = TD3Config()
    assert cfg["twin_q"] and cfg["policy_delay"] == 2
    assert cfg["smooth_target_policy"]
    algo = (TD3Config().environment("Pendulum-v1")
            .rollouts(num_envs_per_worker=1, rollout_fragment_length=32)
            .training(train_batch_size=64, learning_starts=64)
            .debugging(seed=0).build())
    policy = algo.get_policy()
    # twin critic params exist
    assert any("q2" in k for k in policy.params)
    r1 = algo.step()
    r2 = algo.step()
    # delayed actor: with policy_delay=2 the actor loss appears only on
    # even learn steps, critic loss on all
    assert "learner/critic_loss" in r2
    algo.cleanup()


def test_ddpg_learns_pendulum():
    """DDPG reaches good Pendulum reward (random policy: ~-1600; this
    config converges to ~-170 by iter 800 on CPU — threshold leaves
    seed margin). Reference shape: algorithms/ddpg/tests learning tests."""
    from ray_tpu.rllib.algorithms.ddpg import DDPGConfig
    algo = (DDPGConfig().environment("Pendulum-v1")
            .rollouts(num_envs_per_worker=4, rollout_fragment_length=16)
            .training(train_batch_size=128, learning_starts=256,
                      training_intensity=8, actor_lr=1e-3,
                      critic_lr=1e-3, exploration_noise=0.15)
            .debugging(seed=3).build())
    best = -1e9
    for i in range(700):
        r = algo.step()
        m = r["episode_reward_mean"]
        if not np.isnan(m):
            best = max(best, m)
        if best > -500:
            break
    algo.cleanup()
    assert best > -600, f"DDPG stuck at {best}"


def test_apex_dqn_cartpole(cluster):
    from ray_tpu.rllib.algorithms.apex_dqn import ApexDQNConfig
    algo = (ApexDQNConfig().environment("CartPole-v1")
            .rollouts(num_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=16)
            .training(train_batch_size=32, learning_starts=200,
                      replay_buffer_capacity=5000,
                      train_intensity_per_iter=2)
            .debugging(seed=0).build())
    total_learned = 0
    for _ in range(10):
        r = algo.step()
        total_learned = r["num_learner_steps"]
    assert r["replay_size"] >= 200
    assert total_learned > 0, "learner never consumed replay samples"
    assert r["num_env_steps_sampled_this_iter"] > 0
    # per-worker epsilon ladder: first worker explores least
    eps = ray_tpu.get([
        w.apply.remote(lambda w: w.policy.exploration_epsilon)
        for w in algo.workers.remote_workers])
    assert eps[0] > eps[1] or np.isclose(eps[0], 0.4), eps
    assert algo.workers.local_worker.policy.exploration_epsilon == 0.0
    algo.cleanup()


def test_impala_async_learner_overlap(cluster):
    """The learner thread consumes batches while samplers stay in
    flight — the defining IMPALA decoupling."""
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig
    algo = (IMPALAConfig().environment("CartPole-v1")
            .rollouts(num_workers=2, num_envs_per_worker=2,
                      rollout_fragment_length=32)
            .training(max_sample_batches_per_iter=6)
            .debugging(seed=0).build())
    assert algo._learner is not None and algo._learner.is_alive()
    for _ in range(4):
        r = algo.step()
    # learner thread processed batches asynchronously
    assert r["learner/num_learner_steps"] > 0
    assert r["learner/num_samples_trained"] > 0
    # samplers were relaunched while learning happened
    assert len(algo._in_flight) > 0
    assert "learner/policy_loss" in r
    algo.cleanup()
    assert algo._learner.stopped


def test_impala_async_matches_sync_learning(cluster):
    """Async IMPALA still learns CartPole (correctness of the decoupled
    path, not just liveness)."""
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig
    algo = (IMPALAConfig().environment("CartPole-v1")
            .rollouts(num_workers=2, num_envs_per_worker=4,
                      rollout_fragment_length=32)
            .training(lr=3e-3, entropy_coeff=0.005,
                      max_sample_batches_per_iter=4)
            .debugging(seed=1).build())
    best = 0.0
    for _ in range(30):
        r = algo.step()
        if not np.isnan(r["episode_reward_mean"]):
            best = max(best, r["episode_reward_mean"])
        if best > 60:
            break
    algo.cleanup()
    assert best > 60, f"async IMPALA stuck at {best}"


def test_appo_learns_cartpole(cluster):
    """APPO: IMPALA's async machinery with PPO's clipped surrogate."""
    from ray_tpu.rllib.algorithms.appo import APPOConfig
    algo = (APPOConfig().environment("CartPole-v1")
            .rollouts(num_workers=2, num_envs_per_worker=4,
                      rollout_fragment_length=32)
            .training(lr=3e-3, entropy_coeff=0.005,
                      max_sample_batches_per_iter=4)
            .debugging(seed=0).build())
    assert algo._learner is not None  # async learner thread active
    best = 0.0
    # 70 iters: the async learner's sample/update interleaving is
    # timing-dependent under 1-core suite contention — 45 was observed
    # to land at 54-58 under a concurrently running full suite; the
    # early break keeps converged runs at ~12-30 iters
    for _ in range(70):
        r = algo.step()
        if not np.isnan(r["episode_reward_mean"]):
            best = max(best, r["episode_reward_mean"])
        if best > 60:
            break
    algo.cleanup()
    assert best > 60, f"APPO stuck at {best}"
